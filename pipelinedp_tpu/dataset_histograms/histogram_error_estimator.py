"""Fast RMSE estimation for COUNT / PRIVACY_ID_COUNT from histograms.

Estimates the expected error of a DP aggregation directly from the dataset
contribution histograms — no utility-analysis run needed. Parity:
/root/reference/pipeline_dp/dataset_histograms/histogram_error_estimator.py:44-158
(same model: contribution bounding drops data uniformly across partitions;
per-partition RMSE = sqrt((dropped_fraction * size)^2 + noise_std^2),
averaged over the partition-size histogram).

TPU-first difference: the estimator is vectorized — ``estimate_rmse_vec``
scores a whole candidate grid of (l0, linf) bounds in one numpy pass over
the histogram bins, which is what the tuner wants (the reference evaluates
candidates one Python call at a time).
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import numpy as np

from pipelinedp_tpu.aggregate_params import Metric, Metrics, NoiseKind
from pipelinedp_tpu.dataset_histograms import histograms as hist


class CountErrorEstimator:
    """Histogram-based error estimator for COUNT / PRIVACY_ID_COUNT.

    Create via create_error_estimator. Partition-selection error is not
    modeled (same caveat as the reference); only contribution-bounding and
    noise error are.
    """

    def __init__(self, base_std: float, metric: Metric, noise: NoiseKind,
                 l0_ratios_dropped: Sequence[Tuple[int, float]],
                 linf_ratios_dropped: Sequence[Tuple[int, float]],
                 partition_histogram: hist.Histogram):
        self._base_std = base_std
        self._metric = metric
        self._noise = noise
        self._l0_ratios_dropped = l0_ratios_dropped
        self._linf_ratios_dropped = linf_ratios_dropped
        self._partition_histogram = partition_histogram
        # Bin sufficient statistics, precomputed once for the vectorized
        # RMSE averaging.
        bins = partition_histogram.bins
        self._bin_counts = np.array([b.count for b in bins], dtype=np.float64)
        self._bin_means = np.array(
            [b.sum / b.count if b.count else 0.0 for b in bins],
            dtype=np.float64)
        self._num_partitions = float(partition_histogram.total_count())

    def estimate_rmse(self,
                      l0_bound: int,
                      linf_bound: Optional[int] = None) -> float:
        """Expected RMSE of the metric at the given contribution bounds.

        1. Dropped-data ratios for the bounds come from the L0/Linf
           contribution histograms (exact at bin lowers, interpolated
           between).
        2. Assuming bounding drops uniformly across partitions, a partition
           of size n errs by sqrt((n * ratio_dropped)^2 + noise_std^2).
        3. Average over the partition-size histogram.
        """
        return float(
            self.estimate_rmse_vec(np.asarray([l0_bound]),
                                   None if linf_bound is None else
                                   np.asarray([linf_bound]))[0])

    def estimate_rmse_vec(
            self,
            l0_bounds: np.ndarray,
            linf_bounds: Optional[np.ndarray] = None) -> np.ndarray:
        """Vectorized estimate_rmse over a candidate grid."""
        l0_bounds = np.asarray(l0_bounds, dtype=np.float64)
        if self._metric == Metrics.COUNT:
            if linf_bounds is None:
                raise ValueError("linf must be given for COUNT")
            linf_bounds = np.asarray(linf_bounds, dtype=np.float64)
            ratio_linf = _interp_ratio_dropped(self._linf_ratios_dropped,
                                               linf_bounds)
        else:
            linf_bounds = np.ones_like(l0_bounds)
            ratio_linf = np.zeros_like(l0_bounds)
        ratio_l0 = _interp_ratio_dropped(self._l0_ratios_dropped, l0_bounds)
        ratio_dropped = 1.0 - (1.0 - ratio_l0) * (1.0 - ratio_linf)
        if self._noise == NoiseKind.LAPLACE:
            stddev = self._base_std * l0_bounds * linf_bounds
        else:
            stddev = self._base_std * np.sqrt(l0_bounds) * linf_bounds
        # [candidates, bins] broadcast; averaged over bins by count.
        per_bin = np.sqrt(
            (ratio_dropped[:, None] * self._bin_means[None, :])**2 +
            stddev[:, None]**2)
        return per_bin @ self._bin_counts / self._num_partitions

    def get_ratio_dropped_l0(self, l0_bound: int) -> float:
        return float(
            _interp_ratio_dropped(self._l0_ratios_dropped,
                                  np.asarray([l0_bound], dtype=float))[0])

    def get_ratio_dropped_linf(self, linf_bound: int) -> float:
        return float(
            _interp_ratio_dropped(self._linf_ratios_dropped,
                                  np.asarray([linf_bound], dtype=float))[0])


def _interp_ratio_dropped(ratios_dropped: Sequence[Tuple[int, float]],
                          bounds: np.ndarray) -> np.ndarray:
    """Piecewise-linear ratio-dropped at each bound (vectorized).

    ratios_dropped is ascending (threshold, ratio) starting at (0, 1);
    bounds <= 0 drop everything, bounds above the max threshold nothing.
    """
    xs = np.array([r[0] for r in ratios_dropped], dtype=np.float64)
    ys = np.array([r[1] for r in ratios_dropped], dtype=np.float64)
    out = np.interp(bounds, xs, ys)
    out = np.where(bounds <= 0, 1.0, out)
    out = np.where(bounds > xs[-1], 0.0, out)
    return out


def create_error_estimator(histograms: hist.DatasetHistograms,
                           base_std: float, metric: Metric,
                           noise: NoiseKind) -> CountErrorEstimator:
    """Estimator for COUNT or PRIVACY_ID_COUNT.

    base_std: noise standard deviation at l0 = linf = 1.
    """
    if metric not in (Metrics.COUNT, Metrics.PRIVACY_ID_COUNT):
        raise ValueError(f"Only COUNT and PRIVACY_ID_COUNT are supported, "
                         f"but metric={metric}")
    l0_ratios_dropped = hist.compute_ratio_dropped(
        histograms.l0_contributions_histogram)
    linf_ratios_dropped = hist.compute_ratio_dropped(
        histograms.linf_contributions_histogram)
    if metric == Metrics.COUNT:
        partition_histogram = histograms.count_per_partition_histogram
    else:
        partition_histogram = histograms.count_privacy_id_per_partition
    return CountErrorEstimator(base_std, metric, noise, l0_ratios_dropped,
                               linf_ratios_dropped, partition_histogram)
