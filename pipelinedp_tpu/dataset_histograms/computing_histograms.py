"""Pipelines computing the seven dataset histograms in one pass.

Parity: pipeline_dp/dataset_histograms/computing_histograms.py (log binning
:28-47, _compute_frequency_histogram :62, float binning with side inputs
:135-173, per-histogram builders :242-453, compute_dataset_histograms
:456-513, pre-aggregated variants :521-758).

Bins are logarithmic for integer histograms — values keep only their 3
most-significant digits, so histograms stay small no matter the scale — and
10000 equal-width bins between min and max for float (sum) histograms.
"""

from __future__ import annotations

import bisect
import operator
from typing import List, Tuple

import numpy as np

from pipelinedp_tpu import pipeline_functions
from pipelinedp_tpu.backends import base
from pipelinedp_tpu.data_extractors import (DataExtractors,
                                            PreAggregateExtractors)
from pipelinedp_tpu.dataset_histograms import histograms as hist

NUMBER_OF_BUCKETS_SUM_HISTOGRAM = 10000


def _to_bin_lower_upper_logarithmic(value: int) -> Tuple[int, int]:
    """Bin bounds for the log-binning scheme: keep 3 significant digits.

    Must stay in sync with
    private_contribution_bounds.generate_possible_contribution_bounds.
    """
    bound = 1000
    while value > bound:
        bound *= 10
    round_base = bound // 1000
    lower = value // round_base * round_base
    bin_size = round_base if value != bound else round_base * 10
    return lower, lower + bin_size


def _bin_lower_index(lowers: List[float], value: float) -> int:
    """Index of the bin lower for a float value given sorted bin lowers."""
    assert lowers[0] <= value <= lowers[-1]
    if value == lowers[-1]:
        return len(lowers) - 2
    return bisect.bisect_right(lowers, value) - 1


def _compute_frequency_histogram(col, backend: base.PipelineBackend,
                                 name: hist.HistogramType):
    """collection of positive ints -> 1-element collection of Histogram."""
    col = backend.count_per_element(col, "Frequency of elements")
    return _frequency_pairs_to_histogram(col, backend, name)


def _compute_weighted_frequency_histogram(col, backend: base.PipelineBackend,
                                          name: hist.HistogramType):
    """collection of (positive int, weight) -> 1-element Histogram
    collection; weights are summed per value and rounded."""
    col = backend.sum_per_key(col, "Frequency of elements")
    col = backend.map_values(col, lambda x: int(round(x)), "Round")
    return _frequency_pairs_to_histogram(col, backend, name)


def _frequency_pairs_to_histogram(col, backend: base.PipelineBackend,
                                  name: hist.HistogramType):
    """collection of (value:int, frequency:int) -> Histogram collection."""

    def to_bin(value: int, frequency: int):
        lower, upper = _to_bin_lower_upper_logarithmic(value)
        return lower, hist.FrequencyBin(lower=lower,
                                        upper=upper,
                                        count=frequency,
                                        sum=frequency * value,
                                        max=value)

    col = backend.map_tuple(col, to_bin, "To FrequencyBin")
    return _bins_to_histogram(col, backend, name)


def _float_values_to_histogram(col, backend: base.PipelineBackend,
                               name: hist.HistogramType, lowers_col):
    """collection of floats -> Histogram with the given bin lowers."""

    def to_bin(value: float, lowers_container):
        lowers = lowers_container[0]
        idx = _bin_lower_index(lowers, value)
        return lowers[idx], hist.FrequencyBin(lower=lowers[idx],
                                              upper=lowers[idx + 1],
                                              count=1,
                                              sum=value,
                                              max=value)

    col = backend.map_with_side_inputs(col, to_bin, (lowers_col,),
                                       "To FrequencyBin")
    return _bins_to_histogram(col, backend, name)


def _bins_to_histogram(col, backend: base.PipelineBackend, name):
    col = backend.reduce_per_key(col, operator.add, "Combine FrequencyBins")
    col = backend.values(col, "Drop keys")
    col = backend.to_list(col, "To 1 element collection")
    return backend.map(
        col, lambda bins: hist.Histogram(
            name, sorted(bins, key=lambda b: b.lower)), "To histogram")


def _min_max_lowers(col, number_of_buckets, backend: base.PipelineBackend):
    """Equal bin lowers spanning [min, max] of the collection."""
    min_max = pipeline_functions.min_max_elements(backend, col,
                                                  "Min and max value")

    def generate_lowers(mm):
        lo, hi = mm
        if lo == hi:
            return [lo, lo]
        return list(np.linspace(lo, hi, number_of_buckets + 1))

    return backend.map(min_max, generate_lowers, "map to lowers")


# -- raw-dataset builders ----------------------------------------------------


def _compute_l0_contributions_histogram(col_distinct,
                                        backend: base.PipelineBackend):
    """(pid, pk) distinct pairs -> histogram of #partitions per pid."""
    col = backend.keys(col_distinct, "Drop partition id")
    col = backend.count_per_element(col, "Partitions per privacy id")
    col = backend.values(col, "Drop privacy id")
    return _compute_frequency_histogram(col, backend,
                                        hist.HistogramType.L0_CONTRIBUTIONS)


def _compute_l1_contributions_histogram(col, backend: base.PipelineBackend):
    """(pid, pk) pairs -> histogram of #contributions per pid."""
    col = backend.keys(col, "Drop partition id")
    col = backend.count_per_element(col, "Contributions per privacy id")
    col = backend.values(col, "Drop privacy id")
    return _compute_frequency_histogram(col, backend,
                                        hist.HistogramType.L1_CONTRIBUTIONS)


def _compute_linf_contributions_histogram(col,
                                          backend: base.PipelineBackend):
    """(pid, pk) pairs -> histogram of #contributions per (pid, pk)."""
    col = backend.count_per_element(col, "Contributions per (pid, pk)")
    col = backend.values(col, "Drop (privacy_id, partition_key)")
    return _compute_frequency_histogram(
        col, backend, hist.HistogramType.LINF_CONTRIBUTIONS)


def _compute_linf_sum_contributions_histogram(col_with_values,
                                              backend: base.PipelineBackend):
    """((pid, pk), value) -> histogram of per-(pid, pk) sums."""
    col = backend.sum_per_key(col_with_values,
                              "Sum of contributions per (pid, partition)")
    col = backend.values(col, "Drop keys")
    col = backend.to_multi_transformable_collection(col)
    lowers = _min_max_lowers(col, NUMBER_OF_BUCKETS_SUM_HISTOGRAM, backend)
    return _float_values_to_histogram(
        col, backend, hist.HistogramType.LINF_SUM_CONTRIBUTIONS, lowers)


def _compute_partition_count_histogram(col, backend: base.PipelineBackend):
    """(pid, pk) pairs -> histogram of counts per partition."""
    col = backend.values(col, "Drop privacy keys")
    col = backend.count_per_element(col, "Count per partition")
    col = backend.values(col, "Drop partition key")
    return _compute_frequency_histogram(
        col, backend, hist.HistogramType.COUNT_PER_PARTITION)


def _compute_partition_privacy_id_count_histogram(
        col_distinct, backend: base.PipelineBackend):
    """distinct (pid, pk) -> histogram of privacy-id counts per partition."""
    col = backend.values(col_distinct, "Drop privacy key")
    col = backend.count_per_element(col, "Privacy ids per partition")
    col = backend.values(col, "Drop partition key")
    return _compute_frequency_histogram(
        col, backend, hist.HistogramType.COUNT_PRIVACY_ID_PER_PARTITION)


def _compute_partition_sum_histogram(col_with_values,
                                     backend: base.PipelineBackend):
    """((pid, pk), value) -> histogram of sums per partition."""
    col = backend.map_tuple(col_with_values, lambda pid_pk, v:
                            (pid_pk[1], v), "Key by partition")
    col = backend.sum_per_key(col, "Sum per partition")
    col = backend.values(col, "Drop partition key")
    col = backend.to_multi_transformable_collection(col)
    lowers = _min_max_lowers(col, NUMBER_OF_BUCKETS_SUM_HISTOGRAM, backend)
    return _float_values_to_histogram(col, backend,
                                      hist.HistogramType.SUM_PER_PARTITION,
                                      lowers)


def _list_to_dataset_histograms(
        histogram_list: List[hist.Histogram]) -> hist.DatasetHistograms:
    by_type = {h.name: h for h in histogram_list}
    return hist.DatasetHistograms(
        by_type.get(hist.HistogramType.L0_CONTRIBUTIONS),
        by_type.get(hist.HistogramType.L1_CONTRIBUTIONS),
        by_type.get(hist.HistogramType.LINF_CONTRIBUTIONS),
        by_type.get(hist.HistogramType.LINF_SUM_CONTRIBUTIONS),
        by_type.get(hist.HistogramType.COUNT_PER_PARTITION),
        by_type.get(hist.HistogramType.COUNT_PRIVACY_ID_PER_PARTITION),
        by_type.get(hist.HistogramType.SUM_PER_PARTITION))


def _to_dataset_histograms(histogram_cols, backend: base.PipelineBackend):
    col = backend.flatten(histogram_cols, "Histograms to one collection")
    col = backend.to_list(col, "Histograms to List")
    return backend.map(col, _list_to_dataset_histograms,
                       "To DatasetHistograms")


def compute_dataset_histograms(col, data_extractors: DataExtractors,
                               backend: base.PipelineBackend):
    """Computes all seven histograms; returns a 1-element collection with a
    DatasetHistograms.

    ColumnarData input takes the vectorized columnar fast path
    (compute_dataset_histograms_columnar); extractors/backend are unused
    there."""
    from pipelinedp_tpu.ops import encoding as _encoding
    if isinstance(col, _encoding.EncodedColumns):
        # Dense ids are just a special case of raw columns here (histogram
        # semantics never decode keys).
        col = _encoding.ColumnarData(pid=col.pid, pk=col.pk, value=col.value)
    if isinstance(col, _encoding.ColumnarData):
        return [compute_dataset_histograms_columnar(col)]
    col_with_values = backend.map(
        col, lambda row: ((data_extractors.privacy_id_extractor(row),
                           data_extractors.partition_extractor(row)),
                          data_extractors.value_extractor(row)
                          if data_extractors.value_extractor else 0),
        "Extract ((privacy_id, partition_key), value)")
    col_with_values = backend.to_multi_transformable_collection(
        col_with_values)
    col = backend.keys(col_with_values, "Drop values")
    col = backend.to_multi_transformable_collection(col)
    col_distinct = backend.distinct(col, "Distinct (pid, pk)")
    col_distinct = backend.to_multi_transformable_collection(col_distinct)

    return _to_dataset_histograms([
        _compute_l0_contributions_histogram(col_distinct, backend),
        _compute_l1_contributions_histogram(col, backend),
        _compute_linf_contributions_histogram(col, backend),
        _compute_linf_sum_contributions_histogram(col_with_values, backend),
        _compute_partition_count_histogram(col, backend),
        _compute_partition_privacy_id_count_histogram(col_distinct, backend),
        _compute_partition_sum_histogram(col_with_values, backend),
    ], backend)


# -- pre-aggregated builders -------------------------------------------------
# Pre-aggregated rows: (pk, (count, sum, n_partitions, n_contributions)) —
# the output of analysis/pre_aggregation.preaggregate, one row per (pid, pk).


def _preagg_l0_histogram(col, backend: base.PipelineBackend):
    # Each (pid, pk) row carries n_partitions; weighting by 1/n_partitions
    # counts each privacy unit exactly once.
    col = backend.map_tuple(col, lambda _, x: (x[2], 1.0 / x[2]),
                            "Extract n_partitions with weight")
    return _compute_weighted_frequency_histogram(
        col, backend, hist.HistogramType.L0_CONTRIBUTIONS)


def _preagg_l1_histogram(col, backend: base.PipelineBackend):
    col = backend.map_tuple(col, lambda _, x: (x[3], 1.0 / x[2]),
                            "Extract n_contributions with weight")
    return _compute_weighted_frequency_histogram(
        col, backend, hist.HistogramType.L1_CONTRIBUTIONS)


def _preagg_linf_histogram(col, backend: base.PipelineBackend):
    col = backend.map_tuple(col, lambda _, x: x[0], "Extract count")
    return _compute_frequency_histogram(
        col, backend, hist.HistogramType.LINF_CONTRIBUTIONS)


def _preagg_linf_sum_histogram(col, backend: base.PipelineBackend):
    col = backend.map_tuple(col, lambda _, x: x[1], "Extract sum")
    col = backend.to_multi_transformable_collection(col)
    lowers = _min_max_lowers(col, NUMBER_OF_BUCKETS_SUM_HISTOGRAM, backend)
    return _float_values_to_histogram(
        col, backend, hist.HistogramType.LINF_SUM_CONTRIBUTIONS, lowers)


def _preagg_partition_count_histogram(col, backend: base.PipelineBackend):
    col = backend.map_values(col, lambda x: x[0], "Extract count")
    col = backend.sum_per_key(col, "Sum per partition")
    col = backend.values(col, "Drop partition keys")
    return _compute_frequency_histogram(
        col, backend, hist.HistogramType.COUNT_PER_PARTITION)


def _preagg_partition_sum_histogram(col, backend: base.PipelineBackend):
    col = backend.map_values(col, lambda x: x[1], "Extract sum")
    col = backend.sum_per_key(col, "Sum per partition")
    col = backend.values(col, "Drop partition keys")
    col = backend.to_multi_transformable_collection(col)
    lowers = _min_max_lowers(col, NUMBER_OF_BUCKETS_SUM_HISTOGRAM, backend)
    return _float_values_to_histogram(col, backend,
                                      hist.HistogramType.SUM_PER_PARTITION,
                                      lowers)


def _preagg_partition_privacy_id_count_histogram(col,
                                                 backend: base.PipelineBackend):
    col = backend.keys(col, "Extract partition keys")
    col = backend.count_per_element(col, "Privacy IDs per partition")
    col = backend.values(col, "Drop partition keys")
    return _compute_frequency_histogram(
        col, backend, hist.HistogramType.COUNT_PRIVACY_ID_PER_PARTITION)


def compute_dataset_histograms_on_preaggregated_data(
        col, data_extractors: PreAggregateExtractors,
        backend: base.PipelineBackend):
    """compute_dataset_histograms for pre-aggregated input."""
    col = backend.map(
        col, lambda row: (data_extractors.partition_extractor(row),
                          data_extractors.preaggregate_extractor(row)),
        "Extract (partition_key, preaggregate_data)")
    col = backend.to_multi_transformable_collection(col)

    return _to_dataset_histograms([
        _preagg_l0_histogram(col, backend),
        _preagg_l1_histogram(col, backend),
        _preagg_linf_histogram(col, backend),
        _preagg_linf_sum_histogram(col, backend),
        _preagg_partition_count_histogram(col, backend),
        _preagg_partition_privacy_id_count_histogram(col, backend),
        _preagg_partition_sum_histogram(col, backend),
    ], backend)


# -- columnar fast path ------------------------------------------------------
# The per-row builders above cost Python-level work per row; the tuning
# story needs histograms of 100M-row datasets, so ColumnarData gets a fully
# vectorized numpy implementation producing bit-identical Histogram objects
# (same log bins, same float bins) in seconds.


def _int_histogram_from_values(values: np.ndarray,
                               name: hist.HistogramType) -> hist.Histogram:
    """Log-binned integer histogram, vectorized twin of
    _to_bin_lower_upper_logarithmic + _bins_to_histogram."""
    v = np.asarray(values, dtype=np.int64)
    v = v[v > 0]
    if len(v) == 0:
        return hist.Histogram(name, [])
    # Minimal power of 10 >= max(v, 1000), exact integer arithmetic via a
    # power table (float log would wobble at exact powers of ten).
    powers = 10**np.arange(3, 19, dtype=np.int64)
    if v.max() > powers[-1]:
        raise ValueError(
            f"{name}: contribution counts above 1e18 are not supported")
    bound = powers[np.searchsorted(powers, v, side="left")]
    round_base = bound // 1000
    lower = v // round_base * round_base
    bin_size = np.where(v != bound, round_base, round_base * 10)
    upper = lower + bin_size

    uniq, inverse = np.unique(lower, return_inverse=True)
    counts = np.bincount(inverse)
    sums = np.bincount(inverse, weights=v.astype(np.float64))
    maxes = np.zeros(len(uniq), dtype=np.int64)
    np.maximum.at(maxes, inverse, v)
    uppers = np.zeros(len(uniq), dtype=np.int64)
    np.maximum.at(uppers, inverse, upper)
    bins = [
        hist.FrequencyBin(lower=int(lo),
                          upper=int(up),
                          count=int(c),
                          sum=int(s),
                          max=int(m))
        for lo, up, c, s, m in zip(uniq, uppers, counts, sums, maxes)
    ]
    return hist.Histogram(name, bins)


def _float_histogram_from_values(values: np.ndarray,
                                 name: hist.HistogramType) -> hist.Histogram:
    """Equal-width float histogram, vectorized twin of
    _min_max_lowers + _float_values_to_histogram."""
    v = np.asarray(values, dtype=np.float64)
    if len(v) == 0:
        return hist.Histogram(name, [])
    lo, hi = float(v.min()), float(v.max())
    if lo == hi:
        return hist.Histogram(name, [
            hist.FrequencyBin(lower=lo,
                              upper=lo,
                              count=len(v),
                              sum=float(v.sum()),
                              max=hi)
        ])
    lowers = np.linspace(lo, hi, NUMBER_OF_BUCKETS_SUM_HISTOGRAM + 1)
    idx = np.minimum(
        np.searchsorted(lowers, v, side="right") - 1,
        NUMBER_OF_BUCKETS_SUM_HISTOGRAM - 1)
    uniq, inverse = np.unique(idx, return_inverse=True)
    counts = np.bincount(inverse)
    sums = np.bincount(inverse, weights=v)
    maxes = np.full(len(uniq), -np.inf)
    np.maximum.at(maxes, inverse, v)
    bins = [
        hist.FrequencyBin(lower=float(lowers[i]),
                          upper=float(lowers[i + 1]),
                          count=int(c),
                          sum=float(s),
                          max=float(m))
        for i, c, s, m in zip(uniq, counts, sums, maxes)
    ]
    return hist.Histogram(name, bins)


def compute_dataset_histograms_columnar(data) -> hist.DatasetHistograms:
    """All seven histograms from ColumnarData in vectorized numpy.

    Produces the same Histogram objects as the per-row pipeline (pinned by
    tests/dataset_histograms_test.py), at columnar speed: one int64
    group-by via np.unique plus bincounts.
    """
    from pipelinedp_tpu.ops import encoding

    pid_ids, _ = encoding._factorize(np.asarray(data.pid))
    pk_ids, pk_uniques = encoding._factorize(np.asarray(data.pk))
    n_pk = max(len(pk_uniques), 1)
    value = (np.asarray(data.value, dtype=np.float64)
             if data.value is not None else np.zeros(len(pk_ids)))
    if value.ndim != 1:
        raise ValueError(
            "dataset histograms need scalar values; vector-valued "
            f"ColumnarData (shape {value.shape}) is not supported")

    group_key = pid_ids.astype(np.int64) * n_pk + pk_ids
    uniq_g, g_inverse, g_counts = np.unique(group_key,
                                            return_inverse=True,
                                            return_counts=True)
    g_sums = np.bincount(g_inverse, weights=value)
    g_pid = uniq_g // n_pk
    g_pk = (uniq_g % n_pk).astype(np.int64)

    return hist.DatasetHistograms(
        _int_histogram_from_values(np.bincount(g_pid),
                                   hist.HistogramType.L0_CONTRIBUTIONS),
        _int_histogram_from_values(np.bincount(pid_ids),
                                   hist.HistogramType.L1_CONTRIBUTIONS),
        _int_histogram_from_values(g_counts,
                                   hist.HistogramType.LINF_CONTRIBUTIONS),
        _float_histogram_from_values(
            g_sums, hist.HistogramType.LINF_SUM_CONTRIBUTIONS),
        _int_histogram_from_values(np.bincount(pk_ids),
                                   hist.HistogramType.COUNT_PER_PARTITION),
        _int_histogram_from_values(
            np.bincount(g_pk),
            hist.HistogramType.COUNT_PRIVACY_ID_PER_PARTITION),
        _float_histogram_from_values(
            np.bincount(pk_ids, weights=value),
            hist.HistogramType.SUM_PER_PARTITION),
    )
