"""Validation helpers shared across the public API surface.

Parity: pipeline_dp/input_validators.py (reference: input_validators.py:17-35).
"""

from __future__ import annotations

import math
from typing import Any


def validate_epsilon_delta(epsilon: float, delta: float, who: str) -> None:
    """Validates an (epsilon, delta) differential-privacy budget.

    Raises ValueError unless epsilon > 0 and 0 <= delta < 1 (both finite).
    """
    for name, value in (("epsilon", epsilon), ("delta", delta)):
        if value is None:
            raise ValueError(f"{who}: {name} must not be None.")
        if not isinstance(value, (int, float)) or isinstance(value, bool):
            raise TypeError(
                f"{who}: {name} must be a number, got {type(value).__name__}.")
        if math.isnan(value) or math.isinf(value):
            raise ValueError(f"{who}: {name} must be finite, got {value}.")
    if epsilon <= 0:
        raise ValueError(f"{who}: epsilon must be positive, got {epsilon}.")
    if delta < 0:
        raise ValueError(f"{who}: delta must be non-negative, got {delta}.")
    if delta >= 1:
        raise ValueError(f"{who}: delta must be < 1, got {delta}.")


def validate_positive_int(value: Any, name: str, who: str) -> None:
    if value is None:
        raise ValueError(f"{who}: {name} must not be None.")
    if isinstance(value, bool) or not isinstance(value, int):
        raise TypeError(
            f"{who}: {name} must be an int, got {type(value).__name__}.")
    if value <= 0:
        raise ValueError(f"{who}: {name} must be positive, got {value}.")


def validate_non_negative_number(value: Any, name: str, who: str) -> None:
    if value is None:
        raise ValueError(f"{who}: {name} must not be None.")
    if isinstance(value, bool) or not isinstance(value, (int, float)):
        raise TypeError(
            f"{who}: {name} must be a number, got {type(value).__name__}.")
    if math.isnan(value):
        raise ValueError(f"{who}: {name} must not be NaN.")
    if value < 0:
        raise ValueError(f"{who}: {name} must be non-negative, got {value}.")
