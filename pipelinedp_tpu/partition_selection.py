"""Private partition selection strategies.

The reference reaches these through PyDP's C++ objects
(pipeline_dp/partition_selection.py:16-44; used at dp_engine.py:355,
dp_computations.py:804, analysis/per_partition_combiners.py:134). This module
implements the same three strategies natively, exposing the same surface:
``should_keep(n)``, ``probability_of_keep(n)``,
``noised_value_if_should_keep(n)``, ``threshold``, ``epsilon``, ``delta`` —
plus vectorized forms (``probability_of_keep_vec``, and precomputed
threshold/scale scalars) that the JAX backend feeds into batched kernels so
the hot path stays on device.

Strategies:

* ``TruncatedGeometricPartitionSelection`` — the optimal "magic" partition
  selection of Desfontaines, Voss & Lam, "Differentially private partition
  selection" (PoPETs 2022). Keep probabilities follow the saturated
  recurrence  pi_{n+1} = min(e^eps' pi_n + delta', 1 - e^-eps'(1 - pi_n -
  delta'), 1)  with per-partition eps' = eps/m and delta' = 1-(1-delta)^(1/m)
  for l0 bound m; closed forms below (validated against the recurrence in
  tests/partition_selection_test.py).
* ``LaplaceThresholdingPartitionSelection`` / ``GaussianThresholding...`` —
  noise the privacy-unit count and keep if it clears a threshold derived from
  delta (per google/differential-privacy Delta_For_Thresholding.pdf, cited at
  reference dp_computations.py:790-791).
"""

from __future__ import annotations

import abc
import math
import threading
from typing import Optional

import numpy as np
from scipy import stats

from pipelinedp_tpu import noise_core
from pipelinedp_tpu.aggregate_params import PartitionSelectionStrategy

PARTITION_STRATEGY_ENUM_TO_STR = {
    PartitionSelectionStrategy.TRUNCATED_GEOMETRIC: "truncated_geometric",
    PartitionSelectionStrategy.LAPLACE_THRESHOLDING: "laplace",
    PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING: "gaussian",
}

# Keep decisions ("uniform < keep_probability") are as release-critical as
# additive noise, so uniforms come from noise_core.sample_uniform — the
# native kernel-CSPRNG sampler when available (secure_noise.cc
# pdp_sample_uniform_double), never replayable. seed_rng routes draws
# through a private seeded numpy Generator instead (tests only); the lock
# covers backend worker threads (MultiProcLocalBackend parallelizes
# filter/map_values) since numpy Generators are not thread-safe.
_seeded_rng: Optional[np.random.Generator] = None
_rng_lock = threading.Lock()


def seed_rng(seed: Optional[int]) -> None:
    """Routes selection draws through a seeded numpy RNG (tests only).

    Pass seed_rng(None) to restore the secure non-replayable source.
    """
    global _seeded_rng
    # Production draws come from noise_core.sample_uniform (kernel CSPRNG
    # when the native library is available); this generator only exists so
    # tests can replay selection decisions.
    # dplint: disable=DPL004 — test-only seeded fallback
    _seeded_rng = None if seed is None else np.random.default_rng(seed)


def _draw_uniform(shape=None):
    if _seeded_rng is not None:
        with _rng_lock:
            return (_seeded_rng.random()
                    if shape is None else _seeded_rng.random(shape))
    return noise_core.sample_uniform(shape)


def _per_partition_delta(delta: float, max_partitions: int) -> float:
    """delta' such that m independent per-partition failures compose to delta.

    1 - (1 - delta')^m = delta  =>  delta' = 1 - (1 - delta)^(1/m).
    """
    return -math.expm1(math.log1p(-delta) / max_partitions)


class PartitionSelection(abc.ABC):
    """Interface matching the PyDP partition-selection objects."""

    def __init__(self, epsilon: float, delta: float,
                 max_partitions_contributed: int,
                 pre_threshold: Optional[int]):
        if epsilon <= 0:
            raise ValueError(f"epsilon must be positive, got {epsilon}")
        if not 0 < delta < 1:
            raise ValueError(f"delta must be in (0, 1), got {delta}")
        if max_partitions_contributed <= 0:
            raise ValueError("max_partitions_contributed must be positive, "
                             f"got {max_partitions_contributed}")
        if pre_threshold is not None and pre_threshold < 1:
            raise ValueError(f"pre_threshold must be >= 1: {pre_threshold}")
        self._epsilon = epsilon
        self._delta = delta
        self._max_partitions = max_partitions_contributed
        self._pre_threshold = pre_threshold

    @property
    def epsilon(self) -> float:
        return self._epsilon

    @property
    def delta(self) -> float:
        return self._delta

    @property
    def max_partitions_contributed(self) -> int:
        return self._max_partitions

    @property
    def pre_threshold(self) -> Optional[int]:
        return self._pre_threshold

    def _pre_threshold_shift(self, num_privacy_units):
        """Maps the raw count to the count the DP test sees.

        With pre_threshold t, partitions with fewer than t units are never
        kept; otherwise the strategy is applied to n - (t - 1).
        """
        if self._pre_threshold is None:
            return num_privacy_units
        return num_privacy_units - (self._pre_threshold - 1)

    def probability_of_keep(self, num_privacy_units: int) -> float:
        n = self._pre_threshold_shift(num_privacy_units)
        if n <= 0:
            return 0.0
        return float(self._probability_of_keep_shifted(np.asarray([n]))[0])

    def probability_of_keep_vec(self, num_privacy_units) -> np.ndarray:
        """Vectorized keep probabilities for an int array of counts."""
        n = self._pre_threshold_shift(np.asarray(num_privacy_units))
        probs = self._probability_of_keep_shifted(np.maximum(n, 1))
        return np.where(n <= 0, 0.0, probs)

    def should_keep(self, num_privacy_units: int) -> bool:
        return bool(_draw_uniform() < self.probability_of_keep(num_privacy_units))

    @abc.abstractmethod
    def _probability_of_keep_shifted(self, n: np.ndarray) -> np.ndarray:
        """P(keep) for pre-threshold-adjusted counts n >= 1."""

    @property
    @abc.abstractmethod
    def threshold(self) -> float:
        """Count at which a partition is kept with probability >= 1/2
        (exact threshold for thresholding strategies)."""

    def noised_value_if_should_keep(self,
                                    num_privacy_units: int) -> Optional[float]:
        """Returns a DP estimate of the count if the partition is kept."""
        raise NotImplementedError(
            f"{type(self).__name__} does not produce noised values.")

    def select_vec(self, num_privacy_units):
        """Vectorized host selection: (keep bool[N], noised float[N]).

        The float64 twin of ops/selection.select_partitions, used by the
        columnar engine's secure host-noise finalization. For strategies
        without noised values the second array echoes the raw counts.
        """
        counts = np.asarray(num_privacy_units)
        probs = self.probability_of_keep_vec(counts)
        keep = _draw_uniform(counts.shape) < probs
        return keep, counts.astype(np.float64)


class TruncatedGeometricPartitionSelection(PartitionSelection):
    """Optimal partition selection via the generalized geometric mechanism.

    Closed forms for the saturated recurrence (a = e^-eps', d = delta'):
      segment A (small n):  pi_n = d (e^{n eps'} - 1) / (e^{eps'} - 1)
      segment B (large n):  pi_n = pi_inf - (pi_inf - pi_{n1}) e^{-(n-n1) eps'}
    where pi_inf = 1 + d a/(1-a) is the fixed point of the B-branch and n1 is
    the last n on segment A (branch crossover at
    pi* = (1-d)(1-a)/(e^{eps'} - a)).
    """

    def __init__(self, epsilon: float, delta: float,
                 max_partitions_contributed: int,
                 pre_threshold: Optional[int] = None):
        super().__init__(epsilon, delta, max_partitions_contributed,
                         pre_threshold)
        self._eps_p = epsilon / max_partitions_contributed
        self._delta_p = _per_partition_delta(delta, max_partitions_contributed)
        e = self._eps_p
        d = self._delta_p
        a = math.exp(-e)
        if a == 0.0:
            # eps' beyond float range (exp(-eps') underflows): one unit is
            # kept with probability d, two or more always.
            self._n1 = 1
            self._pi_n1 = d
            self._pi_inf = 1.0
            self._n_always_keep = 2
            return
        # Crossover probability between the two branches:
        # (1-d)(1-a)/(e^e - a) == (1-d) a/(1+a) — the right-hand form never
        # overflows, however large eps' gets.
        pi_star = (1.0 - d) * a / (1.0 + a)
        # The recurrence steps with branch A while pi_n <= pi*, so segment
        # A's closed form holds through n1 = (last n with pi_A(n) <= pi*)+1.
        # log(ratio) computed in log space: pi_star (e^e - 1)/d =
        # exp(log(pi_star) + e + log1p(-a) - log(d)).
        log_term = math.log(pi_star) + e + math.log1p(-a) - math.log(d)
        log_ratio = (log_term
                     if log_term > 30 else math.log1p(math.exp(log_term)))
        self._n1 = max(1, math.floor(log_ratio / e) + 1)
        self._pi_n1 = self._segment_a(np.asarray([self._n1], dtype=np.float64))[0]
        self._pi_inf = 1.0 + d * a / (1.0 - a)
        # First n with pi_n == 1 (numerically), for the threshold property.
        # pi_inf - 1 = d a/(1-a) underflows in float for large eps'; compare
        # in log space instead.
        gap = self._pi_inf - self._pi_n1
        log_pi_inf_m1 = math.log(d) - e - math.log1p(-a)
        if gap <= 0 or math.log(gap) <= log_pi_inf_m1:
            self._n_always_keep = self._n1
        else:
            self._n_always_keep = self._n1 + math.ceil(
                (math.log(gap) - log_pi_inf_m1) / e)

    def _segment_a(self, n: np.ndarray) -> np.ndarray:
        # d expm1(n e)/expm1(e) = d e^{(n-1)e} (1-a^n)/(1-a), evaluated in
        # log space so large eps' cannot overflow; values above the clip
        # range are capped (the caller clips probabilities at 1).
        e, d = self._eps_p, self._delta_p
        a = math.exp(-e)
        n = np.asarray(n, dtype=np.float64)
        exponent = ((n - 1.0) * e + np.log1p(-np.power(a, n)) -
                    math.log1p(-a) + math.log(d))
        return np.exp(np.minimum(exponent, math.log(2.0)))

    def _segment_b(self, n: np.ndarray) -> np.ndarray:
        e = self._eps_p
        return self._pi_inf - (self._pi_inf - self._pi_n1) * np.exp(
            -(n - self._n1) * e)

    def _probability_of_keep_shifted(self, n: np.ndarray) -> np.ndarray:
        n = np.asarray(n, dtype=np.float64)
        probs = np.where(n <= self._n1, self._segment_a(np.minimum(n, self._n1)),
                         self._segment_b(n))
        return np.clip(probs, 0.0, 1.0)

    @property
    def threshold(self) -> float:
        """Smallest count kept with probability >= 1/2."""
        probs = self._probability_of_keep_shifted(
            np.arange(1, self._n_always_keep + 1))
        idx = int(np.searchsorted(probs, 0.5))
        base = idx + 1
        if self._pre_threshold is not None:
            base += self._pre_threshold - 1
        return float(base)


class _ThresholdingPartitionSelection(PartitionSelection):
    """Shared noised-count-vs-threshold logic."""

    # Set by subclasses:
    _noise_stddev: float
    _threshold_shifted: float  # threshold in pre-threshold-adjusted count space

    @property
    def threshold(self) -> float:
        if self._pre_threshold is not None:
            return self._threshold_shifted + self._pre_threshold - 1
        return self._threshold_shifted

    @property
    def noise_stddev(self) -> float:
        return self._noise_stddev

    @abc.abstractmethod
    def _sample_noise(self) -> float:
        ...

    @abc.abstractmethod
    def _noise_sf(self, x: np.ndarray) -> np.ndarray:
        """P(noise > x), vectorized."""

    def _probability_of_keep_shifted(self, n: np.ndarray) -> np.ndarray:
        return self._noise_sf(self._threshold_shifted -
                              np.asarray(n, dtype=np.float64))

    def should_keep(self, num_privacy_units: int) -> bool:
        return self.noised_value_if_should_keep(num_privacy_units) is not None

    def noised_value_if_should_keep(self,
                                    num_privacy_units: int) -> Optional[float]:
        n = self._pre_threshold_shift(num_privacy_units)
        if n <= 0:
            return None
        noised = n + self._sample_noise()
        if noised < self._threshold_shifted:
            return None
        if self._pre_threshold is not None:
            noised += self._pre_threshold - 1
        return float(noised)

    def select_vec(self, num_privacy_units):
        counts = np.asarray(num_privacy_units)
        n = self._pre_threshold_shift(counts).astype(np.float64)
        noised = n + self._sample_noise_vec(counts.shape)
        keep = (n > 0) & (noised >= self._threshold_shifted)
        if self._pre_threshold is not None:
            noised = noised + (self._pre_threshold - 1)
        return keep, noised

    @abc.abstractmethod
    def _sample_noise_vec(self, shape) -> np.ndarray:
        ...


class LaplaceThresholdingPartitionSelection(_ThresholdingPartitionSelection):
    """Keep iff count + Lap(m/eps) >= T, T calibrated so that a partition
    with a single privacy unit is kept with probability <= delta'."""

    def __init__(self, epsilon: float, delta: float,
                 max_partitions_contributed: int,
                 pre_threshold: Optional[int] = None):
        super().__init__(epsilon, delta, max_partitions_contributed,
                         pre_threshold)
        m = max_partitions_contributed
        self._scale = m / epsilon  # l1 sensitivity m
        self._noise_stddev = self._scale * math.sqrt(2.0)
        delta_p = _per_partition_delta(delta, m)
        # T solves P(1 + Lap(b) >= T) = delta_p.
        if delta_p <= 0.5:
            self._threshold_shifted = 1.0 - self._scale * math.log(
                2.0 * delta_p)
        else:
            self._threshold_shifted = 1.0 + self._scale * math.log(
                2.0 * (1.0 - delta_p))

    def _sample_noise(self) -> float:
        return float(noise_core.sample_laplace(self._scale))

    def _sample_noise_vec(self, shape) -> np.ndarray:
        return np.asarray(noise_core.sample_laplace(self._scale, shape))

    def _noise_sf(self, x: np.ndarray) -> np.ndarray:
        b = self._scale
        return np.where(x >= 0, 0.5 * np.exp(-x / b),
                        1.0 - 0.5 * np.exp(x / b))


class GaussianThresholdingPartitionSelection(_ThresholdingPartitionSelection):
    """Keep iff count + N(0, sigma^2) >= T.

    delta is split evenly: delta/2 calibrates sigma (analytic Gaussian
    mechanism with l2 sensitivity sqrt(m)), delta/2 calibrates the threshold.
    """

    def __init__(self, epsilon: float, delta: float,
                 max_partitions_contributed: int,
                 pre_threshold: Optional[int] = None):
        super().__init__(epsilon, delta, max_partitions_contributed,
                         pre_threshold)
        m = max_partitions_contributed
        # In-mechanism calibration split (class docstring; parity with the
        # reference's gaussian thresholding) — not a pipeline budget split.
        # dplint: disable=DPL005 — documented mechanism-internal split
        delta_noise = delta / 2.0
        # dplint: disable=DPL005 — documented mechanism-internal split
        delta_thresh = delta / 2.0
        self._sigma = noise_core.analytic_gaussian_sigma(
            epsilon, delta_noise, math.sqrt(m))
        self._noise_stddev = self._sigma
        delta_p = _per_partition_delta(delta_thresh, m)
        self._threshold_shifted = 1.0 + self._sigma * float(
            stats.norm.isf(delta_p))

    @property
    def sigma(self) -> float:
        return self._sigma

    def _sample_noise(self) -> float:
        return float(noise_core.sample_gaussian(self._sigma))

    def _sample_noise_vec(self, shape) -> np.ndarray:
        return np.asarray(noise_core.sample_gaussian(self._sigma, shape))

    def _noise_sf(self, x: np.ndarray) -> np.ndarray:
        return stats.norm.sf(np.asarray(x, dtype=np.float64) / self._sigma)


def create_partition_selection_strategy(
        strategy: PartitionSelectionStrategy,
        epsilon: float,
        delta: float,
        max_partitions_contributed: int,
        pre_threshold: Optional[int] = None) -> PartitionSelection:
    """Factory mirroring pipeline_dp/partition_selection.py:29-44."""
    if strategy == PartitionSelectionStrategy.TRUNCATED_GEOMETRIC:
        cls = TruncatedGeometricPartitionSelection
    elif strategy == PartitionSelectionStrategy.LAPLACE_THRESHOLDING:
        cls = LaplaceThresholdingPartitionSelection
    elif strategy == PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING:
        cls = GaussianThresholdingPartitionSelection
    else:
        raise ValueError(f"Unknown partition selection strategy: {strategy}")
    return cls(epsilon, delta, max_partitions_contributed, pre_threshold)
