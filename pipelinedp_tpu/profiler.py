"""Profiling hooks: JAX profiler traces with DP-stage annotations.

A capability the reference lacks (SURVEY.md §5 — its only observability is
the explain-computation report): wrap any engine call in
``with profiler.profile("/tmp/trace"):`` and open the result in
TensorBoard/Perfetto; the engine's stages show up as named trace spans via
``stage(...)`` annotations.

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import profiler

    with profiler.profile("/tmp/dp_trace"):
        result = engine.aggregate(data, params)
        accountant.compute_budgets()
        result.to_columns()

Annotations are no-ops when no trace is active, so they stay in the engine
permanently.

Thread-safety contract (PR 5's pool + lock machinery, checked by dplint
DPL008): the engine's worker pools (slab prefetch, encode workers) call
into this module from pool threads, so entry points are classified:

  * **pool-safe**: ``stage()``, ``current_sinks()``, ``adopt_sinks()``,
    ``count_event()``, ``event_count()``, ``event_counts()`` — sink
    mutation funnels through ``_add_stage_time`` under ``_sink_lock``,
    counters through the obs metrics registry's single lock, and the
    sink *list* is
    thread-local (``adopt_sinks`` installs the parent's collectors into
    the worker's own ``_collect`` slot, never sharing the list object
    across threads).
  * **owning-thread only**: ``collect_stage_times()`` (registers the
    sink dict on the calling thread; workers must join via
    ``adopt_sinks(current_sinks())`` captured on the parent),
    ``profile()`` / ``reset_events()`` (process-global trace/counter
    state; call from the driver thread, not from workers).

Set ``PIPELINEDP_TPU_DEBUG_LOCKS=1`` (validated via
``native.loader.env_int``) to assert the sink lock is held around every
sink mutation — a cheap canary for refactors that bypass
``_add_stage_time``.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Iterator, Optional

import jax

from pipelinedp_tpu.obs import metrics as obs_metrics

# Active wall-clock stage collectors (see collect_stage_times). Thread-local
# so concurrent engines don't interleave their phase budgets; worker pools
# an engine spawns (slab prefetch, encode workers) join their parent's
# collectors explicitly via adopt_sinks(current_sinks()). Sink updates are
# guarded by _sink_lock — multiple threads record into one sink dict.
_collect = threading.local()
_sink_lock = threading.Lock()


# Debug-locks env knob: name kept here, validation delegated to the
# shared loader.env_int helper (unset/empty -> off; junk raises).
DEBUG_LOCKS_ENV = "PIPELINEDP_TPU_DEBUG_LOCKS"


def _debug_locks() -> bool:
    """Re-read per call so tests can flip the env between stages."""
    from pipelinedp_tpu.native import loader
    return bool(loader.env_int(DEBUG_LOCKS_ENV, 0, 0, 1))


def current_sinks() -> list:
    """This thread's active stage-time sinks (share with adopt_sinks).
    Pool-safe: returns a fresh list snapshot of thread-local state."""
    return list(getattr(_collect, "sinks", None) or ())


def _add_stage_time(sinks, name: str, dt: float) -> None:
    """Thread-safe accumulation of one stage timing into the sinks —
    the single place sink dicts are mutated; every caller (any thread)
    goes through the lock acquired here."""
    with _sink_lock:
        if _debug_locks():
            assert _sink_lock.locked(), (
                "sink mutation outside _sink_lock — a refactor bypassed "
                "_add_stage_time's locking")
        for sink in sinks:
            sink[name] = sink.get(name, 0.0) + dt


@contextlib.contextmanager
def adopt_sinks(sinks) -> "Iterator[None]":
    """Installs a parent thread's collectors into this (worker) thread so
    its stage() timings merge into the parent's collect_stage_times()
    dict. Restores the worker's previous sinks on exit; safe to nest.
    Pool-safe: the handoff half of the cross-thread protocol — capture
    ``current_sinks()`` on the parent, enter this on the worker."""
    prev = getattr(_collect, "sinks", None)
    mine = list(prev or ())
    mine.extend(s for s in sinks if s not in mine)
    _collect.sinks = mine
    try:
        yield
    finally:
        _collect.sinks = prev

# Global named counters: compile/trace/cache telemetry (ops/finalize uses
# them to count epilogue retraces and executable-cache hits). Unlike stage
# times these are process-global — a retrace is a property of the jit
# caches, which are shared across engines and threads.
#
# Since PR 11 these are back-compat shims over the typed metrics
# registry (pipelinedp_tpu/obs/metrics.py, the "events" namespace):
# every historical caller keeps working, and the same storage feeds the
# Prometheus exposition and JSON snapshot exporters. The registry runs
# every event operation under ONE lock, so reset_events(prefix) racing
# count_event from prefetch/watchdog threads can never lose an
# increment to a detached counter (pinned by the obs hammer tests).


def count_event(name: str, n: int = 1) -> None:
    """Increments a named global counter (e.g. one per jit trace).
    Pool-safe: atomic under the obs metrics-registry lock."""
    obs_metrics.default_registry().event_inc(name, n)


def event_count(name: str) -> int:
    """Current value of a named counter (0 if never incremented)."""
    return obs_metrics.default_registry().event_value(name)


def event_counts() -> Dict[str, int]:
    """Snapshot of all named counters."""
    return obs_metrics.default_registry().event_values()


def reset_events(prefix: Optional[str] = None) -> None:
    """Zeros the named counters (those starting with ``prefix``, or all).

    Test/bench plumbing: counters are process-global, so suites that
    assert on deltas (e.g. the runtime/* resilience counters) reset their
    slice first instead of bookkeeping baselines. Atomic with respect to
    concurrent count_event calls (same registry lock).
    """
    obs_metrics.default_registry().reset_events(prefix)


@contextlib.contextmanager
def profile(logdir: str,
            create_perfetto_link: bool = False) -> Iterator[None]:
    """Captures a JAX profiler trace of the enclosed block into logdir."""
    jax.profiler.start_trace(logdir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def stage(name: str) -> Iterator[None]:
    """Names the enclosed host block (and its dispatched device work) in
    the trace; free when no trace is active. When a collect_stage_times()
    block is active, also accumulates the stage's host wall time."""
    sinks = getattr(_collect, "sinks", None)
    if sinks:
        t0 = time.perf_counter()
        try:
            with jax.profiler.TraceAnnotation(name):
                yield
        finally:
            _add_stage_time(sinks, name, time.perf_counter() - t0)
        return
    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def collect_stage_times() -> Iterator[Dict[str, float]]:
    """Collects per-stage host wall seconds for the enclosed block.

    Yields a dict that fills as stages complete: {stage_name: seconds},
    summed over re-entries. Note these are HOST wall times — a stage that
    only dispatches async device work (device_put, jitted kernels) is
    cheap here even when the device is busy long after; that asymmetry is
    exactly what the bench's overlap report keys off.

    Owning-thread only: registers the sink on the *calling* thread's
    collector list; pool workers join through
    ``adopt_sinks(current_sinks())`` captured on this thread instead of
    entering this context themselves.
    """
    sink: Dict[str, float] = {}
    sinks = getattr(_collect, "sinks", None)
    if sinks is None:
        sinks = _collect.sinks = []
    sinks.append(sink)
    try:
        yield sink
    finally:
        sinks.remove(sink)


def annotate_function(fn, name: Optional[str] = None):
    """Decorator form of stage()."""
    return jax.profiler.annotate_function(fn, name=name)


def device_memory_profile(path: str) -> None:
    """Writes a device memory profile (pprof format) to path."""
    with open(path, "wb") as f:
        f.write(jax.profiler.device_memory_profile())
