"""Profiling hooks: JAX profiler traces with DP-stage annotations.

A capability the reference lacks (SURVEY.md §5 — its only observability is
the explain-computation report): wrap any engine call in
``with profiler.profile("/tmp/trace"):`` and open the result in
TensorBoard/Perfetto; the engine's stages show up as named trace spans via
``stage(...)`` annotations.

    import pipelinedp_tpu as pdp
    from pipelinedp_tpu import profiler

    with profiler.profile("/tmp/dp_trace"):
        result = engine.aggregate(data, params)
        accountant.compute_budgets()
        result.to_columns()

Annotations are no-ops when no trace is active, so they stay in the engine
permanently.
"""

from __future__ import annotations

import contextlib
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def profile(logdir: str,
            create_perfetto_link: bool = False) -> Iterator[None]:
    """Captures a JAX profiler trace of the enclosed block into logdir."""
    jax.profiler.start_trace(logdir,
                             create_perfetto_link=create_perfetto_link)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


@contextlib.contextmanager
def stage(name: str) -> Iterator[None]:
    """Names the enclosed host block (and its dispatched device work) in
    the trace; free when no trace is active."""
    with jax.profiler.TraceAnnotation(name):
        yield


def annotate_function(fn, name: Optional[str] = None):
    """Decorator form of stage()."""
    return jax.profiler.annotate_function(fn, name=name)


def device_memory_profile(path: str) -> None:
    """Writes a device memory profile (pprof format) to path."""
    with open(path, "wb") as f:
        f.write(jax.profiler.device_memory_profile())
