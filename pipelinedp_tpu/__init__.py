"""pipelinedp_tpu: a TPU-native differential-privacy aggregation framework.

Computes anonymized statistics (COUNT, SUM, MEAN, VARIANCE, PERCENTILE,
VECTOR_SUM, PRIVACY_ID_COUNT) over keyed datasets with contribution bounding,
private partition selection, and privacy-budget accounting. The compute path
is columnar JAX/XLA (sort + segment reductions + batched noise under jit,
sharded over a device mesh); host-side backends provide the correctness
oracle and small-data execution.

Public API parity: pipeline_dp/__init__.py:14-42.
"""

from pipelinedp_tpu.aggregate_params import (
    AddDPNoiseParams,
    AggregateParams,
    CalculatePrivateContributionBoundsParams,
    CountParams,
    MeanParams,
    MechanismType,
    Metric,
    Metrics,
    NoiseKind,
    NormKind,
    PartitionSelectionStrategy,
    PrivacyIdCountParams,
    PrivateContributionBounds,
    SelectPartitionsParams,
    SumParams,
    VarianceParams,
)
from pipelinedp_tpu.budget_accounting import (
    Budget,
    BudgetAccountant,
    MechanismSpec,
    NaiveBudgetAccountant,
    PLDBudgetAccountant,
)
from pipelinedp_tpu.data_extractors import (
    DataExtractors,
    MultiValueDataExtractors,
    PreAggregateExtractors,
)
from pipelinedp_tpu.ops.encoding import ColumnarData, EncodedColumns
from pipelinedp_tpu.report_generator import ExplainComputationReport
from pipelinedp_tpu.backends.base import PipelineBackend
from pipelinedp_tpu.backends.jax_backend import JaxBackend
from pipelinedp_tpu.backends.local import LocalBackend, MultiProcLocalBackend
from pipelinedp_tpu.combiners import CustomCombiner
from pipelinedp_tpu.dp_engine import DPEngine
from pipelinedp_tpu.jax_engine import JaxDPEngine, LazyJaxResult
from pipelinedp_tpu import dataframes
from pipelinedp_tpu.dataframes import QueryBuilder
from pipelinedp_tpu.private_collection import (PrivateCollection,
                                               make_private)

__version__ = "0.1.0"

__all__ = [
    "AddDPNoiseParams",
    "AggregateParams",
    "Budget",
    "BudgetAccountant",
    "CalculatePrivateContributionBoundsParams",
    "ColumnarData",
    "CountParams",
    "CustomCombiner",
    "DPEngine",
    "DataExtractors",
    "EncodedColumns",
    "JaxBackend",
    "JaxDPEngine",
    "LazyJaxResult",
    "ExplainComputationReport",
    "LocalBackend",
    "MeanParams",
    "MechanismSpec",
    "MechanismType",
    "Metric",
    "Metrics",
    "MultiProcLocalBackend",
    "MultiValueDataExtractors",
    "NaiveBudgetAccountant",
    "NoiseKind",
    "NormKind",
    "PLDBudgetAccountant",
    "PartitionSelectionStrategy",
    "PipelineBackend",
    "PreAggregateExtractors",
    "PrivacyIdCountParams",
    "PrivateCollection",
    "PrivateContributionBounds",
    "QueryBuilder",
    "SelectPartitionsParams",
    "SumParams",
    "VarianceParams",
    "__version__",
    "make_private",
]
