"""User-facing configuration dataclasses, enums, and their validation.

This is the public parameter surface of the framework. API parity with the
reference: pipeline_dp/aggregate_params.py (Metric/Metrics :28-72, NoiseKind
:75, PartitionSelectionStrategy :86, MechanismType :92, NormKind :129,
AggregateParams :189-395, SelectPartitionsParams :398, SumParams :428,
VarianceParams :473, MeanParams :521, CountParams :567, PrivacyIdCountParams
:606, AddDPNoiseParams :645, parameters_to_readable_string :707).
"""

from __future__ import annotations

import dataclasses
import enum
import logging
import math
import numbers
from typing import Any, Callable, List, Optional, Sequence


# ---------------------------------------------------------------------------
# Metrics
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Metric:
    """A metric to compute, e.g. ``Metrics.COUNT`` or ``Metrics.PERCENTILE(90)``.

    ``parameter`` carries the percentile rank for PERCENTILE metrics.
    """
    name: str
    parameter: Optional[float] = None

    def __str__(self) -> str:
        if self.parameter is None:
            return self.name
        return f"{self.name}({self.parameter})"

    def __repr__(self) -> str:
        return self.__str__()

    @property
    def is_percentile(self) -> bool:
        return self.name == "PERCENTILE"


class Metrics:
    """Namespace of supported metrics."""
    COUNT = Metric("COUNT")
    PRIVACY_ID_COUNT = Metric("PRIVACY_ID_COUNT")
    SUM = Metric("SUM")
    MEAN = Metric("MEAN")
    VARIANCE = Metric("VARIANCE")
    VECTOR_SUM = Metric("VECTOR_SUM")

    @classmethod
    def PERCENTILE(cls, percentile_to_compute: float) -> Metric:
        return Metric("PERCENTILE", percentile_to_compute)


# ---------------------------------------------------------------------------
# Enums
# ---------------------------------------------------------------------------


class NoiseKind(enum.Enum):
    LAPLACE = "laplace"
    GAUSSIAN = "gaussian"

    def convert_to_mechanism_type(self) -> "MechanismType":
        if self is NoiseKind.LAPLACE:
            return MechanismType.LAPLACE
        return MechanismType.GAUSSIAN


class PartitionSelectionStrategy(enum.Enum):
    TRUNCATED_GEOMETRIC = "Truncated Geometric"
    LAPLACE_THRESHOLDING = "Laplace Thresholding"
    GAUSSIAN_THRESHOLDING = "Gaussian Thresholding"

    @property
    def mechanism_type(self) -> "MechanismType":
        if self is PartitionSelectionStrategy.LAPLACE_THRESHOLDING:
            return MechanismType.LAPLACE_THRESHOLDING
        if self is PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING:
            return MechanismType.GAUSSIAN_THRESHOLDING
        return MechanismType.GENERIC


class MechanismType(enum.Enum):
    LAPLACE = "Laplace"
    GAUSSIAN = "Gaussian"
    LAPLACE_THRESHOLDING = "Laplace Thresholding"
    GAUSSIAN_THRESHOLDING = "Gaussian Thresholding"
    TRUNCATED_GEOMETRIC = "Truncated Geometric"
    GENERIC = "Generic"

    def to_noise_kind(self) -> NoiseKind:
        if self in (MechanismType.LAPLACE, MechanismType.LAPLACE_THRESHOLDING):
            return NoiseKind.LAPLACE
        if self in (MechanismType.GAUSSIAN,
                    MechanismType.GAUSSIAN_THRESHOLDING):
            return NoiseKind.GAUSSIAN
        raise ValueError(f"MechanismType {self.value} has no noise kind.")

    def to_partition_selection_strategy(self) -> PartitionSelectionStrategy:
        if self is MechanismType.LAPLACE_THRESHOLDING:
            return PartitionSelectionStrategy.LAPLACE_THRESHOLDING
        if self is MechanismType.GAUSSIAN_THRESHOLDING:
            return PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING
        raise ValueError(
            f"MechanismType {self.value} is not a thresholding mechanism.")

    @property
    def is_thresholding_mechanism(self) -> bool:
        return self in (MechanismType.LAPLACE_THRESHOLDING,
                        MechanismType.GAUSSIAN_THRESHOLDING)


def noise_to_thresholding(noise_kind: NoiseKind) -> MechanismType:
    """Maps a noise kind to the corresponding thresholding mechanism.

    Parity: aggregate_params.py:120-126.
    """
    if noise_kind == NoiseKind.LAPLACE:
        return MechanismType.LAPLACE_THRESHOLDING
    if noise_kind == NoiseKind.GAUSSIAN:
        return MechanismType.GAUSSIAN_THRESHOLDING
    raise ValueError(f"Unknown noise kind {noise_kind}")


class NormKind(enum.Enum):
    Linf = "linf"
    L0 = "l0"
    L1 = "l1"
    L2 = "l2"


# ---------------------------------------------------------------------------
# Helpers
# ---------------------------------------------------------------------------


def _is_number(value: Any) -> bool:
    return isinstance(value, numbers.Number) and not isinstance(value, bool)


def _is_finite_number(value: Any) -> bool:
    return _is_number(value) and math.isfinite(value)


def _is_positive_int(value: Any) -> bool:
    return (isinstance(value, numbers.Integral) and
            not isinstance(value, bool) and value > 0)


def _require_positive_int(value: Any, field_name: str) -> None:
    if not _is_positive_int(value):
        raise ValueError(
            f"{field_name} has to be positive integer, but {value} given.")


# ---------------------------------------------------------------------------
# Parameter dataclasses
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class CalculatePrivateContributionBoundsParams:
    """Config for DP computation of contribution bounds.

    The computed bound targets COUNT / PRIVACY_ID_COUNT aggregations.
    Parity: aggregate_params.py:136-174.
    """
    aggregation_noise_kind: NoiseKind
    aggregation_eps: float
    aggregation_delta: float
    calculation_eps: float
    max_partitions_contributed_upper_bound: int

    def __post_init__(self):
        from pipelinedp_tpu import input_validators
        if self.aggregation_noise_kind is None:
            raise ValueError("aggregation_noise_kind must be set.")
        input_validators.validate_epsilon_delta(
            self.aggregation_eps, self.aggregation_delta,
            "CalculatePrivateContributionBoundsParams aggregation")
        if (self.aggregation_noise_kind == NoiseKind.GAUSSIAN and
                self.aggregation_delta == 0):
            raise ValueError(
                "Gaussian noise requires a positive aggregation_delta.")
        if not _is_number(self.calculation_eps) or self.calculation_eps <= 0:
            raise ValueError(
                f"calculation_eps must be positive, got {self.calculation_eps}.")
        _require_positive_int(self.max_partitions_contributed_upper_bound,
                              "max_partitions_contributed_upper_bound")


@dataclasses.dataclass
class PrivateContributionBounds:
    """DP-computed contribution bounds (output of
    DPEngine.calculate_private_contribution_bounds).

    Parity: aggregate_params.py:176-186.
    """
    max_partitions_contributed: int


@dataclasses.dataclass
class AggregateParams:
    """Parameters of a DP aggregation (DPEngine.aggregate).

    Parity: aggregate_params.py:189-395 — same fields, same validation
    semantics (checked by tests/aggregate_params_test.py).
    """
    metrics: List[Metric]
    noise_kind: NoiseKind = NoiseKind.LAPLACE
    max_partitions_contributed: Optional[int] = None
    max_contributions_per_partition: Optional[int] = None
    max_contributions: Optional[int] = None
    budget_weight: float = 1
    min_value: Optional[float] = None
    max_value: Optional[float] = None
    min_sum_per_partition: Optional[float] = None
    max_sum_per_partition: Optional[float] = None
    custom_combiners: Optional[Sequence] = None
    vector_norm_kind: Optional[NormKind] = None
    vector_max_norm: Optional[float] = None
    vector_size: Optional[int] = None
    contribution_bounds_already_enforced: bool = False
    public_partitions_already_filtered: bool = False
    partition_selection_strategy: PartitionSelectionStrategy = (
        PartitionSelectionStrategy.TRUNCATED_GEOMETRIC)
    pre_threshold: Optional[int] = None
    post_aggregation_thresholding: bool = False
    perform_cross_partition_contribution_bounding: bool = True
    # When True, the output carries a "<metric>_noise_stddev" column/field
    # next to each released additive metric (COUNT, PRIVACY_ID_COUNT, SUM,
    # VECTOR_SUM) stating the standard deviation of the noise that was
    # added — useful for downstream error bars. Ratio metrics (MEAN,
    # VARIANCE, PERCENTILE_*) have no single additive noise stddev and are
    # rejected at validation time.
    output_noise_stddev: bool = False

    @property
    def metrics_str(self) -> str:
        if self.metrics:
            return f"metrics={[str(m) for m in self.metrics]}"
        return f"custom combiners={[type(c).__name__ for c in (self.custom_combiners or [])]}"

    @property
    def bounds_per_contribution_are_set(self) -> bool:
        return self.min_value is not None and self.max_value is not None

    @property
    def bounds_per_partition_are_set(self) -> bool:
        return (self.min_sum_per_partition is not None and
                self.max_sum_per_partition is not None)

    def __post_init__(self):
        self._validate_paired("min_value", "max_value")
        self._validate_paired("min_sum_per_partition", "max_sum_per_partition")

        value_bound = self.min_value is not None
        partition_bound = self.min_sum_per_partition is not None
        if value_bound and partition_bound:
            raise ValueError(
                "min_value and min_sum_per_partition can not be both set.")
        if value_bound:
            self._validate_range("min_value", "max_value")
        if partition_bound:
            self._validate_range("min_sum_per_partition",
                                 "max_sum_per_partition")

        if self.metrics:
            self._validate_metric_compatibility(value_bound, partition_bound)

        if self.custom_combiners:
            logging.warning(
                "Custom combiners are an experimental feature; behavior may "
                "change without notice.")
            if self.metrics:
                raise ValueError(
                    "Custom combiners can not be used with standard metrics")

        self._validate_contribution_bounds()

        if self.pre_threshold is not None:
            _require_positive_int(self.pre_threshold, "pre_threshold")

        if self.output_noise_stddev:
            if self.custom_combiners:
                raise ValueError(
                    "output_noise_stddev is not supported with custom "
                    "combiners.")
            supported = {
                Metrics.COUNT, Metrics.PRIVACY_ID_COUNT, Metrics.SUM,
                Metrics.VECTOR_SUM
            }
            unsupported = set(self.metrics or []) - supported
            if unsupported:
                raise ValueError(
                    f"output_noise_stddev supports only additive metrics "
                    f"(COUNT, PRIVACY_ID_COUNT, SUM, VECTOR_SUM); got "
                    f"{sorted(str(m) for m in unsupported)}.")

    def _validate_metric_compatibility(self, value_bound: bool,
                                       partition_bound: bool) -> None:
        metrics = set(self.metrics)
        if Metrics.VECTOR_SUM in metrics:
            if metrics & {Metrics.SUM, Metrics.MEAN, Metrics.VARIANCE}:
                raise ValueError(
                    "AggregateParams: vector sum can not be computed together "
                    "with scalar metrics such as sum, mean etc")
        elif partition_bound:
            disallowed = metrics - {
                Metrics.SUM, Metrics.PRIVACY_ID_COUNT, Metrics.COUNT
            }
            if disallowed:
                raise ValueError(
                    f"AggregateParams: min_sum_per_partition is not compatible "
                    f"with metrics {disallowed}. Please use "
                    f"min_value/max_value.")
        elif not value_bound:
            needs_bounds = metrics - {Metrics.PRIVACY_ID_COUNT, Metrics.COUNT}
            if needs_bounds:
                raise ValueError(
                    f"AggregateParams: for metrics {needs_bounds} bounds per "
                    f"partition are required (e.g. min_value, max_value).")
        if (self.contribution_bounds_already_enforced and
                Metrics.PRIVACY_ID_COUNT in metrics):
            raise ValueError(
                "AggregateParams: Cannot calculate PRIVACY_ID_COUNT when "
                "contribution_bounds_already_enforced is set to True.")

    def _validate_contribution_bounds(self) -> None:
        if self.max_contributions is not None:
            _require_positive_int(self.max_contributions, "max_contributions")
            if (self.max_partitions_contributed is not None or
                    self.max_contributions_per_partition is not None):
                raise ValueError(
                    "AggregateParams: only one in max_contributions or both "
                    "max_partitions_contributed and "
                    "max_contributions_per_partition must be set")
        else:
            n_set = sum(v is not None
                        for v in (self.max_partitions_contributed,
                                  self.max_contributions_per_partition))
            if n_set == 0:
                raise ValueError(
                    "AggregateParams: either max_contributions must be set or "
                    "both max_partitions_contributed and "
                    "max_contributions_per_partition must be set.")
            if n_set == 1:
                raise ValueError(
                    "AggregateParams: either none or both "
                    "max_partitions_contributed and "
                    "max_contributions_per_partition must be set.")
            _require_positive_int(self.max_partitions_contributed,
                                  "max_partitions_contributed")
            _require_positive_int(self.max_contributions_per_partition,
                                  "max_contributions_per_partition")

    def _validate_paired(self, name1: str, name2: str) -> None:
        v1, v2 = getattr(self, name1), getattr(self, name2)
        if (v1 is None) != (v2 is None):
            raise ValueError(
                f"AggregateParams: {name1} and {name2} should be both set or "
                f"both None.")

    def _validate_range(self, min_name: str, max_name: str) -> None:
        for name in (min_name, max_name):
            if not _is_finite_number(getattr(self, name)):
                raise ValueError(
                    f"AggregateParams: {name} must be a finite number")
        if getattr(self, min_name) > getattr(self, max_name):
            raise ValueError(
                f"AggregateParams: {max_name} must be equal to or greater "
                f"than {min_name}")

    def __str__(self):
        return parameters_to_readable_string(self)


@dataclasses.dataclass
class SelectPartitionsParams:
    """Parameters of DP partition selection (DPEngine.select_partitions).

    Parity: aggregate_params.py:398-425.
    """
    max_partitions_contributed: int
    budget_weight: float = 1
    partition_selection_strategy: PartitionSelectionStrategy = (
        PartitionSelectionStrategy.TRUNCATED_GEOMETRIC)
    pre_threshold: Optional[int] = None
    contribution_bounds_already_enforced: bool = False

    def __post_init__(self):
        _require_positive_int(self.max_partitions_contributed,
                              "max_partitions_contributed")
        if self.pre_threshold is not None:
            _require_positive_int(self.pre_threshold, "pre_threshold")

    def __str__(self):
        return "Private Partitions"


@dataclasses.dataclass
class SumParams:
    """Parameters for a DP SUM via the high-level APIs.

    Parity: aggregate_params.py:428-470.
    """
    max_partitions_contributed: int
    max_contributions_per_partition: int
    min_value: float
    max_value: float
    partition_extractor: Callable
    value_extractor: Callable
    budget_weight: float = 1
    noise_kind: NoiseKind = NoiseKind.LAPLACE
    contribution_bounds_already_enforced: bool = False
    pre_threshold: Optional[int] = None
    public_partitions: Optional[Any] = None


@dataclasses.dataclass
class VarianceParams:
    """Parameters for a DP VARIANCE via the high-level APIs.

    Parity: aggregate_params.py:473-518.
    """
    max_partitions_contributed: int
    max_contributions_per_partition: int
    min_value: float
    max_value: float
    partition_extractor: Callable
    value_extractor: Callable
    budget_weight: float = 1
    noise_kind: NoiseKind = NoiseKind.LAPLACE
    contribution_bounds_already_enforced: bool = False
    pre_threshold: Optional[int] = None
    public_partitions: Optional[Any] = None


@dataclasses.dataclass
class MeanParams:
    """Parameters for a DP MEAN via the high-level APIs.

    Parity: aggregate_params.py:521-565.
    """
    max_partitions_contributed: int
    max_contributions_per_partition: int
    min_value: float
    max_value: float
    partition_extractor: Callable
    value_extractor: Callable
    budget_weight: float = 1
    noise_kind: NoiseKind = NoiseKind.LAPLACE
    contribution_bounds_already_enforced: bool = False
    pre_threshold: Optional[int] = None
    public_partitions: Optional[Any] = None


@dataclasses.dataclass
class CountParams:
    """Parameters for a DP COUNT via the high-level APIs.

    Parity: aggregate_params.py:567-604.
    """
    noise_kind: NoiseKind
    max_partitions_contributed: int
    max_contributions_per_partition: int
    partition_extractor: Callable
    budget_weight: float = 1
    contribution_bounds_already_enforced: bool = False
    pre_threshold: Optional[int] = None
    public_partitions: Optional[Any] = None


@dataclasses.dataclass
class PrivacyIdCountParams:
    """Parameters for a DP PRIVACY_ID_COUNT via the high-level APIs.

    Parity: aggregate_params.py:606-643.
    """
    noise_kind: NoiseKind
    max_partitions_contributed: int
    partition_extractor: Callable
    budget_weight: float = 1
    contribution_bounds_already_enforced: bool = False
    pre_threshold: Optional[int] = None
    public_partitions: Optional[Any] = None


@dataclasses.dataclass
class AddDPNoiseParams:
    """Parameters for DPEngine.add_dp_noise.

    Unlike aggregate(), add_dp_noise does NOT enforce contribution bounds; the
    caller is responsible for the provided sensitivities being true.
    Parity: aggregate_params.py:645-675.
    """
    noise_kind: NoiseKind
    l0_sensitivity: int
    linf_sensitivity: float
    budget_weight: float = 1

    def __post_init__(self):
        for name in ("l0_sensitivity", "linf_sensitivity", "budget_weight"):
            value = getattr(self, name)
            if value is not None and value <= 0:
                raise ValueError(
                    f"{name} must be positive, but {value} given.")


# ---------------------------------------------------------------------------
# Readable stringification (used by explain-computation reports)
# ---------------------------------------------------------------------------

_BOUND_PROPERTIES = (
    "max_partitions_contributed",
    "max_contributions_per_partition",
    "max_contributions",
    "min_value",
    "max_value",
    "min_sum_per_partition",
    "max_sum_per_partition",
)
_VECTOR_PROPERTIES = ("vector_max_norm", "vector_size", "vector_norm_kind")


def parameters_to_readable_string(
        params: Any, is_public_partition: Optional[bool] = None) -> str:
    """Renders a params dataclass as the human-readable multi-line string used
    in Explain Computation reports.

    Parity: aggregate_params.py:707-738.
    """
    lines = [f"{type(params).__name__}:"]
    if hasattr(params, "metrics_str"):
        lines.append(f" {params.metrics_str}")
    if getattr(params, "noise_kind", None) is not None:
        lines.append(f" noise_kind={params.noise_kind.value}")
    if hasattr(params, "budget_weight"):
        lines.append(f" budget_weight={params.budget_weight}")
    lines.append(" Contribution bounding:")
    for name in _BOUND_PROPERTIES:
        value = getattr(params, name, None)
        if value is not None:
            lines.append(f"  {name}={value}")
    if getattr(params, "contribution_bounds_already_enforced", False):
        lines.append("  contribution_bounds_already_enforced=True")
    for name in _VECTOR_PROPERTIES:
        value = getattr(params, name, None)
        if value is not None:
            lines.append(f"  {name}={value}")
    if is_public_partition is not None:
        kind = "public" if is_public_partition else "private"
        lines.append(f" Partition selection: {kind} partitions")
    return "\n".join(lines)
