"""DP computation of contribution bounds (currently the L0 bound,
max_partitions_contributed) via the exponential mechanism over dataset
histograms.

Parity: pipeline_dp/private_contribution_bounds.py (PrivateL0Calculator
:27-87, L0ScoringFunction :90-176, generate_possible_contribution_bounds
:179-196).
"""

from __future__ import annotations

import dataclasses
from functools import lru_cache
from typing import List

from pipelinedp_tpu import dp_computations
from pipelinedp_tpu import pipeline_functions
from pipelinedp_tpu.aggregate_params import (
    CalculatePrivateContributionBoundsParams)
from pipelinedp_tpu.dataset_histograms.histograms import Histogram


class PrivateL0Calculator:
    """Chooses max_partitions_contributed in a DP way.

    Scores candidate bounds k by the trade-off between added noise
    (proportional to the count-noise std at l0=k, over all partitions) and
    data dropped by bounding at k (from the L0 contribution histogram), then
    samples a bound with the exponential mechanism.
    """

    def __init__(self, params: CalculatePrivateContributionBoundsParams,
                 partitions, histograms, backend) -> None:
        self._params = params
        self._backend = backend
        self._partitions = partitions
        self._histograms = histograms

    @dataclasses.dataclass
    class Inputs:
        l0_histogram: Histogram
        number_of_partitions: int

    @lru_cache(maxsize=None)
    def calculate(self):
        """Returns a 1-element collection with the chosen l0 bound."""
        l0_histogram = self._backend.to_multi_transformable_collection(
            self._backend.map(self._histograms,
                              lambda h: h.l0_contributions_histogram,
                              "Extract l0_contributions_histogram"))
        number_of_partitions = self._calculate_number_of_partitions()
        inputs_col = pipeline_functions.collect_to_container(
            self._backend, {
                "l0_histogram": l0_histogram,
                "number_of_partitions": number_of_partitions,
            }, PrivateL0Calculator.Inputs,
            "Collect L0 calculation inputs")
        return self._backend.map(inputs_col, self._calculate_l0,
                                 "Calculate private l0 bound")

    def _calculate_l0(self, inputs: "PrivateL0Calculator.Inputs") -> int:
        scoring = L0ScoringFunction(self._params,
                                    inputs.number_of_partitions,
                                    inputs.l0_histogram)
        candidates = generate_possible_contribution_bounds(
            scoring.max_partitions_contributed_best_upper_bound())
        return dp_computations.ExponentialMechanism(scoring).apply(
            self._params.calculation_eps, candidates)

    def _calculate_number_of_partitions(self):
        distinct = self._backend.distinct(self._partitions,
                                          "Keep only distinct partitions")
        return pipeline_functions.size(self._backend, distinct,
                                       "Calculate number of partitions")


class L0ScoringFunction(dp_computations.ExponentialMechanism.ScoringFunction):
    """score(k) = -0.5 * noise_impact(k) - 0.5 * dropped_data(k).

    noise_impact(k) = number_of_partitions * count_noise_std(l0=k, linf=1);
    dropped_data(k) = sum over privacy units of
    max(min(#partitions_contributed, upper_bound) - k, 0), read off the L0
    histogram. Suitable for COUNT / PRIVACY_ID_COUNT.
    """

    def __init__(self, params: CalculatePrivateContributionBoundsParams,
                 number_of_partitions: int, l0_histogram: Histogram):
        super().__init__()
        self._params = params
        self._number_of_partitions = number_of_partitions
        self._l0_histogram = l0_histogram

    def max_partitions_contributed_best_upper_bound(self) -> int:
        return min(self._params.max_partitions_contributed_upper_bound,
                   self._number_of_partitions)

    # Kept for parity with the reference's private name (used in tests).
    _max_partitions_contributed_best_upper_bound = (
        max_partitions_contributed_best_upper_bound)

    def score(self, k: int) -> float:
        impact_noise_weight = 0.5
        return -(impact_noise_weight * self._l0_impact_noise(k) +
                 (1 - impact_noise_weight) * self._l0_impact_dropped(k))

    @property
    def global_sensitivity(self) -> float:
        # One privacy unit can change dropped_data(k) by at most
        # upper_bound - k <= upper_bound; noise impact is data-independent.
        return self.max_partitions_contributed_best_upper_bound()

    @property
    def is_monotonic(self) -> bool:
        return True

    def _l0_impact_noise(self, k: int) -> float:
        noise_params = dp_computations.ScalarNoiseParams(
            eps=self._params.aggregation_eps,
            delta=self._params.aggregation_delta,
            max_partitions_contributed=k,
            max_contributions_per_partition=1,
            noise_kind=self._params.aggregation_noise_kind,
            min_value=None,
            max_value=None,
            min_sum_per_partition=None,
            max_sum_per_partition=None)
        return (self._number_of_partitions *
                dp_computations.compute_dp_count_noise_std(noise_params))

    def _l0_impact_dropped(self, k: int) -> float:
        upper = self.max_partitions_contributed_best_upper_bound()
        return sum(
            max(min(bin_.lower, upper) - k, 0) * bin_.count
            for bin_ in self._l0_histogram.bins)


def generate_possible_contribution_bounds(upper_bound: int) -> List[int]:
    """All integers <= upper_bound with at most 3 significant digits:
    1..999, 1000, 1010, ..., 9990, 10000, 10100, ... (log-size list).

    Kept in sync with the histogram log-binning
    (computing_histograms._to_bin_lower_upper_logarithmic).
    """
    bounds = []
    current = 1
    power = 10
    while current <= upper_bound:
        bounds.append(current)
        if current >= power:
            power *= 10
        current += max(1, power // 1000)
    return bounds
