"""dplint command line: `python -m pipelinedp_tpu.lint [paths...]`.

Exit codes: 0 = clean (or every finding baselined/suppressed), 1 = new
findings, 2 = usage or internal error. The default baseline file,
``dplint-baseline.json`` in the current directory, is loaded when present;
``--write-baseline`` snapshots the current findings so existing debt can
be ratcheted down without blocking CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Sequence

from pipelinedp_tpu.lint import engine
from pipelinedp_tpu.lint.config import DEFAULT_CONFIG

DEFAULT_BASELINE = "dplint-baseline.json"


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pipelinedp-tpu-lint",
        description="AST-based privacy & JAX-correctness linter for "
                    "pipelinedp_tpu (rules DPL001-DPL006).")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to scan (default: "
                             "pipelinedp_tpu/ under the current directory)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline JSON path (default: "
                             f"./{DEFAULT_BASELINE} when it exists)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="snapshot current findings into the baseline "
                             "file and exit 0")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(e.g. DPL001,DPL003)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text", dest="fmt")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings (informational)")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="print fix hints with each finding")
    return parser


def _select_rules(spec: Optional[str]) -> List[engine.Rule]:
    rules = engine.default_rules()
    if spec is None:
        return rules
    wanted = {s.strip().upper() for s in spec.split(",") if s.strip()}
    by_id = {r.rule_id: r for r in rules}
    unknown = wanted - set(by_id)
    if unknown:
        raise SystemExit(
            f"pipelinedp-tpu-lint: unknown rule id(s): "
            f"{', '.join(sorted(unknown))} (known: "
            f"{', '.join(sorted(by_id))})")
    return [by_id[rid] for rid in sorted(wanted)]


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = _build_parser()
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in engine.default_rules():
            print(f"{rule.rule_id}  {rule.name}: {rule.description}")
        return 0

    paths = args.paths or ["pipelinedp_tpu"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"pipelinedp-tpu-lint: path not found: {', '.join(missing)}",
              file=sys.stderr)
        return 2

    try:
        rules = _select_rules(args.rules)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2

    result = engine.lint_paths(paths, config=DEFAULT_CONFIG, rules=rules)
    findings = result.all_reportable

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)

    if args.write_baseline:
        target = args.baseline or DEFAULT_BASELINE
        engine.write_baseline(target, findings, result.lines_by_path)
        print(f"pipelinedp-tpu-lint: wrote {len(findings)} finding(s) to "
              f"{target}")
        return 0

    if baseline_path and not args.no_baseline:
        try:
            baseline = engine.load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"pipelinedp-tpu-lint: cannot load baseline "
                  f"{baseline_path}: {e}", file=sys.stderr)
            return 2
        findings = engine.filter_baselined(findings, result.lines_by_path,
                                           baseline)

    if args.fmt == "json":
        payload = [{
            "rule": f.rule_id, "path": f.path, "line": f.line,
            "col": f.col, "message": f.message, "hint": f.hint,
        } for f in findings]
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.format(verbose=args.verbose))
        if args.show_suppressed:
            for f in result.suppressed:
                print(f"[suppressed] {f.format()}")
        summary = (f"pipelinedp-tpu-lint: {len(findings)} new finding(s), "
                   f"{len(result.suppressed)} suppressed")
        print(summary, file=sys.stderr)

    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
