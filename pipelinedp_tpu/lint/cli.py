"""dplint command line: `python -m pipelinedp_tpu.lint [paths...]`.

Exit codes: 0 = clean (or every finding baselined/suppressed), 1 = new
findings, 2 = usage or internal error. The default baseline file,
``dplint-baseline.json`` in the current directory, is loaded when present;
``--write-baseline`` snapshots the current findings so existing debt can
be ratcheted down without blocking CI.

Pre-commit latency: ``--changed-only`` reports findings for just the
files git says changed (worktree + index, against ``--diff-base`` when
given) plus any module connected to them in the call graph — the whole
tree is still parsed into dpflow summaries (cache-warm, so still
seconds) because the project rules are only sound over the full graph.
``--dump-lock-graph`` prints the dpverify canonical lock inventory and
acquired-while-held edges instead of linting. CI integration:
``--format=sarif`` emits SARIF
2.1.0 for inline annotations, ``--forbid-suppressions`` turns every
suppressed finding into a reported one (the dpflow-strict gates), and
the dpflow summary cache is controlled by ``--flow-cache`` /
``--no-flow-cache`` (default ``./.dpflow-cache.json``).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time
from typing import List, Optional, Sequence

from pipelinedp_tpu.lint import engine
from pipelinedp_tpu.lint.config import DEFAULT_CONFIG

DEFAULT_BASELINE = "dplint-baseline.json"
DEFAULT_FLOW_CACHE = ".dpflow-cache.json"

SARIF_SCHEMA_URI = ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json")


def _build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="pipelinedp-tpu-lint",
        description="AST + dataflow privacy, durability & JAX-"
                    "correctness linter for pipelinedp_tpu "
                    "(rules DPL001-DPL015).")
    parser.add_argument("paths", nargs="*",
                        help="files or directories to scan (default: "
                             "pipelinedp_tpu/ under the current directory)")
    parser.add_argument("--baseline", default=None,
                        help=f"baseline JSON path (default: "
                             f"./{DEFAULT_BASELINE} when it exists)")
    parser.add_argument("--write-baseline", action="store_true",
                        help="snapshot current findings into the baseline "
                             "file and exit 0")
    parser.add_argument("--no-baseline", action="store_true",
                        help="ignore any baseline file")
    parser.add_argument("--rules", default=None,
                        help="comma-separated rule ids to run "
                             "(e.g. DPL001,DPL003)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    parser.add_argument("--format", choices=("text", "json", "sarif"),
                        default="text", dest="fmt")
    parser.add_argument("--changed-only", action="store_true",
                        help="report findings only for files git says "
                             "changed (worktree + index) and modules "
                             "connected to them in the call graph; the "
                             "full tree is still summarized")
    parser.add_argument("--dump-lock-graph", action="store_true",
                        help="print the dpverify canonical lock "
                             "inventory and acquired-while-held edges, "
                             "then exit (0 = acyclic)")
    parser.add_argument("--diff-base", default=None,
                        help="with --changed-only: diff against this git "
                             "rev (default: the working tree vs HEAD)")
    parser.add_argument("--flow-cache", default=DEFAULT_FLOW_CACHE,
                        help="dpflow per-file summary cache path "
                             f"(default: ./{DEFAULT_FLOW_CACHE})")
    parser.add_argument("--no-flow-cache", action="store_true",
                        help="disable the dpflow summary cache")
    parser.add_argument("--forbid-suppressions", action="store_true",
                        help="report suppressed findings as findings "
                             "(the strict gates for ops/finalize.py and "
                             "runtime/)")
    parser.add_argument("--show-suppressed", action="store_true",
                        help="also print suppressed findings (informational)")
    parser.add_argument("-v", "--verbose", action="store_true",
                        help="print fix hints with each finding")
    return parser


def _select_rules(spec: Optional[str]) -> List[engine.Rule]:
    rules = engine.default_rules()
    if spec is None:
        return rules
    wanted = {s.strip().upper() for s in spec.split(",") if s.strip()}
    by_id = {r.rule_id: r for r in rules}
    unknown = wanted - set(by_id)
    if unknown:
        raise SystemExit(
            f"pipelinedp-tpu-lint: unknown rule id(s): "
            f"{', '.join(sorted(unknown))} (known: "
            f"{', '.join(sorted(by_id))})")
    return [by_id[rid] for rid in sorted(wanted)]


def _changed_files(paths: Sequence[str],
                   diff_base: Optional[str]) -> Optional[List[str]]:
    """Changed .py files under ``paths`` per git, or None on git failure.

    Worktree + index changes relative to HEAD by default; with
    ``diff_base``, everything that differs from that rev (the pre-commit
    / PR-gate shapes respectively). Untracked .py files count as changed.
    """
    cmds = [["git", "diff", "--name-only", "-z", diff_base or "HEAD"],
            ["git", "ls-files", "--others", "--exclude-standard", "-z"]]
    changed: List[str] = []
    for cmd in cmds:
        try:
            out = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=30, check=True).stdout
        except (OSError, subprocess.SubprocessError):
            return None
        changed.extend(p for p in out.split("\0") if p.endswith(".py"))
    roots = [os.path.normpath(os.path.abspath(p)) for p in paths]
    selected = []
    for rel in sorted(set(changed)):
        abspath = os.path.normpath(os.path.abspath(rel))
        if not os.path.exists(abspath):
            continue  # deleted files have nothing to lint
        for root in roots:
            if abspath == root or abspath.startswith(root + os.sep):
                selected.append(rel)
                break
    return selected


def _dump_lock_graph(flow) -> int:
    """Prints the canonical lock inventory and acquired-while-held
    edges; exit 1 when the graph has a cycle."""
    if flow is None:
        print("pipelinedp-tpu-lint: no project flow was built (no "
              "project rules selected?)", file=sys.stderr)
        return 2
    sites = flow.lock_sites()
    print(f"{len(sites)} canonical lock(s):")
    for name, acquires in sorted(sites.items()):
        print(f"  {name}  [{len(acquires)} acquire site(s)]")
        for qual, line in sorted(acquires):
            print(f"      {qual}:{line}")
    graph = flow.lock_graph()
    edges = [(outer, inner, site) for outer, inners in graph.items()
             for inner, site in inners.items()]
    print(f"{len(edges)} acquired-while-held edge(s):")
    for outer, inner, (qual, line) in sorted(edges):
        print(f"  {outer} -> {inner}   via {qual}:{line}")
    cycles = flow.lock_cycles()
    for cycle in cycles:
        print(f"CYCLE: {' -> '.join([*cycle, cycle[0]])}")
    print(f"{len(cycles)} cycle(s)")
    return 1 if cycles else 0


def _sarif_payload(findings, rules) -> dict:
    """SARIF 2.1.0 document for CI inline annotations."""
    rule_ids = sorted({f.rule_id for f in findings} |
                      {r.rule_id for r in rules})
    by_id = {r.rule_id: r for r in rules}
    sarif_rules = []
    for rid in rule_ids:
        rule = by_id.get(rid)
        desc = (rule.description if rule is not None
                else "dplint engine diagnostic")
        name = rule.name if rule is not None else "engine"
        entry = {
            "id": rid,
            "name": name,
            "shortDescription": {"text": desc},
        }
        if rule is not None and rule.hint:
            entry["help"] = {"text": rule.hint}
        sarif_rules.append(entry)
    rule_index = {e["id"]: i for i, e in enumerate(sarif_rules)}
    results = [{
        "ruleId": f.rule_id,
        "ruleIndex": rule_index[f.rule_id],
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.path,
                                     "uriBaseId": "SRCROOT"},
                "region": {"startLine": max(f.line, 1),
                           "startColumn": max(f.col, 1)},
            },
        }],
    } for f in findings]
    return {
        "$schema": SARIF_SCHEMA_URI,
        "version": "2.1.0",
        "runs": [{
            "tool": {
                "driver": {
                    "name": "pipelinedp-tpu-lint",
                    "informationUri":
                        "https://github.com/OpenMined/PipelineDP",
                    "rules": sarif_rules,
                },
            },
            "originalUriBaseIds": {"SRCROOT": {"uri": "file:///"}},
            "results": results,
        }],
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = _build_parser()
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in engine.default_rules():
            print(f"{rule.rule_id}  {rule.name}: {rule.description}")
        return 0

    paths = args.paths or ["pipelinedp_tpu"]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing:
        print(f"pipelinedp-tpu-lint: path not found: {', '.join(missing)}",
              file=sys.stderr)
        return 2

    try:
        rules = _select_rules(args.rules)
    except SystemExit as e:
        print(e, file=sys.stderr)
        return 2

    focus = None
    if args.changed_only:
        changed = _changed_files(paths, args.diff_base)
        if changed is None:
            print("pipelinedp-tpu-lint: --changed-only requires a git "
                  "checkout (git diff failed)", file=sys.stderr)
            return 2
        if not changed:
            print("pipelinedp-tpu-lint: no changed files under "
                  f"{', '.join(paths)}", file=sys.stderr)
            return 0
        # Keep the full roots: the project rules are only sound over
        # the complete call graph (a hazard introduced in a changed
        # callee surfaces in its unchanged caller). The changed set
        # narrows what gets reported, not what gets analyzed.
        focus = changed

    flow_cache = None if args.no_flow_cache else args.flow_cache
    t0 = time.perf_counter()
    result = engine.lint_paths(paths, config=DEFAULT_CONFIG, rules=rules,
                               flow_cache_path=flow_cache, focus=focus)

    if args.dump_lock_graph:
        return _dump_lock_graph(result.flow)
    elapsed = time.perf_counter() - t0
    findings = result.all_reportable
    if args.forbid_suppressions and result.suppressed:
        findings = sorted(
            findings + result.suppressed,
            key=lambda f: (f.path, f.line, f.col, f.rule_id))

    baseline_path = args.baseline or (
        DEFAULT_BASELINE if os.path.exists(DEFAULT_BASELINE) else None)

    if args.write_baseline:
        target = args.baseline or DEFAULT_BASELINE
        engine.write_baseline(target, findings, result.lines_by_path)
        print(f"pipelinedp-tpu-lint: wrote {len(findings)} finding(s) to "
              f"{target}")
        return 0

    if baseline_path and not args.no_baseline:
        try:
            baseline = engine.load_baseline(baseline_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            print(f"pipelinedp-tpu-lint: cannot load baseline "
                  f"{baseline_path}: {e}", file=sys.stderr)
            return 2
        findings = engine.filter_baselined(findings, result.lines_by_path,
                                           baseline)

    if args.fmt == "json":
        payload = [{
            "rule": f.rule_id, "path": f.path, "line": f.line,
            "col": f.col, "message": f.message, "hint": f.hint,
        } for f in findings]
        print(json.dumps(payload, indent=2))
    elif args.fmt == "sarif":
        print(json.dumps(_sarif_payload(findings, rules), indent=2))
    else:
        for f in findings:
            print(f.format(verbose=args.verbose))
        if args.show_suppressed:
            for f in result.suppressed:
                print(f"[suppressed] {f.format()}")
        summary = (f"pipelinedp-tpu-lint: {len(findings)} new finding(s), "
                   f"{len(result.suppressed)} suppressed "
                   f"[{elapsed:.2f}s, flow cache "
                   f"{result.flow_cache_hits} hit(s) / "
                   f"{result.flow_cache_misses} miss(es)]")
        print(summary, file=sys.stderr)

    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
