"""Shared AST helpers for dplint rules: import-alias resolution.

Every rule needs to answer "what fully-qualified callable does this
expression refer to?" — `jnp.float64`, `np.random.choice`,
`random.laplace` (which is `jax.random.laplace` under
``from jax import random``) all look different syntactically. The alias
map built from the module's import statements lets rules match on
canonical dotted names (``jax.random.laplace``, ``numpy.random.choice``)
regardless of local import style.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional


def build_aliases(tree: ast.AST) -> Dict[str, str]:
    """Maps local names to the fully-qualified names they were imported as.

    ``import numpy as np``            -> {"np": "numpy"}
    ``import jax.numpy as jnp``       -> {"jnp": "jax.numpy"}
    ``import jax``                    -> {"jax": "jax"}
    ``from jax import random``        -> {"random": "jax.random"}
    ``from functools import partial`` -> {"partial": "functools.partial"}
    """
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.asname:
                    aliases[alias.asname] = alias.name
                else:
                    # `import a.b.c` binds `a` to the root package.
                    root = alias.name.split(".")[0]
                    aliases[root] = root
        elif isinstance(node, ast.ImportFrom):
            if node.module is None or node.level:  # relative imports: the
                continue  # caller's package is unknown; leave unresolved
            for alias in node.names:
                local = alias.asname or alias.name
                aliases[local] = f"{node.module}.{alias.name}"
    return aliases


def dotted_name(node: ast.AST) -> Optional[str]:
    """The source dotted path of a Name/Attribute chain, else None."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    parts.append(node.id)
    return ".".join(reversed(parts))


def resolve(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical fully-qualified dotted name of an expression, else None.

    Resolves the leading component through the module's import aliases, so
    ``jnp.float64`` -> ``jax.numpy.float64`` and a bare ``partial`` ->
    ``functools.partial``. Unimported leading names resolve to themselves
    (a local variable shadowing an import is indistinguishable without
    type inference; dplint accepts that imprecision).
    """
    dotted = dotted_name(node)
    if dotted is None:
        return None
    head, _, rest = dotted.partition(".")
    full_head = aliases.get(head, head)
    return f"{full_head}.{rest}" if rest else full_head


def call_target(node: ast.Call, aliases: Dict[str, str]) -> Optional[str]:
    return resolve(node.func, aliases)


def literal_number(node: ast.AST) -> Optional[float]:
    """The value of a numeric literal, including a leading unary minus."""
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        inner = literal_number(node.operand)
        return None if inner is None else -inner
    if isinstance(node, ast.Constant) and isinstance(node.value, (int, float)) \
            and not isinstance(node.value, bool):
        return float(node.value)
    return None


def annotation_nodes(tree: ast.AST) -> set:
    """ids of every AST node that lives inside a type annotation.

    Rules that flag attribute references (e.g. ``np.random.Generator``)
    must not fire on annotations — ``Optional[np.random.Generator]`` is
    type information, not an RNG use.
    """
    skip: set = set()

    def mark(node):
        if node is None:
            return
        for sub in ast.walk(node):
            skip.add(id(sub))

    for node in ast.walk(tree):
        if isinstance(node, ast.AnnAssign):
            mark(node.annotation)
        elif isinstance(node, ast.arg):
            mark(node.annotation)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            mark(node.returns)
    return skip
