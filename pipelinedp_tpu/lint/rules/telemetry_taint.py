"""DPL011 — telemetry-taint: private data reaching an obs record.

Telemetry (pipelinedp_tpu/obs/: span attributes, metric observations
and labels, audit-record fields) is operator-visible and sits OUTSIDE
the DP mechanism — nothing written there is noise-protected or budget-
accounted. The hard rule (OBSERVABILITY.md "DP-safety stance") is that
raw privacy ids, partition keys, and unreleased (pre-noise) values
never enter any obs record; only operational aggregates and fully
released (bounded AND noised) statistics may.

dpflow tracks values originating in private-column parameters (``pid``
/ ``pk`` / ``value`` raw; ``accs`` / ``qhist`` accumulators, which are
bounded but still pre-noise) through assignments, transforms and
project call chains, and flags any path that reaches an ``obs.*`` API —
a resolved ``pipelinedp_tpu.obs.*`` call, or a structural
``.set_attribute()`` / ``.add_event()`` / ``.observe()`` / ``.record()``
method — while missing either sanitization flag. Note the asymmetry
with DPL007: contribution bounding alone is NOT enough here; a bounded
but un-noised per-partition aggregate in a span attribute is exactly
the leak this rule exists to catch.

The runtime twin of this rule is ``obs.metrics.check_safe_value`` (the
API refuses forbidden keys and non-scalar payloads at call time); the
serving test matrix scans every emitted record dynamically. DPL011 is
the shift-left layer: the flow never ships.
"""

from __future__ import annotations

from typing import Iterable, List

from pipelinedp_tpu.lint.engine import Finding, ProjectContext, ProjectRule
from pipelinedp_tpu.lint.flow.summary import FLAG_NOISE


class TelemetryTaintRule(ProjectRule):
    rule_id = "DPL011"
    name = "telemetry-taint"
    description = ("A private input column (or pre-noise accumulator) "
                   "flows into an obs.* span attribute, metric "
                   "observation, or audit-record field.")
    hint = ("Telemetry may carry operational aggregates and RELEASED "
            "statistics only. Record a count/timing derived from the "
            "DP output (post-noise, post-selection), or drop the field; "
            "never attach pids, partition keys, or pre-noise "
            "accumulator values to a span, metric, or audit record.")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        flow = project.flow
        trusted = project.config.is_telemetry_taint_trusted
        findings: List[Finding] = []
        for qual, tf in flow.root_exposures(trusted,
                                            sink_kinds=frozenset({"obs"})):
            module = flow.function_module[qual]
            func = qual[len(module) + 1:]
            if tf.kind == "obs":
                what = f"enters the obs record API `{tf.detail}`"
            else:
                callee = tf.detail.split(".")[-1]
                what = (f"is handed to `{callee}` which records it into "
                        f"telemetry")
            note = ("" if FLAG_NOISE in tf.gained else
                    " before any noise mechanism")
            findings.append(Finding(
                self.rule_id, project.relpath_of(module), tf.line, 1,
                f"private value `{tf.origin}` in `{func}` {what}{note} — "
                f"telemetry is outside the DP mechanism and must never "
                f"carry unreleased data",
                self.hint))
        return findings
