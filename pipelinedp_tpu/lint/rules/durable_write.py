"""DPL012 — non-atomic durable write: every byte that must survive a
crash goes through tmp+fsync+rename or the WAL append discipline.

The store/WAL/spool/capture trees are read back by crash recovery
(serving/store.py, runtime/journal.py, RESILIENCE.md), so a plain
``open(path, "w")`` is a torn-state generator: a crash mid-write leaves
a half-file that recovery then trusts. The two sanctioned idioms are

  * tmp+fsync+rename — ``tempfile.mkstemp`` (or a dot-tmp sibling),
    write, ``flush``+``os.fsync``, ``os.replace`` (store._atomic_write);
  * the ``JsonlWal`` append discipline — one long-lived append handle,
    every record write+flush+fsync'd, truncate-only recovery.

dpverify checks each function's effect trace: a ``raw_durable_write``
is only clean when the same function also carries ``fsync`` *and*
``rename`` (the atomic idiom), and an ``os.replace`` publish without an
``fsync`` is flagged too — the rename is atomic but the *payload* may
still be sitting in the page cache (the checkpoint-store bug class).
Modeled-exempt patterns live in ``LintConfig.atomic_write_exempt``
(WAL internals, the flush-only flight spool, the /healthz probe,
operator-artifact writers).
"""

from __future__ import annotations

from typing import Iterable, List

from pipelinedp_tpu.lint.engine import Finding, ProjectContext, ProjectRule
from pipelinedp_tpu.lint.flow.summary import (
    EFFECT_FSYNC,
    EFFECT_RAW_WRITE,
    EFFECT_RENAME,
)


class DurableWriteRule(ProjectRule):
    rule_id = "DPL012"
    name = "non-atomic-durable-write"
    description = ("A durable write bypasses the tmp+fsync+rename idiom "
                   "and the JsonlWal append discipline.")
    hint = ("Write through serving/store.py `_atomic_write` (mkstemp -> "
            "write -> flush+fsync -> os.replace) or a JsonlWal; if the "
            "file is genuinely loss-tolerant, add the function to "
            "LintConfig.atomic_write_exempt with the structural reason.")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        flow = project.flow
        config = project.config
        findings: List[Finding] = []
        for qual, fsum in flow.functions.items():
            if config.is_atomic_write_exempt(qual):
                continue
            kinds = {e.kind for e in fsum.effects}
            module = flow.function_module[qual]
            relpath = project.relpath_of(module)
            func = qual[len(module) + 1:]
            atomic = EFFECT_FSYNC in kinds and EFFECT_RENAME in kinds
            for eff in fsum.effects:
                if eff.kind == EFFECT_RAW_WRITE and not atomic:
                    findings.append(Finding(
                        self.rule_id, relpath, eff.line, 1,
                        f"raw `open(..., {eff.detail!r})` write in "
                        f"`{func}` without the tmp+fsync+rename idiom — "
                        f"a crash mid-write leaves a torn file for "
                        f"recovery to trust",
                        self.hint))
                elif eff.kind == EFFECT_RENAME and \
                        EFFECT_FSYNC not in kinds:
                    findings.append(Finding(
                        self.rule_id, relpath, eff.line, 1,
                        f"`{func}` publishes with os.replace/rename but "
                        f"never fsyncs the payload — the rename is "
                        f"atomic, the bytes behind it may not be on "
                        f"disk",
                        self.hint))
        return findings
