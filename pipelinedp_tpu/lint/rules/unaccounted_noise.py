"""DPL002 — noise drawn in a module that never touches a MechanismSpec.

Every DP noise draw must be calibrated by a spec issued by
``BudgetAccountant.request_budget()`` — that is the only place the
(eps, delta) ledger is debited. A module that calls the noise primitives
(``noise_core.add_*`` / ``noise_core.sample_*`` / ``jax.random.laplace`` /
``jax.random.normal``) but contains no trace of MechanismSpec handling is
releasing unaccounted noise: the draw happens, the ledger never moves.

The mechanism-primitive layer (noise_core itself, ops/noise, ops/selection,
ops/quantiles, partition_selection, quantile_tree) is exempt by config —
those modules *are* the sinks; their scales arrive pre-calibrated from
specs resolved upstream.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from pipelinedp_tpu.lint import astutils
from pipelinedp_tpu.lint.engine import Finding, ModuleContext, Rule

_NOISE_CALLS = frozenset({
    "jax.random.laplace",
    "jax.random.normal",
})
_NOISE_CORE_PREFIX = "pipelinedp_tpu.noise_core."
_NOISE_CORE_FUNCS = frozenset({
    "add_laplace_noise", "add_gaussian_noise",
    "add_laplace_noise_array", "add_gaussian_noise_array",
    "add_noise_array",
    "sample_laplace", "sample_gaussian",
})

# Any of these appearing in the module counts as "touches the accountant":
# the module either requests budget or parameterizes mechanisms from specs.
_SPEC_TOKENS = frozenset({
    "MechanismSpec", "MechanismSpecInternal", "request_budget",
    "mechanism_spec", "BudgetAccountant",
})


def _touches_mechanism_spec(ctx: ModuleContext) -> bool:
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Name) and node.id in _SPEC_TOKENS:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _SPEC_TOKENS:
            return True
        if isinstance(node, ast.ImportFrom) and node.module and \
                "budget_accounting" in node.module:
            return True
        if isinstance(node, ast.Import) and any(
                "budget_accounting" in a.name for a in node.names):
            return True
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            args = node.args
            for a in (list(args.posonlyargs) + list(args.args) +
                      list(args.kwonlyargs)):
                if a.arg in ("spec", "mechanism_spec"):
                    return True
    return False


class UnaccountedNoiseRule(Rule):
    rule_id = "DPL002"
    name = "unaccounted-noise"
    description = ("Noise is drawn in a module that never touches a "
                   "MechanismSpec issued by BudgetAccountant."
                   "request_budget().")
    hint = ("Request the budget first: `spec = budget_accountant."
            "request_budget(mechanism_type)` and calibrate the draw from "
            "the resolved spec (see dp_computations."
            "create_additive_mechanism).")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if ctx.config.is_unaccounted_noise_exempt(ctx.module):
            return []
        noise_sites: List[ast.Call] = []
        labels: List[str] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            target = astutils.call_target(node, ctx.aliases)
            if target is None:
                continue
            if target in _NOISE_CALLS:
                noise_sites.append(node)
                labels.append(target)
            elif (target.startswith(_NOISE_CORE_PREFIX) and
                  target[len(_NOISE_CORE_PREFIX):] in _NOISE_CORE_FUNCS):
                noise_sites.append(node)
                labels.append(target)
            elif target in _NOISE_CORE_FUNCS:
                # `from pipelinedp_tpu.noise_core import add_laplace_noise`
                # resolves through the alias map; a bare matching name that
                # did NOT resolve to noise_core is a local redefinition —
                # skip it.
                continue
        if not noise_sites or _touches_mechanism_spec(ctx):
            return []
        return [
            ctx.finding(
                self, node,
                f"`{label}` draws noise but module `{ctx.module}` never "
                f"handles a MechanismSpec — this draw is invisible to the "
                f"privacy-budget ledger")
            for node, label in zip(noise_sites, labels)
        ]
