"""DPL015 — release-path nondeterminism: releases must be a pure
function of (data, params, seed).

Bit-identical releases are a pinned contract (tests/determinism, the
PR 4 FMA-contraction fix): the same dataset, parameters and seed must
produce the same bytes on every host in the fleet. Three
nondeterminism classes defeat that silently:

  * iteration over unordered collections (``set`` literals,
    ``os.listdir``, set algebra) — Python sets hash-order by pointer,
    listdir is filesystem-order; anything derived from the walk order
    (vocab ids, key folds, output order) diverges across hosts;
  * wall-clock / uuid values feeding seeds, keys or tokens — the value
    differs per process by construction;
  * eager ``jax.numpy`` arithmetic outside the blessed compiled
    entries (``ops/noise``, ``ops/selection``, ``ops/finalize``) — the
    PR 4 bug class: op-by-op dispatch and XLA-fused compilation are
    allowed to differ in FMA contraction, so the same math eager vs
    compiled yields different low bits.

dpverify scopes the check to *release paths*: functions whose call
closure reaches a noise/selection draw or a release commit. The
blessed compiled entries and the documented eager parity oracle are
exempted in ``LintConfig.release_determinism_exempt``.
"""

from __future__ import annotations

from typing import Iterable, List

from pipelinedp_tpu.lint.engine import Finding, ProjectContext, ProjectRule
from pipelinedp_tpu.lint.flow.summary import (
    COMMIT_TARGET_RE,
    DRAW_TARGET_RE,
    EFFECT_EAGER_JNP,
    EFFECT_UNORDERED_ITER,
    EFFECT_WALLCLOCK,
)


class ReleaseDeterminismRule(ProjectRule):
    rule_id = "DPL015"
    name = "release-determinism"
    description = ("A nondeterminism source (unordered iteration, "
                   "wall-clock seed, eager jnp arithmetic) sits on a "
                   "release path.")
    hint = ("Releases are a pure function of (data, params, seed): "
            "sort before iterating, derive seeds/keys from the "
            "KeyStream, and keep jnp arithmetic inside the blessed "
            "compiled entries (ops/noise, ops/selection, ops/finalize) "
            "— see the PR 4 FMA-contraction note in DETERMINISM.md.")

    _MESSAGES = {
        EFFECT_UNORDERED_ITER: (
            "iterates {detail} on a release path — hash/filesystem "
            "order diverges across hosts, so the release bytes do too"),
        EFFECT_WALLCLOCK: (
            "{detail}: a wall-clock/uuid value feeds a seed-like "
            "binding on a release path — the release stops being a "
            "function of (data, params, seed)"),
        EFFECT_EAGER_JNP: (
            "eager `{detail}` on a release path outside the blessed "
            "compiled entries — eager dispatch and XLA fusion may "
            "differ in FMA contraction (the PR 4 bug class)"),
    }

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        flow = project.flow
        config = project.config
        release = flow.reaching(DRAW_TARGET_RE.pattern) | \
            flow.reaching(COMMIT_TARGET_RE.pattern)
        closure = flow.effect_kind_closure()
        findings: List[Finding] = []
        for qual, fsum in flow.functions.items():
            if config.is_release_determinism_exempt(qual):
                continue
            if qual not in release and not (
                    closure.get(qual, frozenset()) &
                    frozenset({"noise_draw", "release_commit"})):
                continue
            module = flow.function_module[qual]
            relpath = project.relpath_of(module)
            func = qual[len(module) + 1:]
            for eff in fsum.effects:
                template = self._MESSAGES.get(eff.kind)
                if template is None:
                    continue
                findings.append(Finding(
                    self.rule_id, relpath, eff.line, 1,
                    f"`{func}` " + template.format(detail=eff.detail),
                    self.hint))
        return findings
