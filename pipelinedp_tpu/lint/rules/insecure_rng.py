"""DPL004 — insecure RNG in privacy-critical code.

`np.random.*` and the stdlib `random` module are Mersenne-Twister/PCG
generators: fast, seedable, and *predictable*. A DP release whose noise an
attacker can reconstruct provides no privacy at all (the reference
implementation delegates to a kernel-CSPRNG C++ sampler for exactly this
reason — see noise_core's security note and native/secure_noise.cc).

Every scanned module is privacy-critical by default; the narrow exemptions
(the declared numpy fallback in noise_core, the utility-analysis layer)
live in LintConfig.insecure_rng_exempt. Type annotations like
``Optional[np.random.Generator]`` are not flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from pipelinedp_tpu.lint import astutils
from pipelinedp_tpu.lint.engine import Finding, ModuleContext, Rule

_NUMPY_RANDOM_PREFIX = "numpy.random."
_STDLIB_RANDOM = "random"


class InsecureRngRule(Rule):
    rule_id = "DPL004"
    name = "insecure-rng"
    description = ("numpy/stdlib RNG (predictable, seedable) used in a "
                   "privacy-critical module.")
    hint = ("Draw from the secure sampler instead: noise_core."
            "sample_uniform / sample_laplace / sample_gaussian (kernel "
            "CSPRNG when the native library is available), or `secrets` "
            "for seed material.")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if ctx.config.is_insecure_rng_exempt(ctx.module):
            return []
        annotations = astutils.annotation_nodes(ctx.tree)
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or id(node) in annotations:
                continue
            target = astutils.call_target(node, ctx.aliases)
            if target is None:
                continue
            if target.startswith(_NUMPY_RANDOM_PREFIX):
                findings.append(ctx.finding(
                    self, node,
                    f"`{target}` is a predictable (non-cryptographic) RNG "
                    f"in privacy-critical module `{ctx.module}`"))
            elif target.startswith(_STDLIB_RANDOM + ".") and \
                    self._stdlib_random_imported(ctx):
                findings.append(ctx.finding(
                    self, node,
                    f"stdlib `{target}` (Mersenne Twister) in "
                    f"privacy-critical module `{ctx.module}`"))
        return findings

    @staticmethod
    def _stdlib_random_imported(ctx: ModuleContext) -> bool:
        # `random` must actually be the stdlib module: `from jax import
        # random` resolves to jax.random in the alias map and never
        # reaches here; a bare local named `random` would, so require an
        # explicit toplevel `import random`.
        return ctx.aliases.get("random") == "random" and any(
            isinstance(n, ast.Import) and
            any(a.name == "random" for a in n.names)
            for n in ast.walk(ctx.tree))
