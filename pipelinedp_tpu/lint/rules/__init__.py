"""dplint rule registry: one module per rule, registered in ID order."""

from pipelinedp_tpu.lint.rules.key_reuse import KeyReuseRule
from pipelinedp_tpu.lint.rules.unaccounted_noise import UnaccountedNoiseRule
from pipelinedp_tpu.lint.rules.jit_hostility import JitHostilityRule
from pipelinedp_tpu.lint.rules.insecure_rng import InsecureRngRule
from pipelinedp_tpu.lint.rules.budget_literals import BudgetLiteralRule
from pipelinedp_tpu.lint.rules.float64_guard import Float64GuardRule
from pipelinedp_tpu.lint.rules.release_taint import ReleaseTaintRule
from pipelinedp_tpu.lint.rules.thread_escape import ThreadEscapeRule
from pipelinedp_tpu.lint.rules.commit_before_draw import (
    CommitBeforeDrawRule,
)
from pipelinedp_tpu.lint.rules.donated_reuse import DonatedReuseRule
from pipelinedp_tpu.lint.rules.telemetry_taint import TelemetryTaintRule
from pipelinedp_tpu.lint.rules.durable_write import DurableWriteRule
from pipelinedp_tpu.lint.rules.commit_ordering import CommitOrderingRule
from pipelinedp_tpu.lint.rules.lock_order import LockOrderRule
from pipelinedp_tpu.lint.rules.release_determinism import (
    ReleaseDeterminismRule,
)

ALL_RULES = (
    KeyReuseRule,
    UnaccountedNoiseRule,
    JitHostilityRule,
    InsecureRngRule,
    BudgetLiteralRule,
    Float64GuardRule,
    ReleaseTaintRule,
    ThreadEscapeRule,
    CommitBeforeDrawRule,
    DonatedReuseRule,
    TelemetryTaintRule,
    DurableWriteRule,
    CommitOrderingRule,
    LockOrderRule,
    ReleaseDeterminismRule,
)

__all__ = [cls.__name__ for cls in ALL_RULES] + ["ALL_RULES"]
