"""dplint rule registry: one module per rule, registered in ID order."""

from pipelinedp_tpu.lint.rules.key_reuse import KeyReuseRule
from pipelinedp_tpu.lint.rules.unaccounted_noise import UnaccountedNoiseRule
from pipelinedp_tpu.lint.rules.jit_hostility import JitHostilityRule
from pipelinedp_tpu.lint.rules.insecure_rng import InsecureRngRule
from pipelinedp_tpu.lint.rules.budget_literals import BudgetLiteralRule
from pipelinedp_tpu.lint.rules.float64_guard import Float64GuardRule
from pipelinedp_tpu.lint.rules.release_taint import ReleaseTaintRule
from pipelinedp_tpu.lint.rules.thread_escape import ThreadEscapeRule
from pipelinedp_tpu.lint.rules.commit_before_draw import (
    CommitBeforeDrawRule,
)
from pipelinedp_tpu.lint.rules.donated_reuse import DonatedReuseRule
from pipelinedp_tpu.lint.rules.telemetry_taint import TelemetryTaintRule

ALL_RULES = (
    KeyReuseRule,
    UnaccountedNoiseRule,
    JitHostilityRule,
    InsecureRngRule,
    BudgetLiteralRule,
    Float64GuardRule,
    ReleaseTaintRule,
    ThreadEscapeRule,
    CommitBeforeDrawRule,
    DonatedReuseRule,
    TelemetryTaintRule,
)

__all__ = [cls.__name__ for cls in ALL_RULES] + ["ALL_RULES"]
