"""DPL006 — jnp.float64 without an x64 guard.

JAX defaults to 32-bit: ``jnp.asarray(x, dtype=jnp.float64)`` silently
produces a float32 array unless ``jax_enable_x64`` is set. For this
codebase that silence is dangerous twice over — the Mironov granularity
snapping assumes float64's 52-bit mantissa (noise_core), and secure host
finalization is float64 end-to-end. A silent downcast re-opens the
least-significant-bit channel the snapping exists to close.

A module that demonstrably guards (references ``jax_enable_x64`` /
``x64_enabled``) may use jnp.float64 freely; host-side ``np.float64`` is
always fine and never flagged.
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from pipelinedp_tpu.lint import astutils
from pipelinedp_tpu.lint.engine import Finding, ModuleContext, Rule

_X64_GUARD_TOKENS = ("jax_enable_x64", "enable_x64", "x64_enabled")
_JNP_F64 = "jax.numpy.float64"


class Float64GuardRule(Rule):
    rule_id = "DPL006"
    name = "unguarded-float64"
    description = ("jnp.float64 used without an x64-mode guard — JAX "
                   "silently downcasts to float32 unless jax_enable_x64 "
                   "is set.")
    hint = ("Either verify the mode (`assert jax.config.x64_enabled` / "
            "`jax.config.update('jax_enable_x64', True)`) or keep float64 "
            "math on host with np.float64 (the secure_host_noise path).")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        if ctx.source_contains(*_X64_GUARD_TOKENS):
            return []
        findings: List[Finding] = []
        flagged = set()
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Attribute) and \
                    astutils.resolve(node, ctx.aliases) == _JNP_F64:
                flagged.add(id(node))
                findings.append(ctx.finding(
                    self, node,
                    "`jnp.float64` without an x64 guard: silently float32 "
                    "unless jax_enable_x64 is set"))
            elif isinstance(node, ast.Call):
                target = astutils.call_target(node, ctx.aliases)
                if target is None or not target.startswith("jax."):
                    continue
                for kw in node.keywords:
                    if kw.arg != "dtype":
                        continue
                    if isinstance(kw.value, ast.Constant) and \
                            kw.value.value == "float64":
                        findings.append(ctx.finding(
                            self, kw.value,
                            f"dtype='float64' passed to `{target}` "
                            f"without an x64 guard: silently float32 "
                            f"unless jax_enable_x64 is set"))
        return findings
