"""DPL008 — thread-escape: unlocked shared-state writes in pool workers.

The prefetch/encode pools (ops/streaming.py, parallel/sharded.py) and the
profiler's sink machinery share objects between the pipeline thread and
worker threads. The audited handoffs are (a) the profiler lock
(``_add_stage_time`` under ``_sink_lock``) and (b) the adopt/merge
protocol (``profiler.adopt_sinks(parent_sinks)`` installing a parent's
collectors before any recording). A worker callable that *writes* an
attribute or container element of a captured object the enclosing scope
also touches — outside any lock and outside the adopt handoff — is a data
race: torn stage timings at best, a corrupted slab index feeding the DP
kernel at worst.

Detection is per scope (flow/summary.py): callables handed to
``executor.submit`` / ``executor.map`` / ``threading.Thread(target=...)``
are workers; their free variables are the captured state; writes
(attribute/element assignment, mutator methods, ``nonlocal`` rebinds) to
names the enclosing scope also references must sit inside a ``with``
block on a lock-ish object or inside ``adopt_sinks``.
"""

from __future__ import annotations

from typing import Iterable, List

from pipelinedp_tpu.lint.engine import Finding, ProjectContext, ProjectRule


class ThreadEscapeRule(ProjectRule):
    rule_id = "DPL008"
    name = "thread-escape"
    description = ("A pool-worker callable writes state shared with the "
                   "enclosing scope without a lock or the adopt_sinks "
                   "handoff.")
    hint = ("Guard the write with `with <lock>:`, route timings through "
            "profiler.adopt_sinks/_add_stage_time, or hand the worker an "
            "immutable snapshot and merge results on the pipeline "
            "thread.")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        flow = project.flow
        findings: List[Finding] = []
        for qual, fsum in flow.functions.items():
            if not fsum.hazards:
                continue
            module = flow.function_module[qual]
            relpath = project.relpath_of(module)
            for hz in fsum.hazards:
                findings.append(Finding(
                    self.rule_id, relpath, hz.line, hz.col,
                    f"pool worker `{hz.worker}` performs an unguarded "
                    f"{hz.write} on captured `{hz.name}`, which the "
                    f"enclosing scope also touches (line "
                    f"{hz.shared_line}) — cross-thread write without the "
                    f"lock or an adopt/merge handoff",
                    self.hint))
        return findings
