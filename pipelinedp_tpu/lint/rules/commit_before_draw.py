"""DPL009 — commit-before-draw: release randomness before the journal.

The at-most-once release contract (runtime/journal.py, RESILIENCE.md)
only holds if the ``ReleaseJournal`` commit happens strictly *before*
any release randomness is drawn: a crash between commit and publication
then errs on the side of zero releases, never two correlated ones. A
noise / selection draw that is reachable before the commit inverts the
failure mode — a retried run can publish a second view of the data under
one accounted budget before the journal ever refuses.

For every function that commits (``*.commit`` / ``_commit_release``),
dpflow checks that no call executing before the first commit can
transitively reach a release-randomness draw (``noise_core.add_* /
sample_*``, ``ops.noise``, ``select_partitions`` / ``select_vec`` —
deliberately NOT the contribution-bounding samplers, whose pre-release
randomness legitimately precedes the commit; key *derivation* via
``KeyStream.derive`` / ``fold_in`` is pure and also exempt).
"""

from __future__ import annotations

import re
from typing import Iterable, List

from pipelinedp_tpu.lint.engine import Finding, ProjectContext, ProjectRule
from pipelinedp_tpu.lint.flow.summary import (
    COMMIT_TARGET_RE,
    DRAW_TARGET_RE,
)


class CommitBeforeDrawRule(ProjectRule):
    rule_id = "DPL009"
    name = "commit-before-draw"
    description = ("A release-randomness draw is reachable before the "
                   "ReleaseJournal commit in a release-producing entry "
                   "point.")
    hint = ("Commit the release token first — "
            "`self._commit_release(key_counter)` before any call chain "
            "that can reach a noise or selection draw; see "
            "runtime/journal.py for why the ordering is the whole "
            "at-most-once guarantee.")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        flow = project.flow
        drawers = flow.reaching(DRAW_TARGET_RE.pattern)
        draw_rx = re.compile(DRAW_TARGET_RE.pattern)
        findings: List[Finding] = []
        for qual, fsum in flow.functions.items():
            commit_lines = [c.line for c in fsum.calls
                            if COMMIT_TARGET_RE.search(c.target)]
            if not commit_lines:
                continue
            first_commit = min(commit_lines)
            module = flow.function_module[qual]
            relpath = project.relpath_of(module)
            func = qual[len(module) + 1:]
            seen = set()
            for call in fsum.calls:
                if call.line >= first_commit:
                    continue
                resolved = flow.resolve(call.target, module)
                direct = bool(draw_rx.search(call.target))
                if not direct and resolved not in drawers:
                    continue
                if call.line in seen:
                    continue
                seen.add(call.line)
                leaf = call.target.split(".")[-1]
                how = ("draws release randomness"
                       if direct else "can reach a release-randomness "
                                      "draw")
                findings.append(Finding(
                    self.rule_id, relpath, call.line, 1,
                    f"`{leaf}` {how} before the release-journal commit "
                    f"at line {first_commit} of `{func}` — a retried run "
                    f"could re-draw already-released noise",
                    self.hint))
        return findings
