"""DPL005 — epsilon/delta literal misuse and hand-rolled budget splits.

Two hazards:

1. **Invalid literals**: ``eps=-1`` or ``delta=1.5`` passed to a
   mechanism. Negative epsilon is meaningless; delta >= 1 voids the
   guarantee entirely (every outcome is "allowed to fail"). These are
   caught at runtime by input validators *if* the call path has one — the
   lint catches them everywhere, including test/fixture code that never
   executes the validator.

2. **Manual budget splitting**: ``eps / 2`` scattered through pipeline
   code. The BudgetAccountant owns the composition ledger — splitting by
   raw literals bypasses weight normalization (BudgetAccountantScope) and
   silently diverges from the accounted total when an aggregation is
   added or removed. Sanctioned splitters (budget_accounting,
   dp_computations.equally_split_budget) are exempt by config.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from pipelinedp_tpu.lint import astutils
from pipelinedp_tpu.lint.engine import Finding, ModuleContext, Rule

_EPS_KWARGS = frozenset({
    "eps", "epsilon", "total_epsilon", "calculation_eps",
    "eps_per_coordinate",
})
_DELTA_KWARGS = frozenset({
    "delta", "total_delta", "delta_per_coordinate",
})
_BUDGET_NAME_RE = re.compile(r"(?:^|_)(?:eps|epsilon|delta)(?:$|_)")


def _budget_name(node: ast.expr) -> str:
    """The eps/delta-ish variable a BinOp operand refers to, or ''."""
    if isinstance(node, ast.Name) and _BUDGET_NAME_RE.search(node.id):
        return node.id
    if isinstance(node, ast.Attribute) and _BUDGET_NAME_RE.search(node.attr):
        return astutils.dotted_name(node) or node.attr
    return ""


class BudgetLiteralRule(Rule):
    rule_id = "DPL005"
    name = "budget-literal-misuse"
    description = ("Invalid epsilon/delta literals (eps <= 0, delta >= 1) "
                   "or privacy budget split by raw literals instead of "
                   "the BudgetAccountant.")
    hint = ("Valid ranges: eps > 0, 0 <= delta < 1. For splits, use "
            "BudgetAccountant weights (request_budget(weight=...)) or "
            "dp_computations.equally_split_budget.")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call):
                self._check_call_literals(node, ctx, findings)
            elif isinstance(node, ast.BinOp) and \
                    not ctx.config.is_budget_literal_exempt(ctx.module):
                self._check_split(node, ctx, findings)
        return findings

    def _check_call_literals(self, call: ast.Call, ctx: ModuleContext,
                             findings: List[Finding]) -> None:
        for kw in call.keywords:
            if kw.arg is None:
                continue
            value = astutils.literal_number(kw.value)
            if value is None:
                continue
            if kw.arg in _EPS_KWARGS and value <= 0:
                findings.append(ctx.finding(
                    self, kw.value,
                    f"epsilon literal {value:g} passed as `{kw.arg}=` — "
                    f"epsilon must be strictly positive"))
            elif kw.arg in _DELTA_KWARGS and (value >= 1 or value < 0):
                findings.append(ctx.finding(
                    self, kw.value,
                    f"delta literal {value:g} passed as `{kw.arg}=` — "
                    f"delta must be in [0, 1); delta >= 1 voids the DP "
                    f"guarantee"))

    def _check_split(self, node: ast.BinOp, ctx: ModuleContext,
                     findings: List[Finding]) -> None:
        if not isinstance(node.op, (ast.Div, ast.Mult)):
            return
        # `eps / 2` or `0.5 * delta`: a budget variable *shrunk* by a bare
        # numeric literal — a hand-rolled share. Growth (`2 * delta_p` in
        # CDF-inversion threshold math) is not a split and is left alone.
        pairs = [(node.left, node.right)]
        if isinstance(node.op, ast.Mult):
            pairs.append((node.right, node.left))
        for var_side, lit_side in pairs:
            name = _budget_name(var_side)
            literal = astutils.literal_number(lit_side)
            if literal is None or not name:
                continue
            is_split = (literal > 1 if isinstance(node.op, ast.Div)
                        else 0 < literal < 1)
            if is_split:
                findings.append(ctx.finding(
                    self, node,
                    f"privacy budget `{name}` split by raw literal "
                    f"{literal:g} — budget shares belong to the "
                    f"BudgetAccountant, not inline arithmetic"))
                return
