"""DPL007 — release-path taint: private data reaching the host unnoised.

The DP contract is that nothing derived from private input columns leaves
the device/accumulator world until it has been contribution-**bounded**
AND had a calibrated **noise** mechanism applied. A ``jax.device_get`` or
``.tolist()`` of a value that skipped either step is a raw-statistic
release — invisible to the budget accountant and unprotected by the
mechanism, no matter how many layers of helper functions sit between the
column and the sync.

dpflow tracks values originating in private-column parameters (``pid`` /
``pk`` / ``value`` raw; ``accs`` / ``qhist`` accumulators, which enter
already bounded) through assignments, numpy/jnp transforms and project
call chains (flow/summary.py + flow/graph.py), and flags any path that
reaches a host-materialization sink while missing a sanitization flag.
The mechanism-primitive layer (``LintConfig.release_taint_trusted``) is
opaque-trusted: its internal host syncs are mechanism bookkeeping, not
releases.

Precision over recall, like every dplint rule: values returned by
unrecognized callees stop being tracked rather than guessed at, so a
DPL007 finding means a *demonstrable* unsanitized flow.
"""

from __future__ import annotations

from typing import Iterable, List

from pipelinedp_tpu.lint.engine import Finding, ProjectContext, ProjectRule
from pipelinedp_tpu.lint.flow.summary import ALL_FLAGS


class ReleaseTaintRule(ProjectRule):
    rule_id = "DPL007"
    name = "release-path-taint"
    description = ("A private input column (or pre-noise accumulator) "
                   "reaches host materialization without contribution "
                   "bounding and a noise mechanism on the path.")
    hint = ("Route the value through the bound-and-aggregate kernel and a "
            "noise_core / ops.noise mechanism before any device_get / "
            ".tolist(); if the host transfer is mechanism-internal by "
            "design (e.g. the secure-host-noise epilogue), suppress with "
            "a written justification.")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        flow = project.flow
        trusted = project.config.is_release_taint_trusted
        findings: List[Finding] = []
        for qual, tf in flow.root_exposures(trusted):
            module = flow.function_module[qual]
            missing = sorted(ALL_FLAGS - set(tf.gained))
            func = qual[len(module) + 1:]
            if tf.kind == "sink":
                what = f"is materialized to host by `{tf.detail}`"
            else:
                callee = tf.detail.split(".")[-1]
                what = (f"is handed to `{callee}` which materializes it "
                        f"to host")
            findings.append(Finding(
                self.rule_id, project.relpath_of(module), tf.line, 1,
                f"private value `{tf.origin}` in `{func}` {what} without "
                f"{' or '.join(missing)} applied on the path",
                self.hint))
        return findings
