"""DPL001 — JAX PRNG key reuse.

Consuming the same key in two sampling calls yields *correlated* noise
draws: for DP release code that silently destroys the privacy guarantee
(two "independent" Laplace draws that are bitwise identical). The rule
tracks, per function scope, which key variables have already been consumed
by a `jax.random.*` sampler (or handed to a callee that samples from them)
and flags a second consumption that is not separated by a re-derivation
(`split` / `fold_in` / reassignment).

Precision over recall: a variable is only treated as a PRNG key with
*provenance* — it was assigned from a `jax.random` derivation call, was
already consumed as the key argument of a `jax.random` sampler, or is a
strictly key-named parameter (`key`, `rng_key`, `k_noise`, ...) of a
function that demonstrably works with `jax.random`. Dict keys, sort keys
and chunk counters named `k`/`key` never enter the analysis.

The analysis is branch-aware: consumption in mutually exclusive `if`/`elif`
arms does not conflict, and loop bodies are analyzed twice so a key drawn
from outside the loop is caught on the simulated second iteration.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterable, List

from pipelinedp_tpu.lint import astutils
from pipelinedp_tpu.lint.engine import Finding, ModuleContext, Rule

# Derivation calls: produce fresh keys, do NOT consume their key argument.
_DERIVERS = frozenset({
    "jax.random.PRNGKey",
    "jax.random.key",
    "jax.random.split",
    "jax.random.fold_in",
    "jax.random.clone",
    "jax.random.wrap_key_data",
    "jax.random.key_data",
})

# Parameters with these names are PRNG keys — but only inside functions
# that reference jax.random at all (see _function_uses_jax_random).
_STRICT_PARAM_RE = re.compile(
    r"^(?:key|rng|prng|rng_key|prng_key|root_key|kernel_key|sub_key|"
    r"noise_key)$|^k_\w+$")

# Method-name suffixes treated as derivation: the audited KeyStream idiom
# (jax_engine.KeyStream.derive / .next_key) and lookalikes.
_DERIVER_SUFFIXES = (".derive", ".next_key")

# Handing a key to these never samples from it.
_NON_CONSUMING_BUILTINS = frozenset({
    "len", "range", "min", "max", "zip", "enumerate", "list", "tuple",
    "sorted", "reversed", "print", "isinstance", "issubclass", "type",
    "id", "repr", "str", "int", "float", "bool", "sum", "abs", "hash",
    "getattr", "hasattr", "format",
})

_FRESH = -1  # sentinel: key derived but not yet consumed


def _is_deriver(target) -> bool:
    return target is not None and (
        target in _DERIVERS or
        target.endswith(_DERIVER_SUFFIXES))


def _terminates(stmts: List[ast.stmt]) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


class KeyReuseRule(Rule):
    rule_id = "DPL001"
    name = "prng-key-reuse"
    description = ("A JAX PRNG key is consumed by more than one sampling "
                   "call without an intervening split/fold_in.")
    hint = ("Derive a fresh key per draw: `k1, k2 = jax.random.split(key)` "
            "or `jax.random.fold_in(key, tag)` — or route through "
            "jax_engine.KeyStream, the audited key source.")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        findings: List[Finding] = []
        self._scan_scopes(ctx.tree, ctx, findings)
        # Dedupe (the loop double-pass reports each reuse twice).
        seen = set()
        out = []
        for f in findings:
            key = (f.line, f.col, f.message)
            if key not in seen:
                seen.add(key)
                out.append(f)
        return out

    # -- scope discovery ----------------------------------------------------

    def _scan_scopes(self, node: ast.AST, ctx: ModuleContext,
                     findings: List[Finding]) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._analyze_function(child, ctx, findings)
            self._scan_scopes(child, ctx, findings)

    def _function_uses_jax_random(self, fn, ctx: ModuleContext) -> bool:
        for node in ast.walk(fn):
            if isinstance(node, ast.Attribute):
                resolved = astutils.resolve(node, ctx.aliases)
                if resolved is not None and \
                        resolved.startswith("jax.random."):
                    return True
        return False

    def _analyze_function(self, fn, ctx: ModuleContext,
                          findings: List[Finding]) -> None:
        state: Dict[str, int] = {}
        if self._function_uses_jax_random(fn, ctx):
            args = fn.args
            for a in (list(args.posonlyargs) + list(args.args) +
                      list(args.kwonlyargs)):
                if _STRICT_PARAM_RE.match(a.arg):
                    state[a.arg] = _FRESH
        self._block(fn.body, state, ctx, findings)

    # -- statement walk -----------------------------------------------------

    def _block(self, stmts: List[ast.stmt], state: Dict[str, int],
               ctx: ModuleContext, findings: List[Finding]) -> None:
        for stmt in stmts:
            self._statement(stmt, state, ctx, findings)

    def _statement(self, stmt: ast.stmt, state: Dict[str, int],
                   ctx: ModuleContext, findings: List[Finding]) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate scope, handled by _scan_scopes
        if isinstance(stmt, ast.Assign):
            self._expr(stmt.value, state, ctx, findings)
            for target in stmt.targets:
                self._bind(target, stmt.value, state, ctx)
            return
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value, state, ctx, findings)
                self._bind(stmt.target, stmt.value, state, ctx)
            return
        if isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value, state, ctx, findings)
            return
        if isinstance(stmt, ast.If):
            self._expr(stmt.test, state, ctx, findings)
            merged: Dict[str, int] = dict(state)
            for branch in (stmt.body, stmt.orelse):
                branch_state = dict(state)
                self._block(branch, branch_state, ctx, findings)
                if not _terminates(branch):
                    for name, line in branch_state.items():
                        # Union consumption from surviving branches; a
                        # consumed mark beats fresh.
                        if merged.get(name, _FRESH) == _FRESH:
                            merged[name] = line
            state.clear()
            state.update(merged)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, state, ctx, findings)
            # Two passes simulate a second iteration: consumption of a key
            # defined outside the loop is a reuse on iteration 2.
            loop_state = dict(state)
            for _ in range(2):
                self._block(stmt.body, loop_state, ctx, findings)
            state.update(loop_state)
            self._block(stmt.orelse, state, ctx, findings)
            return
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, state, ctx, findings)
            loop_state = dict(state)
            for _ in range(2):
                self._block(stmt.body, loop_state, ctx, findings)
            state.update(loop_state)
            self._block(stmt.orelse, state, ctx, findings)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr, state, ctx, findings)
            self._block(stmt.body, state, ctx, findings)
            return
        if isinstance(stmt, ast.Try):
            body_state = dict(state)
            self._block(stmt.body, body_state, ctx, findings)
            merged = dict(body_state)
            for handler in stmt.handlers:
                h_state = dict(state)
                self._block(handler.body, h_state, ctx, findings)
                if not _terminates(handler.body):
                    for name, line in h_state.items():
                        if merged.get(name, _FRESH) == _FRESH:
                            merged[name] = line
            state.clear()
            state.update(merged)
            self._block(stmt.orelse, state, ctx, findings)
            self._block(stmt.finalbody, state, ctx, findings)
            return
        # Expression-bearing statements (Expr, Return, Assert, Raise, ...).
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, state, ctx, findings)

    def _bind(self, target: ast.expr, value: ast.expr,
              state: Dict[str, int], ctx: ModuleContext) -> None:
        """Assignment from a `jax.random` derivation makes the target(s)
        fresh tracked keys; any other assignment to a tracked name clears
        it (provenance lost — stop tracking rather than guess)."""
        is_derivation = (isinstance(value, ast.Call) and
                         _is_deriver(astutils.call_target(value,
                                                          ctx.aliases)))
        names: List[str] = []
        if isinstance(target, ast.Name):
            names = [target.id]
        elif isinstance(target, (ast.Tuple, ast.List)):
            names = [e.id for e in target.elts if isinstance(e, ast.Name)]
        for name in names:
            if is_derivation:
                state[name] = _FRESH
            else:
                state.pop(name, None)

    # -- expression walk ----------------------------------------------------

    def _expr(self, node: ast.expr, state: Dict[str, int],
              ctx: ModuleContext, findings: List[Finding]) -> None:
        if isinstance(node, ast.Lambda):
            return  # deferred execution; analyzed as its own scope? no state
        if isinstance(node, ast.Call):
            target = astutils.call_target(node, ctx.aliases)
            # Recurse first so nested calls (fold_in(key, i) as an
            # argument) are classified before the outer call consumes.
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                self._expr(arg, state, ctx, findings)
            if _is_deriver(target):
                return  # derivation: the key argument stays fresh
            if target is not None and target.startswith("jax.random."):
                # Sampler: the first positional argument is the key by
                # signature. First consumption also *establishes*
                # provenance for untracked names.
                if node.args and isinstance(node.args[0], ast.Name):
                    self._consume(node.args[0], node, state, ctx, findings,
                                  via=target.rsplit(".", 1)[-1],
                                  establish=True)
                return
            if target is not None and target in _NON_CONSUMING_BUILTINS:
                return
            # Other callee: a *tracked* key argument is assumed consumed
            # (the callee samples from it); two hand-offs of the same key
            # mean two callees drawing identical streams.
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Name) and arg.id in state:
                    self._consume(arg, node, state, ctx, findings,
                                  via=target or "a function call",
                                  establish=False)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._expr(child, state, ctx, findings)

    def _consume(self, name_node: ast.Name, call: ast.Call,
                 state: Dict[str, int], ctx: ModuleContext,
                 findings: List[Finding], via: str,
                 establish: bool) -> None:
        name = name_node.id
        prior = state.get(name)
        if prior is None and not establish:
            return
        if prior is not None and prior != _FRESH:
            findings.append(ctx.finding(
                self, call,
                f"PRNG key `{name}` is consumed again by `{via}` but was "
                f"already consumed at line {prior}; reusing a key yields "
                f"correlated (non-independent) draws"))
        else:
            state[name] = getattr(call, "lineno", 0)
