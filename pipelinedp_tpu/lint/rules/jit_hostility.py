"""DPL003 — jit-hostile constructs inside jitted functions.

Inside a function compiled with ``jax.jit``, host-only operations on traced
values either fail at trace time (`if tracer:`, `float(tracer)`) or — worse
for a DP system — silently execute at *trace* time and bake one concrete
value into the compiled kernel (a `np.` call on a traced argument). For
noise code that means a "random" draw frozen into XLA and replayed on
every call: a privacy incident, not a crash.

Detected as jitted: ``@jax.jit``-decorated, ``@functools.partial(jax.jit,
...)``-decorated, and local ``def fn(...)`` later wrapped as
``jax.jit(fn)``. Arguments named in ``static_argnames``/``static_argnums``
are excluded from the traced set — branching and host math on statics is
the idiomatic pattern.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pipelinedp_tpu.lint import astutils
from pipelinedp_tpu.lint.engine import Finding, ModuleContext, Rule

_PARTIAL_NAMES = ("functools.partial", "partial")
_CASTS = ("float", "int", "bool")


def _static_names_from_call(call: ast.Call,
                            param_order: List[str]) -> Set[str]:
    statics: Set[str] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            if isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, str):
                statics.add(kw.value.value)
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                for elt in kw.value.elts:
                    if isinstance(elt, ast.Constant) and \
                            isinstance(elt.value, str):
                        statics.add(elt.value)
        elif kw.arg == "static_argnums":
            nums = []
            if isinstance(kw.value, ast.Constant) and \
                    isinstance(kw.value.value, int):
                nums = [kw.value.value]
            elif isinstance(kw.value, (ast.Tuple, ast.List)):
                nums = [e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant) and
                        isinstance(e.value, int)]
            for n in nums:
                if 0 <= n < len(param_order):
                    statics.add(param_order[n])
    return statics


def _param_names(fn) -> List[str]:
    args = fn.args
    return [a.arg for a in (list(args.posonlyargs) + list(args.args) +
                            list(args.kwonlyargs))]


class JitHostilityRule(Rule):
    rule_id = "DPL003"
    name = "jit-hostile-construct"
    description = ("Host-only operations (.item(), np.*, float()/int(), "
                   "Python branching) on traced values inside a "
                   "jax.jit-compiled function.")
    hint = ("Use jnp ops / jnp.where / lax.cond on traced values, or "
            "declare the argument in static_argnames if it is genuinely "
            "compile-time constant.")

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        jitted = self._collect_jitted(ctx)
        findings: List[Finding] = []
        for fn, statics in jitted:
            traced = set(_param_names(fn)) - statics
            self._check_body(fn, traced, ctx, findings)
        return findings

    # -- jitted-function discovery ------------------------------------------

    def _collect_jitted(self, ctx: ModuleContext
                        ) -> List[Tuple[ast.AST, Set[str]]]:
        jitted: List[Tuple[ast.AST, Set[str]]] = []
        # jax.jit(fn) wrapping sites, resolved to same-module FunctionDefs.
        wrapped: Dict[str, Set[str]] = {}
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and \
                    astutils.call_target(node, ctx.aliases) == "jax.jit" \
                    and node.args and isinstance(node.args[0], ast.Name):
                name = node.args[0].id
                wrapped.setdefault(name, set())
                # static names resolved per-function below (needs params)
                wrapped[name] |= _static_names_from_call(node, [])
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            statics = self._decorator_statics(node, ctx)
            if statics is not None:
                jitted.append((node, statics))
            elif node.name in wrapped:
                params = _param_names(node)
                # Re-resolve static_argnums now that params are known.
                statics = set(wrapped[node.name])
                for call in ast.walk(ctx.tree):
                    if isinstance(call, ast.Call) and \
                            astutils.call_target(call, ctx.aliases) == \
                            "jax.jit" and call.args and \
                            isinstance(call.args[0], ast.Name) and \
                            call.args[0].id == node.name:
                        statics |= _static_names_from_call(call, params)
                jitted.append((node, statics))
        return jitted

    def _decorator_statics(self, fn, ctx: ModuleContext) -> Optional[Set[str]]:
        """Static argnames if ``fn`` is decorator-jitted, else None."""
        params = _param_names(fn)
        for dec in fn.decorator_list:
            target = astutils.resolve(dec, ctx.aliases)
            if target == "jax.jit":
                return set()
            if isinstance(dec, ast.Call):
                dec_target = astutils.call_target(dec, ctx.aliases)
                if dec_target == "jax.jit":
                    return _static_names_from_call(dec, params)
                if dec_target in _PARTIAL_NAMES and dec.args and \
                        astutils.resolve(dec.args[0], ctx.aliases) == \
                        "jax.jit":
                    return _static_names_from_call(dec, params)
        return None

    # -- body checks --------------------------------------------------------

    def _check_body(self, fn, traced: Set[str], ctx: ModuleContext,
                    findings: List[Finding]) -> None:
        def references_traced(node: ast.AST) -> bool:
            return any(isinstance(sub, ast.Name) and sub.id in traced
                       for sub in ast.walk(node))

        def is_none_check(test: ast.expr) -> bool:
            return isinstance(test, ast.Compare) and all(
                isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)

        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                target = astutils.call_target(node, ctx.aliases)
                if isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "item" and not node.args:
                    findings.append(ctx.finding(
                        self, node,
                        f"`.item()` inside jitted `{fn.name}` forces a "
                        f"host sync and fails on traced values"))
                elif target is not None and target.startswith("numpy.") \
                        and any(references_traced(a) for a in
                                list(node.args) +
                                [kw.value for kw in node.keywords]):
                    findings.append(ctx.finding(
                        self, node,
                        f"NumPy call `{target}` on traced argument inside "
                        f"jitted `{fn.name}` executes at trace time — the "
                        f"result is baked into the compiled kernel"))
                elif target in _CASTS and node.args and \
                        references_traced(node.args[0]):
                    findings.append(ctx.finding(
                        self, node,
                        f"`{target}()` on a traced value inside jitted "
                        f"`{fn.name}` fails at trace time (concretization "
                        f"of an abstract tracer)"))
            elif isinstance(node, (ast.If, ast.While)):
                test = node.test
                if references_traced(test) and not is_none_check(test):
                    findings.append(ctx.finding(
                        self, test,
                        f"Python branching on traced value inside jitted "
                        f"`{fn.name}`: the branch is resolved once at "
                        f"trace time, not per-input — use jnp.where or "
                        f"lax.cond"))
