"""DPL014 — lock-order cycles and lock-scope (latency-inversion)
hazards over the project lock graph.

The serving/obs/runtime planes hold 18 ``threading.Lock`` sites; none
of them is documented as an ordered hierarchy, so the only defensible
invariant is the one dpverify can check: the *acquired-while-held*
graph — built from every function's ``lock_acquire`` effect spans plus
the transitive acquire sets of everything called inside those spans,
with inherited ``self._lock`` attributes canonicalized to the class
that created them — must stay acyclic. A cycle is a deadlock waiting
for the fleet (ROADMAP item 1) to schedule the interleaving.

The same spans also expose latency inversions: a lock held across an
``fsync``/WAL append or a device synchronization
(``device_get``/``block_until_ready``) serializes millisecond-scale
waits into every contender. Transactions whose *contract* is "the lock
serializes the durable append" are exempted by canonical lock name in
``LintConfig.lock_scope_exempt``.
"""

from __future__ import annotations

import re
from typing import Iterable, List

from pipelinedp_tpu.lint.engine import Finding, ProjectContext, ProjectRule
from pipelinedp_tpu.lint.flow.summary import (
    EFFECT_FSYNC,
    EFFECT_LOCK_ACQUIRE,
    EFFECT_WAL_APPEND,
)

_HELD_KINDS = frozenset({EFFECT_FSYNC, EFFECT_WAL_APPEND})
DEVICE_SYNC_RE = re.compile(
    r"(?:^|\.)(?:device_get|device_put|block_until_ready)$")


class LockOrderRule(ProjectRule):
    rule_id = "DPL014"
    name = "lock-order"
    description = ("The project lock graph has an ordering cycle, or a "
                   "lock is held across fsync/device synchronization.")
    hint = ("Break the cycle by acquiring the locks in one global "
            "order (release the outer lock first, or hoist the inner "
            "acquisition out of the critical section); for scope "
            "findings, move the fsync/device sync outside the lock or "
            "record the serialization contract in "
            "LintConfig.lock_scope_exempt.")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        flow = project.flow
        config = project.config
        findings: List[Finding] = []

        graph = flow.lock_graph()
        for cycle in flow.lock_cycles():
            pairs = list(zip(cycle, cycle[1:] + cycle[:1]))
            qual, line = graph[pairs[0][0]][pairs[0][1]]
            module = flow.function_module[qual]
            loop = " -> ".join([*cycle, cycle[0]])
            findings.append(Finding(
                self.rule_id, project.relpath_of(module), line, 1,
                f"lock-order cycle {loop}: `{qual.split('.')[-1]}` "
                f"acquires `{pairs[0][1].rsplit('.', 1)[-1]}` while "
                f"holding `{pairs[0][0].rsplit('.', 1)[-1]}` and "
                f"another path nests them in the opposite order — "
                f"a deadlock under concurrency",
                self.hint))

        sync_reaching = flow.reaching(DEVICE_SYNC_RE.pattern)
        for qual, fsum in flow.functions.items():
            module = flow.function_module[qual]
            relpath = project.relpath_of(module)
            func = qual[len(module) + 1:]
            for acq, kind in flow.held_effects(qual, _HELD_KINDS):
                name = flow.canonical_lock(acq.detail, module)
                if config.is_lock_scope_exempt(name):
                    continue
                findings.append(Finding(
                    self.rule_id, relpath, acq.line, 1,
                    f"`{name.rsplit('.', 1)[-1]}` is held across "
                    f"`{kind}` in `{func}` — every contender now "
                    f"waits on storage latency",
                    self.hint))
            for acq in fsum.effects:
                if acq.kind != EFFECT_LOCK_ACQUIRE or acq.end < 0:
                    continue
                name = flow.canonical_lock(acq.detail, module)
                if config.is_lock_scope_exempt(name):
                    continue
                for call in fsum.calls:
                    if not (acq.line <= call.line <= acq.end):
                        continue
                    callee = flow.resolve(call.target, module)
                    if DEVICE_SYNC_RE.search(call.target) or \
                            (callee is not None and
                             callee in sync_reaching):
                        findings.append(Finding(
                            self.rule_id, relpath, call.line, 1,
                            f"`{name.rsplit('.', 1)[-1]}` is held "
                            f"across a device synchronization "
                            f"(`{call.target.split('.')[-1]}`) in "
                            f"`{func}` — device latency serializes "
                            f"every contender",
                            self.hint))
                        break
        return findings
