"""DPL013 — commit ordering: nothing the WAL record promises may
precede it, nothing it references may follow it.

Every durable transaction in the tree is write-ahead shaped
(serving/live.py append, runtime/journal.py commit, RESILIENCE.md):

  1. make the *payload* durable (epoch npz, journal temp file);
  2. append the WAL / commit record that references it — this fsync is
     the commit point;
  3. only then mutate in-memory state to reflect the committed fact.

Inverting either half breaks crash-exactly-once: a payload written
*after* the record means recovery finds a record pointing at nothing;
state mutated *before* the record means a crash leaves memory (and
anything derived from it, e.g. dedup indexes) claiming a fact the log
never committed. This generalizes DPL009's commit-before-draw to the
append/release/checkpoint transactions.

dpverify anchors on functions with a direct ``wal_append`` effect (or
``*.commit`` functions whose call closure is durable) and checks the
effect trace against the two orderings. Mutations of the WAL binding
itself (``self._wal = ...``) are the commit *channel*, not transaction
state, and are ignored. ``LintConfig.commit_ordering_trusted`` exempts
functions whose pre-commit durability is itself the protocol.
"""

from __future__ import annotations

import re
from typing import Iterable, List, Optional

from pipelinedp_tpu.lint.engine import Finding, ProjectContext, ProjectRule
from pipelinedp_tpu.lint.flow.summary import (
    EFFECT_FSYNC,
    EFFECT_RAW_WRITE,
    EFFECT_RENAME,
    EFFECT_STATE_MUTATION,
    EFFECT_TMP_CREATE,
    EFFECT_WAL_APPEND,
    WAL_APPEND_TARGET_RE,
)

_DURABLE_KINDS = frozenset({EFFECT_FSYNC, EFFECT_RENAME,
                            EFFECT_RAW_WRITE, EFFECT_TMP_CREATE})
# self._wal assignments establish the commit channel, not state.
_WAL_BINDING_RE = re.compile(r"(?:^|\.)_?wal\b")


class CommitOrderingRule(ProjectRule):
    rule_id = "DPL013"
    name = "commit-ordering"
    description = ("A durable side effect or state mutation is on the "
                   "wrong side of the WAL/commit record.")
    hint = ("Order the transaction payload-first: durable payload "
            "writes, then the WAL append (the commit point), then "
            "in-memory mutations; see serving/live.py _append_locked "
            "and RESILIENCE.md for the crash contract.")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        flow = project.flow
        config = project.config
        closure = flow.effect_kind_closure()
        findings: List[Finding] = []
        for qual, fsum in flow.functions.items():
            if config.is_commit_ordering_trusted(qual):
                continue
            module = flow.function_module[qual]
            commit_line, via_wal = self._commit_point(
                qual, fsum, flow, closure)
            if commit_line is None:
                continue
            relpath = project.relpath_of(module)
            func = qual[len(module) + 1:]
            for eff in fsum.effects:
                if eff.kind == EFFECT_STATE_MUTATION and \
                        eff.line < commit_line and \
                        not _WAL_BINDING_RE.search(eff.detail):
                    findings.append(Finding(
                        self.rule_id, relpath, eff.line, 1,
                        f"`{func}` mutates `{eff.detail}` before the "
                        f"commit record at line {commit_line} is "
                        f"durable — a crash leaves memory claiming a "
                        f"fact the log never committed",
                        self.hint))
            if not via_wal:
                continue
            # The WAL record references the payload: anything durable
            # after the append arrives too late for recovery to find.
            seen = set()
            for eff in fsum.effects:
                if eff.kind in _DURABLE_KINDS and eff.line > commit_line:
                    seen.add(eff.line)
                    findings.append(Finding(
                        self.rule_id, relpath, eff.line, 1,
                        f"durable `{eff.kind}` in `{func}` after the "
                        f"WAL append at line {commit_line} — the "
                        f"record can commit while its payload is lost",
                        self.hint))
            for call in fsum.calls:
                if call.line <= commit_line or call.line in seen:
                    continue
                if WAL_APPEND_TARGET_RE.search(call.target):
                    continue  # a later record is its own commit
                if closure.get(flow.resolve(call.target, module) or "",
                               frozenset()) & _DURABLE_KINDS:
                    seen.add(call.line)
                    leaf = call.target.split(".")[-1]
                    findings.append(Finding(
                        self.rule_id, relpath, call.line, 1,
                        f"`{leaf}` performs durable writes after the "
                        f"WAL append at line {commit_line} of `{func}` "
                        f"— the record can commit while its payload "
                        f"is lost",
                        self.hint))
        return findings

    @staticmethod
    def _commit_point(qual, fsum, flow, closure):
        """(line, via_wal) of the transaction's commit point, or
        (None, False) when the function is not an anchor."""
        wal_lines = [e.line for e in fsum.effects
                     if e.kind == EFFECT_WAL_APPEND]
        if wal_lines:
            return min(wal_lines), True
        if qual.endswith(".commit"):
            module = flow.function_module[qual]
            durable_calls: List[int] = []
            for call in fsum.calls:
                callee = flow.resolve(call.target, module)
                if callee is not None and \
                        closure.get(callee, frozenset()) & \
                        frozenset({EFFECT_FSYNC, EFFECT_RENAME}):
                    durable_calls.append(call.line)
            if durable_calls:
                return min(durable_calls), False
        return None, False
