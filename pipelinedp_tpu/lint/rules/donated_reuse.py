"""DPL010 — donated-buffer reuse: reading an operand jit already ate.

``donate_argnums`` hands the operand's device buffer to XLA: after the
call — **including when the call raises mid-dispatch** — the Python name
still binds the donated (now invalid or aliased) array. Reading it again
double-counts a chunk or feeds poisoned accumulator state into a DP
release; this is exactly the failure class the streaming loop's
checkpoint-restore-on-dispatch-failure and the compact (never-donating)
path were built around (ops/streaming.py, PR 5).

dpflow resolves every call site against the project's donating jit
wrappers (``@functools.partial(jax.jit, ..., donate_argnums=...)``
decorators and ``name = jax.jit(f, donate_argnums=...)`` assignments,
recorded in the per-file summaries) and then runs a path-sensitive walk
of each function: after a donating call, its donated operand names are
poisoned until rebound; a read on any path is a finding. Exception paths
are first-class — a poison event anywhere in a ``try`` body is live in
every handler and the ``finally`` block, because the raise can land
between consumption and the rebinding assignment (``accs =
step(..., accs, ...)`` is safe on the fallthrough path, poisoned in the
handler).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from pipelinedp_tpu.lint.engine import Finding, ProjectContext, ProjectRule
from pipelinedp_tpu.lint.flow import summary as summary_lib


class DonatedReuseRule(ProjectRule):
    rule_id = "DPL010"
    name = "donated-buffer-reuse"
    description = ("An operand donated to a jit call (donate_argnums) is "
                   "read again on some path after the call, including "
                   "exception paths.")
    hint = ("Rebind the name from the call result (`accs = step(...,"
            " accs, ...)`), restore from a checkpoint on the exception "
            "path, or use the compact (non-donating) step when retries "
            "must see intact state.")

    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        flow = project.flow
        donating = flow.donating()
        if not donating:
            return []
        findings: List[Finding] = []
        for relpath, ctx in project.modules.items():
            for qual, fn, scope, ex in summary_lib.iter_scopes(
                    ctx.module, ctx.tree, ctx.aliases):
                walker = _PoisonWalker(ex, scope, ctx.module, flow,
                                       donating)
                for name, call_line, read in walker.run(fn):
                    findings.append(Finding(
                        self.rule_id, relpath, read.lineno,
                        read.col_offset + 1,
                        f"`{name}` was donated to the jit call at line "
                        f"{call_line} and is read again here — the "
                        f"buffer is consumed even if that call raised",
                        self.hint))
        return findings


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


class _PoisonWalker:
    """Path-sensitive poison propagation for one function body."""

    def __init__(self, extractor, scope, module: str, flow, donating):
        self.ex = extractor
        self.scope = scope
        self.module = module
        self.flow = flow
        self.donating = donating
        # (name, read line) dedupe across the loop double-pass.
        self._seen: Set[Tuple[str, int]] = set()
        self.findings: List[Tuple[str, int, ast.AST]] = []

    def run(self, fn) -> List[Tuple[str, int, ast.AST]]:
        state: Dict[str, int] = {}  # poisoned name -> donating call line
        self._block(fn.body, state, events=None)
        return self.findings

    # -- statements ---------------------------------------------------------

    def _block(self, stmts, state: Dict[str, int],
               events: Optional[List[Tuple[str, int]]]) -> None:
        for stmt in stmts:
            self._statement(stmt, state, events)

    def _statement(self, stmt, state, events) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # separate scope (closures analyzed on their own)
        if isinstance(stmt, ast.Assign):
            self._eval(stmt.value, state, events)
            for t in stmt.targets:
                self._kill(t, state)
            return
        if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            if getattr(stmt, "value", None) is not None:
                self._eval(stmt.value, state, events)
            if isinstance(stmt, ast.AugAssign):
                # x += f(...) reads x as well.
                self._read_names(stmt.target, state)
            self._kill(stmt.target, state)
            return
        if isinstance(stmt, ast.If):
            self._eval(stmt.test, state, events)
            surviving = []
            for branch in (stmt.body, stmt.orelse):
                bstate = dict(state)
                self._block(branch, bstate, events)
                if not _terminates(branch):
                    surviving.append(bstate)
            if surviving:
                state.clear()
                for bstate in surviving:  # union: poisoned on any path
                    state.update(bstate)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._eval(stmt.iter, state, events)
            self._kill(stmt.target, state)
            for _ in range(2):  # pass 2 catches loop-carried poison
                self._block(stmt.body, state, events)
            self._block(stmt.orelse, state, events)
            return
        if isinstance(stmt, ast.While):
            self._eval(stmt.test, state, events)
            for _ in range(2):
                self._block(stmt.body, state, events)
            self._block(stmt.orelse, state, events)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._eval(item.context_expr, state, events)
                if item.optional_vars is not None:
                    self._kill(item.optional_vars, state)
            self._block(stmt.body, state, events)
            return
        if isinstance(stmt, ast.Try):
            local_events: List[Tuple[str, int]] = []
            body_state = dict(state)
            self._block(stmt.body, body_state, local_events)
            if events is not None:
                events.extend(local_events)
            # Handlers see every poison event of the try body: the raise
            # can land between the donation and the rebinding kill.
            handler_entry = dict(state)
            for name, line in local_events:
                handler_entry[name] = line
            for handler in stmt.handlers:
                self._block(handler.body, dict(handler_entry), events)
            self._block(stmt.orelse, body_state, events)
            final_state = dict(body_state)
            final_state.update(handler_entry)
            self._block(stmt.finalbody, final_state, events)
            state.clear()
            state.update(body_state)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._eval(child, state, events)

    # -- expressions --------------------------------------------------------

    def _eval(self, node, state, events) -> None:
        """Reads flagged, then donations applied (a call's own operands
        are read *by* the call legally; they poison only afterwards)."""
        if node is None:
            return
        pending: List[Tuple[str, int]] = []
        self._walk_expr(node, state, events, pending)
        for name, line in pending:
            state[name] = line
            if events is not None:
                events.append((name, line))

    def _walk_expr(self, node, state, events, pending) -> None:
        if isinstance(node, (ast.Lambda, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            return
        if isinstance(node, ast.Name):
            if isinstance(node.ctx, ast.Load):
                self._flag(node, state)
            return
        if isinstance(node, ast.Call):
            for child in ast.iter_child_nodes(node):
                self._walk_expr(child, state, events, pending)
            target = self.ex.resolve_call(node, self.scope)
            resolved = self.flow.resolve(target, self.module)
            indices = self.donating.get(resolved, ())
            for idx in indices:
                if idx < len(node.args) and isinstance(node.args[idx],
                                                       ast.Name):
                    pending.append((node.args[idx].id, node.lineno))
            return
        for child in ast.iter_child_nodes(node):
            self._walk_expr(child, state, events, pending)

    def _read_names(self, node, state) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                self._flag(sub, state)

    def _flag(self, name_node: ast.Name, state) -> None:
        line = state.get(name_node.id)
        if line is None:
            return
        key = (name_node.id, name_node.lineno)
        if key in self._seen:
            return
        self._seen.add(key)
        self.findings.append((name_node.id, line, name_node))

    @staticmethod
    def _kill(target, state) -> None:
        if isinstance(target, ast.Name):
            state.pop(target.id, None)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                _PoisonWalker._kill(e, state)
