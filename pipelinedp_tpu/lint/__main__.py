"""Entry point for `python -m pipelinedp_tpu.lint`."""

import sys

from pipelinedp_tpu.lint.cli import main

if __name__ == "__main__":
    sys.exit(main())
