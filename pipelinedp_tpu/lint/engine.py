"""dplint rule engine: findings, suppressions, baselines, and the runner.

Architecture: one AST parse per file, shared by every rule through a
``ModuleContext``; rules are stateless objects returning ``Finding``s.
Three layers decide what the CLI ultimately reports:

1. inline suppressions — ``# dplint: disable=DPL001 — <justification>``
   on the offending line (or on a comment-only line directly above it),
   and ``# dplint: disable-file=DPL004 — <justification>`` anywhere in
   the file. The justification is mandatory: a bare directive still
   suppresses its target but surfaces as a DPL000 finding, so unreviewed
   silencing cannot land;
2. the baseline — a JSON snapshot of accepted findings, matched by
   content fingerprint (rule id + file + normalized line text + occurrence
   index) so findings don't resurrect when unrelated lines shift;
3. everything left is "new" and makes the CLI exit nonzero.
"""

from __future__ import annotations

import abc
import ast
import dataclasses
import hashlib
import json
import os
import re
from collections import Counter
from typing import Dict, Iterable, List, Optional, Sequence, Set

from pipelinedp_tpu.lint import astutils
from pipelinedp_tpu.lint.config import DEFAULT_CONFIG, LintConfig

_SUPPRESS_RE = re.compile(
    r"#\s*dplint:\s*(disable|disable-file)\s*=\s*"
    r"(all|DPL\d{3}(?:\s*,\s*DPL\d{3})*)", re.IGNORECASE)
_COMMENT_ONLY_RE = re.compile(r"^\s*#")
# What follows the directive must contain a word character to count as a
# justification (separators like `—`, `-`, `:` alone do not).
_JUSTIFIED_RE = re.compile(r"\w")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""
    rule_id: str
    path: str  # repo-relative, '/'-separated
    line: int
    col: int
    message: str
    hint: str = ""

    def format(self, verbose: bool = False) -> str:
        text = f"{self.path}:{self.line}:{self.col}: {self.rule_id} " \
               f"{self.message}"
        if verbose and self.hint:
            text += f"\n    hint: {self.hint}"
        return text


@dataclasses.dataclass
class ModuleContext:
    """Everything a rule needs about one parsed file."""
    path: str          # absolute
    relpath: str       # repo-relative, '/'-separated (used in findings)
    module: str        # dotted module name, e.g. pipelinedp_tpu.ops.noise
    tree: ast.AST
    lines: List[str]   # source lines, 0-indexed
    aliases: Dict[str, str]
    config: LintConfig

    def finding(self, rule: "Rule", node: ast.AST, message: str,
                hint: Optional[str] = None) -> Finding:
        return Finding(rule.rule_id, self.relpath,
                       getattr(node, "lineno", 1),
                       getattr(node, "col_offset", 0) + 1, message,
                       rule.hint if hint is None else hint)

    def source_contains(self, *tokens: str) -> bool:
        return any(any(t in line for t in tokens) for line in self.lines)


class Rule(abc.ABC):
    """A dplint rule: stateless; ``check`` returns findings for one module."""

    rule_id: str = "DPL000"
    name: str = ""
    description: str = ""
    hint: str = ""

    @abc.abstractmethod
    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        ...


@dataclasses.dataclass
class ProjectContext:
    """Everything a whole-program rule needs: every parsed module plus
    the dpflow views (symbol table, call graph, fixed points)."""
    modules: Dict[str, ModuleContext]  # keyed by repo-relative path
    config: LintConfig
    flow: object  # lint.flow.ProjectFlow (typed loosely: lazy import)

    def relpath_of(self, module: str) -> str:
        for relpath, ctx in self.modules.items():
            if ctx.module == module:
                return relpath
        return module

    def finding(self, rule: "Rule", module: str, line: int, col: int,
                message: str) -> Finding:
        return Finding(rule.rule_id, self.relpath_of(module), line, col,
                       message, rule.hint)


class ProjectRule(Rule):
    """A rule that analyzes the whole scanned set at once (DPL007-010).

    ``check`` is a no-op; the runner calls ``check_project`` after every
    module has been parsed and summarized. Findings still carry a
    (path, line) location, so inline suppressions and the baseline apply
    exactly as they do to per-module rules.
    """

    def check(self, ctx: ModuleContext) -> Iterable[Finding]:
        return ()

    @abc.abstractmethod
    def check_project(self, project: ProjectContext) -> Iterable[Finding]:
        ...


# ---------------------------------------------------------------------------
# Suppressions
# ---------------------------------------------------------------------------


class Suppressions:
    """Inline `# dplint: disable=...` directives of one file."""

    def __init__(self, lines: Sequence[str]):
        self.file_level: Set[str] = set()
        self.by_line: Dict[int, Set[str]] = {}
        # Directives with no justification text: (line, directive codes).
        self.unjustified: List[tuple] = []
        for i, line in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(line)
            if not m:
                continue
            kind = m.group(1).lower()
            codes = {c.strip().upper() for c in m.group(2).split(",")}
            if not _JUSTIFIED_RE.search(line[m.end():]):
                self.unjustified.append((i, ",".join(sorted(codes))))
            if kind == "disable-file":
                self.file_level |= codes
            else:
                target = i
                if _COMMENT_ONLY_RE.match(line):
                    # A comment-only directive line guards the next line.
                    target = i + 1
                self.by_line.setdefault(target, set()).update(codes)

    def is_suppressed(self, finding: Finding) -> bool:
        def covers(codes: Set[str]) -> bool:
            return "ALL" in codes or finding.rule_id in codes

        if covers(self.file_level):
            return True
        codes = self.by_line.get(finding.line)
        return codes is not None and covers(codes)


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

BASELINE_VERSION = 1


def _fingerprints(findings: Sequence[Finding],
                  lines_by_path: Dict[str, List[str]]) -> List[str]:
    """Content fingerprint per finding: stable across pure line shifts.

    Duplicate (rule, path, line-text) triples are disambiguated by an
    occurrence counter so a second identical violation in the same file is
    still "new" relative to a one-entry baseline.
    """
    seen: Counter = Counter()
    prints = []
    for f in findings:
        lines = lines_by_path.get(f.path, [])
        text = lines[f.line - 1].strip() if 0 < f.line <= len(lines) else ""
        base = f"{f.rule_id}|{f.path}|{text}"
        occurrence = seen[base]
        seen[base] += 1
        digest = hashlib.sha1(f"{base}|{occurrence}".encode()).hexdigest()
        prints.append(digest[:20])
    return prints


def write_baseline(path: str, findings: Sequence[Finding],
                   lines_by_path: Dict[str, List[str]]) -> None:
    entries = [{
        "rule": f.rule_id,
        "path": f.path,
        "fingerprint": fp,
    } for f, fp in zip(findings, _fingerprints(findings, lines_by_path))]
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": BASELINE_VERSION, "findings": entries}, fh,
                  indent=2, sort_keys=True)
        fh.write("\n")


def load_baseline(path: str) -> Counter:
    with open(path, encoding="utf-8") as fh:
        data = json.load(fh)
    if data.get("version") != BASELINE_VERSION:
        raise ValueError(f"Unsupported baseline version in {path}: "
                         f"{data.get('version')!r}")
    return Counter(e["fingerprint"] for e in data.get("findings", []))


def filter_baselined(findings: Sequence[Finding],
                     lines_by_path: Dict[str, List[str]],
                     baseline: Counter) -> List[Finding]:
    """Findings not accounted for by the baseline (multiset semantics)."""
    remaining = Counter(baseline)
    new = []
    for f, fp in zip(findings, _fingerprints(findings, lines_by_path)):
        if remaining[fp] > 0:
            remaining[fp] -= 1
        else:
            new.append(f)
    return new


# ---------------------------------------------------------------------------
# Runner
# ---------------------------------------------------------------------------


def iter_python_files(paths: Sequence[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                yield p
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(d for d in dirnames
                                     if d != "__pycache__"
                                     and not d.startswith("."))
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)


def module_name(relpath: str) -> str:
    """Dotted module for a repo-relative path, anchored at the package
    root when the path runs through ``pipelinedp_tpu``."""
    parts = relpath.split("/")
    if parts[-1].endswith(".py"):
        parts[-1] = parts[-1][:-3]
    if parts[-1] == "__init__":
        parts.pop()
    if "pipelinedp_tpu" in parts:
        parts = parts[parts.index("pipelinedp_tpu"):]
    return ".".join(parts)


@dataclasses.dataclass
class LintResult:
    findings: List[Finding]          # post-suppression, pre-baseline
    suppressed: List[Finding]
    parse_errors: List[Finding]
    lines_by_path: Dict[str, List[str]]
    flow_cache_hits: int = 0
    flow_cache_misses: int = 0
    flow: Optional[object] = None    # ProjectFlow when project rules ran

    @property
    def all_reportable(self) -> List[Finding]:
        return self.parse_errors + self.findings


def default_rules() -> List[Rule]:
    from pipelinedp_tpu.lint.rules import ALL_RULES
    return [cls() for cls in ALL_RULES]


def lint_paths(paths: Sequence[str],
               config: Optional[LintConfig] = None,
               rules: Optional[Sequence[Rule]] = None,
               root: Optional[str] = None,
               flow_cache_path: Optional[str] = None,
               focus: Optional[Sequence[str]] = None) -> LintResult:
    """Runs every rule over every .py file under ``paths``.

    ``flow_cache_path`` persists the dpflow per-file summaries keyed by
    content digest (see lint/flow/cache.py); None keeps the flow layer
    fully in-memory.

    ``focus`` (the --changed-only shape) narrows *reporting*, not
    analysis: every file under ``paths`` is still parsed and summarized
    so the project rules see the whole call graph, but module rules run
    only on the focus files and project findings are kept only for
    modules connected to a focus module in the call graph — a hazard
    introduced in B must still surface at its manifestation site in an
    unchanged caller A.
    """
    config = config or DEFAULT_CONFIG
    rules = list(rules) if rules is not None else default_rules()
    root = os.path.abspath(root or os.getcwd())
    focus_rel: Optional[Set[str]] = None
    if focus is not None:
        focus_rel = {
            os.path.relpath(os.path.abspath(p), root).replace(os.sep, "/")
            for p in focus}
    findings: List[Finding] = []
    suppressed: List[Finding] = []
    parse_errors: List[Finding] = []
    lines_by_path: Dict[str, List[str]] = {}
    module_ctxs: Dict[str, ModuleContext] = {}
    digests: Dict[str, str] = {}
    suppressions_by_path: Dict[str, Suppressions] = {}
    module_rules = [r for r in rules if not isinstance(r, ProjectRule)]
    project_rules = [r for r in rules if isinstance(r, ProjectRule)]

    for path in iter_python_files(paths):
        abspath = os.path.abspath(path)
        relpath = os.path.relpath(abspath, root).replace(os.sep, "/")
        try:
            with open(abspath, encoding="utf-8") as fh:
                source = fh.read()
        except (OSError, UnicodeDecodeError) as e:
            parse_errors.append(
                Finding("DPL000", relpath, 1, 1, f"cannot read file: {e}"))
            continue
        lines = source.splitlines()
        lines_by_path[relpath] = lines
        try:
            tree = ast.parse(source, filename=relpath)
        except SyntaxError as e:
            parse_errors.append(
                Finding("DPL000", relpath, e.lineno or 1, 1,
                        f"syntax error: {e.msg}"))
            continue
        ctx = ModuleContext(path=abspath, relpath=relpath,
                            module=module_name(relpath), tree=tree,
                            lines=lines,
                            aliases=astutils.build_aliases(tree),
                            config=config)
        module_ctxs[relpath] = ctx
        digests[relpath] = hashlib.sha1(source.encode("utf-8")).hexdigest()
        suppressions = Suppressions(lines)
        suppressions_by_path[relpath] = suppressions
        if focus_rel is not None and relpath not in focus_rel:
            continue  # summarized for the graph, not module-linted
        for line, codes in suppressions.unjustified:
            # Unsuppressible by design: the fix is writing the reason.
            findings.append(Finding(
                "DPL000", relpath, line, 1,
                f"suppression of {codes} has no justification; append "
                f"the reviewed reason after the directive"))
        for rule in module_rules:
            for finding in rule.check(ctx):
                if suppressions.is_suppressed(finding):
                    suppressed.append(finding)
                else:
                    findings.append(finding)

    flow_hits = flow_misses = 0
    project_flow = None
    if project_rules and module_ctxs:
        from pipelinedp_tpu.lint import flow as flow_lib

        cache = flow_lib.FlowCache(flow_cache_path)
        summaries = {}
        for relpath, ctx in module_ctxs.items():
            digest = digests[relpath]
            summary = cache.get(relpath, digest)
            if summary is None:
                summary = flow_lib.extract_module(ctx.module, ctx.tree,
                                                  ctx.aliases)
                cache.put(relpath, digest, summary)
            summaries[relpath] = summary
        cache.save()
        flow_hits, flow_misses = cache.hits, cache.misses
        project_flow = flow_lib.ProjectFlow(summaries)
        project = ProjectContext(modules=module_ctxs, config=config,
                                 flow=project_flow)
        report_modules: Optional[Set[str]] = None
        if focus_rel is not None:
            report_modules = _connected_modules(
                project_flow,
                {ctx.module for rp, ctx in module_ctxs.items()
                 if rp in focus_rel})
        for rule in project_rules:
            for finding in rule.check_project(project):
                if report_modules is not None:
                    ctx = module_ctxs.get(finding.path)
                    if ctx is not None and \
                            ctx.module not in report_modules:
                        continue
                supp = suppressions_by_path.get(finding.path)
                if supp is not None and supp.is_suppressed(finding):
                    suppressed.append(finding)
                else:
                    findings.append(finding)

    key = lambda f: (f.path, f.line, f.col, f.rule_id)
    findings.sort(key=key)
    suppressed.sort(key=key)
    parse_errors.sort(key=key)
    return LintResult(findings, suppressed, parse_errors, lines_by_path,
                      flow_cache_hits=flow_hits,
                      flow_cache_misses=flow_misses,
                      flow=project_flow)


def _connected_modules(flow, seeds: Set[str]) -> Set[str]:
    """Modules connected to ``seeds`` in the undirected call graph —
    the set whose project findings a changed-only run must report: a
    changed callee can manifest a violation in its unchanged caller,
    and vice versa."""
    adjacency: Dict[str, Set[str]] = {}
    for qual in flow.functions:
        mod = flow.function_module[qual]
        for callee in flow.edges(qual):
            callee_mod = flow.function_module[callee]
            if callee_mod != mod:
                adjacency.setdefault(mod, set()).add(callee_mod)
                adjacency.setdefault(callee_mod, set()).add(mod)
    reached = set(seeds)
    frontier = list(seeds)
    while frontier:
        mod = frontier.pop()
        for nxt in adjacency.get(mod, ()):
            if nxt not in reached:
                reached.add(nxt)
                frontier.append(nxt)
    return reached
