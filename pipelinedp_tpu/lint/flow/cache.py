"""dpflow per-file digest cache: warm analyzer runs skip extraction.

The extraction walk (flow/summary.py) is a pure function of one file's
source text, so its output is cached keyed by the file's content digest.
A warm run over an unchanged tree loads every summary from the cache and
pays only the cross-file resolution passes (flow/graph.py), which are
cheap — that is what keeps the CI lint gate inside its wall-time budget
as the tree grows.

The cache is a single JSON file (default ``.dpflow-cache.json`` in the
invocation directory; ``--flow-cache``/``--no-flow-cache`` on the CLI).
It is safe to delete at any time and must NOT be committed — a stale or
corrupt cache entry is ignored (digest mismatch or schema drift), never
trusted.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, Optional

from pipelinedp_tpu.lint.flow.summary import ModuleSummary

CACHE_VERSION = 1
DEFAULT_CACHE_PATH = ".dpflow-cache.json"


def source_digest(source: str) -> str:
    return hashlib.sha1(source.encode("utf-8")).hexdigest()


class FlowCache:
    """Digest-keyed summary store with hit/miss counters."""

    def __init__(self, path: Optional[str]):
        self.path = path
        self.hits = 0
        self.misses = 0
        self._entries: Dict[str, dict] = {}
        self._dirty = False
        if path is not None and os.path.exists(path):
            try:
                with open(path, encoding="utf-8") as fh:
                    data = json.load(fh)
                if data.get("version") == CACHE_VERSION:
                    self._entries = dict(data.get("files", {}))
            except (OSError, ValueError):
                self._entries = {}  # corrupt cache: rebuild from scratch

    def get(self, relpath: str, digest: str) -> Optional[ModuleSummary]:
        entry = self._entries.get(relpath)
        if entry is not None and entry.get("digest") == digest:
            summary = ModuleSummary.from_json(entry.get("summary", {}))
            if summary is not None:
                self.hits += 1
                return summary
        self.misses += 1
        return None

    def put(self, relpath: str, digest: str,
            summary: ModuleSummary) -> None:
        self._entries[relpath] = {"digest": digest,
                                  "summary": summary.to_json()}
        self._dirty = True

    def save(self) -> None:
        if self.path is None or not self._dirty:
            return
        payload = {"version": CACHE_VERSION, "files": self._entries}
        tmp = f"{self.path}.tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(payload, fh, sort_keys=True)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except OSError:
            pass  # a cache that cannot persist is a slow run, not an error
