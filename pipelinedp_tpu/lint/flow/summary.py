"""dpflow per-module extraction: one AST walk -> a serializable summary.

The flow layer (LINT.md "dpflow") splits interprocedural analysis into a
per-file *extraction* pass and a cross-file *resolution* pass
(flow/graph.py). Everything extracted here is a pure function of one
file's source text, which is what makes the digest cache (flow/cache.py)
sound: a file whose content hash is unchanged contributes the identical
summary, so warm runs skip the walk entirely.

A :class:`ModuleSummary` carries, per function (including methods and
nested ``<locals>`` functions):

  * every call site with its alias-resolved dotted target — lexically
    visible local/module functions resolve to their full project
    qualname, ``self.x()`` inside a class resolves through the class when
    it defines ``x`` and is left as a ``self:Cls.x`` marker for the
    cross-module MRO walk otherwise;
  * taint flows for DPL007: how values originating in private-column
    parameters reach host-materialization sinks or project callees, and
    which sanitization flags (contribution bounding / noise) the value
    gained on the way;
  * pool-worker hazards for DPL008: unguarded writes, from callables
    handed to an executor/thread, to state shared with the enclosing
    scope — decidable per file, so the summary stores finished hazards;
  * donated-argument positions for DPL010 (``donate_argnums`` on a
    ``jax.jit`` decorator or wrapper assignment);
  * the **dpverify effect trace** for DPL012–DPL015: the function's
    ordered durable/concurrency effects — ``wal_append``, ``fsync``,
    ``rename``, ``raw_durable_write``, ``lock_acquire`` (with the lock
    name and the guarded line span), ``noise_draw``, ``release_commit``,
    ``unordered_iter``, ``eager_jnp_arith``, ``wallclock_source``, plus
    the bookkeeping kinds ``tmp_create``, ``lock_create`` and
    ``state_mutation`` the rules need to model the tmp+fsync+rename
    idiom, the project lock graph and the commit-ordering contract.
"""

from __future__ import annotations

import ast
import dataclasses
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from pipelinedp_tpu.lint import astutils

SUMMARY_VERSION = 4  # v4: PR-16 dpverify effect traces (DPL012-DPL015)

# -- taint vocabulary (DPL007) ----------------------------------------------

FLAG_BOUND = "bound"
FLAG_NOISE = "noise"
ALL_FLAGS = frozenset((FLAG_BOUND, FLAG_NOISE))

# Parameters holding raw private columns (taint with no flags) and
# bounded-but-unnoised aggregates (taint with FLAG_BOUND).
RAW_PARAM_RE = re.compile(r"^(?:pid|pids|pk|pks|value|values|raw_values)$")
BOUNDED_PARAM_RE = re.compile(r"^(?:accs|acc|accumulators|qhist)$")

# Call targets that *sanitize*: passing a tainted value through one of
# these (or through a project function that transitively reaches one)
# adds the flag to the flowing value.
BOUND_TARGET_RE = re.compile(
    r"(?:^|\.)(?:bound_and_aggregate(?:_compact)?|bound_row_mask|"
    r"bound_contributions)$|(?:^|\.)contribution_bounders\.")
NOISE_TARGET_RE = re.compile(
    r"(?:^|\.)noise_core\.(?:add_|sample_)|"
    r"^pipelinedp_tpu\.ops\.noise\.|"
    r"^jax\.random\.(?:laplace|normal)$")

# Host-materialization sinks: a value leaving the device/accumulator
# world for host python. ``.tolist()`` is matched structurally (method
# call on a tainted expression).
SINK_TARGETS = frozenset({"jax.device_get"})
SINK_METHOD = "tolist"

# Telemetry sinks (DPL011): any obs.* record/span-attribute API.
# Telemetry is operator-visible and outside the DP mechanism, so a
# private value reaching one of these is a leak even when
# contribution-bounded — only fully released (bounded AND noised)
# aggregates may enter an obs record. Resolved ``pipelinedp_tpu.obs.*``
# targets match by module; ``.set_attribute()`` / ``.add_event()`` /
# ``.observe()`` / ``.record()`` match structurally (the obs objects —
# spans, histograms, audit trails — are usually held in attributes the
# resolver cannot type).
OBS_TARGET_RE = re.compile(r"^pipelinedp_tpu\.obs\.")
OBS_METHODS = frozenset({"set_attribute", "add_event", "observe",
                         "record", "write_capture"})

# Shape-preserving transforms: taint flows through unchanged.
_PASSTHROUGH_RE = re.compile(r"^(?:numpy|jax\.numpy|jax\.lax)\.")
_PASSTHROUGH_BUILTINS = frozenset({
    "tuple", "list", "abs", "min", "max", "sum", "sorted", "reversed",
    "zip", "enumerate", "float", "int",
})

# Release-randomness draws (DPL009): actual noise/selection sampling,
# deliberately NOT the contribution-bounding samplers (jax.random inside
# ops/columnar) — bounding randomness is pre-release and legitimately
# precedes the journal commit.
DRAW_TARGET_RE = re.compile(
    r"(?:^|\.)noise_core\.(?:add_|sample_)|"
    r"^pipelinedp_tpu\.ops\.noise\.|"
    r"(?:^|\.)select_partitions$|(?:^|\.)select_vec$")

# Journal-commit calls (DPL009 anchors).
COMMIT_TARGET_RE = re.compile(r"(?:^|\.)_?commit(?:_release)?$")

# Mutating container methods (DPL008 write detection).
_MUTATORS = frozenset({
    "append", "extend", "add", "update", "insert", "remove", "discard",
    "pop", "popitem", "clear", "setdefault", "appendleft",
})

# -- dpverify effect vocabulary (DPL012-DPL015) ------------------------------

EFFECT_WAL_APPEND = "wal_append"
EFFECT_FSYNC = "fsync"
EFFECT_RENAME = "rename"
EFFECT_RAW_WRITE = "raw_durable_write"
EFFECT_TMP_CREATE = "tmp_create"
EFFECT_LOCK_ACQUIRE = "lock_acquire"
EFFECT_LOCK_CREATE = "lock_create"
EFFECT_NOISE_DRAW = "noise_draw"
EFFECT_RELEASE_COMMIT = "release_commit"
EFFECT_UNORDERED_ITER = "unordered_iter"
EFFECT_EAGER_JNP = "eager_jnp_arith"
EFFECT_WALLCLOCK = "wallclock_source"
EFFECT_STATE_MUTATION = "state_mutation"

# `self._wal.append(...)` / `wal.append(...)` — the WAL commit point.
# Matched against the module-locally resolved call target, so the
# `self:Cls._wal.append` markers the resolver leaves for untyped
# attribute receivers match too.
WAL_APPEND_TARGET_RE = re.compile(r"(?:^|\.)_?wal\.append$")
FSYNC_TARGETS = frozenset({"os.fsync"})
RENAME_TARGETS = frozenset({"os.replace", "os.rename"})
TMPFILE_TARGETS = frozenset({
    "tempfile.mkstemp", "tempfile.NamedTemporaryFile", "tempfile.mkdtemp",
})
# File-handle constructors whose mode argument decides writability.
_OPEN_TARGETS = frozenset({"open", "io.open", "os.fdopen", "gzip.open"})
_WRITE_MODE_RE = re.compile(r"[wax+]")
_LOCK_CLASS_TARGETS = frozenset({"threading.Lock", "threading.RLock"})
# Wall-clock / uuid sources that must never feed seeds, keys or tokens
# on a release path (DPL015); perf_counter/monotonic are deliberately
# absent — they feed latency metrics, not identity.
WALLCLOCK_TARGET_RE = re.compile(
    r"^(?:time\.time(?:_ns)?|uuid\.uuid[14])$|"
    r"(?:^|\.)datetime\.(?:now|utcnow|today)$|(?:^|\.)date\.today$")
SEEDISH_NAME_RE = re.compile(
    r"(?:^|_)(?:seed|key|token|nonce|salt)s?(?:_|$)", re.IGNORECASE)
# Iteration sources with no deterministic order: sets (dicts are
# insertion-ordered and deterministic since 3.7) and unsorted directory
# listings. `sorted(set(...))` never matches — the iterable inspected is
# the outermost expression.
_UNORDERED_CALL_TARGETS = frozenset({
    "set", "frozenset", "os.listdir", "os.scandir",
})
_UNORDERED_SET_METHODS = frozenset({
    "intersection", "union", "difference", "symmetric_difference",
})
# Eager `jnp` arithmetic (the PR-4 FMA-contraction bug class): outside
# jit the XLA fusion decisions — and therefore the bits — can differ
# from the compiled release path.
JNP_ARITH_RE = re.compile(
    r"^jax\.numpy\.(?:add|subtract|multiply|divide|true_divide|"
    r"floor_divide|mod|power|sum|prod|mean|var|std|dot|matmul|tensordot|"
    r"exp|expm1|log|log1p|log2|sqrt|square|abs|absolute|maximum|minimum|"
    r"clip|where|cumsum|cumprod|round|floor|ceil|sign|reciprocal)$")


@dataclasses.dataclass(frozen=True)
class CallSite:
    """One call with its alias-resolved dotted target.

    ``target`` forms: a full dotted name ("jax.device_get",
    "pipelinedp_tpu.noise_core.add_laplace_noise_array"), a project
    qualname when the callee is lexically visible in the same module,
    a ``self:Cls.meth`` marker for unresolved method calls on self/cls,
    or "" when the callee expression has no dotted name (e.g. a call on
    a subscript).
    """
    target: str
    line: int

    def to_json(self) -> list:
        return [self.target, self.line]

    @staticmethod
    def from_json(data: Sequence) -> "CallSite":
        return CallSite(target=data[0], line=int(data[1]))


@dataclasses.dataclass(frozen=True)
class TaintFlow:
    """One DPL007 flow event inside a function.

    kind == "sink": a value originating in param ``origin`` reached the
    host sink ``detail`` at ``line`` having gained ``gained`` flags.
    kind == "obs": same, but the sink is a telemetry record/attribute
    API (DPL011) instead of a host materialization.
    kind == "call": the value was passed to project callee ``detail`` at
    positional ``arg_pos`` — exposure depends on the callee's summary.
    """
    origin: str
    gained: Tuple[str, ...]
    kind: str
    line: int
    detail: str
    arg_pos: int = -1

    def to_json(self) -> list:
        return [self.origin, list(self.gained), self.kind, self.line,
                self.detail, self.arg_pos]

    @staticmethod
    def from_json(data: Sequence) -> "TaintFlow":
        return TaintFlow(origin=data[0], gained=tuple(data[1]),
                         kind=data[2], line=int(data[3]), detail=data[4],
                         arg_pos=int(data[5]))


@dataclasses.dataclass(frozen=True)
class PoolHazard:
    """One DPL008 finding candidate — fully decided at extraction."""
    line: int
    col: int
    worker: str  # worker callable name
    name: str    # the captured variable written
    write: str   # human-readable write description
    shared_line: int  # where the enclosing scope touches the same name

    def to_json(self) -> list:
        return [self.line, self.col, self.worker, self.name, self.write,
                self.shared_line]

    @staticmethod
    def from_json(data: Sequence) -> "PoolHazard":
        return PoolHazard(line=int(data[0]), col=int(data[1]),
                          worker=data[2], name=data[3], write=data[4],
                          shared_line=int(data[5]))


@dataclasses.dataclass(frozen=True)
class Effect:
    """One ordered durable/concurrency effect (dpverify, DPL012-DPL015).

    ``detail`` carries the effect's operand: the resolved call target
    (draws, fsync), the open() mode for ``raw_durable_write``, the lock
    name for ``lock_acquire``/``lock_create`` (``Cls:attr`` for
    ``self.attr`` locks, the raw dotted name otherwise), the mutated
    ``self.x`` root for ``state_mutation``, or ``source->name`` for a
    ``wallclock_source`` feeding a seed/key/token binding. ``end`` is
    the last guarded line of a ``lock_acquire`` with-block (-1 when the
    span is unknown, e.g. a bare ``.acquire()``).
    """
    kind: str
    line: int
    detail: str = ""
    end: int = -1

    def to_json(self) -> list:
        return [self.kind, self.line, self.detail, self.end]

    @staticmethod
    def from_json(data: Sequence) -> "Effect":
        return Effect(kind=data[0], line=int(data[1]), detail=data[2],
                      end=int(data[3]))


@dataclasses.dataclass
class FunctionSummary:
    name: str       # qualified within the module: "f", "Cls.meth",
    #                 "outer.<locals>.inner"
    line: int
    params: Tuple[str, ...]
    calls: Tuple[CallSite, ...]
    flows: Tuple[TaintFlow, ...]
    hazards: Tuple[PoolHazard, ...]
    donated: Tuple[int, ...]  # donate_argnums positions, if jit-donating
    effects: Tuple[Effect, ...] = ()  # ordered dpverify effect trace

    def to_json(self) -> dict:
        return {
            "name": self.name,
            "line": self.line,
            "params": list(self.params),
            "calls": [c.to_json() for c in self.calls],
            "flows": [f.to_json() for f in self.flows],
            "hazards": [h.to_json() for h in self.hazards],
            "donated": list(self.donated),
            "effects": [e.to_json() for e in self.effects],
        }

    @staticmethod
    def from_json(data: dict) -> "FunctionSummary":
        return FunctionSummary(
            name=data["name"],
            line=int(data["line"]),
            params=tuple(data["params"]),
            calls=tuple(CallSite.from_json(c) for c in data["calls"]),
            flows=tuple(TaintFlow.from_json(f) for f in data["flows"]),
            hazards=tuple(PoolHazard.from_json(h) for h in data["hazards"]),
            donated=tuple(int(i) for i in data["donated"]),
            effects=tuple(Effect.from_json(e) for e in data["effects"]),
        )

    def effects_of(self, *kinds: str) -> Tuple[Effect, ...]:
        return tuple(e for e in self.effects if e.kind in kinds)


@dataclasses.dataclass
class ModuleSummary:
    module: str
    functions: Dict[str, FunctionSummary]  # keyed by in-module qualname
    classes: Dict[str, Tuple[str, ...]]    # class name -> resolved bases
    aliases: Dict[str, str]                # import/re-export aliases
    # Lock objects this module *creates*: bare names for module-level
    # locks, "Cls.attr" for `self.attr = threading.Lock()` in a method.
    # The DPL014 lock graph canonicalizes `self._lock` acquires through
    # the MRO to the creating class, so a lock inherited from a base
    # class is one graph node, not one per subclass.
    locks: Tuple[str, ...] = ()

    def to_json(self) -> dict:
        return {
            "version": SUMMARY_VERSION,
            "module": self.module,
            "functions": {k: f.to_json()
                          for k, f in self.functions.items()},
            "classes": {k: list(v) for k, v in self.classes.items()},
            "aliases": dict(self.aliases),
            "locks": list(self.locks),
        }

    @staticmethod
    def from_json(data: dict) -> Optional["ModuleSummary"]:
        if data.get("version") != SUMMARY_VERSION:
            return None
        return ModuleSummary(
            module=data["module"],
            functions={k: FunctionSummary.from_json(f)
                       for k, f in data["functions"].items()},
            classes={k: tuple(v) for k, v in data["classes"].items()},
            aliases=dict(data["aliases"]),
            locks=tuple(data.get("locks", ())),
        )


# ---------------------------------------------------------------------------
# Extraction
# ---------------------------------------------------------------------------


def _donated_argnums(fn: ast.AST, aliases: Dict[str, str]) -> Tuple[int, ...]:
    """donate_argnums positions from a jit decorator, else ()."""
    for deco in getattr(fn, "decorator_list", ()):
        nums = _donate_from_jit_call(deco, aliases)
        if nums:
            return nums
    return ()


def _donate_from_jit_call(node: ast.AST,
                          aliases: Dict[str, str]) -> Tuple[int, ...]:
    """donate_argnums out of `jax.jit(...)` / `functools.partial(jax.jit,
    ...)` call expressions (decorators or wrapper assignments)."""
    if not isinstance(node, ast.Call):
        return ()
    target = astutils.call_target(node, aliases)
    is_jit = target == "jax.jit"
    if target == "functools.partial" and node.args:
        inner = astutils.resolve(node.args[0], aliases)
        is_jit = inner == "jax.jit"
    if not is_jit:
        return ()
    for kw in node.keywords:
        if kw.arg == "donate_argnums":
            value = kw.value
            if isinstance(value, (ast.Tuple, ast.List)):
                elts = value.elts
            else:
                elts = [value]
            nums = []
            for e in elts:
                n = astutils.literal_number(e)
                if n is not None:
                    nums.append(int(n))
            return tuple(nums)
    return ()


class _Scope:
    """Lexical function scope during extraction."""

    def __init__(self, qual: str, node: ast.AST, parent: Optional["_Scope"],
                 cls: Optional[str]):
        self.qual = qual
        self.node = node
        self.parent = parent
        self.cls = cls  # enclosing class name for methods
        # name -> in-module qualname of lexically visible nested defs
        self.local_defs: Dict[str, str] = {}


class Extractor(ast.NodeVisitor):
    """One-pass extraction of a ModuleSummary from a parsed module."""

    def __init__(self, module: str, tree: ast.AST,
                 aliases: Dict[str, str]):
        self.module = module
        self.tree = tree
        self.aliases = dict(aliases)
        self.functions: Dict[str, FunctionSummary] = {}
        self.classes: Dict[str, Tuple[str, ...]] = {}
        self.locks: Set[str] = set()
        self._module_defs: Dict[str, str] = {}

    def run(self) -> ModuleSummary:
        self._collect_module_level()
        scope = _Scope(qual="", node=self.tree, parent=None, cls=None)
        scope.local_defs = dict(self._module_defs)
        for node in ast.iter_child_nodes(self.tree):
            self._walk_container(node, scope, cls=None)
        return ModuleSummary(module=self.module, functions=self.functions,
                             classes=self.classes, aliases=self.aliases,
                             locks=tuple(sorted(self.locks)))

    # -- module-level symbol discovery --------------------------------------

    def _collect_module_level(self) -> None:
        for node in ast.iter_child_nodes(self.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._module_defs[node.name] = node.name
            elif isinstance(node, ast.ClassDef):
                bases = []
                for b in node.bases:
                    resolved = astutils.resolve(b, self.aliases)
                    if resolved:
                        bases.append(resolved)
                self.classes[node.name] = tuple(bases)
                for meth in ast.iter_child_nodes(node):
                    if isinstance(meth, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self._module_defs.setdefault(
                            f"{node.name}.{meth.name}",
                            f"{node.name}.{meth.name}")
            elif isinstance(node, ast.Assign):
                # Module-level re-export: `name = other.thing` /
                # `name = thing` extends the alias map, and
                # `name = jax.jit(f, donate_argnums=...)` registers a
                # donating wrapper under `name`.
                if len(node.targets) == 1 and isinstance(
                        node.targets[0], ast.Name):
                    target_name = node.targets[0].id
                    if isinstance(node.value, ast.Call) and \
                            astutils.call_target(
                                node.value,
                                self.aliases) in _LOCK_CLASS_TARGETS:
                        self.locks.add(target_name)
                    resolved = astutils.resolve(node.value, self.aliases)
                    if resolved is not None:
                        self.aliases.setdefault(target_name, resolved)
                    nums = _donate_from_jit_call(node.value, self.aliases)
                    if nums and isinstance(node.value, ast.Call):
                        wrapped = (node.value.args[0]
                                   if node.value.args else None)
                        self.functions[target_name] = FunctionSummary(
                            name=target_name, line=node.lineno, params=(),
                            calls=(CallSite(
                                astutils.resolve(wrapped, self.aliases)
                                or "", node.lineno),) if wrapped else (),
                            flows=(), hazards=(), donated=nums)

    # -- scope walking ------------------------------------------------------

    def _walk_container(self, node: ast.AST, scope: _Scope,
                        cls: Optional[str]) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._extract_function(node, scope, cls)
        elif isinstance(node, ast.ClassDef):
            for child in ast.iter_child_nodes(node):
                self._walk_container(child, scope, cls=node.name)

    def _extract_function(self, fn, parent_scope: _Scope,
                          cls: Optional[str]) -> None:
        if cls and not parent_scope.qual:
            qual = f"{cls}.{fn.name}"
        elif parent_scope.qual:
            qual = f"{parent_scope.qual}.<locals>.{fn.name}"
        else:
            qual = fn.name
        scope = _Scope(qual=qual, node=fn, parent=parent_scope, cls=cls)
        # Lexically visible defs: enclosing scopes first, then own nested.
        visible = dict(parent_scope.local_defs)
        for child in ast.iter_child_nodes(fn):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                visible[child.name] = f"{qual}.<locals>.{child.name}"
        scope.local_defs = visible

        args = fn.args
        params = tuple(a.arg for a in (list(args.posonlyargs) +
                                       list(args.args) +
                                       list(args.kwonlyargs)))
        calls = self._collect_calls(fn, scope)
        flows = _TaintWalker(self, scope).run(fn, params)
        hazards = _find_pool_hazards(self, fn, scope)
        effects = _EffectWalker(self, scope).run(fn)
        self.functions[qual] = FunctionSummary(
            name=qual, line=fn.lineno, params=params, calls=tuple(calls),
            flows=tuple(flows), hazards=tuple(hazards),
            donated=_donated_argnums(fn, self.aliases),
            effects=tuple(effects))
        for child in ast.iter_child_nodes(fn):
            self._walk_container(child, scope, cls=None)

    # -- call resolution ----------------------------------------------------

    def resolve_call(self, node: ast.Call, scope: _Scope) -> str:
        """The dotted target of a call, module-locally resolved."""
        dotted = astutils.dotted_name(node.func)
        if dotted is None:
            return ""
        head, _, rest = dotted.partition(".")
        if head in ("self", "cls") and scope.cls_context() is not None:
            cls = scope.cls_context()
            meth = rest.split(".")[0] if rest else ""
            local = f"{cls}.{meth}"
            if local in self._module_defs and not rest.partition(".")[2]:
                return f"{self.module}.{local}"
            return f"self:{cls}.{rest}" if rest else dotted
        if not rest and dotted in scope.local_defs:
            return f"{self.module}.{scope.local_defs[dotted]}"
        resolved = astutils.resolve(node.func, self.aliases)
        return resolved or dotted

    def _collect_calls(self, fn, scope: _Scope) -> List[CallSite]:
        calls: List[CallSite] = []
        own_nested = {id(c) for c in ast.iter_child_nodes(fn)
                      if isinstance(c, (ast.FunctionDef,
                                        ast.AsyncFunctionDef))}

        def walk(node):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef, ast.ClassDef)):
                    continue  # nested scopes summarized separately
                if isinstance(child, ast.Call):
                    calls.append(CallSite(self.resolve_call(child, scope),
                                          child.lineno))
                walk(child)

        walk(fn)
        return calls


def _scope_cls(scope: _Scope) -> Optional[str]:
    s = scope
    while s is not None:
        if s.cls is not None:
            return s.cls
        s = s.parent
    return None


_Scope.cls_context = _scope_cls


def extract_module(module: str, tree: ast.AST,
                   aliases: Dict[str, str]) -> ModuleSummary:
    return Extractor(module, tree, aliases).run()


def iter_scopes(module: str, tree: ast.AST, aliases: Dict[str, str]):
    """Yields ``(qualname, function_node, scope, extractor)`` for every
    function scope in a module, with the extractor's ``resolve_call``
    usable against the yielded scope — the shared walk for analyses that
    need the AST at analysis time (DPL010's path-sensitive pass)."""
    ex = Extractor(module, tree, aliases)
    ex._collect_module_level()
    root = _Scope(qual="", node=tree, parent=None, cls=None)
    root.local_defs = dict(ex._module_defs)
    out = []

    def walk(node, parent_scope, cls):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if cls and not parent_scope.qual:
                qual = f"{cls}.{node.name}"
            elif parent_scope.qual:
                qual = f"{parent_scope.qual}.<locals>.{node.name}"
            else:
                qual = node.name
            scope = _Scope(qual=qual, node=node, parent=parent_scope,
                           cls=cls)
            visible = dict(parent_scope.local_defs)
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    visible[child.name] = f"{qual}.<locals>.{child.name}"
            scope.local_defs = visible
            out.append((qual, node, scope, ex))
            for child in ast.iter_child_nodes(node):
                walk(child, scope, None)
        elif isinstance(node, ast.ClassDef):
            for child in ast.iter_child_nodes(node):
                walk(child, parent_scope, node.name)
        else:
            for child in ast.iter_child_nodes(node):
                walk(child, parent_scope, cls)

    for child in ast.iter_child_nodes(tree):
        walk(child, root, None)
    return out


# ---------------------------------------------------------------------------
# dpverify effect extraction (DPL012-DPL015)
# ---------------------------------------------------------------------------


def _is_jitted(fn, aliases: Dict[str, str]) -> bool:
    """True when the function compiles under a jit decorator — its
    arithmetic is a fixed XLA program, not eager dispatch."""
    for deco in getattr(fn, "decorator_list", ()):
        if isinstance(deco, ast.Call):
            target = astutils.call_target(deco, aliases)
            if target == "jax.jit":
                return True
            if target == "functools.partial" and deco.args and \
                    astutils.resolve(deco.args[0], aliases) == "jax.jit":
                return True
        elif astutils.resolve(deco, aliases) == "jax.jit":
            return True
    return False


def _self_root(node: ast.AST) -> Optional[str]:
    """``self.attr`` dotted root of a write target (subscripts stripped),
    or None when the target is not instance state."""
    while isinstance(node, ast.Subscript):
        node = node.value
    dotted = astutils.dotted_name(node)
    if dotted and dotted.startswith("self.") and dotted.count(".") >= 1:
        return ".".join(dotted.split(".")[:2])
    return None


def _binding_name(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        return node.attr
    return None


class _EffectWalker:
    """Ordered dpverify effect trace of one function body.

    Nested function/class scopes are excluded — they summarize
    separately, exactly like call collection. Statements are visited in
    source order, so line order reflects execution order on the
    straight-line path; that ordering is what the DPL012/DPL013 idiom
    and commit-ordering checks consume.
    """

    def __init__(self, extractor: Extractor, scope: _Scope):
        self.ex = extractor
        self.scope = scope
        self.effects: List[Effect] = []
        self.jitted = False

    def run(self, fn) -> List[Effect]:
        self.jitted = _is_jitted(fn, self.ex.aliases)
        for stmt in fn.body:
            self._visit(stmt)
        self.effects.sort(key=lambda e: e.line)
        return self.effects

    def _visit(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            end = max((getattr(n, "lineno", node.lineno)
                       for n in ast.walk(node)), default=node.lineno)
            for item in node.items:
                self._with_item(item, end)
                self._visit(item.context_expr)
            for stmt in node.body:
                self._visit(stmt)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._assign(node)
        if isinstance(node, (ast.For, ast.AsyncFor)):
            self._iter_source(node.iter)
        if isinstance(node, ast.comprehension):
            self._iter_source(node.iter)
        if isinstance(node, ast.Call):
            self._call(node)
        for child in ast.iter_child_nodes(node):
            self._visit(child)

    # -- with-blocks: lock acquisition with its guarded span ----------------

    def _with_item(self, item: ast.withitem, end: int) -> None:
        expr = item.context_expr
        callee = expr.func if isinstance(expr, ast.Call) else expr
        dotted = astutils.dotted_name(callee)
        if dotted and _LOCKISH_RE.search(dotted.split(".")[-1]):
            self.effects.append(Effect(
                EFFECT_LOCK_ACQUIRE, expr.lineno,
                self._lock_name(dotted), end))

    def _lock_name(self, dotted: str) -> str:
        cls = self.scope.cls_context()
        for head in ("self.", "cls."):
            if dotted.startswith(head) and cls:
                return f"{cls}:{dotted[len(head):]}"
        return dotted

    # -- assignments: lock creation, state mutation, wallclock seeds -------

    def _assign(self, node) -> None:
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        value = getattr(node, "value", None)
        if isinstance(value, ast.Call) and \
                astutils.call_target(value,
                                     self.ex.aliases) in _LOCK_CLASS_TARGETS:
            cls = self.scope.cls_context()
            for t in targets:
                dotted = astutils.dotted_name(t)
                if dotted and dotted.startswith("self.") and cls:
                    attr = dotted[len("self."):]
                    self.ex.locks.add(f"{cls}.{attr}")
                    self.effects.append(Effect(
                        EFFECT_LOCK_CREATE, node.lineno, f"{cls}:{attr}"))
            return  # a lock binding is not transactional state
        for t in targets:
            root = _self_root(t)
            if root is not None:
                self.effects.append(Effect(
                    EFFECT_STATE_MUTATION, node.lineno, root))
        if value is not None:
            wc = self._wallclock_in(value)
            if wc:
                for t in targets:
                    name = _binding_name(t)
                    if name and SEEDISH_NAME_RE.search(name):
                        self.effects.append(Effect(
                            EFFECT_WALLCLOCK, node.lineno,
                            f"{wc}->{name}"))

    def _wallclock_in(self, node: ast.AST) -> Optional[str]:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                target = astutils.call_target(sub, self.ex.aliases)
                if target and WALLCLOCK_TARGET_RE.search(target):
                    return target
        return None

    # -- iteration order ----------------------------------------------------

    def _iter_source(self, iter_expr: ast.AST) -> None:
        detail = None
        if isinstance(iter_expr, ast.Set):
            detail = "set literal"
        elif isinstance(iter_expr, ast.Call):
            target = self.ex.resolve_call(iter_expr, self.scope)
            if target in _UNORDERED_CALL_TARGETS:
                detail = f"{target}()"
            elif isinstance(iter_expr.func, ast.Attribute) and \
                    iter_expr.func.attr in _UNORDERED_SET_METHODS:
                detail = f".{iter_expr.func.attr}()"
        if detail is not None:
            self.effects.append(Effect(
                EFFECT_UNORDERED_ITER, iter_expr.lineno, detail))

    # -- calls --------------------------------------------------------------

    @staticmethod
    def _open_mode(node: ast.Call) -> Optional[str]:
        mode = node.args[1] if len(node.args) >= 2 else None
        for kw in node.keywords:
            if kw.arg == "mode":
                mode = kw.value
        if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
            return mode.value
        return None

    def _call(self, node: ast.Call) -> None:
        target = self.ex.resolve_call(node, self.scope)
        line = node.lineno
        if WAL_APPEND_TARGET_RE.search(target):
            self.effects.append(Effect(EFFECT_WAL_APPEND, line, target))
        elif target in FSYNC_TARGETS:
            self.effects.append(Effect(EFFECT_FSYNC, line, target))
        elif target in RENAME_TARGETS:
            self.effects.append(Effect(EFFECT_RENAME, line, target))
        elif target in TMPFILE_TARGETS:
            self.effects.append(Effect(EFFECT_TMP_CREATE, line, target))
        elif target in _OPEN_TARGETS:
            mode = self._open_mode(node)
            if mode is not None and _WRITE_MODE_RE.search(mode):
                self.effects.append(Effect(EFFECT_RAW_WRITE, line, mode))
        if DRAW_TARGET_RE.search(target):
            self.effects.append(Effect(EFFECT_NOISE_DRAW, line, target))
        elif COMMIT_TARGET_RE.search(target):
            self.effects.append(Effect(EFFECT_RELEASE_COMMIT, line,
                                       target))
        if not self.jitted and JNP_ARITH_RE.match(target):
            self.effects.append(Effect(EFFECT_EAGER_JNP, line, target))
        for kw in node.keywords:
            if kw.arg and SEEDISH_NAME_RE.search(kw.arg):
                wc = self._wallclock_in(kw.value)
                if wc:
                    self.effects.append(Effect(
                        EFFECT_WALLCLOCK, line, f"{wc}->{kw.arg}"))
        if isinstance(node.func, ast.Attribute):
            if node.func.attr == "acquire":
                dotted = astutils.dotted_name(node.func.value)
                if dotted and _LOCKISH_RE.search(dotted.split(".")[-1]):
                    self.effects.append(Effect(
                        EFFECT_LOCK_ACQUIRE, line,
                        self._lock_name(dotted), -1))
            elif node.func.attr in _MUTATORS:
                root = _self_root(node.func.value)
                if root is not None:
                    self.effects.append(Effect(
                        EFFECT_STATE_MUTATION, line,
                        f"{root}.{node.func.attr}()"))


# ---------------------------------------------------------------------------
# DPL007 intraprocedural taint walk
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class _Taint:
    origin: str
    gained: frozenset


class _TaintWalker:
    """Forward walk of one function body tracking private-column taint.

    Precision over recall, the dplint house stance: a value returned by
    an unrecognized callee stops being tracked (no type inference), and
    merges across branches keep only flags guaranteed on every tainted
    path.
    """

    def __init__(self, extractor: Extractor, scope: _Scope):
        self.ex = extractor
        self.scope = scope
        self.flows: List[TaintFlow] = []

    def run(self, fn, params: Tuple[str, ...]) -> List[TaintFlow]:
        state: Dict[str, _Taint] = {}
        for p in params:
            if RAW_PARAM_RE.match(p):
                state[p] = _Taint(p, frozenset())
            elif BOUNDED_PARAM_RE.match(p):
                state[p] = _Taint(p, frozenset((FLAG_BOUND,)))
        if state:
            self._block(fn.body, state)
        return self.flows

    # -- statements ---------------------------------------------------------

    def _block(self, stmts, state) -> None:
        for stmt in stmts:
            self._statement(stmt, state)

    def _statement(self, stmt, state) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, ast.Assign):
            taint = self._expr(stmt.value, state)
            for target in stmt.targets:
                self._bind(target, taint, state)
            return
        if isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
            if getattr(stmt, "value", None) is not None:
                taint = self._expr(stmt.value, state)
                self._bind(stmt.target, taint, state)
            return
        if isinstance(stmt, ast.If):
            self._expr(stmt.test, state)
            states = []
            for branch in (stmt.body, stmt.orelse):
                bstate = dict(state)
                self._block(branch, bstate)
                states.append(bstate)
            merged: Dict[str, _Taint] = {}
            for name in set(states[0]) | set(states[1]):
                taints = [s[name] for s in states if name in s]
                gained = frozenset.intersection(
                    *(t.gained for t in taints))
                merged[name] = _Taint(taints[0].origin, gained)
            state.clear()
            state.update(merged)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter, state)
            self._bind(stmt.target, None, state)
            for _ in range(2):
                self._block(stmt.body, state)
            self._block(stmt.orelse, state)
            return
        if isinstance(stmt, ast.While):
            self._expr(stmt.test, state)
            for _ in range(2):
                self._block(stmt.body, state)
            self._block(stmt.orelse, state)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._expr(item.context_expr, state)
            self._block(stmt.body, state)
            return
        if isinstance(stmt, ast.Try):
            self._block(stmt.body, state)
            for handler in stmt.handlers:
                self._block(handler.body, dict(state))
            self._block(stmt.orelse, state)
            self._block(stmt.finalbody, state)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._expr(child, state)

    def _bind(self, target, taint: Optional[_Taint], state) -> None:
        names: List[str] = []
        if isinstance(target, ast.Name):
            names = [target.id]
        elif isinstance(target, (ast.Tuple, ast.List)):
            names = [e.id for e in target.elts if isinstance(e, ast.Name)]
        for name in names:
            if taint is None:
                state.pop(name, None)
            else:
                state[name] = taint

    # -- expressions --------------------------------------------------------

    def _expr(self, node, state) -> Optional[_Taint]:
        if node is None:
            return None
        if isinstance(node, ast.Name):
            return state.get(node.id)
        if isinstance(node, ast.Call):
            return self._call(node, state)
        if isinstance(node, ast.Attribute):
            return self._expr(node.value, state)
        if isinstance(node, ast.Subscript):
            taint = self._expr(node.value, state)
            self._expr(node.slice, state)
            return taint
        if isinstance(node, (ast.Lambda, ast.FunctionDef)):
            return None
        children = [self._expr(c, state)
                    for c in ast.iter_child_nodes(node)
                    if isinstance(c, ast.expr)]
        return self._merge(children)

    @staticmethod
    def _merge(taints) -> Optional[_Taint]:
        tainted = [t for t in taints if t is not None]
        if not tainted:
            return None
        gained = frozenset.intersection(*(t.gained for t in tainted))
        return _Taint(tainted[0].origin, gained)

    def _call(self, node: ast.Call, state) -> Optional[_Taint]:
        target = self.ex.resolve_call(node, self.scope)
        arg_exprs = list(node.args) + [kw.value for kw in node.keywords]
        # `.tolist()` on a tainted expression is a host sink regardless of
        # what the receiver resolves to.
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr == SINK_METHOD):
            taint = self._expr(node.func.value, state)
            if taint is not None and taint.gained != ALL_FLAGS:
                self._sink(taint, node, ".tolist()")
            return None
        # Telemetry record/attribute methods (DPL011): tainted arguments
        # reaching a span/metric/audit API are an obs leak.
        if (isinstance(node.func, ast.Attribute)
                and node.func.attr in OBS_METHODS):
            self._expr(node.func.value, state)
            for taint in (self._expr(a, state) for a in arg_exprs):
                if taint is not None and taint.gained != ALL_FLAGS:
                    self._obs_sink(taint, node,
                                   f".{node.func.attr}()")
            return None
        arg_taints = [self._expr(a, state) for a in arg_exprs]
        if target in SINK_TARGETS:
            for taint in arg_taints:
                if taint is not None and taint.gained != ALL_FLAGS:
                    self._sink(taint, node, target)
            return None
        if OBS_TARGET_RE.match(target):
            # Resolved obs.* call (span attrs, event payloads, metric
            # constructors): tainted args are an obs leak.
            for taint in arg_taints:
                if taint is not None and taint.gained != ALL_FLAGS:
                    self._obs_sink(taint, node, target)
            return None
        merged = self._merge(arg_taints)
        if BOUND_TARGET_RE.search(target):
            if merged is None:
                return None
            return _Taint(merged.origin,
                          merged.gained | frozenset((FLAG_BOUND,)))
        if NOISE_TARGET_RE.search(target):
            if merged is None:
                return None
            return _Taint(merged.origin,
                          merged.gained | frozenset((FLAG_NOISE,)))
        if (_PASSTHROUGH_RE.match(target)
                or target in _PASSTHROUGH_BUILTINS):
            return merged
        # Project-resolvable callee: record per-argument pass-through
        # flows; exposure is decided interprocedurally (flow/graph.py).
        if target.startswith(f"{self.ex.module}.") or \
                target.startswith("pipelinedp_tpu.") or \
                target.startswith("self:") or \
                target.startswith("tests."):
            for pos, taint in enumerate(arg_taints[:len(node.args)]):
                if taint is not None and taint.gained != ALL_FLAGS:
                    self.flows.append(TaintFlow(
                        origin=taint.origin,
                        gained=tuple(sorted(taint.gained)),
                        kind="call", line=node.lineno, detail=target,
                        arg_pos=pos))
        # Unknown result: stop tracking (no type inference).
        return None

    def _sink(self, taint: _Taint, node: ast.AST, sink: str) -> None:
        self.flows.append(TaintFlow(
            origin=taint.origin, gained=tuple(sorted(taint.gained)),
            kind="sink", line=node.lineno, detail=sink))

    def _obs_sink(self, taint: _Taint, node: ast.AST, sink: str) -> None:
        self.flows.append(TaintFlow(
            origin=taint.origin, gained=tuple(sorted(taint.gained)),
            kind="obs", line=node.lineno, detail=sink))


# ---------------------------------------------------------------------------
# DPL008 pool-worker hazard detection
# ---------------------------------------------------------------------------


def _bound_names(fn) -> Set[str]:
    """Names locally bound inside a function scope (params, assignments,
    loop/with/except targets, comprehension targets, nested def names)."""
    bound: Set[str] = set()
    args = fn.args
    for a in (list(args.posonlyargs) + list(args.args) +
              list(args.kwonlyargs)):
        bound.add(a.arg)
    if args.vararg:
        bound.add(args.vararg.arg)
    if args.kwarg:
        bound.add(args.kwarg.arg)

    def collect_target(t):
        # Only true bindings: `x.attr = ...` / `x[k] = ...` mutate an
        # existing object and must NOT make `x` look locally bound.
        if isinstance(t, ast.Name):
            bound.add(t.id)
        elif isinstance(t, ast.Starred):
            collect_target(t.value)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                collect_target(e)

    for node in ast.walk(fn):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                collect_target(t)
        elif isinstance(node, (ast.AnnAssign, ast.AugAssign)):
            collect_target(node.target)
        elif isinstance(node, (ast.For, ast.AsyncFor)):
            collect_target(node.target)
        elif isinstance(node, ast.withitem) and node.optional_vars:
            collect_target(node.optional_vars)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            bound.add(node.name)
        elif isinstance(node, ast.comprehension):
            collect_target(node.target)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            bound.add(node.name)
        elif isinstance(node, ast.Nonlocal):
            bound.difference_update(node.names)  # shared, not local
        elif isinstance(node, ast.Global):
            bound.difference_update(node.names)
    return bound


_LOCKISH_RE = re.compile(r"lock", re.IGNORECASE)
_HANDOFF_RE = re.compile(r"(?:^|\.)adopt_sinks$")


def _guarded_lines(fn, aliases: Dict[str, str]) -> Set[int]:
    """Line numbers inside `with <lock>:` / `with adopt_sinks(...):`
    blocks of the worker body."""
    guarded: Set[int] = set()
    for node in ast.walk(fn):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        for item in node.items:
            expr = item.context_expr
            dotted = astutils.dotted_name(
                expr.func if isinstance(expr, ast.Call) else expr)
            if dotted and (_LOCKISH_RE.search(dotted)
                           or _HANDOFF_RE.search(dotted)):
                for sub in ast.walk(node):
                    guarded.add(getattr(sub, "lineno", node.lineno))
                break
    return guarded


def _worker_refs(fn, aliases: Dict[str, str]) -> Dict[str, int]:
    """Names of callables handed to a pool/thread in this scope ->
    submit-site line: `x.submit(f, ...)`, `x.map(f, ...)`,
    `threading.Thread(target=f)`."""
    refs: Dict[str, int] = {}
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        fn_expr = None
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("submit", "map") and node.args:
            fn_expr = node.args[0]
        elif astutils.call_target(node, aliases) == "threading.Thread":
            for kw in node.keywords:
                if kw.arg == "target":
                    fn_expr = kw.value
        if isinstance(fn_expr, ast.Name):
            refs.setdefault(fn_expr.id, node.lineno)
    return refs


def _find_pool_hazards(ex: Extractor, fn, scope: _Scope) -> List[PoolHazard]:
    refs = _worker_refs(fn, ex.aliases)
    if not refs:
        return []
    workers = {child.name: child for child in ast.iter_child_nodes(fn)
               if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef))
               and child.name in refs}
    if not workers:
        return []

    # Names the enclosing scope touches outside the worker defs (the
    # "other side" of a cross-thread conflict), with a representative line.
    outside: Dict[str, int] = {}
    worker_ids = {id(w) for w in workers.values()}

    def scan_outside(node):
        for child in ast.iter_child_nodes(node):
            if id(child) in worker_ids:
                continue
            if isinstance(child, ast.Name):
                outside.setdefault(child.id, child.lineno)
            scan_outside(child)

    scan_outside(fn)

    hazards: List[PoolHazard] = []
    for wname, worker in workers.items():
        bound = _bound_names(worker)
        guarded = _guarded_lines(worker, ex.aliases)
        nonlocals: Set[str] = set()
        for node in ast.walk(worker):
            if isinstance(node, ast.Nonlocal):
                nonlocals.update(node.names)

        def free_base(expr) -> Optional[ast.Name]:
            while isinstance(expr, (ast.Attribute, ast.Subscript)):
                expr = expr.value
            if isinstance(expr, ast.Name) and expr.id not in bound:
                return expr
            return None

        def emit(node, base: ast.Name, write: str):
            if node.lineno in guarded:
                return
            if base.id not in outside:
                return
            hazards.append(PoolHazard(
                line=node.lineno, col=node.col_offset + 1, worker=wname,
                name=base.id, write=write,
                shared_line=outside[base.id]))

        for node in ast.walk(worker):
            if isinstance(node, (ast.Assign, ast.AugAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    if isinstance(t, (ast.Attribute, ast.Subscript)):
                        base = free_base(t)
                        if base is not None:
                            kind = ("attribute"
                                    if isinstance(t, ast.Attribute)
                                    else "element")
                            emit(node, base, f"{kind} write")
                    elif isinstance(t, ast.Name) and t.id in nonlocals:
                        emit(node, t, "nonlocal rebind")
            elif isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr in _MUTATORS:
                base = free_base(node.func.value)
                if base is not None:
                    emit(node, base, f".{node.func.attr}() mutation")
    return hazards
