"""dpflow: interprocedural privacy-dataflow and concurrency analysis.

The flow layer underneath dplint's whole-program rules (DPL007–DPL015):

  summary.py  per-file extraction — call sites, taint flows, pool-worker
              hazards, donate_argnums, and the dpverify ordered effect
              traces (wal_append/fsync/rename/lock_acquire/...) — a pure
              function of one file
  cache.py    digest-keyed summary cache so warm runs skip extraction
  graph.py    project symbol table, import-resolved call graph (method
              resolution through project classes, __init__ re-exports,
              import cycles), reachability + taint-exposure fixed
              points, effect-kind closures, and the canonical lock
              graph (DPL014)

See LINT.md ("dpflow" and "dpverify") for the analysis contracts and
knobs.
"""

from pipelinedp_tpu.lint.flow.cache import (
    DEFAULT_CACHE_PATH,
    FlowCache,
    source_digest,
)
from pipelinedp_tpu.lint.flow.graph import ProjectFlow
from pipelinedp_tpu.lint.flow.summary import (
    Effect,
    FunctionSummary,
    ModuleSummary,
    extract_module,
)

__all__ = [
    "DEFAULT_CACHE_PATH",
    "Effect",
    "FlowCache",
    "FunctionSummary",
    "ModuleSummary",
    "ProjectFlow",
    "extract_module",
    "source_digest",
]
