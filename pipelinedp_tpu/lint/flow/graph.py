"""dpflow project graph: symbol resolution, call edges, fixed points.

Consumes the per-file :class:`~pipelinedp_tpu.lint.flow.summary.ModuleSummary`
objects (fresh or digest-cached) and builds the whole-program views the
DPL007–DPL010 rules query:

  * a project **symbol table**: every function/method qualname, classes
    with their resolved base lists, and module import/re-export aliases —
    so ``pipelinedp_tpu.ops.noise.add_noise`` resolves whether it was
    imported directly, through ``from ... import`` renames, or via an
    ``__init__`` re-export (import cycles are a non-issue: resolution runs
    over the already-built index, not at import time);
  * an import-resolved **call graph** with ``self.meth()`` resolved
    through the defining class and its project bases (method resolution
    through ``JaxDPEngine`` and friends);
  * ``reaching(pattern)``: the set of functions whose transitive call
    closure contains a target matching ``pattern`` — the "can this call
    chain draw noise / bound contributions" queries;
  * the DPL007 **exposure** fixed point: per function parameter, whether
    a value entering with a given sanitization-flag set can reach a host
    sink unsanitized through this function (monotone, cycle-safe).
"""

from __future__ import annotations

import re
from typing import Callable, Dict, FrozenSet, List, Optional, Set, Tuple

from pipelinedp_tpu.lint.flow import summary as summary_lib
from pipelinedp_tpu.lint.flow.summary import (
    ALL_FLAGS,
    EFFECT_LOCK_ACQUIRE,
    CallSite,
    Effect,
    FunctionSummary,
    ModuleSummary,
    TaintFlow,
)

_SELF_RE = re.compile(r"^self:(?P<cls>\w+)\.(?P<rest>.+)$")


class ProjectFlow:
    """Whole-program view over a set of module summaries."""

    def __init__(self, summaries: Dict[str, ModuleSummary]):
        # module dotted name -> summary
        self.modules: Dict[str, ModuleSummary] = {
            s.module: s for s in summaries.values()}
        # function qualname (module + in-module name) -> summary
        self.functions: Dict[str, FunctionSummary] = {}
        # function qualname -> module dotted name
        self.function_module: Dict[str, str] = {}
        for mod, msum in self.modules.items():
            for name, fsum in msum.functions.items():
                qual = f"{mod}.{name}"
                self.functions[qual] = fsum
                self.function_module[qual] = mod
        self._edges: Dict[str, Tuple[str, ...]] = {}
        self._reach_cache: Dict[str, FrozenSet[str]] = {}
        self._resolve_cache: Dict[Tuple[str, str], Optional[str]] = {}
        self._kind_closure: Optional[Dict[str, FrozenSet[str]]] = None
        self._locks_acquired: Optional[Dict[str, FrozenSet[str]]] = None
        self._lock_owner_cache: Dict[Tuple[str, str, str],
                                     Optional[str]] = {}

    # -- symbol resolution --------------------------------------------------

    def resolve(self, target: str, module: str) -> Optional[str]:
        """Project function qualname for a call target, else None.

        Handles full qualnames, ``__init__`` re-exports and assignment
        aliases (followed with a cycle guard), classes (-> their
        ``__init__``), and ``self:Cls.meth`` markers (method resolution
        through the class and its project bases).
        """
        key = (target, module)
        if key not in self._resolve_cache:
            self._resolve_cache[key] = self._resolve(target, module, set())
        return self._resolve_cache[key]

    def _resolve(self, target: str, module: str,
                 seen: Set[str]) -> Optional[str]:
        if not target or target in seen:
            return None
        seen.add(target)
        m = _SELF_RE.match(target)
        if m:
            return self._resolve_method(m.group("cls"), m.group("rest"),
                                        module, seen)
        if target in self.functions:
            return target
        # Split `pkg.mod.name` into a known module prefix + remainder.
        parts = target.split(".")
        for cut in range(len(parts) - 1, 0, -1):
            mod = ".".join(parts[:cut])
            if mod not in self.modules:
                continue
            rest = ".".join(parts[cut:])
            msum = self.modules[mod]
            if rest in msum.functions:
                return f"{mod}.{rest}"
            head = parts[cut]
            if head in msum.classes:
                meth = ".".join(parts[cut + 1:]) or "__init__"
                return self._resolve_method(head, meth, mod, seen)
            if head in msum.aliases:
                forwarded = msum.aliases[head]
                tail = ".".join(parts[cut + 1:])
                full = f"{forwarded}.{tail}" if tail else forwarded
                return self._resolve(full, mod, seen)
            return None
        return None

    def _resolve_method(self, cls: str, meth: str, module: str,
                        seen: Set[str]) -> Optional[str]:
        """`Cls.meth` through the class and its (project) bases."""
        mod: Optional[str] = module
        queue: List[Tuple[str, str]] = [(module, cls)]
        visited: Set[Tuple[str, str]] = set()
        while queue:
            mod, cname = queue.pop(0)
            if (mod, cname) in visited or mod not in self.modules:
                continue
            visited.add((mod, cname))
            msum = self.modules[mod]
            qual = f"{mod}.{cname}.{meth}"
            if qual in self.functions:
                return qual
            for base in msum.classes.get(cname, ()):
                resolved_base = self._resolve_class(base, mod)
                if resolved_base is not None:
                    queue.append(resolved_base)
        return None

    def _resolve_class(self, dotted: str,
                       module: str) -> Optional[Tuple[str, str]]:
        """(module, class) for a resolved base-class dotted name."""
        parts = dotted.split(".")
        for cut in range(len(parts), 0, -1):
            mod = ".".join(parts[:cut])
            if mod in self.modules:
                rest = parts[cut:]
                if len(rest) == 1 and rest[0] in self.modules[mod].classes:
                    return (mod, rest[0])
                if len(rest) == 1 and rest[0] in self.modules[mod].aliases:
                    return self._resolve_class(
                        self.modules[mod].aliases[rest[0]], mod)
                return None
        # Same-module bare class name.
        if len(parts) == 1 and module in self.modules and \
                parts[0] in self.modules[module].classes:
            return (module, parts[0])
        return None

    # -- call graph ---------------------------------------------------------

    def edges(self, qual: str) -> Tuple[str, ...]:
        """Project callees of one function (resolved, deduped)."""
        if qual not in self._edges:
            fsum = self.functions.get(qual)
            out: List[str] = []
            if fsum is not None:
                module = self.function_module[qual]
                for call in fsum.calls:
                    callee = self.resolve(call.target, module)
                    if callee is not None and callee not in out:
                        out.append(callee)
            self._edges[qual] = tuple(out)
        return self._edges[qual]

    def reaching(self, pattern: str) -> FrozenSet[str]:
        """Functions whose transitive call closure contains a call-site
        target matching ``pattern`` (regex search over the raw resolved
        target string, so external facts like ``jax.device_get`` match
        without being project symbols)."""
        if pattern in self._reach_cache:
            return self._reach_cache[pattern]
        rx = re.compile(pattern)
        hits: Set[str] = set()
        for qual, fsum in self.functions.items():
            if any(rx.search(c.target) for c in fsum.calls):
                hits.add(qual)
        # Reverse propagation to callers, to a fixed point.
        changed = True
        while changed:
            changed = False
            for qual in self.functions:
                if qual in hits:
                    continue
                if any(callee in hits for callee in self.edges(qual)):
                    hits.add(qual)
                    changed = True
        result = frozenset(hits)
        self._reach_cache[pattern] = result
        return result

    def direct_hits(self, qual: str, pattern: str) -> List[CallSite]:
        """This function's own call sites matching ``pattern``."""
        rx = re.compile(pattern)
        fsum = self.functions.get(qual)
        if fsum is None:
            return []
        return [c for c in fsum.calls if rx.search(c.target)]

    # -- DPL010 support -----------------------------------------------------

    def donating(self) -> Dict[str, Tuple[int, ...]]:
        """qualname -> donated positional indices for every jit-donating
        function/wrapper in the project."""
        return {qual: fsum.donated
                for qual, fsum in self.functions.items() if fsum.donated}

    # -- dpverify effect closures (DPL012-DPL015) ----------------------------

    def effect_kind_closure(self) -> Dict[str, FrozenSet[str]]:
        """qualname -> every effect kind present in the function itself
        or any transitive project callee. Monotone fixed point, so call
        cycles converge. Lets the ordering rules treat `self.save(...)`
        as durable when the chain ends in fsync/rename."""
        if self._kind_closure is None:
            kinds: Dict[str, Set[str]] = {
                qual: {e.kind for e in fsum.effects}
                for qual, fsum in self.functions.items()}
            changed = True
            while changed:
                changed = False
                for qual in self.functions:
                    own = kinds[qual]
                    before = len(own)
                    for callee in self.edges(qual):
                        own |= kinds[callee]
                    if len(own) != before:
                        changed = True
            self._kind_closure = {q: frozenset(s)
                                  for q, s in kinds.items()}
        return self._kind_closure

    def callee_effect_kinds(self, target: str,
                            module: str) -> FrozenSet[str]:
        """Closure effect kinds behind one raw call target (empty when
        the callee is not a project function)."""
        callee = self.resolve(target, module)
        if callee is None:
            return frozenset()
        return self.effect_kind_closure().get(callee, frozenset())

    # -- dpverify lock graph (DPL014) ----------------------------------------

    def canonical_lock(self, detail: str, module: str) -> str:
        """Project-unique lock name for one acquire-site detail.

        ``Cls:attr`` details walk the MRO to the class whose summary
        *created* the lock (``ModuleSummary.locks``), so an inherited
        ``self._lock`` unifies with its base-class definition. Module
        -level lock names resolve against the module's own ``locks``.
        Anything else stays opaque, prefixed with the observing module —
        conservative: unresolved locks never unify, so they can't
        manufacture false cycles."""
        if ":" in detail:
            cls, attr = detail.split(":", 1)
            key = (module, cls, attr)
            if key not in self._lock_owner_cache:
                self._lock_owner_cache[key] = self._lock_owner(
                    module, cls, attr)
            owner = self._lock_owner_cache[key]
            return owner if owner else f"{module}.{cls}.{attr}"
        head = detail.split(".")[0]
        msum = self.modules.get(module)
        if msum is not None and detail in msum.locks:
            return f"{module}.{detail}"
        if msum is not None and head in msum.aliases:
            fwd = msum.aliases[head]
            fwd_mod = fwd.rsplit(".", 1)[0] if "." in fwd else fwd
            fwd_name = fwd.rsplit(".", 1)[-1]
            fsum = self.modules.get(fwd_mod)
            if fsum is not None and fwd_name in fsum.locks:
                return f"{fwd_mod}.{fwd_name}"
        return f"{module}.{detail}"

    def _lock_owner(self, module: str, cls: str,
                    attr: str) -> Optional[str]:
        queue: List[Tuple[str, str]] = [(module, cls)]
        visited: Set[Tuple[str, str]] = set()
        while queue:
            mod, cname = queue.pop(0)
            if (mod, cname) in visited or mod not in self.modules:
                continue
            visited.add((mod, cname))
            msum = self.modules[mod]
            if f"{cname}.{attr}" in msum.locks:
                return f"{mod}.{cname}.{attr}"
            for base in msum.classes.get(cname, ()):
                resolved = self._resolve_class(base, mod)
                if resolved is not None:
                    queue.append(resolved)
        return None

    def locks_acquired(self) -> Dict[str, FrozenSet[str]]:
        """qualname -> canonical locks acquired by the function or any
        transitive project callee (fixed point, cycle-safe)."""
        if self._locks_acquired is None:
            acq: Dict[str, Set[str]] = {}
            for qual, fsum in self.functions.items():
                module = self.function_module[qual]
                acq[qual] = {
                    self.canonical_lock(e.detail, module)
                    for e in fsum.effects
                    if e.kind == EFFECT_LOCK_ACQUIRE}
            changed = True
            while changed:
                changed = False
                for qual in self.functions:
                    own = acq[qual]
                    before = len(own)
                    for callee in self.edges(qual):
                        own |= acq[callee]
                    if len(own) != before:
                        changed = True
            self._locks_acquired = {q: frozenset(s)
                                    for q, s in acq.items()}
        return self._locks_acquired

    def lock_sites(self) -> Dict[str, List[Tuple[str, int]]]:
        """canonical lock -> every (function qualname, line) that
        acquires it — the --dump-lock-graph inventory."""
        sites: Dict[str, List[Tuple[str, int]]] = {}
        for qual, fsum in self.functions.items():
            module = self.function_module[qual]
            for eff in fsum.effects:
                if eff.kind == EFFECT_LOCK_ACQUIRE:
                    name = self.canonical_lock(eff.detail, module)
                    sites.setdefault(name, []).append((qual, eff.line))
        return sites

    def lock_graph(self) -> Dict[str, Dict[str, Tuple[str, int]]]:
        """Ordered acquisition edges: ``graph[outer][inner]`` = one
        witness ``(function qualname, line)`` where ``inner`` is
        acquired (directly, or through a call chain) while ``outer`` is
        held. Only with-block acquires contribute outer scopes — a bare
        ``.acquire()`` has no statically known extent (``end == -1``)."""
        acquired = self.locks_acquired()
        graph: Dict[str, Dict[str, Tuple[str, int]]] = {}

        def add(outer: str, inner: str, qual: str, line: int) -> None:
            if inner != outer:
                graph.setdefault(outer, {}).setdefault(
                    inner, (qual, line))

        for qual, fsum in self.functions.items():
            module = self.function_module[qual]
            lacqs = [e for e in fsum.effects
                     if e.kind == EFFECT_LOCK_ACQUIRE]
            for i, outer_eff in enumerate(lacqs):
                if outer_eff.end < 0:
                    continue
                outer = self.canonical_lock(outer_eff.detail, module)
                for inner_eff in lacqs[i + 1:]:
                    if inner_eff.line > outer_eff.end:
                        break
                    add(outer,
                        self.canonical_lock(inner_eff.detail, module),
                        qual, inner_eff.line)
                for call in fsum.calls:
                    if not (outer_eff.line <= call.line
                            <= outer_eff.end):
                        continue
                    callee = self.resolve(call.target, module)
                    if callee is None:
                        continue
                    for inner in acquired[callee]:
                        add(outer, inner, qual, call.line)
        return graph

    def lock_cycles(self) -> List[List[str]]:
        """Elementary cycles in the lock graph (each reported once,
        rotated to start at its lexicographically smallest lock)."""
        graph = self.lock_graph()
        cycles: List[List[str]] = []
        seen: Set[Tuple[str, ...]] = set()

        def dfs(node: str, path: List[str], on_path: Set[str]) -> None:
            for nxt in sorted(graph.get(node, ())):
                if nxt in on_path:
                    cyc = path[path.index(nxt):]
                    k = min(range(len(cyc)), key=lambda i: cyc[i])
                    key = tuple(cyc[k:] + cyc[:k])
                    if key not in seen:
                        seen.add(key)
                        cycles.append(list(key))
                    continue
                if len(path) < 16:
                    dfs(nxt, path + [nxt], on_path | {nxt})

        for start in sorted(graph):
            dfs(start, [start], {start})
        return cycles

    def held_effects(self, qual: str,
                     kinds: FrozenSet[str]) -> List[Tuple[Effect, str]]:
        """(lock-acquire effect, offending kind) pairs where an effect
        of one of ``kinds`` happens — directly or through a call chain —
        inside the acquire's with-block span. The DPL014 lock-scope
        (latency-inversion) query."""
        fsum = self.functions.get(qual)
        if fsum is None:
            return []
        module = self.function_module[qual]
        closure = self.effect_kind_closure()
        out: List[Tuple[Effect, str]] = []
        for acq in fsum.effects:
            if acq.kind != EFFECT_LOCK_ACQUIRE or acq.end < 0:
                continue
            hit: Optional[str] = None
            for eff in fsum.effects:
                if eff.kind in kinds and \
                        acq.line <= eff.line <= acq.end:
                    hit = eff.kind
                    break
            if hit is None:
                for call in fsum.calls:
                    if not (acq.line <= call.line <= acq.end):
                        continue
                    callee = self.resolve(call.target, module)
                    if callee is None:
                        continue
                    inner = closure.get(callee, frozenset()) & kinds
                    if inner:
                        hit = sorted(inner)[0]
                        break
            if hit is not None:
                out.append((acq, hit))
        return out

    # -- DPL007 exposure fixed point -----------------------------------------

    def exposure(self, trusted: Callable[[str], bool],
                 sink_kinds: FrozenSet[str] = frozenset({"sink"})
                 ) -> Dict[Tuple[str, str, FrozenSet[str]], bool]:
        """exposed[(func_qual, param, have_flags)] — can a value entering
        ``param`` with ``have_flags`` already applied reach a sink of one
        of ``sink_kinds`` ("sink" = host materialization for DPL007,
        "obs" = telemetry record for DPL011) without gaining the full
        {bound, noise} set?

        ``trusted(module)`` marks modules whose internals are exempt
        (the mechanism-primitive layer): their functions never expose.
        Monotone fixed point from all-False, so call cycles converge.
        """
        flag_sets = [frozenset(), frozenset((summary_lib.FLAG_BOUND,)),
                     frozenset((summary_lib.FLAG_NOISE,))]
        exposed: Dict[Tuple[str, str, FrozenSet[str]], bool] = {}
        for qual, fsum in self.functions.items():
            for p in fsum.params:
                for have in flag_sets:
                    exposed[(qual, p, have)] = False

        def flow_exposes(qual: str, module: str, flow: TaintFlow,
                         have: FrozenSet[str]) -> bool:
            combined = have | frozenset(flow.gained)
            if combined == ALL_FLAGS:
                return False
            if flow.kind in sink_kinds:
                return True
            if flow.kind != "call":
                return False
            callee = self.resolve(flow.detail, module)
            if callee is None or trusted(self.function_module[callee]):
                return False
            csum = self.functions[callee]
            if flow.arg_pos >= len(csum.params):
                return False
            cparam = csum.params[flow.arg_pos]
            key = (callee, cparam, combined)
            return exposed.get(key, False)

        changed = True
        while changed:
            changed = False
            for qual, fsum in self.functions.items():
                module = self.function_module[qual]
                if trusted(module):
                    continue
                for flow in fsum.flows:
                    for have in flag_sets:
                        key = (qual, flow.origin, have)
                        if key not in exposed or exposed[key]:
                            continue
                        if flow_exposes(qual, module, flow, have):
                            exposed[key] = True
                            changed = True
        self._flow_exposes = flow_exposes
        return exposed

    def root_exposures(self, trusted: Callable[[str], bool],
                       sink_kinds: FrozenSet[str] = frozenset({"sink"})
                       ) -> List[Tuple[str, TaintFlow]]:
        """(function qualname, flow) pairs where a private value that
        *originates* in that function's parameters reaches a sink of
        ``sink_kinds`` unsanitized — the DPL007 ("sink") / DPL011
        ("obs") finding sites. A flow's ``gained`` already includes the
        origin parameter's base flags (e.g. ``accs`` parameters start
        contribution-bounded), so roots evaluate with no extra incoming
        flags."""
        self.exposure(trusted, sink_kinds)
        out: List[Tuple[str, TaintFlow]] = []
        for qual, fsum in self.functions.items():
            module = self.function_module[qual]
            if trusted(module):
                continue
            for flow in fsum.flows:
                if self._flow_exposes(qual, module, flow, frozenset()):
                    out.append((qual, flow))
        return out
