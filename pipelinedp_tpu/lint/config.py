"""dplint configuration: which modules are exempt from which rules.

The default stance is deny-by-default: every scanned module is treated as
privacy-critical unless a pattern below says otherwise. Exemptions are
*narrow and documented* — each entry names the structural reason the rule
does not apply there. Tests construct custom configs to exercise rules in
isolation.
"""

from __future__ import annotations

import dataclasses
import fnmatch
from typing import Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class LintConfig:
    """Per-rule module exemptions (fnmatch patterns over dotted modules)."""

    # DPL004 — modules allowed to reference numpy/stdlib RNGs.
    #  * noise_core: the declared seedable numpy fallback sampler
    #    (noise_core.py `_fallback_*`) — distributionally equivalent,
    #    documented weaker bit-level guarantees, test-reseedable.
    #  * analysis / dataset_histograms: utility-analysis tooling; estimates
    #    error on non-released intermediates, not on the DP release path.
    insecure_rng_exempt: Tuple[str, ...] = (
        "pipelinedp_tpu.noise_core",
        "pipelinedp_tpu.analysis.*",
        "pipelinedp_tpu.dataset_histograms.*",
        "pipelinedp_tpu.lint.*",
    )

    # DPL002 — the mechanism-primitive layer: these modules *are* the noise
    # sinks; their scales/eps/delta arrive pre-calibrated from MechanismSpecs
    # resolved upstream (jax_engine/dp_computations read the specs and pass
    # scalars down).
    unaccounted_noise_exempt: Tuple[str, ...] = (
        "pipelinedp_tpu.noise_core",
        "pipelinedp_tpu.ops.noise",
        "pipelinedp_tpu.ops.selection",
        "pipelinedp_tpu.ops.quantiles",
        "pipelinedp_tpu.partition_selection",
        "pipelinedp_tpu.quantile_tree",
        "pipelinedp_tpu.native.*",
        "pipelinedp_tpu.lint.*",
    )

    # DPL005 — modules whose job is budget arithmetic: the accountant
    # itself, and dp_computations.equally_split_budget (the sanctioned
    # splitter the reference uses for MEAN/VARIANCE internal splits).
    budget_literal_exempt: Tuple[str, ...] = (
        "pipelinedp_tpu.budget_accounting",
        "pipelinedp_tpu.dp_computations",
        "pipelinedp_tpu.pld",
        "pipelinedp_tpu.lint.*",
    )

    # DPL007 — the mechanism-primitive and host-encode layer dpflow
    # trusts as *opaque*: handling raw private columns is these modules'
    # job, their host materializations are mechanism-internal (never a
    # release), and exposures must not propagate to callers. Everything
    # else — the orchestration layer (jax_engine, dp_engine, runtime,
    # backends, dataframes) — is analyzed.
    release_taint_trusted: Tuple[str, ...] = (
        "pipelinedp_tpu.noise_core",
        "pipelinedp_tpu.ops.noise",
        "pipelinedp_tpu.ops.selection",
        "pipelinedp_tpu.ops.quantiles",
        "pipelinedp_tpu.ops.columnar",
        "pipelinedp_tpu.ops.encoding",
        "pipelinedp_tpu.ops.wirecodec",
        "pipelinedp_tpu.contribution_bounders",
        "pipelinedp_tpu.partition_selection",
        "pipelinedp_tpu.quantile_tree",
        "pipelinedp_tpu.data_extractors",
        "pipelinedp_tpu.native.*",
        "pipelinedp_tpu.dataset_histograms.*",
        "pipelinedp_tpu.analysis.*",
        "pipelinedp_tpu.lint.*",
    )

    # DPL011 — telemetry-taint exemptions: the obs package itself (its
    # job is building the records from already-validated scalars; the
    # API-level check_safe_value gate plus its own tests are the
    # control there) and the lint tree.
    telemetry_taint_trusted: Tuple[str, ...] = (
        "pipelinedp_tpu.obs.*",
        "pipelinedp_tpu.lint.*",
    )

    @staticmethod
    def _matches(module: str, patterns: Sequence[str]) -> bool:
        return any(fnmatch.fnmatch(module, p) for p in patterns)

    def is_insecure_rng_exempt(self, module: str) -> bool:
        return self._matches(module, self.insecure_rng_exempt)

    def is_unaccounted_noise_exempt(self, module: str) -> bool:
        return self._matches(module, self.unaccounted_noise_exempt)

    def is_budget_literal_exempt(self, module: str) -> bool:
        return self._matches(module, self.budget_literal_exempt)

    def is_release_taint_trusted(self, module: str) -> bool:
        return self._matches(module, self.release_taint_trusted)

    def is_telemetry_taint_trusted(self, module: str) -> bool:
        return self._matches(module, self.telemetry_taint_trusted)


DEFAULT_CONFIG = LintConfig()
