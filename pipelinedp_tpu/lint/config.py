"""dplint configuration: which modules are exempt from which rules.

The default stance is deny-by-default: every scanned module is treated as
privacy-critical unless a pattern below says otherwise. Exemptions are
*narrow and documented* — each entry names the structural reason the rule
does not apply there. Tests construct custom configs to exercise rules in
isolation.
"""

from __future__ import annotations

import dataclasses
import fnmatch
from typing import Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class LintConfig:
    """Per-rule module exemptions (fnmatch patterns over dotted modules)."""

    # DPL004 — modules allowed to reference numpy/stdlib RNGs.
    #  * noise_core: the declared seedable numpy fallback sampler
    #    (noise_core.py `_fallback_*`) — distributionally equivalent,
    #    documented weaker bit-level guarantees, test-reseedable.
    #  * analysis / dataset_histograms: utility-analysis tooling; estimates
    #    error on non-released intermediates, not on the DP release path.
    insecure_rng_exempt: Tuple[str, ...] = (
        "pipelinedp_tpu.noise_core",
        "pipelinedp_tpu.analysis.*",
        "pipelinedp_tpu.dataset_histograms.*",
        "pipelinedp_tpu.lint.*",
    )

    # DPL002 — the mechanism-primitive layer: these modules *are* the noise
    # sinks; their scales/eps/delta arrive pre-calibrated from MechanismSpecs
    # resolved upstream (jax_engine/dp_computations read the specs and pass
    # scalars down).
    unaccounted_noise_exempt: Tuple[str, ...] = (
        "pipelinedp_tpu.noise_core",
        "pipelinedp_tpu.ops.noise",
        "pipelinedp_tpu.ops.selection",
        "pipelinedp_tpu.ops.quantiles",
        "pipelinedp_tpu.partition_selection",
        "pipelinedp_tpu.quantile_tree",
        "pipelinedp_tpu.native.*",
        "pipelinedp_tpu.lint.*",
    )

    # DPL005 — modules whose job is budget arithmetic: the accountant
    # itself, and dp_computations.equally_split_budget (the sanctioned
    # splitter the reference uses for MEAN/VARIANCE internal splits).
    budget_literal_exempt: Tuple[str, ...] = (
        "pipelinedp_tpu.budget_accounting",
        "pipelinedp_tpu.dp_computations",
        "pipelinedp_tpu.pld",
        "pipelinedp_tpu.lint.*",
    )

    # DPL007 — the mechanism-primitive and host-encode layer dpflow
    # trusts as *opaque*: handling raw private columns is these modules'
    # job, their host materializations are mechanism-internal (never a
    # release), and exposures must not propagate to callers. Everything
    # else — the orchestration layer (jax_engine, dp_engine, runtime,
    # backends, dataframes) — is analyzed.
    release_taint_trusted: Tuple[str, ...] = (
        "pipelinedp_tpu.noise_core",
        "pipelinedp_tpu.ops.noise",
        "pipelinedp_tpu.ops.selection",
        "pipelinedp_tpu.ops.quantiles",
        "pipelinedp_tpu.ops.columnar",
        "pipelinedp_tpu.ops.encoding",
        "pipelinedp_tpu.ops.wirecodec",
        "pipelinedp_tpu.contribution_bounders",
        "pipelinedp_tpu.partition_selection",
        "pipelinedp_tpu.quantile_tree",
        "pipelinedp_tpu.data_extractors",
        "pipelinedp_tpu.native.*",
        "pipelinedp_tpu.dataset_histograms.*",
        "pipelinedp_tpu.analysis.*",
        "pipelinedp_tpu.lint.*",
    )

    # DPL011 — telemetry-taint exemptions: the obs package itself (its
    # job is building the records from already-validated scalars; the
    # API-level check_safe_value gate plus its own tests are the
    # control there) and the lint tree.
    telemetry_taint_trusted: Tuple[str, ...] = (
        "pipelinedp_tpu.obs.*",
        "pipelinedp_tpu.lint.*",
    )

    # DPL012 — durable-write discipline exemptions. Unlike the module
    # patterns above these match *function qualnames* (module + in-module
    # dotted path) because the verdict is per-transaction, not per-file.
    #  * JsonlWal internals: the append discipline IS the durability
    #    protocol — one long-lived 'ab' handle, every record
    #    write+flush+fsync'd; rewrite/recover manage that handle.
    #  * flight-recorder spool: flush-only by design (obs/flight.py) —
    #    an fsync per appended event would serialize the hot path, and
    #    the crash spool tolerates losing the final buffered lines.
    #  * ops_plane._writable: the /healthz writability probe creates and
    #    unlinks a throwaway file; durability is the question it asks,
    #    not a property it needs.
    #  * regress/profiler/lint: operator-facing report and cache
    #    artifacts — loss is repaired by re-running the tool.
    atomic_write_exempt: Tuple[str, ...] = (
        "pipelinedp_tpu.runtime.journal.JsonlWal.*",
        "pipelinedp_tpu.obs.flight.FlightRecorder.bind_spool",
        "pipelinedp_tpu.obs.flight.FlightRecorder._rotate_spool_locked",
        "pipelinedp_tpu.obs.ops_plane._writable",
        "pipelinedp_tpu.obs.regress.*",
        "pipelinedp_tpu.profiler.*",
        "pipelinedp_tpu.lint.*",
    )

    # DPL013 — transactions whose pre-commit durability is itself the
    # protocol (none in-tree today; the tuple exists so a future
    # write-behind cache documents its contract here instead of
    # sprinkling suppressions through strict-gated trees).
    commit_ordering_trusted: Tuple[str, ...] = ()

    # DPL014 — canonical lock names whose *contract* is "the lock
    # serializes the durable append", so holding them across the WAL
    # fsync is the design, not an inversion:
    #  * live-session append lock: the append transaction (payload save
    #    -> WAL record -> fold) must be serialized end-to-end or two
    #    appends could commit records out of payload order.
    #  * audit-trail lock: audit records are ordered by the lock; the
    #    fsync under it is what makes "ordered" mean anything on disk.
    lock_scope_exempt: Tuple[str, ...] = (
        "pipelinedp_tpu.serving.live.LiveDatasetSession._append_lock",
        "pipelinedp_tpu.obs.audit.AuditTrail._lock",
    )

    # DPL015 — function qualnames allowed nondeterminism primitives on
    # release paths:
    #  * ops.noise / ops.selection / ops.finalize: the blessed compiled
    #    entries — their jnp arithmetic traces under jit into one XLA
    #    program, which is exactly the determinism contract.
    #  * JaxDPEngine._legacy_finalize: the unfused eager parity oracle,
    #    pinned bit-identical to the fused path by finalize tests.
    #  * lint itself analyzes release code without being on the path.
    release_determinism_exempt: Tuple[str, ...] = (
        "pipelinedp_tpu.ops.noise.*",
        "pipelinedp_tpu.ops.selection.*",
        "pipelinedp_tpu.ops.finalize.*",
        "pipelinedp_tpu.jax_engine.JaxDPEngine._legacy_finalize",
        "pipelinedp_tpu.lint.*",
    )

    @staticmethod
    def _matches(module: str, patterns: Sequence[str]) -> bool:
        return any(fnmatch.fnmatch(module, p) for p in patterns)

    def is_insecure_rng_exempt(self, module: str) -> bool:
        return self._matches(module, self.insecure_rng_exempt)

    def is_unaccounted_noise_exempt(self, module: str) -> bool:
        return self._matches(module, self.unaccounted_noise_exempt)

    def is_budget_literal_exempt(self, module: str) -> bool:
        return self._matches(module, self.budget_literal_exempt)

    def is_release_taint_trusted(self, module: str) -> bool:
        return self._matches(module, self.release_taint_trusted)

    def is_telemetry_taint_trusted(self, module: str) -> bool:
        return self._matches(module, self.telemetry_taint_trusted)

    def is_atomic_write_exempt(self, qualname: str) -> bool:
        return self._matches(qualname, self.atomic_write_exempt)

    def is_commit_ordering_trusted(self, qualname: str) -> bool:
        return self._matches(qualname, self.commit_ordering_trusted)

    def is_lock_scope_exempt(self, lock_name: str) -> bool:
        return self._matches(lock_name, self.lock_scope_exempt)

    def is_release_determinism_exempt(self, qualname: str) -> bool:
        return self._matches(qualname, self.release_determinism_exempt)


DEFAULT_CONFIG = LintConfig()
