"""dplint: AST-based privacy & JAX-correctness static analysis.

PipelineDP-TPU's DP guarantees rest on invariants the type system cannot
see: every noise draw must trace back to a ``MechanismSpec`` issued by
``BudgetAccountant.request_budget()``, every JAX PRNG key must be consumed
exactly once, jitted kernels must not concretize traced values, and
release-path randomness must come from the secure sampler. dplint checks
these machine-checkably on every change — the same role secure-RNG review
plays for Google's C++ differential-privacy library.

Rules (DPL007-010 are whole-program, built on the dpflow layer in
lint/flow/ — project symbol table, import-resolved call graph, forward
dataflow with per-file digest caching):
  DPL001 prng-key-reuse        — key consumed twice without split/fold_in
  DPL002 unaccounted-noise     — noise drawn with no MechanismSpec in sight
  DPL003 jit-hostile-construct — .item()/np.*/branching on traced values
  DPL004 insecure-rng          — np.random / stdlib random on release path
  DPL005 budget-literal-misuse — eps<=0, delta>=1, hand-rolled eps/2 splits
  DPL006 unguarded-float64     — jnp.float64 that silently becomes float32
  DPL007 release-path-taint    — private column to host without bound+noise
  DPL008 thread-escape         — unlocked pool-worker write to shared state
  DPL009 commit-before-draw    — noise reachable before the journal commit
  DPL010 donated-buffer-reuse  — donate_argnums operand read after the call

Run: ``python -m pipelinedp_tpu.lint pipelinedp_tpu/`` (exits nonzero on
new findings) — see LINT.md for the rule catalog with before/after
examples, suppression syntax, and baseline workflow.
"""

from pipelinedp_tpu.lint.config import DEFAULT_CONFIG, LintConfig
from pipelinedp_tpu.lint.engine import (
    Finding,
    LintResult,
    ModuleContext,
    ProjectContext,
    ProjectRule,
    Rule,
    default_rules,
    lint_paths,
)

__all__ = [
    "DEFAULT_CONFIG",
    "Finding",
    "LintConfig",
    "LintResult",
    "ModuleContext",
    "ProjectContext",
    "ProjectRule",
    "Rule",
    "default_rules",
    "lint_paths",
]
