"""Resilient streaming runtime: the unified slab driver, checkpoint/
resume, OOM-adaptive retry, dispatch watchdog, and durable at-most-once
DP release.

The reference inherits fault tolerance from its backends (Beam and Spark
re-execute lost work transparently); the TPU-native runtime gets the
equivalent here, built on two properties the streamed execution path
already has:

  * determinism — per-chunk PRNG keys are ``fold_in(key, c)`` and the
    host encode is a pure function of the input, so re-running any chunk
    reproduces it bitwise;
  * mergeability — ``PartitionAccumulators`` (and the quantile leaf
    histogram) add across pid-disjoint chunks, so a prefix of the chunk
    sequence is a complete, resumable intermediate state.

What lives where:

  * :mod:`driver` — ``SlabDriver``: THE slab loop, written once, driving
    both streaming entry points (single-device ``ops/streaming`` and
    mesh ``parallel/sharded``) through a ``DevicePlacement`` strategy;
    checkpointing, retry, prefetch, compact merge, fault injection and
    the watchdog each exist exactly once here.
  * :mod:`checkpoint` — ``StreamCheckpoint`` snapshots
    ``(accs, qhist, next_chunk, wire/rng fingerprints, KeyStream
    counter)`` after each slab into a ``CheckpointStore`` (in-memory or
    file-backed with payload digests + keep-last-K retention); a resumed
    run is bit-identical to an uninterrupted one.
  * :mod:`retry` — ``RetryPolicy``: bounded exponential backoff for
    transient transfer/kernel errors and watchdog hangs; on
    ``RESOURCE_EXHAUSTED`` the slab byte budget is halved and the failed
    slab re-issued (the per-chunk key schedule never changes, so results
    stay distribution-identical — bit-identical for a seeded run).
  * :mod:`watchdog` — ``DispatchWatchdog``: bounded timeouts around the
    transfer/dispatch/sync points so a wedged device operation surfaces
    as a typed, retryable ``DispatchHangError`` instead of hanging the
    loop forever.
  * :mod:`journal` — ``ReleaseJournal`` / ``FileReleaseJournal``:
    at-most-once noise release, in-memory or durable (fsync'd WAL with
    per-record digests, torn-tail-tolerant recovery, atomic compaction)
    so even a re-exec'd process refuses to re-draw released noise (the
    budget side lives in ``budget_accounting`` as the spend journal,
    durable through the same WAL via ``durable_spend_journal=``).
  * :mod:`faults` — ``FaultInjector``: scripted OOM / transfer / kernel /
    hang / host-crash / SIGKILL faults at slab N, driving
    ``tests/resilience_test.py`` and the cross-process kill harness.

``JaxDPEngine`` exposes all of it via the ``checkpoint_policy=``,
``retry_policy=``, ``release_journal=``, ``fault_injector=`` and
``watchdog_timeout_s=`` knobs; ``ops/streaming.stream_bound_and_aggregate``
and the mesh twin take a ``resilience=`` bundle plus an explicit
``resume_from=`` hook. See RESILIENCE.md for the failure model and
recovery semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from pipelinedp_tpu import profiler
from pipelinedp_tpu.runtime.checkpoint import (  # noqa: F401
    CheckpointMismatchError, CheckpointPolicy, CheckpointStore,
    FileCheckpointStore, InMemoryCheckpointStore, StreamCheckpoint,
    key_fingerprint, wire_fingerprint)
from pipelinedp_tpu.runtime.faults import (  # noqa: F401
    FaultInjector, FaultSpec, HostCrash, InjectedFault, InjectedKernelError,
    InjectedOom, InjectedTransferError)
from pipelinedp_tpu.runtime.journal import (  # noqa: F401
    EVENT_JOURNAL_BYTES, EVENT_JOURNAL_RECOVERIES, DoubleReleaseError,
    FileReleaseJournal, JournalCorruptError, JsonlWal, ReleaseJournal,
    ReleaseRecord)
from pipelinedp_tpu.runtime.retry import RetryPolicy, classify  # noqa: F401
from pipelinedp_tpu.runtime.watchdog import (  # noqa: F401
    EVENT_WATCHDOG_TIMEOUTS, Deadline, DispatchHangError, DispatchWatchdog,
    QueryDeadlineError)
from pipelinedp_tpu.runtime.driver import (  # noqa: F401
    EVENT_CHECKPOINT_BYTES, EVENT_DEGRADATIONS, EVENT_HANGS, EVENT_RESUMES,
    EVENT_RETRIES, DevicePlacement, SlabDriver, SlabPlan)

# Profiler event-counter names (profiler.count_event / event_count).
# Loop-owned counters live in runtime/driver.py, watchdog/journal
# counters in their modules; the native-fallback counter is credited by
# ops/streaming._pack_native.
EVENT_NATIVE_FALLBACK = "runtime/native_fallback"


@dataclasses.dataclass
class StreamResilience:
    """The resilience bundle the streaming drivers consume.

    ``key_counter`` is the engine KeyStream position the streamed kernel
    key was drawn at; checkpoints record it so a resume under a different
    key schedule (which could never be bit-identical) is refused instead
    of silently diverging. -1 = unknown (direct streaming-API callers).

    ``watchdog_timeout_s`` bounds every device transfer/dispatch and adds
    one per-window sync: a wedged operation surfaces as a retryable
    ``DispatchHangError`` within the timeout instead of hanging forever.
    None defers to ``PIPELINEDP_TPU_WATCHDOG_S`` (0 = disabled, the
    default — enabling it trades a little cross-window pipelining for
    bounded hang detection).

    ``deadline`` is the serving layer's per-query time budget
    (watchdog.Deadline): the driver checks it between windows and
    before backoff sleeps and raises ``QueryDeadlineError`` — outside
    the retry handler, so an expired query propagates immediately.
    """
    retry_policy: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    fault_injector: Optional[FaultInjector] = None
    checkpoint_policy: Optional[CheckpointPolicy] = None
    resume_from: Optional[StreamCheckpoint] = None
    key_counter: int = -1
    watchdog_timeout_s: Optional[float] = None
    deadline: Optional[Deadline] = None


def resilience_counters() -> Dict[str, int]:
    """Snapshot of the runtime's resilience counters (bench.py surfaces
    this dict; all keys always present so dashboards can rely on them)."""
    return {
        "retries": profiler.event_count(EVENT_RETRIES),
        "degradations": profiler.event_count(EVENT_DEGRADATIONS),
        "resumes": profiler.event_count(EVENT_RESUMES),
        "checkpoint_bytes": profiler.event_count(EVENT_CHECKPOINT_BYTES),
        "native_fallbacks": profiler.event_count(EVENT_NATIVE_FALLBACK),
        "watchdog_timeouts": profiler.event_count(EVENT_WATCHDOG_TIMEOUTS),
        "hangs_detected": profiler.event_count(EVENT_HANGS),
        "journal_recoveries": profiler.event_count(EVENT_JOURNAL_RECOVERIES),
        "journal_bytes": profiler.event_count(EVENT_JOURNAL_BYTES),
    }
