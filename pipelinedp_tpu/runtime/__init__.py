"""Resilient streaming runtime: checkpoint/resume, OOM-adaptive retry,
and at-most-once DP release.

The reference inherits fault tolerance from its backends (Beam and Spark
re-execute lost work transparently); the TPU-native runtime gets the
equivalent here, built on two properties the streamed execution path
already has:

  * determinism — per-chunk PRNG keys are ``fold_in(key, c)`` and the
    host encode is a pure function of the input, so re-running any chunk
    reproduces it bitwise;
  * mergeability — ``PartitionAccumulators`` (and the quantile leaf
    histogram) add across pid-disjoint chunks, so a prefix of the chunk
    sequence is a complete, resumable intermediate state.

What lives where:

  * :mod:`checkpoint` — ``StreamCheckpoint`` snapshots
    ``(accs, qhist, next_chunk, wire/rng fingerprints, KeyStream
    counter)`` after each slab into a ``CheckpointStore`` (in-memory or
    file-backed); a resumed run is bit-identical to an uninterrupted one.
  * :mod:`retry` — ``RetryPolicy``: bounded exponential backoff for
    transient transfer/kernel errors; on ``RESOURCE_EXHAUSTED`` the slab
    byte budget is halved and the failed slab re-issued (the per-chunk
    key schedule never changes, so results stay distribution-identical —
    bit-identical for a seeded run).
  * :mod:`journal` — ``ReleaseJournal``: at-most-once noise release. A
    resumed or retried run that would re-draw already-released noise
    raises instead of silently degrading the DP guarantee (the budget
    side lives in ``budget_accounting`` as the spend journal).
  * :mod:`faults` — ``FaultInjector``: scripted OOM / transfer / kernel /
    host-crash faults at slab N, driving ``tests/resilience_test.py``.

``JaxDPEngine`` exposes all of it via the ``checkpoint_policy=``,
``retry_policy=``, ``release_journal=`` and ``fault_injector=`` knobs;
``ops/streaming.stream_bound_and_aggregate`` and the mesh twin take a
``resilience=`` bundle plus an explicit ``resume_from=`` hook. See
RESILIENCE.md for the failure model and recovery semantics.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from pipelinedp_tpu import profiler
from pipelinedp_tpu.runtime.checkpoint import (  # noqa: F401
    CheckpointMismatchError, CheckpointPolicy, CheckpointStore,
    FileCheckpointStore, InMemoryCheckpointStore, StreamCheckpoint,
    key_fingerprint, wire_fingerprint)
from pipelinedp_tpu.runtime.faults import (  # noqa: F401
    FaultInjector, FaultSpec, HostCrash, InjectedFault, InjectedKernelError,
    InjectedOom, InjectedTransferError)
from pipelinedp_tpu.runtime.journal import (  # noqa: F401
    DoubleReleaseError, ReleaseJournal, ReleaseRecord)
from pipelinedp_tpu.runtime.retry import RetryPolicy, classify  # noqa: F401

# Profiler event-counter names (profiler.count_event / event_count).
EVENT_RETRIES = "runtime/retries"
EVENT_DEGRADATIONS = "runtime/degradations"
EVENT_RESUMES = "runtime/resumes"
EVENT_CHECKPOINT_BYTES = "runtime/checkpoint_bytes"
EVENT_NATIVE_FALLBACK = "runtime/native_fallback"


@dataclasses.dataclass
class StreamResilience:
    """The resilience bundle the streaming drivers consume.

    ``key_counter`` is the engine KeyStream position the streamed kernel
    key was drawn at; checkpoints record it so a resume under a different
    key schedule (which could never be bit-identical) is refused instead
    of silently diverging. -1 = unknown (direct streaming-API callers).
    """
    retry_policy: RetryPolicy = dataclasses.field(default_factory=RetryPolicy)
    fault_injector: Optional[FaultInjector] = None
    checkpoint_policy: Optional[CheckpointPolicy] = None
    resume_from: Optional[StreamCheckpoint] = None
    key_counter: int = -1


def resilience_counters() -> Dict[str, int]:
    """Snapshot of the runtime's resilience counters (bench.py surfaces
    this dict; all keys always present so dashboards can rely on them)."""
    return {
        "retries": profiler.event_count(EVENT_RETRIES),
        "degradations": profiler.event_count(EVENT_DEGRADATIONS),
        "resumes": profiler.event_count(EVENT_RESUMES),
        "checkpoint_bytes": profiler.event_count(EVENT_CHECKPOINT_BYTES),
        "native_fallbacks": profiler.event_count(EVENT_NATIVE_FALLBACK),
    }
