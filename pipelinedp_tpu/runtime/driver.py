"""The unified resilient slab driver.

Every streaming encode path — the single-device slab pipeline
(ops/streaming.py) and the mesh chunk pipeline (parallel/sharded.py) —
is the same fold: host-encode a window of pid-disjoint chunks, ship it
with one async ``device_put``, fold each chunk into the running
accumulators under its ``fold_in(key, c)`` key, checkpoint at window
boundaries, and recover from faults without changing a single released
bit. Until this module existed that fold lived twice
(``ops/streaming._run_slab_loop`` and
``parallel/sharded._run_codec_chunks``), and every resilience or
scheduling feature — checkpoint/resume, OOM-adaptive retry, lookahead
prefetch, compact merge, fault injection — had to be patched in both.

:class:`SlabDriver` is that loop, written once. Everything
device-topology-specific hides behind a :class:`DevicePlacement`
strategy: how a slab lands on silicon, how a chunk folds, how state is
snapshotted and restored. Two placements exist today (single device,
mesh); a multi-host placement plugs in without a third copy of the
loop.

The driver additionally owns the dispatch watchdog
(runtime/watchdog.py): with a timeout configured, the injector check +
transfer, every chunk dispatch, and one per-window
``block_until_ready`` sync run under a bounded budget, so a wedged
transfer surfaces as a typed, retryable :class:`~pipelinedp_tpu.runtime
.watchdog.DispatchHangError` instead of hanging the loop forever. A
timed-out *step or sync* is treated like an in-dispatch failure: the
abandoned operation may still be mutating donated buffers, so the only
trustworthy state is the last checkpoint (restore, or re-raise when
none exists).

Failure handling (see RESILIENCE.md for the full fault-domain table):

  * ``oom`` — degradable placements halve the slab window and re-issue
    from the failed chunk (chunk keys don't depend on the window
    grouping, so released values are unchanged); non-degradable
    placements (mesh: the chunk granularity is fixed by the mesh shape)
    fall back to counted retries.
  * ``transient`` (injected faults, gRPC-style transient status codes,
    watchdog hangs) — bounded exponential backoff, re-issue.
  * ``fatal`` (HostCrash, privacy guards, everything else) — propagate.

A failure raised while a *donating* chunk step was in flight may have
consumed the donated accumulator buffers; those retries restore from
the last checkpoint and re-raise when no checkpoint exists (resuming
from possibly-poisoned buffers could double-count a chunk).
"""

from __future__ import annotations

import abc
import dataclasses
import time
from typing import Any, Callable, List, Optional, Tuple

from pipelinedp_tpu import profiler
from pipelinedp_tpu.obs import flight as obs_flight
from pipelinedp_tpu.obs import metrics as obs_metrics
from pipelinedp_tpu.obs import trace as obs_trace
from pipelinedp_tpu.runtime import checkpoint as checkpoint_lib
from pipelinedp_tpu.runtime import retry as retry_lib
from pipelinedp_tpu.runtime import watchdog as watchdog_lib

# Profiler event counters owned by the slab loop (profiler.count_event /
# event_count; surfaced by runtime.resilience_counters and bench.py).
EVENT_RETRIES = "runtime/retries"
EVENT_DEGRADATIONS = "runtime/degradations"
EVENT_RESUMES = "runtime/resumes"
EVENT_CHECKPOINT_BYTES = "runtime/checkpoint_bytes"
# One per DispatchHangError the driver acted on (retried or surfaced);
# the raw per-timeout count is watchdog.EVENT_WATCHDOG_TIMEOUTS.
EVENT_HANGS = "runtime/hangs_detected"

# Per-executed-chunk counters (canonical here; ops/streaming re-exports
# them under the same names for bench.py and the test suites):
#   EVENT_PARTITION_SCATTERS — full-[num_partitions] scatter passes whose
#     input is row/group scale (one set per chunk on the legacy path);
#   EVENT_COMPACT_CHUNKS — chunks that emitted compact group columns
#     (their merge-time scatters are counted by the merge closures under
#     ops/streaming.EVENT_COMPACT_MERGE_SCATTERS).
EVENT_PARTITION_SCATTERS = "ops/partition_scatter_passes"
EVENT_COMPACT_CHUNKS = "ops/compact_chunk_emits"


class DevicePlacement(abc.ABC):
    """Where slabs land and how chunk results fold, for one topology.

    The driver owns scheduling, retries, checkpoints, prefetch and the
    watchdog; the placement owns everything that touches device state.
    Implementations: ``ops/streaming._SingleDevicePlacement`` and
    ``parallel/sharded._MeshPlacement``. A future multi-host placement
    implements this same interface.

    Class attributes:
      stage_prefix: profiler stage name prefix per slab window (the
        window's first chunk index is appended).
      prefetch_prefix: thread-name prefix for the lookahead encode pool.
      degradable: device OOM halves the slab window (single-device);
        False re-issues the window as a counted retry (mesh — chunk
        granularity is fixed by the mesh shape).
      donates: non-compact chunk steps donate the accumulator buffers
        into the kernel, so a failure mid-step poisons them (recovery
        must restore from a checkpoint). Compact steps never donate.
      compact: chunk results are compact per-group columns collected in
        ``pending`` and folded by :meth:`merge_pending` at checkpoints
        and once at the end, instead of dense per-chunk scatters.
    """

    stage_prefix: str = "dp/stream_slab_"
    prefetch_prefix: str = "pdp-slab-prefetch"
    degradable: bool = False
    donates: bool = False
    compact: bool = False

    @abc.abstractmethod
    def init_state(self) -> Tuple[Any, Any]:
        """Initial (accs, qhist) before any chunk folds."""

    @abc.abstractmethod
    def transfer(self, slab, s0: int, s1: int) -> Any:
        """Ships the host slab for window [s0, s1); returns the device
        payload the chunk steps consume."""

    @abc.abstractmethod
    def step(self, c: int, payload, offset: int, accs, qhist
             ) -> Tuple[Any, Any]:
        """Folds chunk ``c`` (``payload`` row ``offset``) into the
        accumulators; returns the new (accs, qhist)."""

    def compact_step(self, c: int, payload, offset: int) -> Any:
        """Compact-mode chunk kernel: returns the chunk's pending
        compact-group columns (only called when ``compact``)."""
        raise NotImplementedError

    def merge_pending(self, accs, pending: List[Any]) -> Any:
        """Folds the pending compact chunks into the dense accumulators
        (only called when ``compact``)."""
        raise NotImplementedError

    @abc.abstractmethod
    def snapshot(self, accs, qhist) -> Tuple[Tuple, Optional[Any]]:
        """Host copies of the accumulator state for a checkpoint."""

    @abc.abstractmethod
    def restore(self, cp: checkpoint_lib.StreamCheckpoint,
                expects_qhist: bool) -> Tuple[Any, Any]:
        """Fresh device state from a validated checkpoint."""

    def sync(self, accs, qhist, pending) -> None:
        """Blocks until the window's dispatched work is materialized —
        the watchdog's per-window progress bound (only called with a
        watchdog attached)."""
        import jax

        state = [x for x in (accs, qhist) if x is not None]
        jax.block_until_ready(state + list(pending))


@dataclasses.dataclass
class SlabPlan:
    """The static schedule of one streamed run.

    fmt_desc is an opaque description of the wire layout (it enters the
    checkpoint wire fingerprint verbatim, so it must be stable across
    the checkpointing and resuming processes). on_chunk, when set, is
    called once per executed chunk (the sort-cost counter crediting the
    jitted kernels cannot do per execution). prefetch_depth bounds the
    background host-encode lookahead (0 disables).

    retain_sink, when set, is the driver's retain-wire mode: it is
    called with ``(s0, s1, slab)`` for every successfully prepared host
    slab window, letting a resident-dataset session keep the sorted wire
    chunks instead of discarding them after the fold
    (ops/streaming.ingest_resident_wire; SERVING.md). It must be
    idempotent per ``(s0, s1)`` range — retries, OOM-degraded windows
    and resumes may prepare (and therefore retain) a range more than
    once, and degradations change the window boundaries.
    """
    n_chunks: int
    window_chunks: int
    fmt_desc: str
    counts: Any
    n_uniq: Optional[Any]
    scatter_passes: int = 5
    quantile: bool = False
    data_digest_fn: Optional[Callable[[], str]] = None
    on_chunk: Optional[Callable[[], None]] = None
    prefetch_depth: int = 0
    retain_sink: Optional[Callable[[int, int, Any], None]] = None


class SlabDriver:
    """One resilient pass over a :class:`SlabPlan`'s chunk schedule.

    ``prepare_slab(s0, s1)`` is the pure host encode of window
    [s0, s1) — pure in the sense that a discarded prefetch, a degraded
    window, or a resume may simply call it again (the native per-bucket
    sort is idempotent; released values never depend on scheduling).
    """

    def __init__(self, placement: DevicePlacement, plan: SlabPlan,
                 prepare_slab: Callable[[int, int], Any], key,
                 resilience=None):
        self._placement = placement
        self._plan = plan
        self._prepare_slab = prepare_slab
        self._key = key
        self._resilience = resilience

    def _watchdog(self) -> Optional[watchdog_lib.DispatchWatchdog]:
        timeout = None
        if self._resilience is not None:
            timeout = self._resilience.watchdog_timeout_s
        if timeout is None:
            timeout = watchdog_lib.env_timeout_s()
        return (watchdog_lib.DispatchWatchdog(timeout)
                if timeout is not None else None)

    def run(self) -> Tuple[Any, Any]:
        """Returns the final (accs, qhist); qhist is None unless the
        plan streams quantile histograms."""
        placement, plan = self._placement, self._plan
        resilience = self._resilience
        k = plan.n_chunks
        accs, qhist = placement.init_state()

        policy = injector = cp_policy = deadline = None
        key_fp = wire_fp = None
        cursor = 0
        if resilience is not None:
            policy = resilience.retry_policy
            injector = resilience.fault_injector
            cp_policy = resilience.checkpoint_policy
            deadline = getattr(resilience, "deadline", None)
            if cp_policy is not None or resilience.resume_from is not None:
                key_fp = checkpoint_lib.key_fingerprint(self._key)
                wire_fp = checkpoint_lib.wire_fingerprint(
                    k, plan.fmt_desc, plan.counts, plan.n_uniq,
                    data_digest=(plan.data_digest_fn()
                                 if plan.data_digest_fn else ""))
                cp = resilience.resume_from
                if cp is None and cp_policy is not None:
                    cp = cp_policy.store.load(cp_policy.run_id)
                if cp is not None:
                    cp.validate(key_fp=key_fp, wire_fp=wire_fp, n_chunks=k,
                                key_counter=resilience.key_counter)
                    accs, qhist = placement.restore(
                        cp, expects_qhist=plan.quantile)
                    cursor = int(cp.next_chunk)
                    profiler.count_event(EVENT_RESUMES)
                    obs_trace.event("resume", next_chunk=cursor)

        def save_checkpoint(next_chunk, accs, qhist):
            import time as time_lib
            t0 = time_lib.perf_counter()
            with obs_trace.span("driver/checkpoint",
                                next_chunk=int(next_chunk)):
                host_accs, host_q = placement.snapshot(accs, qhist)
                cp = checkpoint_lib.StreamCheckpoint(
                    run_id=cp_policy.run_id, next_chunk=next_chunk,
                    n_chunks=k, accs=host_accs, qhist=host_q,
                    key_fingerprint=key_fp, wire_fingerprint=wire_fp,
                    key_counter=resilience.key_counter)
                cp_policy.store.save(cp)
            profiler.count_event(EVENT_CHECKPOINT_BYTES, cp.nbytes())
            obs_metrics.checkpoint_write_seconds().observe(
                time_lib.perf_counter() - t0)

        compact = placement.compact
        donating = placement.donates and not compact
        pending = []  # compact mode: per-chunk columns since last merge

        window = max(1, plan.window_chunks)
        ordinal = 0  # window starts incl. re-issues (fault script index)
        failures = 0  # consecutive failed attempts of the current window
        since_checkpoint = 0

        wd = self._watchdog()

        def guarded(what, fn):
            return wd.call(what, fn) if wd is not None else fn()

        # Lookahead prefetch pool: window keys are the exact (s0, s1)
        # ranges, so a budget degradation naturally invalidates stale
        # prefetches; stage times recorded by pool threads merge into
        # this thread's collectors via the adopted sinks.
        depth = plan.prefetch_depth
        executor = None
        inflight = {}
        parent_sinks = profiler.current_sinks()
        parent_span = obs_trace.current()

        def prefetch_call(a, b):
            with profiler.adopt_sinks(parent_sinks), \
                    obs_trace.attach(parent_span):
                with profiler.stage("dp/wire_sort_parallel"), \
                        obs_trace.span("driver/prefetch_encode",
                                       chunk0=a, chunk1=b):
                    return self._prepare_slab(a, b)

        def discard_inflight():
            for fut in inflight.values():
                fut.cancel()
            inflight.clear()

        try:
            if depth > 0 and k > 1:
                import concurrent.futures
                executor = concurrent.futures.ThreadPoolExecutor(
                    max_workers=depth,
                    thread_name_prefix=placement.prefetch_prefix)
            while cursor < k:
                if deadline is not None:
                    # Cooperative per-query deadline (serving): checked
                    # OUTSIDE the retry handler so an expired query
                    # propagates typed and immediately — it never burns
                    # retries or backoff against an exhausted budget.
                    deadline.check(f"slab window starting at chunk "
                                   f"{cursor}")
                s1 = min(cursor + window, k)
                this_window = ordinal
                ordinal += 1
                in_dispatch = False
                w0, t_w0 = cursor, time.perf_counter()
                try:
                    with profiler.stage(
                            f"{placement.stage_prefix}{cursor}"), \
                            obs_trace.span("driver/window", chunk0=cursor,
                                           chunk1=s1, attempt=failures):
                        fut = inflight.pop((cursor, s1), None)
                        with obs_trace.span("driver/encode",
                                            prefetched=fut is not None):
                            slab = (fut.result() if fut is not None
                                    else self._prepare_slab(cursor, s1))
                        if plan.retain_sink is not None:
                            # Retain-wire mode: hand the validated host
                            # slab to the session before it is consumed
                            # (the corruption guard already ran inside
                            # prepare_slab).
                            plan.retain_sink(cursor, s1, slab)
                        if executor is not None:
                            nxt0 = s1
                            while len(inflight) < depth and nxt0 < k:
                                nxt1 = min(nxt0 + window, k)
                                if (nxt0, nxt1) not in inflight:
                                    inflight[(nxt0, nxt1)] = \
                                        executor.submit(prefetch_call,
                                                        nxt0, nxt1)
                                nxt0 = nxt1
                        s0 = cursor

                        def do_transfer():
                            # The injector's transfer-point faults (incl.
                            # the blocking ``hang`` kind) fire inside the
                            # watchdog guard, like the real transfer.
                            if injector is not None:
                                injector.check("transfer", this_window)
                            return placement.transfer(slab, s0, s1)

                        with obs_trace.span("driver/transfer"):
                            payload = guarded(f"transfer of window "
                                              f"[{s0}, {s1})", do_transfer)
                        if injector is not None:
                            injector.check("kernel", this_window)
                        for c in range(s0, s1):
                            with obs_trace.span("driver/dispatch",
                                                chunk=c):
                                if compact:
                                    pending.append(guarded(
                                        f"chunk {c} dispatch",
                                        lambda c=c: placement.compact_step(
                                            c, payload, c - s0)))
                                    profiler.count_event(
                                        EVENT_COMPACT_CHUNKS)
                                else:
                                    in_dispatch = donating
                                    accs, qhist = guarded(
                                        f"chunk {c} dispatch",
                                        lambda c=c: placement.step(
                                            c, payload, c - s0, accs,
                                            qhist))
                                    in_dispatch = False
                                    profiler.count_event(
                                        EVENT_PARTITION_SCATTERS,
                                        plan.scatter_passes)
                            if plan.on_chunk is not None:
                                plan.on_chunk()
                            cursor = c + 1
                        if wd is not None:
                            # The per-window progress bound. A timeout
                            # here means dispatched-but-unmaterialized
                            # state: only a checkpoint is trustworthy.
                            in_dispatch = True
                            with obs_trace.span("driver/sync"):
                                wd.call("window sync",
                                        lambda: placement.sync(accs, qhist,
                                                               pending))
                            in_dispatch = False
                except Exception as exc:
                    failure_kind = retry_lib.classify(exc)
                    if isinstance(exc, watchdog_lib.DispatchHangError):
                        profiler.count_event(EVENT_HANGS)
                        obs_trace.event("watchdog_timeout",
                                        error=type(exc).__name__)
                    if policy is None or failure_kind == retry_lib.FATAL:
                        raise
                    if in_dispatch:
                        # The failing step may have consumed its donated
                        # accumulator buffers (or, after a sync timeout,
                        # the abandoned dispatch may still be mutating
                        # them); only a checkpoint restores trustworthy
                        # state.
                        cp = (cp_policy.store.load(cp_policy.run_id)
                              if cp_policy is not None else None)
                        if cp is None:
                            raise
                        cp.validate(key_fp=key_fp, wire_fp=wire_fp,
                                    n_chunks=k,
                                    key_counter=resilience.key_counter)
                        accs, qhist = placement.restore(
                            cp, expects_qhist=plan.quantile)
                        cursor = int(cp.next_chunk)
                        pending.clear()
                        profiler.count_event(EVENT_RESUMES)
                        obs_trace.event("resume", next_chunk=cursor)
                    if (failure_kind == retry_lib.OOM
                            and placement.degradable):
                        smaller = policy.degrade_slab_buckets(window)
                        if smaller < window:
                            # Re-issue from the failed chunk with a
                            # halved window; the per-chunk key schedule
                            # is untouched, so results are unchanged.
                            # Window boundaries move — in-flight
                            # prefetches for the old boundaries are
                            # discarded (pure recompute).
                            window = smaller
                            discard_inflight()
                            profiler.count_event(EVENT_DEGRADATIONS)
                            obs_trace.event("degrade",
                                            window_chunks=smaller)
                            continue
                    failures += 1
                    if failures > policy.max_retries:
                        raise
                    if deadline is not None:
                        # Never back off past the query's budget.
                        deadline.check(f"retry of window [{cursor}, {s1})")
                    profiler.count_event(EVENT_RETRIES)
                    obs_trace.event("retry", error=type(exc).__name__,
                                    attempt=failures)
                    policy.sleep(policy.backoff_s(failures - 1))
                    continue
                # Window timing into the always-on flight recorder: the
                # post-mortem of a later hang shows how far the stream
                # got and how fast it was moving.
                obs_flight.record(
                    "window", chunk0=w0, chunk1=cursor,
                    ms=round((time.perf_counter() - t_w0) * 1000.0, 3),
                    attempt=failures)
                failures = 0
                since_checkpoint += 1
                if (cp_policy is not None and cursor < k
                        and since_checkpoint >= cp_policy.every_slabs):
                    if compact and pending:
                        # Fold pending compact chunks into the dense base
                        # so the checkpoint format stays dense
                        # accumulators.
                        accs = placement.merge_pending(accs, pending)
                        pending = []
                    save_checkpoint(cursor, accs, qhist)
                    since_checkpoint = 0
        finally:
            discard_inflight()
            if executor is not None:
                executor.shutdown(wait=True)
            if wd is not None:
                wd.close()
        if compact and pending:
            accs = placement.merge_pending(accs, pending)
            pending = []
        if cp_policy is not None and cp_policy.delete_on_success:
            cp_policy.store.delete(cp_policy.run_id)
        return accs, qhist
