"""Retry/degradation policy for the streamed execution path.

Failure taxonomy (what the slab drivers do with a caught exception):

  * ``oom`` — the device ran out of memory (``RESOURCE_EXHAUSTED``, real
    or injected). Retrying the identical slab would fail the identical
    way, so the driver *degrades*: it halves the slab window (equivalently
    the slab byte budget) and re-issues from the failed slab. The
    per-chunk key schedule is untouched — chunk keys are
    ``fold_in(key, c)`` regardless of how chunks group into slabs — so
    the released values are distribution-identical (bit-identical for a
    seeded run).
  * ``transient`` — transfer hiccups, preempted dispatches, injected
    transfer/kernel faults, and dispatch-watchdog timeouts
    (:class:`watchdog.DispatchHangError` — a hang is retried with
    backoff like any transient fault, and retry exhaustion surfaces the
    typed error instead of an indefinite hang). Re-issued after bounded
    exponential backoff. :class:`watchdog.QueryDeadlineError` (a serving
    query past its per-query deadline) is also transient — *retryable by
    the caller* with a fresh deadline, since the expired attempt
    released nothing — but the slab driver itself never retries it: the
    deadline is checked before each window and before each backoff
    sleep, outside the retry handler, so an expired query propagates
    immediately instead of burning retries against an exhausted budget.
  * ``fatal`` — everything else (including :class:`faults.HostCrash` and
    privacy-relevant guards like the wirecodec corrupted-input
    RuntimeError). Propagates; recovery is restart + checkpoint resume.

Classification is by exception type for injected faults and by status-code
substring for real runtime errors (JAX surfaces XLA/PJRT failures as
RuntimeErrors whose messages carry the gRPC-style status code).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import time
from typing import Callable, Optional

from pipelinedp_tpu.runtime import faults
from pipelinedp_tpu.runtime import watchdog as watchdog_lib

OOM = "oom"
TRANSIENT = "transient"
FATAL = "fatal"

# Status codes worth re-issuing a slab for (preemption, link hiccups).
_TRANSIENT_CODES = ("ABORTED", "UNAVAILABLE", "DEADLINE_EXCEEDED",
                    "CANCELLED")


def classify(exc: BaseException) -> str:
    """OOM / TRANSIENT / FATAL for a caught slab-loop exception."""
    if isinstance(exc, faults.HostCrash):
        return FATAL
    message = str(exc)
    if isinstance(exc, faults.InjectedOom) or "RESOURCE_EXHAUSTED" in message:
        return OOM
    if isinstance(exc, faults.InjectedFault):
        return TRANSIENT
    if isinstance(exc, watchdog_lib.DispatchHangError):
        # Covers QueryDeadlineError too (a subclass): both mean "the
        # time budget expired with nothing released".
        return TRANSIENT
    if isinstance(exc, RuntimeError) and any(code in message
                                             for code in _TRANSIENT_CODES):
        return TRANSIENT
    return FATAL


def _jitter_uniform(seed: int, draw: int) -> float:
    """The ``draw``-th uniform in [0, 1) of the seeded jitter stream —
    sha256-derived, so it is deterministic under ``seed`` without a
    stateful stdlib PRNG. Timing jitter only; never a DP noise source
    (DP noise rides the engine's threefry/native generators)."""
    digest = hashlib.sha256(f"retry-jitter:{seed}:{draw}".encode()).digest()
    return int.from_bytes(digest[:8], "big") / float(1 << 64)


@dataclasses.dataclass
class RetryPolicy:
    """Bounded backoff + OOM degradation knobs for the slab drivers.

    max_retries bounds *consecutive* failed attempts of one slab window;
    a completed window resets the count. OOM degradations that actually
    shrink the window don't count against it (each halving changes the
    attempted work, so it is progress, not a blind retry) — the floor is
    a 1-chunk window, after which OOM falls back to counted retries.
    """
    max_retries: int = 3
    backoff_base_s: float = 0.05
    backoff_max_s: float = 2.0
    # sleep is injectable so tests assert backoff without waiting it out.
    sleep: Callable[[float], None] = time.sleep
    # jitter="decorrelated" spreads a fleet of hosts retrying the same
    # store (lease renews, shared-WAL contention) so they don't
    # thundering-herd on synchronized exponential steps. Default "none"
    # keeps the historical pure-exponential delays bit-for-bit.
    # jitter_seed pins the jitter sequence (chaos tests must reproduce);
    # None draws an OS seed — fine for timing, never used for DP noise.
    jitter: str = "none"
    jitter_seed: Optional[int] = None

    def __post_init__(self):
        if self.jitter not in ("none", "decorrelated"):
            raise ValueError(
                f"jitter must be 'none' or 'decorrelated', got "
                f"{self.jitter!r}")
        if self.jitter_seed is None:
            self.jitter_seed = int.from_bytes(os.urandom(8), "big")
        self._jitter_draws = 0
        self._prev_backoff_s = self.backoff_base_s

    def backoff_s(self, attempt: int) -> float:
        """Backoff delay before retry ``attempt`` (0-based).

        jitter="none": deterministic bounded exponential. With
        "decorrelated" jitter each delay is drawn uniformly from
        [base, 3 * previous_delay] and capped at backoff_max_s (the
        AWS "decorrelated jitter" recipe) — successive retries spread
        apart instead of marching in lockstep with every other host
        that failed at the same instant. Deterministic under
        ``jitter_seed``; :meth:`reset_backoff` restarts the sequence."""
        base = min(self.backoff_max_s, self.backoff_base_s * (2.0**attempt))
        if self.jitter == "none":
            return base
        hi = max(self.backoff_base_s, self._prev_backoff_s * 3.0)
        u = _jitter_uniform(self.jitter_seed, self._jitter_draws)
        self._jitter_draws += 1
        delay = min(self.backoff_max_s,
                    self.backoff_base_s + (hi - self.backoff_base_s) * u)
        self._prev_backoff_s = delay
        return delay

    def reset_backoff(self) -> None:
        """Restarts the decorrelated-jitter chain (call after a success
        so the next failure backs off from the base again)."""
        self._prev_backoff_s = self.backoff_base_s

    def degrade_slab_buckets(self, slab_buckets: int) -> int:
        """Halved slab window (>= 1 chunk) after a device OOM."""
        return max(1, slab_buckets // 2)
