"""At-most-once release journal (in-memory and durable file-backed).

DP correctness survives crashes only if recovery is at-most-once with
respect to randomness release: a retry that re-draws already-released
noise publishes two correlated views of the data under one accounted
budget. The journal makes the release step explicit — the engine commits a
*release token* derived from the KeyStream state (root-key fingerprint +
counter) immediately before finalization, and committing the same token
twice raises :class:`DoubleReleaseError` instead of silently leaking.

The budget side (each mechanism's epsilon/delta spend committed exactly
once) lives on the accountant itself: ``BudgetAccountant.spend_journal``
plus the one-shot ``MechanismSpec`` setters in budget_accounting.py — and
the accountant's ``durable_spend_journal=`` knob persists those spends
through this module's file journal, so a re-exec'd pipeline refuses to
replay a committed spend too.

Durability: the in-memory :class:`ReleaseJournal` dies with the process —
which is exactly the failure the resilient runtime exists to survive, so
production runs use :class:`FileReleaseJournal`: a WAL-style append-only
file, one fsync'd JSON record per commit with a per-record digest. The
commit ordering guarantee is *write-ahead*: the record is durable on disk
before ``commit`` returns, and ``commit`` returns before any noise is
drawn, so a crash at any point errs toward zero releases, never two.
Recovery tolerates a torn tail (a crash mid-append leaves a partial last
line, which by the write-ahead rule was never acknowledged — it is
truncated away); any other malformed record is real corruption and raises
:class:`JournalCorruptError` rather than silently forgetting a committed
release. ``compact()`` rewrites the file atomically (tmp + fsync +
rename).

The journal is deliberately an explicit, caller-owned object (engine knob
``release_journal=``): its scope defines what "the same release" means.
Share one journal across the retries/resumes/re-execs of a production run
(a file path for FileReleaseJournal); give independent experiments
independent journals (or None — the default — for the reference's
semantics, where re-release is the caller's accounting decision).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
import threading
from typing import List, Tuple

from pipelinedp_tpu import profiler

# Profiler event counters (profiler.count_event / event_count):
#   journal_recoveries — durable journals opened with committed records
#     recovered from disk (i.e. a re-exec picked up prior releases);
#   journal_bytes — bytes appended to durable journals.
EVENT_JOURNAL_RECOVERIES = "runtime/journal_recoveries"
EVENT_JOURNAL_BYTES = "runtime/journal_bytes"


class DoubleReleaseError(RuntimeError):
    """A committed release (or spend) was about to be replayed."""


class JournalCorruptError(RuntimeError):
    """A durable journal holds a malformed interior record — committed
    release history cannot be trusted, so recovery refuses rather than
    silently forgetting a release."""


@dataclasses.dataclass(frozen=True)
class ReleaseRecord:
    """One committed release, in commit order."""
    seq: int
    kind: str  # e.g. "noise_release"
    token: Tuple


class ReleaseJournal:
    """Append-only set of committed release tokens (process-local)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._committed = {}
        self._records: List[ReleaseRecord] = []

    def commit(self, token: Tuple, kind: str = "noise_release"
               ) -> ReleaseRecord:
        """Records the release; raises if ``token`` was already committed.

        Must be called *before* the release is computed/published, so the
        failure mode is "refused to re-release", never "released twice".
        """
        with self._lock:
            token = _canonical_token(token)
            if token in self._committed:
                prior = self._committed[token]
                raise DoubleReleaseError(
                    f"release token {token!r} was already committed "
                    f"(record #{prior.seq}, kind={prior.kind!r}): a "
                    f"resumed or retried run is about to re-draw "
                    f"already-released noise. Use a fresh seed (or a "
                    f"fresh journal) if a second, separately-accounted "
                    f"release is intended.")
            record = ReleaseRecord(seq=len(self._records), kind=kind,
                                   token=token)
            # Write-ahead: durable journals persist (fsync) before the
            # commit is acknowledged in memory.
            self._persist(record)
            self._committed[token] = record
            self._records.append(record)
            return record

    def _persist(self, record: ReleaseRecord) -> None:
        """Durability hook; the in-memory journal keeps nothing."""

    def has(self, token: Tuple) -> bool:
        with self._lock:
            return _canonical_token(token) in self._committed

    @property
    def records(self) -> Tuple[ReleaseRecord, ...]:
        with self._lock:
            return tuple(self._records)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


def _canonical_token(token):
    """Tokens in a canonical, JSON-round-trippable form: sequences become
    tuples, numpy scalars become their Python twins — so a token read
    back from disk compares equal to the live one that wrote it."""
    if isinstance(token, (tuple, list)):
        return tuple(_canonical_token(t) for t in token)
    if hasattr(token, "item") and not isinstance(
            token, (str, bytes, bool, int, float)):
        return token.item()
    return token


def _record_payload(record: ReleaseRecord) -> str:
    """The canonical serialized form of one record (digest input)."""
    return json.dumps(
        {"seq": record.seq, "kind": record.kind, "token": record.token},
        sort_keys=True, separators=(",", ":"))


def _record_digest(payload: str) -> str:
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


class FileReleaseJournal(ReleaseJournal):
    """WAL-backed journal surviving process death (module docstring)."""

    def __init__(self, path: str):
        super().__init__()
        self._path = path
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fh = None
        self.recovered_records = self._recover()
        self._fh = open(self._path, "ab")

    # -- recovery ---------------------------------------------------------

    def _recover(self) -> int:
        if not os.path.exists(self._path):
            return 0
        with open(self._path, "rb") as f:
            data = f.read()
        records: List[ReleaseRecord] = []
        good_end = 0
        lines = data.split(b"\n")
        # A trailing b"" element means the file ends with a complete
        # newline-terminated record; anything else is a tail candidate.
        for i, raw in enumerate(lines):
            if raw == b"" and i == len(lines) - 1:
                break
            record = self._parse_line(raw, expected_seq=len(records))
            if record is None:
                if i == len(lines) - 1 or (i == len(lines) - 2
                                           and lines[-1] == b""):
                    # Torn tail: the crash happened mid-append, so this
                    # record was never acknowledged — drop it.
                    break
                raise JournalCorruptError(
                    f"{self._path}: record {len(records)} is malformed "
                    f"but later records follow — the journal is "
                    f"corrupted, not torn; refusing to guess at release "
                    f"history")
            records.append(record)
            good_end += len(raw) + 1
        if good_end != len(data):
            # Truncate the torn tail so the next append starts a clean
            # line (a partial line would otherwise fuse with it).
            with open(self._path, "r+b") as f:
                f.truncate(good_end)
        for record in records:
            self._committed[record.token] = record
            self._records.append(record)
        if records:
            profiler.count_event(EVENT_JOURNAL_RECOVERIES)
        return len(records)

    @staticmethod
    def _parse_line(raw: bytes, expected_seq: int):
        """ReleaseRecord from one WAL line, or None when malformed."""
        try:
            obj = json.loads(raw.decode())
            digest = obj.pop("digest")
            record = ReleaseRecord(seq=int(obj["seq"]), kind=obj["kind"],
                                   token=_canonical_token(obj["token"]))
        except (ValueError, KeyError, TypeError, UnicodeDecodeError):
            return None
        if _record_digest(_record_payload(record)) != digest:
            return None
        if record.seq != expected_seq:
            return None
        return record

    # -- durability -------------------------------------------------------

    def _persist(self, record: ReleaseRecord) -> None:
        payload = _record_payload(record)
        line = (payload[:-1] + f',"digest":"{_record_digest(payload)}"}}'
                + "\n").encode()
        self._fh.write(line)
        self._fh.flush()
        os.fsync(self._fh.fileno())
        profiler.count_event(EVENT_JOURNAL_BYTES, len(line))

    def compact(self) -> None:
        """Atomically rewrites the WAL from the in-memory records (drops
        any truncated torn-tail bytes for good; tmp + fsync + rename, so
        a crash mid-compaction leaves the previous file intact)."""
        with self._lock:
            parent = os.path.dirname(self._path) or "."
            fd, tmp = tempfile.mkstemp(dir=parent, suffix=".tmp")
            try:
                with os.fdopen(fd, "wb") as f:
                    for record in self._records:
                        payload = _record_payload(record)
                        f.write((payload[:-1] +
                                 f',"digest":"{_record_digest(payload)}"}}'
                                 + "\n").encode())
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self._path)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
            if self._fh is not None:
                self._fh.close()
            self._fh = open(self._path, "ab")

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None

    def __enter__(self) -> "FileReleaseJournal":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        self.close()
