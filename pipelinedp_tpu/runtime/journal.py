"""At-most-once release journal (in-memory and durable file-backed).

DP correctness survives crashes only if recovery is at-most-once with
respect to randomness release: a retry that re-draws already-released
noise publishes two correlated views of the data under one accounted
budget. The journal makes the release step explicit — the engine commits a
*release token* derived from the KeyStream state (root-key fingerprint +
counter) immediately before finalization, and committing the same token
twice raises :class:`DoubleReleaseError` instead of silently leaking.

The budget side (each mechanism's epsilon/delta spend committed exactly
once) lives on the accountant itself: ``BudgetAccountant.spend_journal``
plus the one-shot ``MechanismSpec`` setters in budget_accounting.py — and
the accountant's ``durable_spend_journal=`` knob persists those spends
through this module's file journal, so a re-exec'd pipeline refuses to
replay a committed spend too.

Durability: the in-memory :class:`ReleaseJournal` dies with the process —
which is exactly the failure the resilient runtime exists to survive, so
production runs use :class:`FileReleaseJournal`: a WAL-style append-only
file, one fsync'd JSON record per commit with a per-record digest. The
commit ordering guarantee is *write-ahead*: the record is durable on disk
before ``commit`` returns, and ``commit`` returns before any noise is
drawn, so a crash at any point errs toward zero releases, never two.
Recovery tolerates a torn tail (a crash mid-append leaves a partial last
line, which by the write-ahead rule was never acknowledged — it is
truncated away); any other malformed record is real corruption and raises
:class:`JournalCorruptError` rather than silently forgetting a committed
release. ``compact()`` rewrites the file atomically (tmp + fsync +
rename).

The journal is deliberately an explicit, caller-owned object (engine knob
``release_journal=``): its scope defines what "the same release" means.
Share one journal across the retries/resumes/re-execs of a production run
(a file path for FileReleaseJournal); give independent experiments
independent journals (or None — the default — for the reference's
semantics, where re-release is the caller's accounting decision).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import tempfile
import threading
import time
from typing import List, Optional, Tuple

from pipelinedp_tpu import profiler

# Profiler event counters (profiler.count_event / event_count):
#   journal_recoveries — durable journals opened with committed records
#     recovered from disk (i.e. a re-exec picked up prior releases);
#   journal_bytes — bytes appended to durable journals.
EVENT_JOURNAL_RECOVERIES = "runtime/journal_recoveries"
EVENT_JOURNAL_BYTES = "runtime/journal_bytes"


class DoubleReleaseError(RuntimeError):
    """A committed release (or spend) was about to be replayed."""


class JournalCorruptError(RuntimeError):
    """A durable journal holds a malformed interior record — committed
    release history cannot be trusted, so recovery refuses rather than
    silently forgetting a release."""


class StaleWriterError(RuntimeError):
    """A WAL append was refused by its writer fence: the appending
    process no longer holds the session's single-writer lease (a newer
    fencing token exists on disk), so its write must not land — a
    partitioned-away ex-primary is fenced *at the journal*, not merely
    raced (serving/fleet.py owns the lease protocol)."""


class JsonlWal:
    """The shared fsync'd JSON-lines WAL (one implementation, many
    journals): FileReleaseJournal, the durable tenant ledgers, and the
    obs release-audit trail (pipelinedp_tpu/obs/audit.py) all ride it.

    Disk format: one JSON object per line, ``seq``-numbered from 0,
    with a truncated-sha256 ``digest`` over the canonical (sorted-key)
    payload appended as the last key. Appends are write-ahead durable:
    the line is flushed and fsync'd before :meth:`append` returns.
    Recovery truncates a torn tail (a partial last line was never
    acknowledged) but raises ``corrupt_error`` on interior corruption —
    committed history is never silently forgotten. :meth:`rewrite`
    compacts atomically (tmp + fsync + rename).
    """

    def __init__(self, path: str,
                 corrupt_error=None):
        self._path = path
        self._corrupt_error = (corrupt_error if corrupt_error is not None
                               else JournalCorruptError)
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        self._fh = None
        # Optional single-writer fence (serving/fleet.py): a callable
        # returning the current fencing token (or raising
        # StaleWriterError); every append consults it and embeds the
        # token in the payload, so the record itself proves which
        # lease generation wrote it.
        self._fence = None
        self.recovered: List[dict] = self._recover()
        self._fh = open(self._path, "ab")
        self._next_seq = len(self.recovered)
        # Group-commit state: appends under ``sync=False`` are written
        # and flushed but not yet fsync'd; ``sync_through`` runs one
        # fsync covering every write up to its ticket (leader/follower —
        # concurrent callers coalesce behind a single fsync).
        self._io_lock = threading.Lock()
        self._sync_cond = threading.Condition()
        self._written_ticket = 0   # monotone count of appended records
        self._synced_ticket = 0    # fsync has covered tickets <= this
        self._sync_leader = False

    @property
    def path(self) -> str:
        return self._path

    @property
    def next_seq(self) -> int:
        """The dense ``seq`` the next appended payload must carry:
        recovered records plus appends since open. Callers that number
        their own records (the serving append WAL, release schedules)
        read it instead of re-deriving the count."""
        return self._next_seq

    @staticmethod
    def _canonical(payload: dict) -> str:
        return json.dumps(payload, sort_keys=True, separators=(",", ":"))

    @classmethod
    def _line(cls, payload: dict) -> bytes:
        canonical = cls._canonical(payload)
        return (canonical[:-1]
                + f',"digest":"{_record_digest(canonical)}"}}'
                + "\n").encode()

    @classmethod
    def _parse_line(cls, raw: bytes, expected_seq: int) -> Optional[dict]:
        """Validated payload dict from one WAL line, or None."""
        try:
            obj = json.loads(raw.decode())
            digest = obj.pop("digest")
        except (ValueError, KeyError, TypeError, UnicodeDecodeError):
            return None
        if not isinstance(obj, dict) or \
                _record_digest(cls._canonical(obj)) != digest:
            return None
        if obj.get("seq") != expected_seq:
            return None
        return obj

    @classmethod
    def _scan(cls, data: bytes, path: str, corrupt_error
              ) -> Tuple[List[dict], int]:
        """(validated payloads, byte offset past the last good record).

        Shared by :meth:`_recover` (which then truncates the torn tail)
        and :func:`read_records` (which never writes — follower-safe)."""
        payloads: List[dict] = []
        good_end = 0
        lines = data.split(b"\n")
        # A trailing b"" element means the file ends with a complete
        # newline-terminated record; anything else is a tail candidate.
        for i, raw in enumerate(lines):
            if raw == b"" and i == len(lines) - 1:
                break
            payload = cls._parse_line(raw, expected_seq=len(payloads))
            if payload is None:
                if i == len(lines) - 1 or (i == len(lines) - 2
                                           and lines[-1] == b""):
                    # Torn tail: the crash happened mid-append, so this
                    # record was never acknowledged — drop it.
                    break
                raise corrupt_error(
                    f"{path}: record {len(payloads)} is malformed "
                    f"but later records follow — the journal is "
                    f"corrupted, not torn; refusing to guess at its "
                    f"history")
            payloads.append(payload)
            good_end += len(raw) + 1
        return payloads, good_end

    def _recover(self) -> List[dict]:
        if not os.path.exists(self._path):
            return []
        with open(self._path, "rb") as f:
            data = f.read()
        payloads, good_end = self._scan(data, self._path,
                                        self._corrupt_error)
        if good_end != len(data):
            # Truncate the torn tail so the next append starts a clean
            # line (a partial line would otherwise fuse with it).
            with open(self._path, "r+b") as f:
                f.truncate(good_end)
        return payloads

    def attach_fence(self, fence) -> None:
        """Installs a single-writer fence: a callable returning the
        current fencing token (int), consulted on *every* append and
        embedded in the record as ``writer_token`` (digest-covered, so
        the token is tamper-evident). The fence raises
        :class:`StaleWriterError` when this process no longer holds the
        lease — the append is refused before any byte lands. ``None``
        detaches (followers replaying a fenced WAL tolerate the extra
        key; only the appender needs the lease)."""
        self._fence = fence

    def append(self, payload: dict, sync: bool = True) -> int:
        """Durably appends one payload (must carry its ``seq``; must not
        carry a ``digest`` key); returns the bytes written.

        With ``sync=False`` the line is written and flushed to the OS
        (it survives SIGKILL via the page cache) but not fsync'd — the
        caller must follow with :meth:`sync_through` before treating the
        record as committed against power loss. Group commit rides this:
        many appends, one fsync."""
        if "digest" in payload:
            raise ValueError("payload key 'digest' is reserved by the WAL")
        if self._fence is not None:
            # The fence re-checks the on-disk lease and raises
            # StaleWriterError if a newer token exists — a partitioned
            # ex-primary is refused here, before the write lands.
            payload = dict(payload, writer_token=int(self._fence()))
        line = self._line(payload)
        with self._io_lock:
            self._fh.write(line)
            self._fh.flush()
            self._written_ticket += 1
            ticket = self._written_ticket
            fd = self._fh.fileno()
            seq = payload.get("seq")
            if isinstance(seq, int):
                self._next_seq = max(self._next_seq, seq + 1)
        if sync:
            # fsync OUTSIDE the io lock: contenders keep writing while
            # storage syncs (an fsync covers every byte written before
            # it runs, so crediting `ticket` stays conservative).
            os.fsync(fd)
            with self._sync_cond:
                if ticket > self._synced_ticket:
                    self._synced_ticket = ticket
                    self._sync_cond.notify_all()
        return len(line)

    def sync_ticket(self) -> int:
        """The current write ticket: passing it to :meth:`sync_through`
        guarantees every append that returned before this call is
        fsync'd. Callers serializing their own appends (the serving
        append WAL holds its append lock across append + sync_ticket)
        get exactly their record's ticket."""
        with self._io_lock:
            return self._written_ticket

    @property
    def synced_ticket(self) -> int:
        """Tickets <= this are fsync'd (durable against power loss)."""
        with self._sync_cond:
            return self._synced_ticket

    def sync_through(self, ticket: int, window_s: float = 0.0) -> None:
        """Blocks until every append up to ``ticket`` is fsync'd,
        coalescing concurrent callers behind one fsync (group commit).

        One caller becomes the leader: it optionally waits ``window_s``
        (a bounded commit window, letting more appends land), then runs
        a single fsync covering everything written so far and wakes the
        followers. Followers whose ticket is still uncovered loop and
        elect a new leader."""
        while True:
            with self._sync_cond:
                if self._synced_ticket >= ticket:
                    return
                if self._sync_leader:
                    self._sync_cond.wait(timeout=1.0)
                    continue
                self._sync_leader = True
            covered = None
            try:
                if window_s > 0.0:
                    time.sleep(window_s)
                with self._io_lock:
                    target = self._written_ticket
                    self._fh.flush()
                    fd = self._fh.fileno()
                # fsync OUTSIDE the io lock so appenders never stall on
                # storage latency; it covers every byte flushed above,
                # so crediting `target` afterwards stays conservative.
                os.fsync(fd)
                covered = target
            finally:
                with self._sync_cond:
                    self._sync_leader = False
                    if covered is not None and covered > self._synced_ticket:
                        self._synced_ticket = covered
                    self._sync_cond.notify_all()

    def rewrite(self, payloads) -> None:
        """Atomically replaces the file with ``payloads`` (compaction;
        tmp + fsync + rename so a crash leaves the previous file)."""
        payloads = list(payloads)
        parent = os.path.dirname(self._path) or "."
        fd, tmp = tempfile.mkstemp(dir=parent, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                for payload in payloads:
                    f.write(self._line(payload))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        with self._io_lock:
            if self._fh is not None:
                self._fh.close()
            self._fh = open(self._path, "ab")
            self._next_seq = len(payloads)
            covered = self._written_ticket
        with self._sync_cond:
            # The rewritten file is fully fsync'd: every prior append is
            # durable by construction.
            if covered > self._synced_ticket:
                self._synced_ticket = covered
            self._sync_cond.notify_all()

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def read_records(path: str, corrupt_error=None) -> List[dict]:
    """Read-only snapshot of a WAL's committed records — *no* side
    effects on the file.

    Constructing a :class:`JsonlWal` opens the file for append and
    truncates any torn tail — both writes, both forbidden against a file
    a *live* primary still owns. A hot follower (serving/fleet.py) tails
    the primary's WALs with this scanner instead: same digest/seq
    validation, same interior-corruption refusal, but a torn or
    still-being-written tail line is simply ignored (to a reader it is
    indistinguishable from an append in flight — the next poll sees it
    complete or truncated by recovery, never half-applied)."""
    if corrupt_error is None:
        corrupt_error = JournalCorruptError
    if not os.path.exists(path):
        return []
    with open(path, "rb") as f:
        data = f.read()
    payloads, _ = JsonlWal._scan(data, path, corrupt_error)
    return payloads


@dataclasses.dataclass(frozen=True)
class ReleaseRecord:
    """One committed release, in commit order."""
    seq: int
    kind: str  # e.g. "noise_release"
    token: Tuple


class ReleaseJournal:
    """Append-only set of committed release tokens (process-local)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._committed = {}
        self._records: List[ReleaseRecord] = []

    def commit(self, token: Tuple, kind: str = "noise_release"
               ) -> ReleaseRecord:
        """Records the release; raises if ``token`` was already committed.

        Must be called *before* the release is computed/published, so the
        failure mode is "refused to re-release", never "released twice".
        """
        with self._lock:
            token = _canonical_token(token)
            if token in self._committed:
                prior = self._committed[token]
                raise DoubleReleaseError(
                    f"release token {token!r} was already committed "
                    f"(record #{prior.seq}, kind={prior.kind!r}): a "
                    f"resumed or retried run is about to re-draw "
                    f"already-released noise. Use a fresh seed (or a "
                    f"fresh journal) if a second, separately-accounted "
                    f"release is intended.")
            record = ReleaseRecord(seq=len(self._records), kind=kind,
                                   token=token)
            # Write-ahead: durable journals persist (fsync) before the
            # commit is acknowledged in memory.
            self._persist(record)
            self._committed[token] = record
            self._records.append(record)
            return record

    def _persist(self, record: ReleaseRecord) -> None:
        """Durability hook; the in-memory journal keeps nothing."""

    def has(self, token: Tuple) -> bool:
        with self._lock:
            return _canonical_token(token) in self._committed

    @property
    def records(self) -> Tuple[ReleaseRecord, ...]:
        with self._lock:
            return tuple(self._records)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


def _canonical_token(token):
    """Tokens in a canonical, JSON-round-trippable form: sequences become
    tuples, numpy scalars become their Python twins — so a token read
    back from disk compares equal to the live one that wrote it."""
    if isinstance(token, (tuple, list)):
        return tuple(_canonical_token(t) for t in token)
    if hasattr(token, "item") and not isinstance(
            token, (str, bytes, bool, int, float)):
        return token.item()
    return token


def _record_digest(payload: str) -> str:
    return hashlib.sha256(payload.encode()).hexdigest()[:16]


class FileReleaseJournal(ReleaseJournal):
    """WAL-backed journal surviving process death (module docstring).
    The file discipline — fsync'd appends, per-record digests, torn-tail
    truncation, interior-corruption refusal, atomic compaction — lives
    in the shared :class:`JsonlWal`."""

    def __init__(self, path: str):
        super().__init__()
        self._path = path
        self._wal = JsonlWal(path)
        records: List[ReleaseRecord] = []
        try:
            for payload in self._wal.recovered:
                records.append(ReleaseRecord(
                    seq=int(payload["seq"]), kind=payload["kind"],
                    token=_canonical_token(payload["token"])))
        except (KeyError, TypeError, ValueError) as exc:
            raise self._corrupt(
                f"{path}: record {len(records)} is not a release record "
                f"({exc})")
        for record in records:
            self._committed[record.token] = record
            self._records.append(record)
        if records:
            profiler.count_event(EVENT_JOURNAL_RECOVERIES)
        self.recovered_records = len(records)

    @staticmethod
    def _corrupt(msg: str) -> "JournalCorruptError":
        return JournalCorruptError(msg)

    # -- durability -------------------------------------------------------

    @staticmethod
    def _payload(record: ReleaseRecord) -> dict:
        return {"seq": record.seq, "kind": record.kind,
                "token": record.token}

    def _persist(self, record: ReleaseRecord) -> None:
        nbytes = self._wal.append(self._payload(record))
        profiler.count_event(EVENT_JOURNAL_BYTES, nbytes)

    def attach_fence(self, fence) -> None:
        """Single-writer fence pass-through (see JsonlWal.attach_fence):
        tenant ledgers and release journals are fenced too, so a stale
        primary cannot spend budget any more than it can append data."""
        self._wal.attach_fence(fence)

    def compact(self) -> None:
        """Atomically rewrites the WAL from the in-memory records (drops
        any truncated torn-tail bytes for good; tmp + fsync + rename, so
        a crash mid-compaction leaves the previous file intact)."""
        with self._lock:
            self._wal.rewrite(self._payload(r) for r in self._records)

    def close(self) -> None:
        self._wal.close()

    def __enter__(self) -> "FileReleaseJournal":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        self.close()
