"""At-most-once release journal.

DP correctness survives crashes only if recovery is at-most-once with
respect to randomness release: a retry that re-draws already-released
noise publishes two correlated views of the data under one accounted
budget. The journal makes the release step explicit — the engine commits a
*release token* derived from the KeyStream state (root-key fingerprint +
counter) immediately before finalization, and committing the same token
twice raises :class:`DoubleReleaseError` instead of silently leaking.

The budget side (each mechanism's epsilon/delta spend committed exactly
once) lives on the accountant itself: ``BudgetAccountant.spend_journal``
plus the one-shot ``MechanismSpec`` setters in budget_accounting.py.

The journal is deliberately an explicit, caller-owned object (engine knob
``release_journal=``): its scope defines what "the same release" means.
Share one journal across the retries/resumes of a production run; give
independent experiments independent journals (or None — the default — for
the reference's semantics, where re-release is the caller's accounting
decision).
"""

from __future__ import annotations

import dataclasses
import threading
from typing import List, Tuple


class DoubleReleaseError(RuntimeError):
    """A committed release (or spend) was about to be replayed."""


@dataclasses.dataclass(frozen=True)
class ReleaseRecord:
    """One committed release, in commit order."""
    seq: int
    kind: str  # e.g. "noise_release"
    token: Tuple


class ReleaseJournal:
    """Append-only set of committed release tokens."""

    def __init__(self):
        self._lock = threading.Lock()
        self._committed = {}
        self._records: List[ReleaseRecord] = []

    def commit(self, token: Tuple, kind: str = "noise_release"
               ) -> ReleaseRecord:
        """Records the release; raises if ``token`` was already committed.

        Must be called *before* the release is computed/published, so the
        failure mode is "refused to re-release", never "released twice".
        """
        with self._lock:
            if token in self._committed:
                prior = self._committed[token]
                raise DoubleReleaseError(
                    f"release token {token!r} was already committed "
                    f"(record #{prior.seq}, kind={prior.kind!r}): a "
                    f"resumed or retried run is about to re-draw "
                    f"already-released noise. Use a fresh seed (or a "
                    f"fresh journal) if a second, separately-accounted "
                    f"release is intended.")
            record = ReleaseRecord(seq=len(self._records), kind=kind,
                                   token=token)
            self._committed[token] = record
            self._records.append(record)
            return record

    def has(self, token: Tuple) -> bool:
        with self._lock:
            return token in self._committed

    @property
    def records(self) -> Tuple[ReleaseRecord, ...]:
        with self._lock:
            return tuple(self._records)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)
