"""Deterministic fault injection for the streamed execution path.

The streaming drivers call ``FaultInjector.check(point, slab_ordinal)`` at
two points per slab window — ``"transfer"`` (just before ``device_put``)
and ``"kernel"`` (just before the first chunk dispatch of the window) — and
the injector raises the scripted fault when its spec matches. Faults fire
*before* the real operation, so accumulator state is never half-mutated by
an injected failure (real mid-dispatch failures recover through the
checkpoint instead; see ops/streaming.py).

``slab_ordinal`` counts slab-window *starts*, including re-issues after a
retry or degradation — so ``FaultSpec(kind, at_slab=N, times=t)`` means
"fail the Nth window start and the next t-1 attempts", which is exactly the
"fails twice, then succeeds" script a retry test needs.

Kinds:
  * ``oom`` — raises :class:`InjectedOom` (message carries
    ``RESOURCE_EXHAUSTED`` so the retry classifier treats it like a real
    device OOM) at the transfer point.
  * ``transfer`` / ``kernel`` — transient faults at their points.
  * ``host_crash`` — raises :class:`HostCrash` at the transfer point; the
    retry layer never catches it (it simulates process death — the test
    harness "restarts" by building a fresh engine and resuming).
"""

from __future__ import annotations

import dataclasses
import hashlib
from typing import List, Sequence, Tuple


class InjectedFault(RuntimeError):
    """Base class of scripted transient faults (retryable)."""


class InjectedOom(InjectedFault):
    """Scripted device OOM; classified like a real RESOURCE_EXHAUSTED."""

    def __init__(self, slab_ordinal: int):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected device OOM at slab "
            f"{slab_ordinal} (fault injection)")


class InjectedTransferError(InjectedFault):
    """Scripted host->device transfer failure."""

    def __init__(self, slab_ordinal: int):
        super().__init__(
            f"injected transfer fault at slab {slab_ordinal}")


class InjectedKernelError(InjectedFault):
    """Scripted chunk-kernel dispatch failure."""

    def __init__(self, slab_ordinal: int):
        super().__init__(f"injected kernel fault at slab {slab_ordinal}")


class HostCrash(RuntimeError):
    """Simulated process death: never retried, propagates out of the
    stream so tests can exercise the resume-from-checkpoint path."""

    def __init__(self, slab_ordinal: int):
        super().__init__(f"injected host crash at slab {slab_ordinal}")


KIND_OOM = "oom"
KIND_TRANSFER = "transfer"
KIND_KERNEL = "kernel"
KIND_HOST_CRASH = "host_crash"

# Which driver callpoint each fault kind fires at, and what it raises.
_POINT_OF_KIND = {
    KIND_OOM: "transfer",
    KIND_TRANSFER: "transfer",
    KIND_HOST_CRASH: "transfer",
    KIND_KERNEL: "kernel",
}
_EXC_OF_KIND = {
    KIND_OOM: InjectedOom,
    KIND_TRANSFER: InjectedTransferError,
    KIND_KERNEL: InjectedKernelError,
    KIND_HOST_CRASH: HostCrash,
}


@dataclasses.dataclass
class FaultSpec:
    """Fire ``kind`` starting at slab-window ``at_slab``, ``times`` times."""
    kind: str
    at_slab: int
    times: int = 1

    def __post_init__(self):
        if self.kind not in _POINT_OF_KIND:
            raise ValueError(f"unknown fault kind {self.kind!r}; expected "
                             f"one of {sorted(_POINT_OF_KIND)}")


class FaultInjector:
    """Scripted, deterministic fault source for the streaming drivers."""

    def __init__(self, specs: Sequence[FaultSpec]):
        self._specs = [dataclasses.replace(s) for s in specs]
        self.fired: List[Tuple[str, int]] = []  # (kind, slab_ordinal) log

    def check(self, point: str, slab_ordinal: int) -> None:
        """Raises the scripted fault if any armed spec matches ``point``
        at this window; consumes one firing from the spec."""
        for spec in self._specs:
            if (spec.times > 0 and _POINT_OF_KIND[spec.kind] == point
                    and slab_ordinal >= spec.at_slab):
                spec.times -= 1
                self.fired.append((spec.kind, slab_ordinal))
                raise _EXC_OF_KIND[spec.kind](slab_ordinal)

    @property
    def pending(self) -> int:
        """Scripted firings not yet consumed."""
        return sum(max(spec.times, 0) for spec in self._specs)

    @classmethod
    def chaos(cls, seed: int, n_slabs: int,
              fire_percent: int = 25) -> "FaultInjector":
        """A deterministic pseudo-random script over ``n_slabs`` windows.

        Hash-derived (no RNG state, identical across platforms and
        calls): each window fires one transient fault kind with
        ``fire_percent`` probability. host_crash is excluded — a chaos
        run must be completable by retries alone; crash-and-resume has
        its own scripted tests.
        """
        retryable = (KIND_OOM, KIND_TRANSFER, KIND_KERNEL)
        specs = []
        for slab in range(n_slabs):
            digest = hashlib.sha256(f"chaos:{seed}:{slab}".encode()).digest()
            if digest[0] % 100 < fire_percent:
                specs.append(
                    FaultSpec(kind=retryable[digest[1] % len(retryable)],
                              at_slab=slab))
        return cls(specs)
