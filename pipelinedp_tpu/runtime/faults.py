"""Deterministic fault injection for the streamed execution path.

The streaming drivers call ``FaultInjector.check(point, slab_ordinal)`` at
two points per slab window — ``"transfer"`` (just before ``device_put``)
and ``"kernel"`` (just before the first chunk dispatch of the window) — and
the injector raises the scripted fault when its spec matches. Faults fire
*before* the real operation, so accumulator state is never half-mutated by
an injected failure (real mid-dispatch failures recover through the
checkpoint instead; see ops/streaming.py).

``slab_ordinal`` counts slab-window *starts*, including re-issues after a
retry or degradation — so ``FaultSpec(kind, at_slab=N, times=t)`` means
"fail the Nth window start and the next t-1 attempts", which is exactly the
"fails twice, then succeeds" script a retry test needs.

Kinds:
  * ``oom`` — raises :class:`InjectedOom` (message carries
    ``RESOURCE_EXHAUSTED`` so the retry classifier treats it like a real
    device OOM) at the transfer point.
  * ``transfer`` / ``kernel`` — transient faults at their points.
  * ``host_crash`` — raises :class:`HostCrash` at the transfer point; the
    retry layer never catches it (it simulates process death — the test
    harness "restarts" by building a fresh engine and resuming).
  * ``hang`` — raises nothing: ``check`` *blocks* for ``hang_s`` seconds
    at the transfer point, simulating a wedged transfer. The slab driver
    runs the transfer-point check inside its dispatch watchdog
    (runtime/watchdog.py), so a configured watchdog surfaces the hang as
    a typed, retryable ``DispatchHangError`` within its timeout; without
    a watchdog the stall is simply endured — exactly the failure mode
    the watchdog exists for. ``hang_s`` bounds the simulated wedge so an
    unguarded test still terminates.
  * ``sigkill`` — ``os.kill(getpid(), SIGKILL)`` at the transfer point:
    *real* process death, no interpreter cleanup, for the cross-process
    kill/re-exec/resume harness (tests/kill_harness.py). Unlike
    ``host_crash`` nothing propagates — the process is simply gone.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import signal
import time
from typing import List, Sequence, Tuple


class InjectedFault(RuntimeError):
    """Base class of scripted transient faults (retryable)."""


class InjectedOom(InjectedFault):
    """Scripted device OOM; classified like a real RESOURCE_EXHAUSTED."""

    def __init__(self, slab_ordinal: int):
        super().__init__(
            f"RESOURCE_EXHAUSTED: injected device OOM at slab "
            f"{slab_ordinal} (fault injection)")


class InjectedTransferError(InjectedFault):
    """Scripted host->device transfer failure."""

    def __init__(self, slab_ordinal: int):
        super().__init__(
            f"injected transfer fault at slab {slab_ordinal}")


class InjectedKernelError(InjectedFault):
    """Scripted chunk-kernel dispatch failure."""

    def __init__(self, slab_ordinal: int):
        super().__init__(f"injected kernel fault at slab {slab_ordinal}")


class HostCrash(RuntimeError):
    """Simulated process death: never retried, propagates out of the
    stream so tests can exercise the resume-from-checkpoint path."""

    def __init__(self, slab_ordinal: int):
        super().__init__(f"injected host crash at slab {slab_ordinal}")


KIND_OOM = "oom"
KIND_TRANSFER = "transfer"
KIND_KERNEL = "kernel"
KIND_HOST_CRASH = "host_crash"
KIND_HANG = "hang"
KIND_SIGKILL = "sigkill"

# Which driver callpoint each fault kind fires at, and what it raises
# (hang blocks and sigkill kills instead of raising).
_POINT_OF_KIND = {
    KIND_OOM: "transfer",
    KIND_TRANSFER: "transfer",
    KIND_HOST_CRASH: "transfer",
    KIND_KERNEL: "kernel",
    KIND_HANG: "transfer",
    KIND_SIGKILL: "transfer",
}
_EXC_OF_KIND = {
    KIND_OOM: InjectedOom,
    KIND_TRANSFER: InjectedTransferError,
    KIND_KERNEL: InjectedKernelError,
    KIND_HOST_CRASH: HostCrash,
}


@dataclasses.dataclass
class FaultSpec:
    """Fire ``kind`` starting at slab-window ``at_slab``, ``times`` times.

    hang_s: how long a ``hang`` firing blocks (its consumption is
    recorded *before* the stall, so a watchdog-aborted attempt does not
    re-fire on retry)."""
    kind: str
    at_slab: int
    times: int = 1
    hang_s: float = 30.0

    def __post_init__(self):
        if self.kind not in _POINT_OF_KIND:
            raise ValueError(f"unknown fault kind {self.kind!r}; expected "
                             f"one of {sorted(_POINT_OF_KIND)}")


class FaultInjector:
    """Scripted, deterministic fault source for the streaming drivers."""

    def __init__(self, specs: Sequence[FaultSpec]):
        self._specs = [dataclasses.replace(s) for s in specs]
        self.fired: List[Tuple[str, int]] = []  # (kind, slab_ordinal) log

    def check(self, point: str, slab_ordinal: int) -> None:
        """Raises (or blocks, or kills — see the kind catalog above) the
        scripted fault if any armed spec matches ``point`` at this
        window; consumes one firing from the spec."""
        for spec in self._specs:
            if (spec.times > 0 and _POINT_OF_KIND[spec.kind] == point
                    and slab_ordinal >= spec.at_slab):
                spec.times -= 1
                self.fired.append((spec.kind, slab_ordinal))
                if spec.kind == KIND_HANG:
                    time.sleep(spec.hang_s)
                    return
                if spec.kind == KIND_SIGKILL:
                    os.kill(os.getpid(), signal.SIGKILL)
                raise _EXC_OF_KIND[spec.kind](slab_ordinal)

    @property
    def pending(self) -> int:
        """Scripted firings not yet consumed."""
        return sum(max(spec.times, 0) for spec in self._specs)

    @classmethod
    def chaos(cls, seed: int, n_slabs: int, fire_percent: int = 25,
              include_hang: bool = False,
              hang_s: float = 1.0) -> "FaultInjector":
        """A deterministic pseudo-random script over ``n_slabs`` windows.

        Hash-derived (no RNG state, identical across platforms and
        calls): each window fires one transient fault kind with
        ``fire_percent`` probability. host_crash and sigkill are
        excluded — a chaos run must be completable by retries alone;
        crash-and-resume has its own scripted tests. include_hang adds
        the blocking ``hang`` kind to the rotation (same seed => same
        oom/transfer/kernel placement as without it, hangs layered on a
        distinct hash byte) — run those scripts with a dispatch watchdog
        shorter than ``hang_s`` so every hang is detected and retried.
        """
        retryable = (KIND_OOM, KIND_TRANSFER, KIND_KERNEL)
        specs = []
        for slab in range(n_slabs):
            digest = hashlib.sha256(f"chaos:{seed}:{slab}".encode()).digest()
            if digest[0] % 100 < fire_percent:
                specs.append(
                    FaultSpec(kind=retryable[digest[1] % len(retryable)],
                              at_slab=slab))
            elif include_hang and digest[2] % 100 < fire_percent:
                specs.append(FaultSpec(kind=KIND_HANG, at_slab=slab,
                                       hang_s=hang_s))
        return cls(specs)
