"""Bounded-timeout watchdog for device dispatch operations.

A wedged host->device transfer or a device that stops making progress
does not raise — it *blocks*. Without a watchdog the slab loop inherits
that behavior and hangs forever, which is the one failure mode the
retry/checkpoint layer cannot even see (there is no exception to
classify). The watchdog turns "blocked longer than the budget" into a
typed :class:`DispatchHangError` that the retry layer handles like any
other transient fault: bounded backoff re-issues, and exhaustion
surfaces the typed error instead of an indefinite hang.

Mechanics: the guarded operation runs on a dedicated *daemon* worker
thread and the caller waits ``timeout_s`` for its result. On timeout the
worker is *abandoned* (a truly wedged low-level call cannot be
interrupted from Python; the daemon thread parks until the runtime
unwedges or the process exits — daemon so it can never block interpreter
shutdown the way a pooled thread's atexit join would) and a fresh worker
serves the next attempt. An abandoned operation's eventual result is
discarded, so the driver must treat a timed-out step as state-poisoning
and restore from a checkpoint before re-dispatching anything that
donated buffers (runtime/driver.py does).

The watchdog is OFF by default (``StreamResilience.watchdog_timeout_s``
is None and ``PIPELINEDP_TPU_WATCHDOG_S`` is 0): enabling it adds one
``block_until_ready`` sync per slab window — bounded hang detection is
bought with a little cross-window pipelining (RESILIENCE.md).
"""

from __future__ import annotations

import dataclasses
import itertools
import queue
import threading
import time
from typing import Callable, Optional, TypeVar

from pipelinedp_tpu import profiler
from pipelinedp_tpu.obs import flight as flight_lib

# Profiler event counter: one per timed-out guarded operation (the
# runtime/hangs_detected counter — one per hang the driver acted on —
# lives in runtime/driver.py and is credited by the slab driver).
EVENT_WATCHDOG_TIMEOUTS = "runtime/watchdog_timeouts"

# Validated env default for the timeout (seconds; 0 = disabled) when
# StreamResilience.watchdog_timeout_s is None. See README "Tuning knobs".
WATCHDOG_ENV = "PIPELINEDP_TPU_WATCHDOG_S"

T = TypeVar("T")


class DispatchHangError(RuntimeError):
    """A guarded device operation exceeded the watchdog budget.

    Classified as ``transient`` by runtime/retry.py: bounded retries
    re-issue the slab window, and retry exhaustion propagates this typed
    error — either way the slab loop never hangs indefinitely.
    """

    def __init__(self, what: str, timeout_s: float,
                 postmortem: str = ""):
        super().__init__(
            f"dispatch watchdog: {what} made no progress within "
            f"{timeout_s:g}s (wedged transfer/dispatch abandoned; the "
            f"operation will be re-issued or surfaced by the retry "
            f"policy)"
            + (f" [{postmortem}]" if postmortem else ""))
        self.what = what
        self.timeout_s = timeout_s
        self.postmortem = postmortem


class QueryDeadlineError(DispatchHangError):
    """A serving query exceeded its per-query deadline.

    Raised by two cooperating mechanisms (serving/manager.py,
    SERVING.md "Fleet operation"): the slab driver checks the
    :class:`Deadline` between windows (a long-but-progressing replay
    stops at the next window boundary), and the serving layer runs the
    whole query under a :class:`DispatchWatchdog` whose budget is the
    remaining deadline (a *wedged* replay — which never reaches a window
    boundary — is abandoned and surfaced within the deadline).

    Classified ``transient`` by runtime/retry.py (it subclasses
    DispatchHangError): the caller may safely retry with a fresh
    deadline — no randomness was released by the expired attempt on the
    cooperative path, and on the watchdog path the at-most-once journal
    refuses any replay the abandoned worker might still commit.
    """

    def __init__(self, what: str, deadline_s: float,
                 postmortem: str = ""):
        # Skip DispatchHangError.__init__'s message; a deadline is a
        # budget the caller chose, not a wedged dispatch.
        RuntimeError.__init__(
            self, f"query deadline: {what} did not complete within the "
            f"{deadline_s:g}s deadline (shed or retry with a fresh "
            f"deadline; no noise was released by this attempt)"
            + (f" [{postmortem}]" if postmortem else ""))
        self.what = what
        self.timeout_s = deadline_s
        self.postmortem = postmortem


@dataclasses.dataclass
class Deadline:
    """A monotonic per-query time budget (serving/manager.py).

    ``expires_at`` is a ``time.monotonic()`` timestamp so the budget is
    immune to wall-clock jumps. The slab driver calls :meth:`check`
    between windows and before retry backoff sleeps, so an expired
    query surfaces promptly as :class:`QueryDeadlineError` instead of
    finishing (or backing off) past its budget.
    """
    expires_at: float
    total_s: float

    @classmethod
    def after(cls, seconds: float) -> "Deadline":
        return cls(expires_at=time.monotonic() + float(seconds),
                   total_s=float(seconds))

    def remaining_s(self) -> float:
        return self.expires_at - time.monotonic()

    @property
    def expired(self) -> bool:
        return self.remaining_s() <= 0

    def fraction_remaining(self) -> float:
        """Budget left as a fraction of the total, clamped to [0, 1].

        Shared pacing signal for the fleet tier (serving/fleet.py): a
        lease holder renews once its expiry deadline drops below half,
        and the FleetRouter hedges a warm read to a follower when a
        query's deadline budget is nearly burnt — both ride the same
        monotonic arithmetic the slab driver's checks use, so neither is
        fooled by wall-clock jumps."""
        if self.total_s <= 0:
            return 0.0
        return max(0.0, min(1.0, self.remaining_s() / self.total_s))

    def check(self, what: str) -> None:
        if self.expired:
            # A deadline expiry is a hang report: leave the flight dump
            # and make the error message self-diagnosing (the dump path
            # plus the last recorded events).
            flight_lib.record("deadline_expired", what=what[:200],
                              deadline_s=self.total_s)
            dump = flight_lib.dump_now("deadline_expired")
            raise QueryDeadlineError(what, self.total_s,
                                     postmortem=flight_lib.postmortem(dump))


def env_timeout_s() -> Optional[float]:
    """The PIPELINEDP_TPU_WATCHDOG_S default (None when 0/unset)."""
    from pipelinedp_tpu.native import loader
    seconds = loader.env_int(WATCHDOG_ENV, 0, 0, 24 * 3600)
    return float(seconds) if seconds > 0 else None


class _ResultBox:
    """One guarded call's completion handoff (condition-guarded)."""

    def __init__(self):
        self.cond = threading.Condition()
        self.done = False
        self.result = None
        self.error: Optional[BaseException] = None

    def finish(self, result, error) -> None:
        with self.cond:
            self.result = result
            self.error = error
            self.done = True
            self.cond.notify_all()

    def wait(self, timeout_s: float) -> bool:
        with self.cond:
            return self.cond.wait_for(lambda: self.done, timeout=timeout_s)


class _Worker:
    """A daemon thread executing guarded calls in submission order."""

    def __init__(self, name: str):
        self._queue: "queue.SimpleQueue" = queue.SimpleQueue()
        self._thread = threading.Thread(target=self._loop, name=name,
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is None:
                return
            fn, box = item
            try:
                result, error = fn(), None
            except BaseException as exc:  # handed to the waiter verbatim
                result, error = None, exc
            box.finish(result, error)

    def submit(self, fn: Callable[[], T]) -> _ResultBox:
        box = _ResultBox()
        self._queue.put((fn, box))
        return box

    def stop(self) -> None:
        self._queue.put(None)


class DispatchWatchdog:
    """Runs device operations under a bounded timeout.

    One worker thread serves all guarded calls of a slab loop in order
    (device dispatch is serialized per loop anyway, so a pool would buy
    nothing); after a timeout the wedged worker is abandoned and
    replaced. ``close()`` stops the current worker; abandoned workers
    are daemons and exit with the process at the latest.
    """

    _ids = itertools.count()

    def __init__(self, timeout_s: float):
        if timeout_s <= 0:
            raise ValueError(f"watchdog timeout must be positive, got "
                             f"{timeout_s}")
        self.timeout_s = float(timeout_s)
        self._worker: Optional[_Worker] = None

    def call(self, what: str, fn: Callable[[], T]) -> T:
        """Runs ``fn`` with the timeout; raises DispatchHangError on
        expiry (fn's own exceptions propagate unchanged)."""
        if self._worker is None:
            self._worker = _Worker(f"pdp-watchdog-{next(self._ids)}")
        box = self._worker.submit(fn)
        if not box.wait(self.timeout_s):
            # Abandon the wedged worker: its blocked call cannot be
            # interrupted, but the next attempt must not queue behind it.
            self._worker.stop()
            self._worker = None
            profiler.count_event(EVENT_WATCHDOG_TIMEOUTS)
            # The post-mortem, while the evidence is fresh: one flight
            # event, one atomic dump (when a dump dir is bound), and a
            # self-diagnosing error message carrying both.
            flight_lib.record("watchdog_timeout", what=what[:200],
                              timeout_s=self.timeout_s)
            dump = flight_lib.dump_now("watchdog_timeout")
            raise DispatchHangError(what, self.timeout_s,
                                    postmortem=flight_lib.postmortem(dump))
        if box.error is not None:
            raise box.error
        return box.result

    def close(self) -> None:
        if self._worker is not None:
            self._worker.stop()
            self._worker = None
