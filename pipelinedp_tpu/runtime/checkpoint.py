"""Streaming checkpoint/resume: snapshot the slab loop's mergeable state.

The streamed execution path (ops/streaming.py, parallel/sharded.py) is a
fold over pid-disjoint chunks: ``accs_{c+1} = step(fold_in(key, c),
chunk_c, accs_c)``. Both the per-chunk keys and the host encode are pure
functions of ``(input, key)``, so the complete resumable state after chunk
``c`` is just the accumulator arrays (plus the quantile leaf histogram when
PERCENTILE rides the stream) and the cursor ``c+1`` — everything else is
re-derived on resume and *verified* against the checkpoint's fingerprints:

  * ``key_fingerprint`` — digest of the streamed kernel key. A resume
    under a different seed could never be bit-identical; refuse it.
  * ``wire_fingerprint`` — digest of the wire format + per-bucket row/RLE
    counts. Catches changed input data, chunk count, or codec planning
    drift between the checkpointing and the resuming process.
  * ``key_counter`` — the engine KeyStream position the kernel key was
    drawn at (-1 when streaming is driven directly, without an engine).

A resumed run replays the remaining chunks with the original per-chunk key
schedule, so it is bit-identical to an uninterrupted run
(tests/resilience_test.py pins this on the single-device and mesh paths).

Checkpoints must never contain released noise: they hold pre-noise
accumulators only, and the at-most-once release rule is enforced
separately by the release journal (runtime/journal.py).
"""

from __future__ import annotations

import abc
import dataclasses
import hashlib
import json
import logging
import os
import re
import tempfile
import zipfile
from typing import Optional, Tuple

import numpy as np


class CheckpointMismatchError(RuntimeError):
    """The checkpoint does not belong to this (input, key, format) run."""


def key_fingerprint(key) -> str:
    """Stable digest of a JAX PRNG key (old-style uint32 or typed)."""
    import jax

    try:
        data = jax.random.key_data(key)
    except (TypeError, ValueError, AttributeError):
        data = key
    arr = np.asarray(data)
    digest = hashlib.sha256()
    digest.update(str(arr.dtype).encode())
    digest.update(arr.tobytes())
    return digest.hexdigest()[:32]


def wire_fingerprint(n_chunks: int, fmt_desc,
                     counts: np.ndarray,
                     n_uniq: Optional[np.ndarray] = None,
                     data_digest: str = "") -> str:
    """Digest of the wire schedule: chunk count, format, per-bucket
    row counts, (RLE modes) entry counts, and the caller's input-column
    digest (array_digest) — per-bucket counts depend only on the privacy
    ids, so the column digest is what catches a mutated pk/value column
    between checkpoint and resume."""
    digest = hashlib.sha256()
    digest.update(repr((int(n_chunks), fmt_desc, data_digest)).encode())
    digest.update(np.ascontiguousarray(counts, dtype=np.int64).tobytes())
    if n_uniq is not None:
        digest.update(np.ascontiguousarray(n_uniq, dtype=np.int64).tobytes())
    return digest.hexdigest()[:32]


def array_digest(*arrays) -> str:
    """Cheap deterministic digest of (possibly huge) input columns:
    dtype/shape, a <=64Ki-element stride sample, and the float64 column
    sum. O(1)-ish in the input size — corruption *detection* for resume
    validation, not an adversarial integrity check."""
    digest = hashlib.sha256()
    for arr in arrays:
        if arr is None:
            digest.update(b"none")
            continue
        arr = np.asarray(arr)
        digest.update(str((arr.dtype, arr.shape)).encode())
        flat = arr.reshape(-1)
        if flat.size:
            stride = max(1, flat.size // 65536)
            digest.update(np.ascontiguousarray(flat[::stride]).tobytes())
            if np.issubdtype(arr.dtype, np.number):
                digest.update(
                    np.float64(flat.sum(dtype=np.float64)).tobytes())
    return digest.hexdigest()[:32]


@dataclasses.dataclass
class StreamCheckpoint:
    """One snapshot of the slab loop, taken at a chunk boundary."""
    run_id: str
    next_chunk: int  # first chunk NOT yet folded into accs
    n_chunks: int
    accs: Tuple[np.ndarray, ...]  # the 5 PartitionAccumulators arrays
    qhist: Optional[np.ndarray]  # quantile leaf histogram, when streamed
    key_fingerprint: str
    wire_fingerprint: str
    key_counter: int = -1

    def nbytes(self) -> int:
        total = sum(int(a.nbytes) for a in self.accs)
        if self.qhist is not None:
            total += int(self.qhist.nbytes)
        return total

    def validate(self, *, key_fp: str, wire_fp: str, n_chunks: int,
                 key_counter: int = -1) -> None:
        """Refuses a resume that could not be bit-identical."""
        if self.key_fingerprint != key_fp:
            raise CheckpointMismatchError(
                "checkpoint was written under a different PRNG key; "
                "resuming would change the released distribution")
        if self.wire_fingerprint != wire_fp:
            raise CheckpointMismatchError(
                "checkpoint wire fingerprint does not match this input "
                "(data, chunk count, or wire format changed since the "
                "checkpoint was written)")
        if self.n_chunks != n_chunks:
            raise CheckpointMismatchError(
                f"checkpoint covers {self.n_chunks} chunks, this run has "
                f"{n_chunks}")
        if (key_counter >= 0 and self.key_counter >= 0
                and self.key_counter != key_counter):
            raise CheckpointMismatchError(
                f"checkpoint was taken at KeyStream position "
                f"{self.key_counter}, this run is at {key_counter}")
        if not 0 <= self.next_chunk <= self.n_chunks:
            raise CheckpointMismatchError(
                f"corrupt checkpoint cursor {self.next_chunk}")


class CheckpointStore(abc.ABC):
    """Where StreamCheckpoints live between (possibly crashed) runs."""

    @abc.abstractmethod
    def save(self, checkpoint: StreamCheckpoint) -> None:
        """Durably replaces the checkpoint for checkpoint.run_id."""

    @abc.abstractmethod
    def load(self, run_id: str) -> Optional[StreamCheckpoint]:
        """The latest checkpoint for run_id, or None."""

    @abc.abstractmethod
    def delete(self, run_id: str) -> None:
        """Drops run_id's checkpoint (no-op when absent)."""


class InMemoryCheckpointStore(CheckpointStore):
    """Process-local store: survives engine instances, not the process.
    Arrays are copied on save so donated device buffers and later slab
    arithmetic can never alias checkpointed state."""

    def __init__(self):
        self._checkpoints = {}

    def save(self, checkpoint: StreamCheckpoint) -> None:
        self._checkpoints[checkpoint.run_id] = dataclasses.replace(
            checkpoint,
            accs=tuple(np.array(a) for a in checkpoint.accs),
            qhist=(None if checkpoint.qhist is None
                   else np.array(checkpoint.qhist)))

    def load(self, run_id: str) -> Optional[StreamCheckpoint]:
        return self._checkpoints.get(run_id)

    def delete(self, run_id: str) -> None:
        self._checkpoints.pop(run_id, None)


def content_digest(meta_core: str, *arrays) -> str:
    """Full-content digest of a durable payload: the caller's core
    metadata string plus dtype/shape/every byte of each array. Unlike
    ``array_digest`` nothing is sampled — this names payloads small
    enough to hash whole (checkpoint snapshots, spilled serving-session
    chunks and bound-cache entries), where a torn or bit-rotted file
    must be *distinguishable* from a legitimate fingerprint mismatch so
    recovery can fall back (or recompute) instead of refusing or —
    worse — serving wrong bits."""
    digest = hashlib.sha256()
    digest.update(meta_core.encode())
    for arr in arrays:
        arr = np.asarray(arr)
        digest.update(str((arr.dtype, arr.shape)).encode())
        digest.update(np.ascontiguousarray(arr).tobytes())
    return digest.hexdigest()[:32]


def _payload_digest(accs, qhist, meta_core: str) -> str:
    """One checkpoint snapshot's content digest (metadata + arrays)."""
    return content_digest(meta_core,
                          *(accs + ((qhist,) if qhist is not None else ())))


class FileCheckpointStore(CheckpointStore):
    """File-backed store surviving the process.

    One ``<run_id>.<seq>.npz`` per snapshot under ``root``, written
    atomically (tmp file + rename) so a crash mid-save leaves the
    previous snapshot intact. Every snapshot embeds a full payload
    digest, so ``load`` can tell a torn/corrupted file (skipped, with a
    warning, falling back to the previous good snapshot) from a
    checkpoint that simply belongs to a different run (surfaced as a
    ``CheckpointMismatchError`` at validation). ``keep`` bounds how many
    snapshots per run survive on disk: after each successful save, older
    snapshots beyond the newest ``keep`` are pruned (each prune is a
    single unlink after the new snapshot's rename, so no crash window
    ever leaves fewer than ``keep - 1`` good snapshots).
    """

    def __init__(self, root: str, keep: int = 2):
        if keep < 1:
            raise ValueError(f"keep must be >= 1, got {keep}")
        self._root = root
        self._keep = keep
        os.makedirs(root, exist_ok=True)

    def _safe(self, run_id: str) -> str:
        return re.sub(r"[^A-Za-z0-9._-]", "_", run_id)

    def _snapshots(self, run_id: str):
        """[(seq, path)] for run_id, newest first. Legacy single-file
        checkpoints (``<run_id>.npz``, written before retention existed)
        participate as seq -1."""
        safe = self._safe(run_id)
        pattern = re.compile(re.escape(safe) + r"\.(\d{8})\.npz$")
        out = []
        for name in os.listdir(self._root):
            m = pattern.fullmatch(name)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self._root, name)))
            elif name == f"{safe}.npz":
                out.append((-1, os.path.join(self._root, name)))
        return sorted(out, reverse=True)

    def save(self, checkpoint: StreamCheckpoint) -> None:
        meta_fields = {
            "run_id": checkpoint.run_id,
            "next_chunk": int(checkpoint.next_chunk),
            "n_chunks": int(checkpoint.n_chunks),
            "key_fingerprint": checkpoint.key_fingerprint,
            "wire_fingerprint": checkpoint.wire_fingerprint,
            "key_counter": int(checkpoint.key_counter),
            "has_qhist": checkpoint.qhist is not None,
        }
        meta_core = json.dumps(meta_fields, sort_keys=True)
        accs = tuple(np.asarray(a) for a in checkpoint.accs)
        qhist = (None if checkpoint.qhist is None
                 else np.asarray(checkpoint.qhist))
        meta_fields["payload_digest"] = _payload_digest(accs, qhist,
                                                        meta_core)
        arrays = {f"accs_{i}": a for i, a in enumerate(accs)}
        if qhist is not None:
            arrays["qhist"] = qhist
        arrays["meta"] = np.frombuffer(
            json.dumps(meta_fields).encode(), dtype=np.uint8)
        snapshots = self._snapshots(checkpoint.run_id)
        seq = (snapshots[0][0] + 1) if snapshots else 0
        path = os.path.join(self._root,
                            f"{self._safe(checkpoint.run_id)}.{seq:08d}.npz")
        fd, tmp = tempfile.mkstemp(dir=self._root, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                np.savez(f, **arrays)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        # Retention: prune beyond the newest `keep` only after the new
        # snapshot is durably in place.
        for _, old_path in self._snapshots(checkpoint.run_id)[self._keep:]:
            try:
                os.unlink(old_path)
            except FileNotFoundError:
                pass

    def _load_snapshot(self, path: str) -> Optional[StreamCheckpoint]:
        """One snapshot file, or None when torn/corrupt (digest or
        container failure)."""
        try:
            with np.load(path, allow_pickle=False) as data:
                meta = json.loads(bytes(data["meta"]).decode())
                n_accs = sum(1 for name in data.files
                             if name.startswith("accs_"))
                accs = tuple(data[f"accs_{i}"] for i in range(n_accs))
                qhist = data["qhist"] if meta["has_qhist"] else None
        except (OSError, ValueError, KeyError, zipfile.BadZipFile):
            return None
        expected = meta.pop("payload_digest", None)
        if expected is not None:
            meta_core = json.dumps(meta, sort_keys=True)
            if _payload_digest(accs, qhist, meta_core) != expected:
                return None
        # expected None: legacy pre-digest snapshot — accepted as-is.
        return StreamCheckpoint(
            run_id=meta["run_id"],
            next_chunk=meta["next_chunk"],
            n_chunks=meta["n_chunks"],
            accs=accs,
            qhist=qhist,
            key_fingerprint=meta["key_fingerprint"],
            wire_fingerprint=meta["wire_fingerprint"],
            key_counter=meta["key_counter"])

    def load(self, run_id: str) -> Optional[StreamCheckpoint]:
        for seq, path in self._snapshots(run_id):
            checkpoint = self._load_snapshot(path)
            if checkpoint is not None:
                return checkpoint
            logging.warning(
                "pipelinedp_tpu checkpoint: snapshot %s is torn or "
                "corrupt (payload digest mismatch); falling back to the "
                "previous snapshot", path)
        return None

    def delete(self, run_id: str) -> None:
        for _, path in self._snapshots(run_id):
            try:
                os.unlink(path)
            except FileNotFoundError:
                pass


@dataclasses.dataclass
class CheckpointPolicy:
    """The engine/streaming knob: where and how often to checkpoint.

    every_slabs: snapshot after this many completed slab windows (1 =
      after every slab). A snapshot syncs the accumulators to host, so
      larger values trade recovery granularity for less sync overhead.
    delete_on_success: drop the checkpoint once the stream completes (the
      release journal — not a stale checkpoint — is what enforces
      at-most-once release afterwards).
    """
    store: CheckpointStore
    run_id: str = "default"
    every_slabs: int = 1
    delete_on_success: bool = True
