"""Typed metrics registry: counters, gauges, fixed-bucket histograms.

The fleet's quantitative telemetry lives here (OBSERVABILITY.md "Metric
catalog"). Three typed instrument families plus the legacy *event*
namespace that absorbed ``profiler.count_event``:

  * :class:`Counter` — monotonically increasing, optionally labeled.
  * :class:`Gauge` — last-write-wins point-in-time value (fleet resident
    bytes, in-flight queries).
  * :class:`Histogram` — fixed cumulative buckets + sum + count, the
    shape Prometheus quantile queries (``histogram_quantile``) consume.
    The default bucket ladder spans 100µs..120s, the serving latency
    range.
  * events — the flat ``profiler.count_event`` counter namespace
    (``runtime/retries``, ``serving/queries``, ...). ``profiler``'s
    ``count_event`` / ``event_count`` / ``event_counts`` /
    ``reset_events`` are back-compat shims over this registry, so
    ``runtime.resilience_counters()`` and ``serving.fleet_counters()``
    read the same storage exporters scrape.

Exports: :meth:`MetricsRegistry.to_prometheus` (text exposition format)
and :meth:`MetricsRegistry.snapshot` (a JSON-able dict; bench.py embeds
it per row). ``PIPELINEDP_TPU_METRICS=<path>`` writes the exposition
there at process exit (a ``.json`` suffix writes the snapshot instead).

Atomicity contract (the PR-11 counter-hygiene fix): every registry
operation — increments, gauge sets, histogram observations, reads, and
``reset_events(prefix)`` — runs under ONE registry lock, so a
``reset_events`` racing ``count_event`` from prefetch or watchdog
threads can never lose an increment to a detached family (the hammer
tests in tests/obs_test.py pin this).

DP-safety: instruments carry *operational* aggregates — timings,
counts of queries/retries/evictions — never raw pids, partition keys,
or pre-noise values. Label values are validated scalars; arrays are
refused outright. dplint DPL011 statically flags private columns
flowing into any ``obs.*`` API.

This module is deliberately dependency-free (stdlib only): it imports
neither jax nor any pipelinedp_tpu module, so the profiler shim and the
runtime can use it without import cycles.
"""

from __future__ import annotations

import atexit
import bisect
import math
import os
import re
import tempfile
import threading
from typing import Dict, List, Optional, Sequence, Tuple

METRICS_ENV = "PIPELINEDP_TPU_METRICS"

# Cumulative upper bounds (seconds) for latency histograms: 100µs..120s
# covers everything from a bound-cache hit to a cold mesh ingest.
DEFAULT_LATENCY_BUCKETS_S: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_RE = re.compile(r"[^a-zA-Z0-9_]")

# Attribute/label keys that smell like raw private data. The hard rule
# (OBSERVABILITY.md "DP-safety stance"): raw pids, partition keys and
# unreleased (pre-noise) values never enter any obs record. Shared with
# obs.trace and obs.audit.
FORBIDDEN_KEYS = frozenset({
    "pid", "pids", "privacy_id", "privacy_ids", "pk", "pks",
    "partition_key", "partition_keys", "value", "values", "raw_values",
    "accs", "acc", "accumulators", "qhist",
})


class TelemetryLeakError(ValueError):
    """A private-data-shaped payload was about to enter an obs record."""


def check_safe_value(key: str, value) -> None:
    """The shared obs-record payload gate: refuses forbidden key names
    and non-scalar values (an array or sequence reaching telemetry is
    row-level data by construction — aggregate it or drop it)."""
    if key in FORBIDDEN_KEYS:
        raise TelemetryLeakError(
            f"obs record key {key!r} names a raw private column; "
            f"telemetry must carry operational aggregates only "
            f"(OBSERVABILITY.md DP-safety stance)")
    if value is None or isinstance(value, (bool, int, float, str)):
        return
    raise TelemetryLeakError(
        f"obs record key {key!r} carries a non-scalar {type(value).__name__}; "
        f"arrays and sequences never enter telemetry records")


def sanitize_name(name: str) -> str:
    """A legal Prometheus metric name for an arbitrary event name."""
    out = _NAME_RE.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _label_key(labels: Dict[str, str]) -> Tuple[Tuple[str, str], ...]:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _fmt_labels(pairs: Tuple[Tuple[str, str], ...],
                extra: Optional[Tuple[str, str]] = None) -> str:
    items = list(pairs)
    if extra is not None:
        items.append(extra)
    if not items:
        return ""
    body = ",".join(
        '%s="%s"' % (_LABEL_RE.sub("_", k),
                     v.replace("\\", "\\\\").replace('"', '\\"'))
        for k, v in items)
    return "{" + body + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f == int(f) else repr(f)


class _Instrument:
    """Base: one named family of labeled series, locked by the registry."""

    kind = "untyped"

    def __init__(self, name: str, help_text: str, lock: threading.RLock):
        self.name = name
        self.help = help_text
        self._lock = lock
        self._series: Dict[Tuple[Tuple[str, str], ...], object] = {}

    def _check_labels(self, labels: Dict[str, str]) -> None:
        for k, v in labels.items():
            check_safe_value(k, v)

    def series(self) -> dict:
        """Snapshot {label-string: value} of every series."""
        with self._lock:
            return {json_label(k): self._series_value(v)
                    for k, v in self._series.items()}

    def _series_value(self, raw):
        return raw


def json_label(pairs: Tuple[Tuple[str, str], ...]) -> str:
    return ",".join(f"{k}={v}" for k, v in pairs) if pairs else ""


class Counter(_Instrument):
    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        self._check_labels(labels)
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0)


class Gauge(_Instrument):
    kind = "gauge"

    def set(self, v: float, **labels) -> None:
        self._check_labels(labels)
        check_safe_value("gauge_value", v)
        with self._lock:
            self._series[_label_key(labels)] = v

    def inc(self, n: float = 1, **labels) -> None:
        self._check_labels(labels)
        key = _label_key(labels)
        with self._lock:
            self._series[key] = self._series.get(key, 0) + n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0)


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets  # per-bucket (non-cumulative)
        self.sum = 0.0
        self.count = 0


class Histogram(_Instrument):
    """Fixed-bucket histogram; buckets are cumulative upper bounds in
    the exposition (``le``), stored non-cumulative internally."""

    kind = "histogram"

    def __init__(self, name: str, help_text: str, lock: threading.RLock,
                 buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S):
        super().__init__(name, help_text, lock)
        bounds = sorted(float(b) for b in buckets)
        if not bounds:
            raise ValueError(f"histogram {name!r} needs at least 1 bucket")
        self.buckets: Tuple[float, ...] = tuple(bounds)

    def observe(self, v: float, **labels) -> None:
        self._check_labels(labels)
        check_safe_value("observation", v)
        v = float(v)
        key = _label_key(labels)
        with self._lock:
            series = self._series.get(key)
            if series is None:
                series = self._series[key] = _HistSeries(
                    len(self.buckets) + 1)
            # bisect_left: bucket bound is inclusive (le semantics).
            series.counts[bisect.bisect_left(self.buckets, v)] += 1
            series.sum += v
            series.count += 1

    def snapshot(self, **labels) -> dict:
        """{buckets, counts (cumulative), sum, count} of one series."""
        with self._lock:
            series = self._series.get(_label_key(labels))
            counts = (list(series.counts) if series is not None
                      else [0] * (len(self.buckets) + 1))
            cumulative, acc = [], 0
            for c in counts:
                acc += c
                cumulative.append(acc)
            return {
                "buckets": list(self.buckets) + [math.inf],
                "counts": cumulative,
                "sum": series.sum if series is not None else 0.0,
                "count": series.count if series is not None else 0,
            }

    def _series_value(self, raw: _HistSeries):
        cumulative, acc = [], 0
        for c in raw.counts:
            acc += c
            cumulative.append(acc)
        return {"counts": cumulative, "sum": raw.sum, "count": raw.count}


class MetricsRegistry:
    """The process metric store (module docstring). One lock guards
    every family and the event namespace, making reset-vs-increment
    races impossible by construction."""

    def __init__(self):
        self._lock = threading.RLock()
        self._families: Dict[str, _Instrument] = {}
        self._events: Dict[str, int] = {}

    # -- typed families ---------------------------------------------------

    def _family(self, cls, name: str, help_text: str, **kwargs):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = self._families[name] = cls(name, help_text,
                                                 self._lock, **kwargs)
            elif not isinstance(fam, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {fam.kind}")
            return fam

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._family(Counter, name, help_text)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._family(Gauge, name, help_text)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS_S
                  ) -> Histogram:
        return self._family(Histogram, name, help_text, buckets=buckets)

    # -- the legacy event namespace (profiler.count_event shims) ----------

    def event_inc(self, name: str, n: int = 1) -> None:
        with self._lock:
            self._events[name] = self._events.get(name, 0) + n

    def event_value(self, name: str) -> int:
        with self._lock:
            return self._events.get(name, 0)

    def event_values(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._events)

    def reset_events(self, prefix: Optional[str] = None) -> None:
        """Zeros event counters (those starting with ``prefix``, or
        all) — atomic with respect to concurrent ``event_inc``: both
        run under the registry lock, so an increment lands either
        before the reset (and is cleared) or after (and survives),
        never in a detached family."""
        with self._lock:
            if prefix is None:
                self._events.clear()
            else:
                for name in [n for n in self._events
                             if n.startswith(prefix)]:
                    del self._events[name]

    # -- exports ----------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-able dump of everything (bench.py embeds this)."""
        with self._lock:
            families = {}
            for name, fam in self._families.items():
                families[name] = {"kind": fam.kind, "series": fam.series()}
                if isinstance(fam, Histogram):
                    families[name]["buckets"] = (list(fam.buckets)
                                                 + ["+Inf"])
            return {"events": dict(self._events), "families": families}

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4."""
        with self._lock:
            lines: List[str] = []
            for name in sorted(self._families):
                fam = self._families[name]
                pname = sanitize_name(name)
                if fam.kind == "counter" and not pname.endswith("_total"):
                    pname += "_total"
                if fam.help:
                    lines.append(f"# HELP {pname} {fam.help}")
                lines.append(f"# TYPE {pname} {fam.kind}")
                for key, raw in sorted(fam._series.items()):
                    if isinstance(fam, Histogram):
                        acc = 0
                        for bound, c in zip(
                                list(fam.buckets) + [math.inf],
                                raw.counts):
                            acc += c
                            lines.append(
                                f"{pname}_bucket"
                                f"{_fmt_labels(key, ('le', _fmt_value(bound)))}"
                                f" {acc}")
                        lines.append(
                            f"{pname}_sum{_fmt_labels(key)}"
                            f" {_fmt_value(raw.sum)}")
                        lines.append(
                            f"{pname}_count{_fmt_labels(key)} {raw.count}")
                    else:
                        lines.append(
                            f"{pname}{_fmt_labels(key)} {_fmt_value(raw)}")
            if self._events:
                lines.append("# HELP pipelinedp_tpu_events_total Legacy "
                             "profiler.count_event counters.")
                lines.append("# TYPE pipelinedp_tpu_events_total counter")
                for name in sorted(self._events):
                    lines.append(
                        "pipelinedp_tpu_events_total"
                        f"{_fmt_labels((), ('event', name))}"
                        f" {self._events[name]}")
            return "\n".join(lines) + "\n"

    def reset(self) -> None:
        """Full reset (tests only): families and events."""
        with self._lock:
            self._families.clear()
            self._events.clear()


_default: Optional[MetricsRegistry] = None
_default_lock = threading.Lock()


def default_registry() -> MetricsRegistry:
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                _default = MetricsRegistry()
    return _default


# -- the serving metric catalog (OBSERVABILITY.md) ---------------------------
#
# Central constructors so every call site shares one family (names,
# types, label sets live here and in the doc's catalog table).

def query_seconds() -> Histogram:
    """End-to-end serving query latency, labeled by outcome
    (released / refunded / shed / deadline-expired /
    double-release-refused)."""
    return default_registry().histogram(
        "pipelinedp_tpu_query_seconds",
        "End-to-end DatasetSession.query latency by outcome.")


def admission_wait_seconds() -> Histogram:
    """Time a query spent acquiring the fleet admission gate."""
    return default_registry().histogram(
        "pipelinedp_tpu_admission_wait_seconds",
        "Admission-gate acquisition wait per query.")


def replay_seconds() -> Histogram:
    """Resident-wire replay (chunk kernels) per bound-cache miss."""
    return default_registry().histogram(
        "pipelinedp_tpu_replay_seconds",
        "Resident-wire kernel replay latency per bound-cache miss.")


def finalize_seconds() -> Histogram:
    """The fused DP finalize epilogue (selection + noise + transfer)."""
    return default_registry().histogram(
        "pipelinedp_tpu_finalize_seconds",
        "Fused finalize epilogue latency per aggregate.")


def checkpoint_write_seconds() -> Histogram:
    """One checkpoint snapshot+persist in the slab driver."""
    return default_registry().histogram(
        "pipelinedp_tpu_checkpoint_write_seconds",
        "Slab-driver checkpoint snapshot+persist latency.")


def rehydration_seconds() -> Histogram:
    """Spilled-session re-hydration (store load + wire reload)."""
    return default_registry().histogram(
        "pipelinedp_tpu_rehydration_seconds",
        "Spilled-session re-hydration latency.")


def append_seconds() -> Histogram:
    """One live-session append end to end (digest + micro-encode + WAL
    commit + epoch fold), labeled by outcome (committed / duplicate /
    shed / late-rejected / dead-lettered / failed)."""
    return default_registry().histogram(
        "pipelinedp_tpu_append_seconds",
        "LiveDatasetSession.append latency by outcome.")


def release_tick_seconds() -> Histogram:
    """One scheduled continual-release window (ReleaseSchedule), labeled
    by outcome (released / recovered / suppressed)."""
    return default_registry().histogram(
        "pipelinedp_tpu_release_tick_seconds",
        "Scheduled continual-release window latency by outcome.")


def fleet_resident_bytes() -> Gauge:
    """Fleet-wide resident bytes across admitted sessions."""
    return default_registry().gauge(
        "pipelinedp_tpu_fleet_resident_bytes",
        "Resident bytes across all non-spilled admitted sessions.")


def inflight_queries() -> Gauge:
    """Queries currently inside the admission gate."""
    return default_registry().gauge(
        "pipelinedp_tpu_inflight_queries",
        "Queries currently executing under the admission gate.")


# -- PIPELINEDP_TPU_METRICS exit export --------------------------------------

_exit_registered = False


def _export_at_exit(path: str) -> None:
    reg = default_registry()
    data = (reg.to_prometheus() if not path.endswith(".json")
            else __import__("json").dumps(reg.snapshot(), indent=1))
    try:
        # Atomic publish: scrapers polling the textfile never see a
        # half-written export, even if the process dies mid-dump.
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".",
                                   suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
    except OSError:
        pass  # exit-time export is best-effort by design


def _maybe_register_exit_export() -> None:
    global _exit_registered
    path = os.environ.get(METRICS_ENV, "")
    if path and not _exit_registered:
        _exit_registered = True
        atexit.register(_export_at_exit, path)


_maybe_register_exit_export()
