"""Bench-trajectory regression gate: a perf regression fails the build.

The repo tracks its performance as a trajectory of ``BENCH_r*.json``
rows (bench.py output + metadata). Until this module, the trajectory
was inspected by hand; now ``python -m pipelinedp_tpu.obs.regress
BENCH_*.json`` loads it, compares the newest round's headline metrics
against the **best comparable prior round**, and exits nonzero when
any headline regressed beyond its noise-aware threshold — wired into
CI so a perf regression fails the build the way a test failure does.

Comparability: rounds are only compared when they ran the same
workload shape — the ``BENCH_*`` env assignments parsed from the
recorded ``cmd`` (or an explicit ``"shape"`` key, which newer bench.py
rows embed). A round with no comparable prior reports ``NEW`` and
cannot fail the gate.

Noise awareness: every metric carries a base relative tolerance (CPU
smoke numbers jitter; ratio metrics like ``warm_vs_cold`` jitter more
because both numerator and denominator move), and when three or more
comparable priors exist the tolerance widens to twice the trajectory's
own coefficient of variation (capped). The gate compares against the
best prior — a slow round never lowers the bar for the next one.

Output is a markdown report (stdout, and ``--out`` for a file /
``$GITHUB_STEP_SUMMARY``); exit status 0 = no regressions.
"""

from __future__ import annotations

import argparse
import json
import math
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

# Headline metrics: (label, dotted path under the row's "parsed" dict,
# base relative tolerance). All are higher-is-better.
HEADLINE_METRICS: Tuple[Tuple[str, str, float], ...] = (
    ("e2e_partitions_per_sec", "value", 0.15),
    ("kernel_partitions_per_sec", "kernel_partitions_per_sec", 0.15),
    ("kernel_general_pps", "kernel_sort.general_partitions_per_sec", 0.20),
    ("kernel_packed_pps", "kernel_sort.packed_partitions_per_sec", 0.20),
    ("kernel_tiled_pps", "kernel_sort.tiled_partitions_per_sec", 0.20),
    ("kernel_hash_pps", "kernel_sort.hash_partitions_per_sec", 0.20),
    ("e2e_steady_pps", "e2e_steady.steady_state_partitions_per_sec", 0.20),
    ("serving_warm_vs_cold", "serving.warm_vs_cold", 0.35),
    ("serving_warm_query_pps",
     "serving.warm_query_partitions_per_sec", 0.25),
    ("serving_cold_pps", "serving.cold_partitions_per_sec", 0.20),
    ("serving_batched_qps_w1",
     "serving.batched.width_1_queries_per_sec", 0.40),
    ("serving_batched_qps_w8",
     "serving.batched.width_8_queries_per_sec", 0.40),
    ("serving_batched_qps_w32",
     "serving.batched.width_32_queries_per_sec", 0.40),
    ("serving_batched_qps_w256",
     "serving.batched.width_256_queries_per_sec", 0.40),
    ("utility_sweep_vs_host", "utility_sweep_vs_host", 0.35),
    ("live_append_rows_per_sec", "live.append_rows_per_sec", 0.30),
    ("live_release_windows_per_sec",
     "live.release_windows_per_sec", 0.40),
    # Failover headline (ISSUE 19): reciprocal of failover_time_s so
    # the gate stays higher-is-better; promotion cost is dominated by
    # the writable reopen, so the tolerance is generous.
    ("fleet_failovers_per_sec", "fleet.failovers_per_sec", 0.50),
)

MAX_TOLERANCE = 0.50
_SHAPE_RE = re.compile(r"\b(BENCH_[A-Z_]+)=(\S+)")


def _get_path(d: dict, dotted: str) -> Optional[float]:
    cur: object = d
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    if isinstance(cur, bool) or not isinstance(cur, (int, float)):
        return None
    return float(cur)


def shape_signature(row: dict) -> Tuple[Tuple[str, str], ...]:
    """The workload-shape identity two rounds must share to compare:
    the explicit ``"shape"`` dict when bench.py embedded one (at the
    row top level or inside ``parsed``), else the BENCH_* env
    assignments parsed out of the recorded command line."""
    shape = row.get("shape")
    if not (isinstance(shape, dict) and shape):
        shape = (row.get("parsed") or {}).get("shape")
    if isinstance(shape, dict) and shape:
        return tuple(sorted((str(k), str(v)) for k, v in shape.items()))
    return tuple(sorted(_SHAPE_RE.findall(row.get("cmd", ""))))


def shapes_comparable(a, b) -> bool:
    """Whether two shape signatures describe the same workload. Exact
    equality always qualifies; two non-empty signatures also qualify
    when they agree on every knob BOTH recorded — bench.py grows new
    knobs over time (each defaulted in older rounds), and a richer
    recording of the same workload must not orphan the trajectory.
    Signatures that share no knobs, or disagree on one, don't compare;
    an empty signature (nothing recorded) only matches another empty."""
    if a == b:
        return True
    da, db = dict(a), dict(b)
    shared = set(da) & set(db)
    if not shared:
        return False
    return all(da[k] == db[k] for k in shared)


def load_rows(paths: Sequence[str]) -> List[dict]:
    rows = []
    for path in paths:
        with open(path) as f:
            row = json.load(f)
        row["_path"] = path
        rows.append(row)
    rows.sort(key=lambda r: (r.get("n", 0), r["_path"]))
    return rows


def _tolerance(base: float, priors: Sequence[float]) -> float:
    tol = base
    if len(priors) >= 3:
        mean = sum(priors) / len(priors)
        if mean > 0:
            var = sum((p - mean) ** 2 for p in priors) / (len(priors) - 1)
            cv = math.sqrt(var) / mean
            tol = max(base, 2.0 * cv)
    return min(tol, MAX_TOLERANCE)


def compare(rows: Sequence[dict],
            tol_scale: float = 1.0) -> Tuple[List[dict], dict]:
    """Compares the newest round against the best comparable prior per
    headline metric. Returns (findings, summary); a finding with
    ``status == "REGRESSION"`` fails the gate."""
    if not rows:
        raise ValueError("no bench rows given")
    latest = rows[-1]
    latest_sig = shape_signature(latest)
    priors = [r for r in rows[:-1]
              if shapes_comparable(shape_signature(r), latest_sig)]
    findings: List[dict] = []
    for label, path, base_tol in HEADLINE_METRICS:
        current = _get_path(latest.get("parsed") or {}, path)
        history = [v for v in
                   (_get_path(r.get("parsed") or {}, path) for r in priors)
                   if v is not None]
        if current is None:
            if history:
                findings.append({
                    "metric": label, "status": "GONE", "current": None,
                    "best_prior": max(history), "ratio": None,
                    "tolerance": None})
            continue
        if not history:
            findings.append({
                "metric": label, "status": "NEW", "current": current,
                "best_prior": None, "ratio": None, "tolerance": None})
            continue
        best = max(history)
        tol = _tolerance(base_tol, history) * tol_scale
        ratio = current / best if best > 0 else math.inf
        status = "REGRESSION" if ratio < 1.0 - tol else "OK"
        findings.append({
            "metric": label, "status": status, "current": current,
            "best_prior": best, "ratio": round(ratio, 4),
            "tolerance": round(tol, 4)})
    summary = {
        "latest_round": latest.get("n"),
        "latest_path": latest["_path"],
        "comparable_priors": [r.get("n") for r in priors],
        "regressions": sum(1 for f in findings
                           if f["status"] == "REGRESSION"),
        "checked": sum(1 for f in findings if f["status"] in
                       ("OK", "REGRESSION")),
    }
    return findings, summary


def _fmt(v: Optional[float]) -> str:
    if v is None:
        return "—"
    return f"{v:,.2f}" if abs(v) < 1000 else f"{v:,.0f}"


def markdown_report(findings: Sequence[dict], summary: dict) -> str:
    lines = [
        "# Bench regression gate",
        "",
        f"Latest round: **r{summary['latest_round']}** "
        f"(`{summary['latest_path']}`); comparable priors: "
        f"{summary['comparable_priors'] or 'none'}.",
        "",
        "| metric | status | latest | best prior | ratio | tolerance |",
        "|---|---|---|---|---|---|",
    ]
    for f in findings:
        mark = {"REGRESSION": "❌ REGRESSION", "OK": "✅ OK",
                "NEW": "🆕 NEW", "GONE": "⚠️ GONE"}[f["status"]]
        lines.append(
            f"| {f['metric']} | {mark} | {_fmt(f['current'])} | "
            f"{_fmt(f['best_prior'])} | "
            f"{f['ratio'] if f['ratio'] is not None else '—'} | "
            f"{f['tolerance'] if f['tolerance'] is not None else '—'} |")
    lines.append("")
    if summary["regressions"]:
        lines.append(f"**{summary['regressions']} regression(s)** out of "
                     f"{summary['checked']} checked headline metrics — "
                     f"the gate FAILS.")
    else:
        lines.append(f"No regressions across {summary['checked']} checked "
                     f"headline metrics.")
    return "\n".join(lines) + "\n"


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m pipelinedp_tpu.obs.regress",
        description="Bench-trajectory perf regression gate.")
    parser.add_argument("files", nargs="+",
                        help="BENCH_r*.json trajectory files")
    parser.add_argument("--out", default=None,
                        help="also write the markdown report here")
    parser.add_argument("--tol-scale", type=float, default=1.0,
                        help="scale every tolerance (tests use <1 to "
                             "tighten, emergencies >1 to loosen)")
    args = parser.parse_args(argv)
    rows = load_rows(args.files)
    findings, summary = compare(rows, tol_scale=args.tol_scale)
    report = markdown_report(findings, summary)
    sys.stdout.write(report)
    if args.out:
        with open(args.out, "w") as f:
            f.write(report)
    return 1 if summary["regressions"] else 0


if __name__ == "__main__":
    sys.exit(main())
