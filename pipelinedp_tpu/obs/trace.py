"""Span tracing: why was THIS query slow?

A :class:`Tracer` records a tree of timed spans with explicit parent
links — admission → replay → per-window encode/transfer/dispatch/sync →
finalize — plus point events (retry, degrade, watchdog timeout, resume)
attached to the span they happened under. The export is Chrome
trace-event JSON (``ph: "X"`` complete events + ``ph: "i"`` instants),
loadable directly in Perfetto / ``chrome://tracing``, per process
(:meth:`Tracer.export_chrome`) or per query (filter by the root span's
``trace_id``; ``DatasetSession.query(trace_path=...)`` does this).

Zero cost when disabled — the design constraint that lets the
instrumentation live permanently in the slab driver and the serving hot
path: with no tracer installed, :func:`span` returns one shared
null context and :func:`event` returns immediately; no dict, no clock
read, no lock. Released values are bit-identical with tracing on or
off (spans read clocks, never data or keys; pinned by
tests/obs_serving_test.py).

Enabling: install programmatically (``trace.install(trace.Tracer())``)
or set ``PIPELINEDP_TPU_TRACE=<path>`` — a tracer is installed at
import and the process trace is written to ``<path>`` at exit (a
directory gets ``trace_<pid>.json`` inside it).

Cross-thread spans: the current span is thread-local; worker threads
(watchdog query runner, slab prefetch pool) join their parent's tree
with ``with trace.attach(parent_span):`` — the same handoff shape as
``profiler.adopt_sinks``.

DP-safety: span names are static strings; attribute and event payloads
go through :func:`~pipelinedp_tpu.obs.metrics.check_safe_value` — raw
pids, partition keys, pre-noise values and any array are refused at the
API (TelemetryLeakError), and dplint DPL011 flags offending call sites
statically.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import dataclasses
import itertools
import json
import os
import tempfile
import threading
import time
from typing import Deque, Dict, List, Optional, Tuple

from pipelinedp_tpu.obs import flight as flight_lib
from pipelinedp_tpu.obs import metrics as metrics_lib

TRACE_ENV = "PIPELINEDP_TPU_TRACE"

# Bounded finished-span buffer: a long-lived serving process must not
# grow its trace without bound; the newest spans win (the ones an
# operator debugging "why was that query slow" wants).
MAX_SPANS = 200_000


@dataclasses.dataclass
class Span:
    """One finished (or in-flight) span. Times are perf_counter_ns."""
    name: str
    span_id: int
    parent_id: Optional[int]
    trace_id: int
    thread_id: int
    t0_ns: int
    dur_ns: int = -1  # -1 while in flight
    attrs: Dict[str, object] = dataclasses.field(default_factory=dict)
    events: List[Tuple[str, int, Dict[str, object]]] = dataclasses.field(
        default_factory=list)

    def set_attribute(self, key: str, value) -> None:
        metrics_lib.check_safe_value(key, value)
        self.attrs[key] = value

    def add_event(self, name: str, **attrs) -> None:
        for k, v in attrs.items():
            metrics_lib.check_safe_value(k, v)
        self.events.append((name, time.perf_counter_ns(), dict(attrs)))


class _SpanCtx:
    """Context manager entering ``span`` as the thread's current span
    and finishing it on exit."""

    __slots__ = ("_tracer", "_span")

    def __init__(self, tracer: "Tracer", span: Span):
        self._tracer = tracer
        self._span = span

    def __enter__(self) -> Span:
        self._tracer._push(self._span)
        return self._span

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        self._tracer._pop_finish(self._span, failed=exc_type is not None)


class Tracer:
    """Thread-safe span recorder (module docstring)."""

    def __init__(self, max_spans: int = MAX_SPANS):
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._tls = threading.local()
        self._finished: Deque[Span] = collections.deque(maxlen=max_spans)

    # -- span lifecycle ---------------------------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def span(self, name: str, parent: Optional[Span] = None,
             **attrs) -> _SpanCtx:
        """A new span under ``parent`` (default: this thread's current
        span; None makes a root). Use as a context manager."""
        for k, v in attrs.items():
            metrics_lib.check_safe_value(k, v)
        if parent is None:
            parent = self.current()
        with self._lock:
            span_id = next(self._ids)
        sp = Span(
            name=name, span_id=span_id,
            parent_id=parent.span_id if parent is not None else None,
            trace_id=parent.trace_id if parent is not None else span_id,
            thread_id=threading.get_ident(),
            t0_ns=time.perf_counter_ns(), attrs=dict(attrs))
        return _SpanCtx(self, sp)

    def _push(self, span: Span) -> None:
        self._stack().append(span)

    def _pop_finish(self, span: Span, failed: bool) -> None:
        span.dur_ns = time.perf_counter_ns() - span.t0_ns
        if failed:
            span.attrs.setdefault("error", True)
        stack = self._stack()
        if stack and stack[-1] is span:
            stack.pop()
        else:  # defensive: unbalanced exit never corrupts other spans
            try:
                stack.remove(span)
            except ValueError:
                pass
        with self._lock:
            self._finished.append(span)

    def event(self, name: str, **attrs) -> None:
        """Attaches a point event to the current span (dropped when no
        span is open — events without context have no tree to hang on)."""
        cur = self.current()
        if cur is not None:
            cur.add_event(name, **attrs)

    @contextlib.contextmanager
    def attach(self, parent: Optional[Span]):
        """Installs ``parent`` as this thread's current span so spans
        opened here join the parent's tree (cross-thread handoff)."""
        if parent is None:
            yield
            return
        stack = self._stack()
        stack.append(parent)
        try:
            yield
        finally:
            if stack and stack[-1] is parent:
                stack.pop()

    # -- export -----------------------------------------------------------

    def spans(self, trace_id: Optional[int] = None) -> List[Span]:
        with self._lock:
            out = list(self._finished)
        if trace_id is not None:
            out = [s for s in out if s.trace_id == trace_id]
        return out

    def export_chrome(self, trace_id: Optional[int] = None) -> dict:
        """Chrome trace-event JSON object (Perfetto-loadable):
        ``{"traceEvents": [...], "displayTimeUnit": "ms"}``. Span
        timestamps are microseconds from an arbitrary epoch; parent
        links ride ``args.span_id`` / ``args.parent_id``."""
        pid = os.getpid()
        events = []
        for s in self.spans(trace_id):
            args = {"span_id": s.span_id, "trace_id": s.trace_id}
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            args.update(s.attrs)
            events.append({
                "name": s.name, "ph": "X", "pid": pid,
                "tid": s.thread_id, "ts": s.t0_ns / 1000.0,
                "dur": max(s.dur_ns, 0) / 1000.0, "args": args,
            })
            for ev_name, ts_ns, ev_attrs in s.events:
                events.append({
                    "name": ev_name, "ph": "i", "s": "t", "pid": pid,
                    "tid": s.thread_id, "ts": ts_ns / 1000.0,
                    "args": dict(ev_attrs, span_id=s.span_id),
                })
        events.sort(key=lambda e: e["ts"])
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def write_chrome(self, path: str,
                     trace_id: Optional[int] = None) -> str:
        """Writes the Chrome trace JSON to ``path`` (a directory gets
        ``trace_<pid>.json`` inside it); returns the file path."""
        if os.path.isdir(path):
            path = os.path.join(path, f"trace_{os.getpid()}.json")
        parent = os.path.dirname(path)
        if parent:
            os.makedirs(parent, exist_ok=True)
        # Atomic publish: a reader (or crash) never sees a torn trace.
        fd, tmp = tempfile.mkstemp(dir=parent or ".", suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(self.export_chrome(trace_id), f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return path

    def reset(self) -> None:
        with self._lock:
            self._finished.clear()


# -- the process-global tracer ----------------------------------------------

_active: Optional[Tracer] = None
_NULL_CTX = contextlib.nullcontext(None)


def install(tracer: Optional[Tracer] = None) -> Tracer:
    """Installs (and returns) the process tracer; spans start recording
    on every instrumented path."""
    global _active
    if tracer is None:
        tracer = Tracer()
    _active = tracer
    return tracer


def shutdown() -> None:
    """Uninstalls the process tracer; span()/event() return to no-ops."""
    global _active
    _active = None


def active() -> Optional[Tracer]:
    return _active


def enabled() -> bool:
    return _active is not None


def span(name: str, parent: Optional[Span] = None, **attrs):
    """Module-level span entry: a real span ctx when a tracer is
    installed, the shared null context (zero cost) otherwise."""
    t = _active
    if t is None:
        return _NULL_CTX
    return t.span(name, parent=parent, **attrs)


def event(name: str, **attrs) -> None:
    # Every span event also lands in the always-on flight recorder
    # (obs/flight.py): the retry/degrade/evict/hit vocabulary is exactly
    # the post-mortem an operator wants from a dead process, and it must
    # exist with no tracer installed.
    flight_lib.record(name, **attrs)
    t = _active
    if t is not None:
        t.event(name, **attrs)


def current() -> Optional[Span]:
    t = _active
    return t.current() if t is not None else None


def attach(parent: Optional[Span]):
    t = _active
    if t is None or parent is None:
        return _NULL_CTX
    return t.attach(parent)


def _init_from_env() -> None:
    path = os.environ.get(TRACE_ENV, "")
    if not path or path == "0":
        return
    tracer = install()
    if path != "1":
        atexit.register(lambda: tracer.write_chrome(path))


_init_from_env()
