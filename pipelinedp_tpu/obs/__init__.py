"""Fleet observability: span tracing, typed metrics, DP release audit.

The serving fleet's answer to "why was this query slow", "what is p99
admission wait", and "exactly which DP releases has tenant X been
charged for" (OBSERVABILITY.md):

  * :mod:`~pipelinedp_tpu.obs.trace` — a thread-safe,
    zero-cost-when-disabled span :class:`~pipelinedp_tpu.obs.trace
    .Tracer` with explicit parent links, threaded through
    ``DatasetSession.query``/``query_batch``, the ``SessionManager``
    gate, the ``runtime.SlabDriver`` windows (encode / transfer /
    dispatch / sync, retry / degrade / watchdog events) and the fused
    finalize epilogue; exports Chrome trace-event JSON
    (Perfetto-loadable) per query or per process.
    Knob: ``PIPELINEDP_TPU_TRACE``.
  * :mod:`~pipelinedp_tpu.obs.metrics` — a typed registry (counters,
    gauges, fixed-bucket latency histograms) with Prometheus text
    exposition and a JSON snapshot API; absorbs the legacy
    ``profiler.count_event`` namespace behind back-compat shims.
    Knob: ``PIPELINEDP_TPU_METRICS``.
  * :mod:`~pipelinedp_tpu.obs.audit` — an append-only, per-tenant
    release audit trail on the runtime's fsync'd WAL machinery:
    mechanism kinds, (ε, δ) charged, kept/dropped partition counts,
    timings, typed outcomes; survives SIGKILL on store-bound sessions.

The operational plane (PR 13) serves and persists all of it:

  * :mod:`~pipelinedp_tpu.obs.flight` — the always-on bounded
    flight recorder (post-mortem ring buffer + spool + slow-query
    captures). Knobs: ``PIPELINEDP_TPU_FLIGHT_DIR``,
    ``PIPELINEDP_TPU_SLOW_QUERY_S``, ``PIPELINEDP_TPU_CAPTURE_DIR``.
  * :mod:`~pipelinedp_tpu.obs.ops_plane` — stdlib HTTP endpoints over
    a live fleet: ``/metrics``, ``/healthz``, ``/statusz``,
    ``/debug/flightz``. Knob: ``PIPELINEDP_TPU_OPS_PORT``.
  * :mod:`~pipelinedp_tpu.obs.regress` — the bench-trajectory perf
    regression gate (``python -m pipelinedp_tpu.obs.regress
    BENCH_*.json``), wired into CI.

DP-safety is a hard API rule, not a convention: raw pids, partition
keys, and unreleased (pre-noise) values never enter any obs record —
span attributes, metric labels and audit fields are validated scalars
(``TelemetryLeakError`` otherwise), and dplint rule DPL011
(telemetry-taint) flags offending flows statically.

Instrumented code must never be able to change released bits: tracing
reads clocks and counters, never data or keys, and results are pinned
bit-identical with tracing on or off (tests/obs_serving_test.py).
"""

from pipelinedp_tpu.obs import flight, metrics, ops_plane, trace  # noqa: F401
from pipelinedp_tpu.obs.flight import (  # noqa: F401
    CAPTURE_DIR_ENV, FLIGHT_DIR_ENV, SLOW_QUERY_ENV, FlightEvent,
    FlightRecorder)
from pipelinedp_tpu.obs.metrics import (  # noqa: F401
    METRICS_ENV, Counter, Gauge, Histogram, MetricsRegistry,
    TelemetryLeakError, check_safe_value, default_registry)
from pipelinedp_tpu.obs.ops_plane import (  # noqa: F401
    OPS_PORT_ENV, OpsServer, serve_ops)
from pipelinedp_tpu.obs.trace import TRACE_ENV, Span, Tracer  # noqa: F401

# obs.audit imports runtime.journal (which imports the profiler); load
# it lazily so `import pipelinedp_tpu.profiler` -> obs never cycles.
_LAZY = {"audit", "AuditRecord", "AuditTrail", "AuditCorruptError",
         "OUTCOMES"}


def __getattr__(name: str):
    if name in _LAZY:
        import importlib
        mod = importlib.import_module("pipelinedp_tpu.obs.audit")
        return mod if name == "audit" else getattr(mod, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
