"""Flight recorder: the post-mortem the process leaves when it dies.

A serving process that wedges, crashes, or is SIGKILL'd takes its spans
and metrics with it — the operator is left with WALs (what *committed*)
and nothing about what the process was *doing*. The flight recorder
closes that gap: an always-on, bounded ring buffer of operational
events (window timings, retries, degradations, evictions, admission
decisions, query lifecycle), fed from the existing span-event hooks in
``runtime.SlabDriver``, ``DatasetSession.query`` and the dispatch
watchdog, with three exit doors:

  * **dump** — an atomic JSON snapshot of the ring (tmp + rename),
    written on watchdog timeout, deadline expiry, unhandled engine
    error, and at process exit. Never torn: readers see the previous
    dump or the new one.
  * **spool** — an append-per-event JSON-lines file next to the
    session WALs (bound automatically for store-bound sessions, or via
    ``PIPELINEDP_TPU_FLIGHT_DIR``). Each line hits the OS page cache at
    record time, so even a SIGKILL'd process — which runs no atexit
    handler — leaves a parseable event trail (a torn final line is
    tolerated on read, like the WALs' torn tail).
  * **slow-query capture** — queries exceeding
    ``PIPELINEDP_TPU_SLOW_QUERY_S`` (or landing within 20% of their
    deadline) write a full per-query bundle — Chrome trace, metrics
    delta, flight-recorder slice — into a bounded capture directory,
    correlated to the audit record by ``trace_id``
    (:func:`write_capture`; the session drives it).

DP-safety: every event attribute passes the shared obs payload gate
(:func:`~pipelinedp_tpu.obs.metrics.check_safe_value`) — forbidden keys
and non-scalar payloads are refused at the API, so a dump can never
carry raw pids, partition keys, or pre-noise values; the serving leak
scan covers dumps, spools and captures dynamically, and dplint DPL011
counts this module's APIs among its telemetry sinks.

Recording can never change released bits (it reads clocks and scalars,
never data or keys) and never raises on I/O: a full disk degrades the
post-mortem, not the query.

This module is stdlib-only (plus obs.metrics, itself stdlib-only) so
the runtime and watchdog can import it without cycles.
"""

from __future__ import annotations

import atexit
import collections
import dataclasses
import json
import os
import tempfile
import threading
import time
from typing import Deque, Dict, List, Optional

from pipelinedp_tpu.obs import metrics as metrics_lib

# Tuning knobs (README "Tuning knobs" + OBSERVABILITY.md):
#   PIPELINEDP_TPU_FLIGHT_DIR — binds the process spool + dump dir
#     (store-bound sessions bind it automatically next to their WALs).
#   PIPELINEDP_TPU_FLIGHT_EVENTS — ring capacity (default 2048).
#   PIPELINEDP_TPU_SLOW_QUERY_S — slow-query capture threshold in
#     seconds (0/unset = deadline-proximity captures only).
#   PIPELINEDP_TPU_CAPTURE_DIR — where slow-query captures land
#     (unset = captures disabled).
#   PIPELINEDP_TPU_CAPTURES — max capture files kept (oldest pruned).
#   PIPELINEDP_TPU_FLIGHT_SPOOL_BYTES — total byte budget across all
#     spool segments (default 64 MiB); the active spool rotates at
#     budget/segments bytes.
#   PIPELINEDP_TPU_FLIGHT_SPOOL_SEGMENTS — how many spool files the
#     budget is split over (active + rotated ``.1``..``.K-1``;
#     default 4). Oldest segment is dropped on rotation.
FLIGHT_DIR_ENV = "PIPELINEDP_TPU_FLIGHT_DIR"
FLIGHT_EVENTS_ENV = "PIPELINEDP_TPU_FLIGHT_EVENTS"
SPOOL_BYTES_ENV = "PIPELINEDP_TPU_FLIGHT_SPOOL_BYTES"
SPOOL_SEGMENTS_ENV = "PIPELINEDP_TPU_FLIGHT_SPOOL_SEGMENTS"
SLOW_QUERY_ENV = "PIPELINEDP_TPU_SLOW_QUERY_S"
CAPTURE_DIR_ENV = "PIPELINEDP_TPU_CAPTURE_DIR"
CAPTURE_LIMIT_ENV = "PIPELINEDP_TPU_CAPTURES"

DUMP_VERSION = 1

# How many trailing event kinds a hang/deadline error message carries
# (the "self-diagnosing hang report" satellite).
POSTMORTEM_EVENTS = 8


def ring_capacity() -> int:
    """Validated PIPELINEDP_TPU_FLIGHT_EVENTS (default 2048)."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(FLIGHT_EVENTS_ENV, 2048, 64, 1_000_000)


def spool_byte_budget() -> int:
    """Validated PIPELINEDP_TPU_FLIGHT_SPOOL_BYTES (default 64 MiB):
    the total on-disk budget across the active spool and its rotated
    segments. A long-lived serving process records events forever; the
    budget is what keeps the post-mortem from eating the WAL volume."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(SPOOL_BYTES_ENV, 64 << 20, 4096, 1 << 40)


def spool_segment_count() -> int:
    """Validated PIPELINEDP_TPU_FLIGHT_SPOOL_SEGMENTS (default 4)."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(SPOOL_SEGMENTS_ENV, 4, 1, 64)


def _env_float_s(name: str, lo: float, hi: float) -> Optional[float]:
    """Validated float-seconds env knob: unset/empty/0 -> None; junk or
    out-of-range raises (the env_int stance, for fractional seconds)."""
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return None
    try:
        parsed = float(raw.strip())
    except ValueError:
        raise ValueError(f"{name} must be a number of seconds, "
                         f"got {raw!r}") from None
    if parsed == 0:
        return None
    if not lo <= parsed <= hi:
        raise ValueError(f"{name} must be in [{lo}, {hi}] (or 0 to "
                         f"disable), got {parsed}")
    return parsed


def slow_query_threshold_s() -> Optional[float]:
    """Validated PIPELINEDP_TPU_SLOW_QUERY_S (None when 0/unset)."""
    return _env_float_s(SLOW_QUERY_ENV, 1e-6, 24 * 3600.0)


def capture_dir() -> Optional[str]:
    """The slow-query capture directory (None = captures disabled)."""
    raw = os.environ.get(CAPTURE_DIR_ENV, "")
    return raw if raw else None


def capture_limit() -> int:
    """Validated PIPELINEDP_TPU_CAPTURES (default 32 files kept)."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(CAPTURE_LIMIT_ENV, 32, 1, 10_000)


@dataclasses.dataclass(frozen=True)
class FlightEvent:
    """One recorded operational event. ``t_ns`` is perf_counter_ns (the
    span clock — flight slices align with trace timestamps);
    ``ts_unix`` anchors it to wall clock for cross-process correlation."""
    seq: int
    kind: str
    ts_unix: float
    t_ns: int
    thread_id: int
    attrs: Dict[str, object]

    def to_payload(self) -> dict:
        return {"seq": self.seq, "kind": self.kind,
                "ts_unix": self.ts_unix, "t_ns": self.t_ns,
                "thread_id": self.thread_id, "attrs": dict(self.attrs)}


class FlightRecorder:
    """Bounded ring of :class:`FlightEvent` (module docstring). Always
    on; recording is one lock + one deque append (plus one buffered
    line write when a spool is bound). Newest events win — the ones an
    operator reconstructing a hang wants."""

    def __init__(self, max_events: Optional[int] = None):
        self._lock = threading.Lock()
        self._events: Deque[FlightEvent] = collections.deque(
            maxlen=max_events if max_events is not None else ring_capacity())
        self._seq = 0
        self._spool_fh = None
        self._spool_path: Optional[str] = None
        self._spool_bytes = 0
        self._spool_segment_bytes = 0  # rotate threshold; 0 = unbound
        self._spool_segments = 1
        self._dump_dir: Optional[str] = None

    # -- recording --------------------------------------------------------

    def record(self, kind: str, **attrs) -> FlightEvent:
        """Appends one event; every attribute passes the shared obs
        payload gate (TelemetryLeakError on private-data-shaped input)."""
        for k, v in attrs.items():
            metrics_lib.check_safe_value(k, v)
        with self._lock:
            event = FlightEvent(
                seq=self._seq, kind=str(kind), ts_unix=time.time(),
                t_ns=time.perf_counter_ns(),
                thread_id=threading.get_ident(), attrs=dict(attrs))
            self._seq += 1
            self._events.append(event)
            if self._spool_fh is not None:
                try:
                    line = (json.dumps(event.to_payload(),
                                       separators=(",", ":")) + "\n")
                    self._spool_fh.write(line)
                    # flush() lands the line in the OS page cache: it
                    # survives SIGKILL (only an OS/power crash loses it;
                    # the dump path is for that — and fsync per event
                    # would put a disk sync on the serving hot path).
                    self._spool_fh.flush()
                    self._spool_bytes += len(line)
                    if (self._spool_segment_bytes
                            and self._spool_bytes
                            >= self._spool_segment_bytes):
                        self._rotate_spool_locked()
                except (OSError, ValueError):
                    pass  # a dead spool degrades the post-mortem only
        return event

    # -- reads ------------------------------------------------------------

    def events(self, last: Optional[int] = None,
               since_seq: Optional[int] = None) -> List[FlightEvent]:
        with self._lock:
            out = list(self._events)
        if since_seq is not None:
            out = [e for e in out if e.seq >= since_seq]
        if last is not None:
            out = out[-last:]
        return out

    def watermark(self) -> int:
        """The next event's seq — slice with events(since_seq=mark)."""
        with self._lock:
            return self._seq

    # -- spool + dump destinations ---------------------------------------

    @property
    def spool_path(self) -> Optional[str]:
        return self._spool_path

    @property
    def dump_dir(self) -> Optional[str]:
        return self._dump_dir

    def bind_spool(self, path: str) -> str:
        """Opens (append) the JSON-lines spool at ``path``; subsequent
        events stream there as they are recorded. Idempotent for the
        same path; rebinding moves the stream. The spool is size-capped:
        it rotates at ``spool_byte_budget() / spool_segment_count()``
        bytes into ``path.1`` .. ``path.K-1`` (oldest dropped), so an
        always-on recorder holds a bounded slice of recent history
        instead of growing without bound next to the WALs."""
        with self._lock:
            if self._spool_path == path and self._spool_fh is not None:
                return path
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            if self._spool_fh is not None:
                try:
                    self._spool_fh.close()
                except OSError:
                    pass
            self._spool_fh = open(path, "a")
            self._spool_path = path
            self._spool_segments = spool_segment_count()
            self._spool_segment_bytes = max(
                4096, spool_byte_budget() // self._spool_segments)
            try:
                # Re-binding after a restart resumes an existing spool
                # mid-segment: the counter starts at its current size so
                # the rotation point is where it would have been.
                self._spool_bytes = os.path.getsize(path)
            except OSError:
                self._spool_bytes = 0
        return path

    def _rotate_spool_locked(self) -> None:
        """Shifts the segment chain (``.K-1`` dropped, ``.i`` ->
        ``.i+1``, active -> ``.1``) and reopens a fresh active spool.
        Caller holds ``_lock``. A torn final line in a rotated segment
        stays torn — :func:`read_dump` tolerates it per segment. With
        one segment configured the active file is simply truncated.
        Best-effort like all spool I/O: on failure the old handle keeps
        streaming and the next threshold crossing retries."""
        path = self._spool_path
        if path is None or self._spool_fh is None:
            return
        try:
            self._spool_fh.close()
        except OSError:
            pass
        try:
            if self._spool_segments > 1:
                oldest = f"{path}.{self._spool_segments - 1}"
                if os.path.exists(oldest):
                    os.unlink(oldest)
                for i in range(self._spool_segments - 2, 0, -1):
                    src = f"{path}.{i}"
                    if os.path.exists(src):
                        os.replace(src, f"{path}.{i + 1}")
                os.replace(path, f"{path}.1")
            self._spool_fh = open(path, "w")
            self._spool_bytes = 0
        except OSError:
            try:
                self._spool_fh = open(path, "a")
            except OSError:
                self._spool_fh = None

    def set_dump_dir(self, path: str) -> None:
        self._dump_dir = path

    def dump(self, path: Optional[str] = None,
             reason: str = "manual") -> Optional[str]:
        """Atomically writes the ring as one JSON document (tmp + fsync
        + rename; a reader never sees a torn dump). ``path`` defaults to
        ``<dump_dir>/flight_<pid>.json``; returns the file path, or
        None when no destination is configured or the write failed
        (dumping is best-effort by design — it runs on error paths)."""
        if path is None:
            if self._dump_dir is None:
                return None
            path = os.path.join(self._dump_dir,
                                f"flight_{os.getpid()}.json")
        doc = {
            "version": DUMP_VERSION,
            "process_id": os.getpid(),
            "ts_unix": time.time(),
            "reason": reason,
            "events": [e.to_payload() for e in self.events()],
        }
        try:
            parent = os.path.dirname(path) or "."
            os.makedirs(parent, exist_ok=True)
            fd, tmp = tempfile.mkstemp(dir=parent, suffix=".tmp")
            try:
                with os.fdopen(fd, "w") as f:
                    json.dump(doc, f)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, path)
            except BaseException:
                if os.path.exists(tmp):
                    os.unlink(tmp)
                raise
        except OSError:
            return None
        return path

    def postmortem(self, dump_path: Optional[str] = None,
                   last: int = POSTMORTEM_EVENTS) -> str:
        """The one-line hang summary DispatchHangError/QueryDeadlineError
        messages carry: the last recorded event kinds plus the dump
        location, so a hang report is self-diagnosing."""
        kinds = [e.kind for e in self.events(last=last)]
        where = dump_path or self._spool_path
        return (f"flight recorder: last events "
                f"[{', '.join(kinds) if kinds else 'none'}]"
                + (f"; dump: {where}" if where else ""))

    def reset(self) -> None:
        """Tests only: clears the ring (spool/dump bindings stay)."""
        with self._lock:
            self._events.clear()

    def close_spool(self) -> None:
        with self._lock:
            if self._spool_fh is not None:
                try:
                    self._spool_fh.close()
                except OSError:
                    pass
                self._spool_fh = None
                self._spool_path = None


# -- the process-global recorder (always on) ---------------------------------

_recorder = FlightRecorder()


def recorder() -> FlightRecorder:
    return _recorder


def record(kind: str, **attrs) -> FlightEvent:
    """Module-level entry: records into the process flight recorder.
    Also fed automatically by every ``obs.trace.event`` call site, so
    the span-event vocabulary (retry / degrade / resume /
    watchdog_timeout / device_fallback / bound_cache_hit / demote /
    spill / shed) lands in the ring with no tracer installed."""
    return _recorder.record(kind, **attrs)


def events(last: Optional[int] = None) -> List[FlightEvent]:
    return _recorder.events(last=last)


def dump_now(reason: str) -> Optional[str]:
    return _recorder.dump(reason=reason)


def postmortem(dump_path: Optional[str] = None) -> str:
    return _recorder.postmortem(dump_path)


def ensure_process_spool(directory: str) -> str:
    """Binds the process recorder's spool (and dump dir) under
    ``directory`` — ``<directory>/flight_<pid>.jsonl`` — unless a spool
    is already bound (first binding wins: the post-mortem lives next to
    the first store's WALs). Store-bound sessions call this."""
    if _recorder.spool_path is not None:
        return _recorder.spool_path
    path = os.path.join(directory, f"flight_{os.getpid()}.jsonl")
    _recorder.bind_spool(path)
    if _recorder.dump_dir is None:
        _recorder.set_dump_dir(directory)
    return path


# -- reading dumps and spools back -------------------------------------------


class FlightDumpError(ValueError):
    """The artifact is corrupted beyond the tolerated torn tail."""


def read_dump(path: str) -> dict:
    """Parses either artifact shape into ``{..., "events": [...]}``:

    * an atomic ``.json`` dump — parsed verbatim (it cannot be torn);
    * a ``.jsonl`` spool — line-per-event with the WALs' torn-tail
      stance: a malformed FINAL line was mid-write at death and is
      dropped; a malformed interior line is real corruption and raises
      :class:`FlightDumpError`.
    """
    with open(path, "r") as f:
        raw = f.read()
    # An atomic dump is one JSON document with an "events" key; anything
    # else (including a one-event spool, which also parses as a single
    # dict) reads as a line-per-event spool.
    try:
        doc = json.loads(raw)
    except ValueError:
        doc = None
    if isinstance(doc, dict) and "events" in doc:
        return doc
    events_out: List[dict] = []
    lines = raw.split("\n")
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            obj = json.loads(line)
            if not isinstance(obj, dict) or "kind" not in obj:
                raise ValueError("not an event record")
        except ValueError as exc:
            tail = all(not later.strip() for later in lines[i + 1:])
            if tail:
                break  # torn tail: the write died mid-line
            raise FlightDumpError(
                f"{path}: spool line {i} is malformed but later events "
                f"follow — corrupted, not torn ({exc})")
        events_out.append(obj)
    return {"version": DUMP_VERSION, "reason": "spool",
            "source": "spool", "events": events_out}


def spool_segment_paths(path: str) -> List[str]:
    """All on-disk segments of a rotated spool, oldest first
    (``path.K-1`` .. ``path.1``, then the active ``path``)."""
    out: List[str] = []
    for i in range(spool_segment_count() - 1, 0, -1):
        seg = f"{path}.{i}"
        if os.path.exists(seg):
            out.append(seg)
    if os.path.exists(path):
        out.append(path)
    return out


def read_spool(path: str) -> dict:
    """Reads a rotated spool chain back as one event stream, oldest
    segment first. Torn-tail tolerance applies per segment — a segment
    rotated away mid-write keeps its torn final line, and each file is
    parsed with :func:`read_dump`'s stance independently."""
    events_out: List[dict] = []
    for seg in spool_segment_paths(path):
        events_out.extend(read_dump(seg)["events"])
    return {"version": DUMP_VERSION, "reason": "spool",
            "source": "spool", "events": events_out}


# -- slow-query captures -----------------------------------------------------


def _capture_name(trace_id: str) -> str:
    safe = "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in str(trace_id))
    return f"slowquery_{safe}.json"


def write_capture(trace_id: str, document: dict,
                  directory: Optional[str] = None) -> Optional[str]:
    """Atomically writes one slow-query capture bundle, named by the
    query's ``trace_id`` (the audit-record correlation key), and prunes
    the directory to the newest ``capture_limit()`` files so a slow
    fleet can never fill the disk with post-mortems. Best-effort:
    returns None instead of raising on I/O failure."""
    directory = directory if directory is not None else capture_dir()
    if directory is None:
        return None
    path = os.path.join(directory, _capture_name(trace_id))
    try:
        os.makedirs(directory, exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=directory, suffix=".tmp")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(document, f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        _prune_captures(directory, capture_limit())
    except OSError:
        return None
    return path


def _prune_captures(directory: str, keep: int) -> None:
    entries = []
    for name in os.listdir(directory):
        if name.startswith("slowquery_") and name.endswith(".json"):
            full = os.path.join(directory, name)
            try:
                entries.append((os.path.getmtime(full), full))
            except OSError:
                continue
    entries.sort()
    for _, full in entries[:max(0, len(entries) - keep)]:
        try:
            os.unlink(full)
        except OSError:
            pass


# -- env wiring --------------------------------------------------------------


def _atexit_dump() -> None:
    _recorder.dump(reason="atexit")


def _init_from_env() -> None:
    directory = os.environ.get(FLIGHT_DIR_ENV, "")
    if directory:
        ensure_process_spool(directory)


_init_from_env()
# Registered unconditionally: with no dump dir bound it is a no-op, and
# a dir bound later (store binding) still gets the exit dump.
atexit.register(_atexit_dump)
