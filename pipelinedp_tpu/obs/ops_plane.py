"""The operational plane: live HTTP endpoints over a running fleet.

PR-11 built the telemetry substrate (tracer, typed metrics registry,
audit trail); this module *serves* it, so an operator can ask a live
process "are you healthy, what is p99, which tenant is burning budget"
without attaching a debugger. One stdlib-only
:class:`http.server.ThreadingHTTPServer` (no new dependencies, safe in
any container) exposes:

  * ``GET /metrics``  — the process metric registry in Prometheus text
    exposition 0.0.4 (scrape it directly).
  * ``GET /healthz``  — typed readiness JSON: sessions resident vs
    spilled, watchdog/hang counters, WAL-directory writability, flight
    recorder state. HTTP 200 when healthy, 503 when a hard check (WAL
    writable) fails.
  * ``GET /statusz``  — the fleet snapshot JSON: per-session residency
    tier + inflight work, shed/deadline counters, bound-cache hit rate,
    and the per-tenant ε/δ spent-vs-ledger burn-down. Budgets are
    public quantities; released values (and of course raw data) never
    appear — the serving leak scan covers this surface dynamically.
  * ``GET /debug/flightz`` — the most recent flight-recorder events
    (obs/flight.py), newest last.
  * ``GET /fleetz``   — the failover plane (serving/fleet.py): lease
    holder + fencing token per session, follower replication lag, and
    the process-wide takeover/fence/hedge counters. Lease metadata is
    operational (pid/host/token), never data.

Start it with :func:`serve_ops(manager_or_session, port)` — any object
with a ``stats()`` dict works; ``SessionManager`` and ``DatasetSession``
are the intended targets — or let ``PIPELINEDP_TPU_OPS_PORT`` start it
automatically when a ``SessionManager`` is constructed. ``port=0``
binds an ephemeral port (``server.port`` reports it). The server runs
on daemon threads and holds no locks while rendering: it reads the
same snapshot APIs bench.py does, so a wedged query cannot wedge the
diagnostics that would explain it, and the plane being up or down
cannot change a single released bit (pinned by
tests/obs_serving_test.py).
"""

from __future__ import annotations

import json
import os
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional, Tuple

from pipelinedp_tpu.obs import flight as flight_lib
from pipelinedp_tpu.obs import metrics as metrics_lib

OPS_PORT_ENV = "PIPELINEDP_TPU_OPS_PORT"

# How many flight events /debug/flightz returns (newest last).
FLIGHTZ_EVENTS = 256


def env_ops_port() -> Optional[int]:
    """Validated PIPELINEDP_TPU_OPS_PORT (None when 0/unset)."""
    from pipelinedp_tpu.native import loader
    port = loader.env_int(OPS_PORT_ENV, 0, 0, 65535)
    return port if port > 0 else None


# -- payload builders (shared with tests and the kill harness) ---------------


def _is_manager(target) -> bool:
    return hasattr(target, "max_inflight") and hasattr(target, "store")


def _residency_tier(session_stats: dict) -> str:
    if session_stats.get("spilled"):
        return "spilled"
    if session_stats.get("wire_device_bytes", 0) > 0:
        return "device"
    return "host"


def _session_statusz(session_stats: dict) -> dict:
    tenants = {}
    for tid, t in (session_stats.get("tenants") or {}).items():
        spent = float(t.get("spent_epsilon", 0.0))
        total = float(t.get("total_epsilon",
                            spent + float(t.get("remaining_epsilon", 0.0))))
        tenants[tid] = dict(
            t,
            total_epsilon=total,
            epsilon_burn_pct=(round(100.0 * spent / total, 2)
                              if total > 0 else 0.0))
    out = {
        "residency": _residency_tier(session_stats),
        "resident_bytes": session_stats.get("resident_bytes", 0),
        "wire_host_bytes": session_stats.get("wire_host_bytes", 0),
        "wire_device_bytes": session_stats.get("wire_device_bytes", 0),
        "bound_cache_bytes": session_stats.get("bound_cache_bytes", 0),
        "bound_cache_entries": session_stats.get("bound_cache_entries", 0),
        "queries": session_stats.get("queries", 0),
        "active_queries": session_stats.get("active_queries", 0),
        "n_chunks": session_stats.get("n_chunks", 0),
        "store": session_stats.get("store"),
        "tenants": tenants,
    }
    if "live" in session_stats:
        out["live"] = session_stats["live"]
    if "planner" in session_stats:
        out["planner"] = session_stats["planner"]
    if session_stats.get("read_only"):
        out["read_only"] = True
    if session_stats.get("fleet"):
        out["fleet"] = session_stats["fleet"]
    return out


def _fleet_counters() -> dict:
    ev = metrics_lib.default_registry().event_values()
    hits = ev.get("serving/bound_cache_hits", 0)
    misses = ev.get("serving/bound_cache_misses", 0)
    return {
        "queries": ev.get("serving/queries", 0),
        "queries_shed": ev.get("serving/queries_shed", 0),
        "query_deadline_hits": ev.get("serving/query_deadline_hits", 0),
        "bound_cache_hits": hits,
        "bound_cache_misses": misses,
        "bound_cache_hit_rate": (round(hits / (hits + misses), 4)
                                 if hits + misses else None),
        "device_fallbacks": ev.get("serving/device_fallbacks", 0),
        "rehydrations": ev.get("serving/sessions_rehydrations", 0),
        "demotions": ev.get("serving/sessions_demotions", 0),
        "spills": ev.get("serving/sessions_spills", 0),
        "watchdog_timeouts": ev.get("runtime/watchdog_timeouts", 0),
        "hangs_detected": ev.get("runtime/hangs_detected", 0),
        "retries": ev.get("runtime/retries", 0),
        "audit_records": ev.get("obs/audit_records", 0),
    }


def statusz_payload(target) -> dict:
    """The /statusz JSON: fleet shape, counters, per-session residency
    and per-tenant budget burn-down. Operational aggregates and public
    budget quantities only — never values, keys, or ids."""
    out = {
        "process_id": os.getpid(),
        "kind": "manager" if _is_manager(target) else "session",
        "counters": _fleet_counters(),
        "flight_events_recorded": flight_lib.recorder().watermark(),
    }
    stats = target.stats()
    if _is_manager(target):
        out.update({
            "budget_bytes": stats.get("budget_bytes"),
            "resident_bytes": stats.get("resident_bytes"),
            "inflight": stats.get("inflight"),
            "max_inflight": stats.get("max_inflight"),
            "default_deadline_s": stats.get("default_deadline_s"),
            "sessions": {name: _session_statusz(s)
                         for name, s in stats.get("sessions", {}).items()},
        })
    else:
        name = getattr(target, "name", "session")
        out["sessions"] = {name: _session_statusz(stats)}
    return out


def _writable(path: Optional[str]) -> Optional[bool]:
    if not path:
        return None
    try:
        probe = os.path.join(path, f".ops_probe_{os.getpid()}")
        with open(probe, "w") as f:
            f.write("ok")
        os.unlink(probe)
        return True
    except OSError:
        return False


def healthz_payload(target) -> Tuple[dict, bool]:
    """The /healthz JSON plus overall readiness. Hard failure: the WAL
    directory (session store root / flight spool dir) is not writable —
    a fleet that cannot persist releases must not take traffic."""
    stats = target.stats()
    if _is_manager(target):
        sessions = stats.get("sessions", {})
        store_root = getattr(target.store, "root", None)
    else:
        sessions = {getattr(target, "name", "session"): stats}
        binding = getattr(target, "store_binding", None)
        store_root = getattr(binding[0], "root", None) if binding else None
    ev = metrics_lib.default_registry().event_values()
    recorder = flight_lib.recorder()
    wal_writable = _writable(store_root)
    spool_dir = (os.path.dirname(recorder.spool_path)
                 if recorder.spool_path else None)
    spool_writable = _writable(spool_dir)
    checks = {
        "sessions_resident": sum(1 for s in sessions.values()
                                 if not s.get("spilled")),
        "sessions_spilled": sum(1 for s in sessions.values()
                                if s.get("spilled")),
        "inflight": stats.get("inflight", stats.get("active_queries", 0)),
        "watchdog": {
            "timeouts": ev.get("runtime/watchdog_timeouts", 0),
            "hangs_detected": ev.get("runtime/hangs_detected", 0),
            "query_deadline_hits": ev.get("serving/query_deadline_hits", 0),
        },
        "wal_writable": wal_writable,
        "flight_recorder": {
            "events": recorder.watermark(),
            "spool": recorder.spool_path,
            "spool_writable": spool_writable,
        },
    }
    ok = wal_writable is not False and spool_writable is not False
    return {"status": "ok" if ok else "unavailable",
            "checks": checks}, ok


def fleetz_payload(target) -> dict:
    """The /fleetz JSON: lease holder, fencing token, follower
    replication lag, and the process-wide failover counters. ``target``
    may be a SessionManager/DatasetSession (``stats()``) or a
    FollowerSession/FleetRouter (``statusz()``)."""
    from pipelinedp_tpu.serving import fleet as fleet_lib
    out = {
        "process_id": os.getpid(),
        "counters": fleet_lib.fleet_counters(),
    }
    statusz = getattr(target, "statusz", None)
    if callable(statusz):  # FollowerSession / FleetRouter
        out["target"] = statusz()
        return out
    stats = target.stats()
    if _is_manager(target):
        per_session = stats.get("sessions", {})
    else:
        per_session = {getattr(target, "name", "session"): stats}
    out["sessions"] = {
        name: {"fleet": s.get("fleet"),
               "read_only": bool(s.get("read_only", False))}
        for name, s in per_session.items()}
    return out


def flightz_payload(last: int = FLIGHTZ_EVENTS) -> dict:
    return {
        "process_id": os.getpid(),
        "spool": flight_lib.recorder().spool_path,
        "events": [e.to_payload()
                   for e in flight_lib.recorder().events(last=last)],
    }


# -- the server --------------------------------------------------------------


class _OpsHandler(BaseHTTPRequestHandler):
    server_version = "pdp-ops/1"

    def log_message(self, fmt, *args):  # keep serving stdout clean
        pass

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, code: int, payload: dict) -> None:
        self._send(code, json.dumps(payload, indent=1).encode(),
                   "application/json")

    def do_GET(self) -> None:  # noqa: N802 (http.server API)
        target = self.server.ops_target  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0]
        try:
            if path == "/metrics":
                text = metrics_lib.default_registry().to_prometheus()
                self._send(200, text.encode(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif path == "/healthz":
                payload, ok = healthz_payload(target)
                self._send_json(200 if ok else 503, payload)
            elif path == "/statusz":
                self._send_json(200, statusz_payload(target))
            elif path == "/debug/flightz":
                self._send_json(200, flightz_payload())
            elif path == "/fleetz":
                self._send_json(200, fleetz_payload(target))
            else:
                self._send_json(404, {"error": "unknown endpoint", "endpoints": [
                    "/metrics", "/healthz", "/statusz", "/debug/flightz",
                    "/fleetz"]})
        except BrokenPipeError:
            pass
        except Exception as exc:  # diagnostics must not kill the server
            try:
                self._send_json(500, {"error": f"{type(exc).__name__}: {exc}"})
            except OSError:
                pass


class OpsServer:
    """A running operational-plane endpoint (module docstring).
    Construct via :func:`serve_ops`; ``close()`` stops it."""

    def __init__(self, target, port: int = 0, host: str = "127.0.0.1"):
        self._httpd = ThreadingHTTPServer((host, port), _OpsHandler)
        self._httpd.daemon_threads = True
        self._httpd.ops_target = target  # type: ignore[attr-defined]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name=f"pdp-ops-{self._httpd.server_address[1]}", daemon=True)
        self._thread.start()

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        host, port = self._httpd.server_address[:2]
        return f"http://{host}:{port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)

    def __enter__(self) -> "OpsServer":
        return self

    def __exit__(self, exc_type, exc_val, exc_tb) -> None:
        self.close()


def serve_ops(target, port: Optional[int] = None,
              host: str = "127.0.0.1") -> OpsServer:
    """Starts the observability endpoint over ``target`` (a
    SessionManager or DatasetSession). ``port=None`` consults
    ``PIPELINEDP_TPU_OPS_PORT`` and falls back to an ephemeral port;
    pass an explicit 0 for ephemeral regardless of the env."""
    if port is None:
        port = env_ops_port() or 0
    return OpsServer(target, port=port, host=host)
