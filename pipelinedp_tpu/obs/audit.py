"""DP-safe release audit trail: exactly which releases was tenant X
charged for?

Every query a :class:`~pipelinedp_tpu.serving.session.DatasetSession`
finishes — successfully or not — appends one :class:`AuditRecord`: the
release token it committed (or would have), the mechanism kinds and
(ε, δ) charged, DP-released partition counts, timing, and a typed
outcome. The trail is the operator's ground truth for budget disputes
("show me every release acme paid for") and incident forensics ("what
did the fleet do between 14:02 and 14:07") — per tenant, append-only,
and durable when the session is store-bound.

Durability rides the same fsync'd WAL machinery as the release journal
(:class:`~pipelinedp_tpu.runtime.journal.JsonlWal`): write-ahead
appends with per-record digests, torn-tail truncation on recovery,
typed refusal on interior corruption, so the trail a SIGKILL'd process
left behind replays exactly on reopen (tests/process_kill_test.py pins
this through the kill harness). A query that died before its outcome
was decided leaves NO record — the trail errs toward under-reporting
in-flight work, never toward inventing outcomes.

Outcomes (:data:`OUTCOMES`):

  * ``released`` — the release token committed and the columns went out.
  * ``refunded`` — the query failed before its token committed; any
    tenant charge was exactly refunded.
  * ``shed`` — admission control refused the query (typed overload).
  * ``deadline-expired`` — the per-query deadline fired; the charge is
    conservatively kept (the abandoned worker may still commit).
  * ``double-release-refused`` — the at-most-once journal refused a
    replayed token before any noise was drawn.

DP-safety stance (the hard rule, OBSERVABILITY.md): an audit record
carries *mechanism metadata and DP-released aggregates only*. Raw
privacy ids, partition keys, and unreleased (pre-noise) values are
refused at the API — the schema is FIXED (no free-form payloads), every
field value is validated scalar, and ``partitions_kept`` /
``partitions_dropped`` are counts of the *noised, selection-filtered*
output, i.e. already-released information. dplint DPL011 flags private
columns flowing into this module statically; the serving test matrix
scans every emitted record dynamically.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import List, Optional, Tuple

from pipelinedp_tpu.obs import metrics as metrics_lib

# Profiler event counters (kept importable without the profiler: these
# are credited through metrics_lib.default_registry() directly).
EVENT_AUDIT_RECORDS = "obs/audit_records"
EVENT_AUDIT_RECOVERIES = "obs/audit_recoveries"

OUTCOMES = frozenset({
    "released", "refunded", "shed", "deadline-expired",
    "double-release-refused",
})


class AuditCorruptError(RuntimeError):
    """The audit WAL holds a malformed interior record — the trail
    cannot be trusted, so recovery refuses rather than silently
    forgetting a committed outcome."""


@dataclasses.dataclass(frozen=True)
class AuditRecord:
    """One query outcome, in commit order. ``token`` is the canonical
    release token string (root-key fingerprint + KeyStream counter) —
    the same identity the at-most-once journal refuses replays by."""
    seq: int
    ts_unix: float
    session: str
    tenant: Optional[str]
    token: str
    outcome: str
    mechanisms: Tuple[str, ...]
    noise_kind: str
    epsilon: float
    delta: float
    partitions_kept: int
    partitions_dropped: int
    duration_s: float
    seed: int
    # Correlation key across the observability plane (PR 13): the same
    # id appears on the query's root span, its flight-recorder events,
    # and any slow-query capture file — so "show me why audit record N
    # was slow" is one grep. Defaults to "" so PR-11 WAL records
    # (which predate the field) keep parsing (pinned by tests).
    trace_id: str = ""

    def to_payload(self) -> dict:
        out = dataclasses.asdict(self)
        out["mechanisms"] = list(self.mechanisms)
        return out

    @staticmethod
    def from_payload(payload: dict) -> "AuditRecord":
        return AuditRecord(
            seq=int(payload["seq"]),
            ts_unix=float(payload["ts_unix"]),
            session=payload["session"],
            tenant=payload["tenant"],
            token=payload["token"],
            outcome=payload["outcome"],
            mechanisms=tuple(payload["mechanisms"]),
            noise_kind=payload["noise_kind"],
            epsilon=float(payload["epsilon"]),
            delta=float(payload["delta"]),
            partitions_kept=int(payload["partitions_kept"]),
            partitions_dropped=int(payload["partitions_dropped"]),
            duration_s=float(payload["duration_s"]),
            seed=int(payload["seed"]),
            trace_id=str(payload.get("trace_id", "")),
        )


class AuditTrail:
    """Append-only per-session outcome log (module docstring).

    ``path=None`` keeps the trail in memory (dies with the process —
    fine for ad-hoc sessions); a path makes it a durable
    :class:`~pipelinedp_tpu.runtime.journal.JsonlWal`.
    :meth:`bind` upgrades an in-memory trail in place when a session
    becomes store-bound, replaying the already-recorded outcomes onto
    the WAL so nothing is lost at the save boundary.
    """

    def __init__(self, path: Optional[str] = None):
        self._lock = threading.Lock()
        self._records: List[AuditRecord] = []
        self._wal = None
        if path is not None:
            self._open_wal(path)

    def _open_wal(self, path: str) -> None:
        from pipelinedp_tpu.runtime import journal as journal_lib
        self._wal = journal_lib.JsonlWal(
            path, corrupt_error=AuditCorruptError)
        recovered = [AuditRecord.from_payload(p)
                     for p in self._wal.recovered]
        self._records = recovered + self._records
        if recovered:
            metrics_lib.default_registry().event_inc(
                EVENT_AUDIT_RECOVERIES)

    @property
    def path(self) -> Optional[str]:
        return self._wal.path if self._wal is not None else None

    @property
    def durable(self) -> bool:
        return self._wal is not None

    def bind(self, path: str) -> None:
        """Makes the trail durable at ``path``: recovers whatever a
        previous process committed there, then appends this trail's
        in-memory records after it (re-sequenced). Idempotent for an
        already-durable trail."""
        with self._lock:
            if self._wal is not None:
                return
            pending = self._records
            self._records = []
            self._open_wal(path)
            for record in pending:
                self._append_locked(record)

    def _append_locked(self, record: AuditRecord) -> AuditRecord:
        record = dataclasses.replace(record, seq=len(self._records))
        if self._wal is not None:
            self._wal.append(record.to_payload())
        self._records.append(record)
        return record

    def record(self, *, session: str, tenant: Optional[str], token: str,
               outcome: str, mechanisms, noise_kind: str,
               epsilon: float, delta: float, partitions_kept: int,
               partitions_dropped: int, duration_s: float,
               seed: int, trace_id: str = "") -> AuditRecord:
        """Appends one outcome. The schema is closed — there is no
        free-form field, so nothing data-shaped can ride along — and
        every value passes the shared obs payload gate."""
        if outcome not in OUTCOMES:
            raise ValueError(
                f"unknown audit outcome {outcome!r}; expected one of "
                f"{sorted(OUTCOMES)}")
        mechanisms = tuple(str(m) for m in mechanisms)
        fields = {
            "session": session, "tenant": tenant, "token": str(token),
            "noise_kind": str(noise_kind), "epsilon": float(epsilon),
            "delta": float(delta),
            "partitions_kept": int(partitions_kept),
            "partitions_dropped": int(partitions_dropped),
            "duration_s": float(duration_s), "seed": int(seed),
            "trace_id": str(trace_id),
        }
        for key, value in fields.items():
            metrics_lib.check_safe_value(key, value)
        record = AuditRecord(
            seq=-1, ts_unix=time.time(), outcome=outcome,
            mechanisms=mechanisms, **fields)
        with self._lock:
            record = self._append_locked(record)
        metrics_lib.default_registry().event_inc(EVENT_AUDIT_RECORDS)
        return record

    def records(self, tenant: Optional[str] = None
                ) -> Tuple[AuditRecord, ...]:
        """The trail in commit order (optionally one tenant's slice)."""
        with self._lock:
            if tenant is None:
                return tuple(self._records)
            return tuple(r for r in self._records if r.tenant == tenant)

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def close(self) -> None:
        with self._lock:
            if self._wal is not None:
                self._wal.close()
