"""Budget-enforced "private collection" wrapper.

Role parity with the reference's idiomatic L5 wrappers — private_spark.py's
PrivateRDD (:21-374) and the PrivatePCollection of private_beam.py: wrap a
keyed collection once with its privacy-id extractor and a budget
accountant, then express DP aggregations fluently; every aggregation draws
from the shared budget, and non-DP transforms (map / flat_map) preserve the
privacy-id association.

    private = make_private(rows, budget_accountant, lambda r: r.user_id)
    visits = private.count(pdp.CountParams(...))
    spend = private.sum(pdp.SumParams(...))
    budget_accountant.compute_budgets()

Executes on any host backend (LocalBackend default — Beam/Spark are not
targets of this framework; the columnar TPU engine's high-level API is the
QueryBuilder, pipelinedp_tpu/dataframes.py).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from pipelinedp_tpu import aggregate_params as agg
from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import dp_engine as dp_engine_lib
from pipelinedp_tpu.backends import base as backend_base
from pipelinedp_tpu.backends.local import LocalBackend
from pipelinedp_tpu.data_extractors import DataExtractors


class PrivateCollection:
    """A collection bound to a privacy-id per element and a budget.

    Internal representation: (privacy_id, element) pairs — the same shape
    the reference's PrivateRDD keeps (private_spark.py:33-38). Create via
    make_private.
    """

    def __init__(self, pairs, budget_accountant, backend):
        self._pairs = pairs
        self._budget_accountant = budget_accountant
        self._backend = backend

    # -- non-DP transforms (privacy-id preserving) --------------------------

    def map(self, fn: Callable[[Any], Any]) -> "PrivateCollection":
        pairs = self._backend.map_tuple(self._pairs,
                                        lambda pid, x: (pid, fn(x)),
                                        "PrivateCollection map")
        return PrivateCollection(list(pairs), self._budget_accountant,
                                 self._backend)

    def flat_map(self, fn: Callable[[Any], Any]) -> "PrivateCollection":
        pairs = self._backend.flat_map(
            self._pairs, lambda pair: ((pair[0], y) for y in fn(pair[1])),
            "PrivateCollection flat_map")
        return PrivateCollection(list(pairs), self._budget_accountant,
                                 self._backend)

    # -- DP aggregations ----------------------------------------------------

    def count(self, params: agg.CountParams):
        """DP count per partition; lazy (pk, count) pairs."""
        return self._aggregate(params, agg.Metrics.COUNT, "count")

    def sum(self, params: agg.SumParams):
        return self._aggregate(params, agg.Metrics.SUM, "sum")

    def mean(self, params: agg.MeanParams):
        return self._aggregate(params, agg.Metrics.MEAN, "mean")

    def variance(self, params: agg.VarianceParams):
        return self._aggregate(params, agg.Metrics.VARIANCE, "variance")

    def privacy_id_count(self, params: agg.PrivacyIdCountParams):
        return self._aggregate(params, agg.Metrics.PRIVACY_ID_COUNT,
                               "privacy_id_count")

    def aggregate(self,
                  params: agg.AggregateParams,
                  partition_extractor: Callable[[Any], Any],
                  value_extractor: Optional[Callable[[Any], Any]] = None,
                  public_partitions=None):
        """Full AggregateParams aggregation on the wrapped collection —
        including custom combiners (params.metrics=None,
        params.custom_combiners=[...]) and multi-metric sets.

        Role parity: the reference's private_beam custom-combiner transform
        (PrivateCombineFn / CombinePerKey, private_beam.py:491-649); this
        framework's engine-level CustomCombiner API plugs in directly.
        Returns lazy (pk, metrics) pairs; budget is drawn from the shared
        accountant like every other aggregation on this collection.
        """
        value_free = {agg.Metrics.COUNT, agg.Metrics.PRIVACY_ID_COUNT}
        needs_values = (params.custom_combiners is not None
                        or any(m not in value_free
                               for m in params.metrics or []))
        if value_extractor is None and needs_values:
            # A constant-0 extractor would return plausible noisy zeros for
            # SUM/MEAN/custom metrics — silently wrong DP output.
            raise ValueError(
                "value_extractor is required for value-dependent metrics "
                "or custom combiners")
        engine = dp_engine_lib.DPEngine(self._budget_accountant,
                                        self._backend)
        extractors = DataExtractors(
            privacy_id_extractor=lambda pair: pair[0],
            partition_extractor=lambda pair: partition_extractor(pair[1]),
            value_extractor=((lambda pair: value_extractor(pair[1]))
                             if value_extractor is not None else
                             (lambda pair: 0)))
        return engine.aggregate(self._pairs, params, extractors,
                                public_partitions=public_partitions)

    def select_partitions(self, params: agg.SelectPartitionsParams,
                          partition_extractor: Callable[[Any], Any]):
        """DP-selected partition keys (lazy)."""
        engine = dp_engine_lib.DPEngine(self._budget_accountant,
                                        self._backend)
        extractors = DataExtractors(
            privacy_id_extractor=lambda pair: pair[0],
            partition_extractor=lambda pair: partition_extractor(pair[1]))
        return engine.select_partitions(self._pairs, params, extractors)

    def _aggregate(self, params, metric: agg.Metric, metric_name: str):
        """Translates a high-level params dataclass into one AggregateParams
        run; optional fields (value caps, linf) are read off the dataclass
        where present."""
        aggregate_params = agg.AggregateParams(
            noise_kind=params.noise_kind,
            metrics=[metric],
            max_partitions_contributed=params.max_partitions_contributed,
            max_contributions_per_partition=getattr(
                params, "max_contributions_per_partition", 1),
            min_value=getattr(params, "min_value", None),
            max_value=getattr(params, "max_value", None),
            budget_weight=params.budget_weight,
            contribution_bounds_already_enforced=params.
            contribution_bounds_already_enforced,
            pre_threshold=params.pre_threshold)
        engine = dp_engine_lib.DPEngine(self._budget_accountant,
                                        self._backend)
        value_extractor = getattr(params, "value_extractor", None)
        extractors = DataExtractors(
            privacy_id_extractor=lambda pair: pair[0],
            partition_extractor=lambda pair: params.partition_extractor(
                pair[1]),
            value_extractor=(
                (lambda pair: value_extractor(pair[1]))
                if value_extractor is not None else (lambda pair: 0)))
        result = engine.aggregate(self._pairs, aggregate_params, extractors,
                                  public_partitions=params.public_partitions)
        # (pk, MetricsTuple) -> (pk, scalar), like the reference wrappers
        # (private_spark.py:178-232 maps the namedtuple down to the value).
        return self._backend.map_values(
            result, lambda metrics: getattr(metrics, metric_name),
            f"Extract {metric_name}")


def make_private(
    col,
    budget_accountant: budget_accounting.BudgetAccountant,
    privacy_id_extractor: Callable[[Any], Any],
    backend: Optional[backend_base.PipelineBackend] = None,
) -> PrivateCollection:
    """Binds a collection to privacy ids and a budget (parity:
    private_spark.make_private, :377)."""
    backend = backend or LocalBackend()
    pairs = list(
        backend.map(col, lambda x: (privacy_id_extractor(x), x),
                    "Extract privacy id"))
    return PrivateCollection(pairs, budget_accountant, backend)
