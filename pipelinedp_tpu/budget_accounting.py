"""Lazy privacy-budget accounting.

The contract (parity: pipeline_dp/budget_accounting.py): DP operations call
``request_budget()`` while the computation graph is being built, receiving a
*lazy* ``MechanismSpec`` whose eps/delta (or noise std) are unset; after all
aggregations are registered the user calls ``compute_budgets()``, which
resolves every spec in place. The same spec objects are captured inside
compiled/jitted closures, so resolution must happen before execution — with
JAX this maps to treating eps/delta/sigma as runtime scalars fed into jitted
kernels (see pipelinedp_tpu/ops/noise.py), not trace-time constants.

API parity map: MechanismSpec (:40-111), MechanismSpecInternal (:114),
Budget (:122), BudgetAccountant (:125-270), BudgetAccountantScope (:273-298),
NaiveBudgetAccountant (:301-408), PLDBudgetAccountant (:411-619).
"""

from __future__ import annotations

import abc
import collections
import dataclasses
import logging
import math
import threading
from typing import List, Optional

from pipelinedp_tpu import input_validators
from pipelinedp_tpu import pld as pld_lib
from pipelinedp_tpu.obs import metrics as obs_metrics
from pipelinedp_tpu.obs import trace as obs_trace
from pipelinedp_tpu.aggregate_params import MechanismType

Budget = collections.namedtuple("Budget", ["epsilon", "delta"])


class BudgetAccountantError(Exception):
    """Budget-accounting contract violation: compute_budgets called twice,
    request_budget after finalization, or a committed mechanism spend
    about to be replayed. Typed (instead of the historical bare
    ``Exception``) so recovery/retry layers can distinguish an accounting
    replay — which must abort, per the at-most-once rule in
    RESILIENCE.md — from transient execution failures."""


class BudgetExhaustedError(BudgetAccountantError):
    """A tenant's cross-query budget ledger cannot cover a new charge."""


@dataclasses.dataclass(frozen=True)
class LedgerCharge:
    """One committed cross-query budget charge of a TenantBudgetLedger.

    ``window`` tags charges made on behalf of one continual-release
    window of a live session (serving/live.py); None for ordinary
    (un-windowed) queries."""
    index: int
    epsilon: float
    delta: float
    note: str
    window: Optional[str] = None


class TenantBudgetLedger:
    """Cross-query (epsilon, delta) ledger for one tenant of a long-lived
    serving session (pipelinedp_tpu/serving/, SERVING.md).

    Per-query accounting stays on the per-query ``BudgetAccountant`` (the
    same request_budget / compute_budgets / spend_journal machinery as a
    batch run); this ledger sits ABOVE it and answers the serving-layer
    question the per-query accountant cannot: how much total budget this
    tenant has left across all the queries it has ever run against the
    dataset. ``charge`` is all-or-nothing and thread-safe — a charge that
    would overdraw either epsilon or delta raises
    :class:`BudgetExhaustedError` and leaves the ledger untouched, so one
    tenant exhausting its budget can never consume (or block) another
    tenant's. ``make_accountant`` is the normal entry point: it charges
    the ledger, then hands back a fresh ``NaiveBudgetAccountant`` scoped
    to exactly the charged slice.
    """

    # Relative slack on the exhaustion comparison so a tenant can spend
    # its budget to exactly zero across many queries despite float
    # summation error; anything past it is a real overdraw.
    _REL_SLACK = 1e-9

    # WAL record kinds (runtime.journal record ``kind``; tokens are
    # ("ledger_charge", index, eps, delta, note) — with a sixth
    # ``window`` element when the charge is window-tagged — /
    # ("ledger_refund", index) — index-unique, so the journal's
    # duplicate-token refusal never fires on legitimate ledger traffic).
    _KIND_CHARGE = "ledger_charge"
    _KIND_REFUND = "ledger_refund"

    def __init__(self, tenant_id: str, total_epsilon: float,
                 total_delta: float = 0.0, wal=None,
                 window_epsilon: Optional[float] = None,
                 window_delta: Optional[float] = None):
        input_validators.validate_epsilon_delta(total_epsilon, total_delta,
                                                "TenantBudgetLedger")
        self._tenant_id = str(tenant_id)
        self._total_epsilon = float(total_epsilon)
        self._total_delta = float(total_delta)
        # Budget-over-time caps (serving/live.py, SERVING.md "Live
        # sessions"): a window-tagged charge must also fit under the
        # per-window (epsilon, delta) cap summed over every charge that
        # ever carried the same window tag — so a tenant's exposure per
        # release window stays bounded no matter how many scheduled
        # releases (catch-ups, retries with fresh seeds) touch it.
        if window_epsilon is not None or window_delta is not None:
            input_validators.validate_epsilon_delta(
                window_epsilon if window_epsilon is not None else 1.0,
                window_delta or 0.0, "TenantBudgetLedger window cap")
        self._window_epsilon = (None if window_epsilon is None
                                else float(window_epsilon))
        self._window_delta = (None if window_delta is None
                              else float(window_delta))
        self._lock = threading.Lock()
        self._charges: List[LedgerCharge] = []
        self._refunded: set = set()
        # Durability (serving fleet, SERVING.md "Fleet operation"): a
        # runtime.ReleaseJournal-shaped WAL makes the ledger survive
        # process death — each charge is fsync'd write-ahead (durable
        # BEFORE the query it pays for runs, so a crash errs toward
        # over-counting spend, never under), refunds append their own
        # records, and construction replays the recovered records into
        # the in-memory state.
        self._wal = wal
        if wal is not None:
            self._restore_from_wal()

    def _restore_from_wal(self) -> None:
        for record in self._wal.records:
            if record.kind == self._KIND_CHARGE:
                # Pre-window records carry 5 token elements; windowed
                # ones append the tag — both generations replay.
                _, index, eps, delta, note = record.token[:5]
                window = (str(record.token[5])
                          if len(record.token) > 5 else None)
                self._charges.append(
                    LedgerCharge(index=int(index), epsilon=float(eps),
                                 delta=float(delta), note=str(note),
                                 window=window))
            elif record.kind == self._KIND_REFUND:
                self._refunded.add(int(record.token[1]))

    @property
    def tenant_id(self) -> str:
        return self._tenant_id

    @property
    def total_epsilon(self) -> float:
        return self._total_epsilon

    @property
    def total_delta(self) -> float:
        return self._total_delta

    @property
    def charges(self) -> tuple:
        """Committed charges, in commit order (the tenant-level spend
        journal; each entry's per-mechanism detail lives on that query's
        accountant spend_journal)."""
        with self._lock:
            return tuple(self._charges)

    def _live_charges(self) -> List[LedgerCharge]:
        """Committed, un-refunded charges (lock held by the caller)."""
        return [c for c in self._charges if c.index not in self._refunded]

    @property
    def refunded_indices(self) -> frozenset:
        with self._lock:
            return frozenset(self._refunded)

    @property
    def spent_epsilon(self) -> float:
        with self._lock:
            return math.fsum(c.epsilon for c in self._live_charges())

    @property
    def spent_delta(self) -> float:
        with self._lock:
            return math.fsum(c.delta for c in self._live_charges())

    @property
    def remaining_epsilon(self) -> float:
        return max(0.0, self._total_epsilon - self.spent_epsilon)

    @property
    def remaining_delta(self) -> float:
        return max(0.0, self._total_delta - self.spent_delta)

    @property
    def window_epsilon(self) -> Optional[float]:
        return self._window_epsilon

    @property
    def window_delta(self) -> Optional[float]:
        return self._window_delta

    def window_spent(self, window: str) -> Budget:
        """Live (un-refunded) spend charged against one window tag."""
        with self._lock:
            live = [c for c in self._live_charges()
                    if c.window == str(window)]
            return Budget(math.fsum(c.epsilon for c in live),
                          math.fsum(c.delta for c in live))

    def charge(self, epsilon: float, delta: float = 0.0,
               note: str = "",
               window: Optional[str] = None) -> LedgerCharge:
        """Commits a charge, or raises BudgetExhaustedError untouched."""
        input_validators.validate_epsilon_delta(
            epsilon, delta, "TenantBudgetLedger.charge")
        window = None if window is None else str(window)
        with self._lock:
            live = self._live_charges()
            eps_after = math.fsum([c.epsilon for c in live] + [epsilon])
            delta_after = math.fsum([c.delta for c in live] + [delta])
            slack = 1.0 + self._REL_SLACK
            if (eps_after > self._total_epsilon * slack
                    or delta_after > self._total_delta * slack
                    or (delta_after > 0 and self._total_delta == 0)):
                raise BudgetExhaustedError(
                    f"tenant {self._tenant_id!r}: charge (eps={epsilon}, "
                    f"delta={delta}) would overdraw the ledger "
                    f"(spent eps={eps_after - epsilon:.6g} of "
                    f"{self._total_epsilon:.6g}, "
                    f"delta={delta_after - delta:.6g} of "
                    f"{self._total_delta:.6g})")
            if window is not None and (self._window_epsilon is not None
                                       or self._window_delta is not None):
                win = [c for c in live if c.window == window]
                win_eps = math.fsum([c.epsilon for c in win] + [epsilon])
                win_delta = math.fsum([c.delta for c in win] + [delta])
                cap_eps = (self._window_epsilon
                           if self._window_epsilon is not None
                           else self._total_epsilon)
                cap_delta = (self._window_delta
                             if self._window_delta is not None
                             else self._total_delta)
                if (win_eps > cap_eps * slack
                        or win_delta > cap_delta * slack
                        or (win_delta > 0 and cap_delta == 0)):
                    raise BudgetExhaustedError(
                        f"tenant {self._tenant_id!r}: charge (eps="
                        f"{epsilon}, delta={delta}) would overdraw the "
                        f"per-window cap of window {window!r} (window "
                        f"spent eps={win_eps - epsilon:.6g} of "
                        f"{cap_eps:.6g}, delta={win_delta - delta:.6g} "
                        f"of {cap_delta:.6g})")
            record = LedgerCharge(index=len(self._charges),
                                  epsilon=float(epsilon),
                                  delta=float(delta), note=note,
                                  window=window)
            if self._wal is not None:
                # Write-ahead: the charge is durable before it is
                # acknowledged in memory (and therefore before the query
                # it pays for runs). Window-tagged charges append the
                # tag as a sixth token element (older records stay
                # readable — _restore_from_wal handles both shapes).
                token = (self._KIND_CHARGE, record.index, record.epsilon,
                         record.delta, record.note)
                if window is not None:
                    token = token + (window,)
                self._wal.commit(token, kind=self._KIND_CHARGE)
            self._charges.append(record)
        obs_metrics.default_registry().event_inc("serving/tenant_charges")
        obs_trace.event("tenant_charge", epsilon=float(epsilon),
                        delta=float(delta))
        return record

    def refund(self, charge: LedgerCharge) -> None:
        """Exactly reverses one committed charge.

        The serving layer's failure-isolation contract (SERVING.md):
        a query whose release token never committed drew no randomness
        and published nothing, so its pre-paid slice goes back to the
        tenant — ``spent_epsilon``/``spent_delta`` return exactly to
        their pre-charge values (the refunded charge is excluded from
        the fsum, not approximately subtracted). Refunding twice, or
        refunding a charge this ledger never committed, raises
        ``BudgetAccountantError``. Durable ledgers append the refund to
        the WAL write-ahead, so the refund survives process death too.
        """
        with self._lock:
            if (charge.index >= len(self._charges)
                    or self._charges[charge.index] != charge):
                raise BudgetAccountantError(
                    f"tenant {self._tenant_id!r}: refund of a charge "
                    f"this ledger never committed ({charge!r})")
            if charge.index in self._refunded:
                raise BudgetAccountantError(
                    f"tenant {self._tenant_id!r}: charge #{charge.index} "
                    f"was already refunded")
            if self._wal is not None:
                self._wal.commit((self._KIND_REFUND, charge.index),
                                 kind=self._KIND_REFUND)
            self._refunded.add(charge.index)
        obs_metrics.default_registry().event_inc("serving/tenant_refunds")
        obs_trace.event("tenant_refund", epsilon=charge.epsilon)

    def make_accountant(self, epsilon: float, delta: float = 0.0,
                        note: str = "",
                        **accountant_kwargs) -> "NaiveBudgetAccountant":
        """Charges the ledger and returns a fresh per-query accountant
        over exactly the charged slice. The charge commits BEFORE the
        accountant exists, so a query that later fails has conservatively
        spent its slice (never the reverse — the at-most-once stance of
        RESILIENCE.md applied to tenant budgets)."""
        self.charge(epsilon, delta, note=note)
        return NaiveBudgetAccountant(epsilon, delta, **accountant_kwargs)


@dataclasses.dataclass(frozen=True)
class SpendRecord:
    """One mechanism's committed budget spend (see
    BudgetAccountant.spend_journal). Exactly one record per registered
    mechanism, written when compute_budgets resolves it."""
    index: int
    mechanism_type: MechanismType
    eps: Optional[float]
    delta: Optional[float]
    noise_standard_deviation: Optional[float]
    count: int


@dataclasses.dataclass
class MechanismSpec:
    """A lazily-resolved mechanism budget.

    Created unset by ``request_budget``; ``compute_budgets`` fills in either
    (eps, delta) (naive accounting) or the noise standard deviation (PLD
    accounting). Accessing an unresolved field raises AssertionError.
    """
    mechanism_type: MechanismType
    _noise_standard_deviation: Optional[float] = None
    _eps: Optional[float] = None
    _delta: Optional[float] = None
    _count: int = 1

    @property
    def noise_standard_deviation(self) -> float:
        if self._noise_standard_deviation is None:
            raise AssertionError(
                "Noise standard deviation is not calculated yet.")
        return self._noise_standard_deviation

    @property
    def eps(self) -> float:
        if self._eps is None:
            raise AssertionError("Privacy budget is not calculated yet.")
        return self._eps

    @property
    def delta(self) -> float:
        if self._delta is None:
            raise AssertionError("Privacy budget is not calculated yet.")
        return self._delta

    @property
    def count(self) -> int:
        return self._count

    def set_eps_delta(self, eps: float, delta: Optional[float]) -> None:
        if eps is None:
            raise AssertionError("eps must not be None.")
        if self._eps is not None:
            # At-most-once spend: a resolved spec is a committed budget
            # spend — re-resolving it (e.g. a replayed compute_budgets in
            # a retried run) would silently change what the released
            # noise was calibrated against.
            raise BudgetAccountantError(
                "Mechanism (eps, delta) is already committed; replaying a "
                "committed budget spend is not allowed.")
        self._eps = eps
        self._delta = delta

    def set_noise_standard_deviation(self, stddev: float) -> None:
        if self._noise_standard_deviation is not None:
            raise BudgetAccountantError(
                "Mechanism noise standard deviation is already committed; "
                "replaying a committed budget spend is not allowed.")
        self._noise_standard_deviation = stddev

    def use_delta(self) -> bool:
        return self.mechanism_type != MechanismType.LAPLACE

    @property
    def standard_deviation_is_set(self) -> bool:
        return self._noise_standard_deviation is not None


@dataclasses.dataclass
class MechanismSpecInternal:
    """Sensitivity and weight bookkeeping not exposed via MechanismSpec."""
    sensitivity: float
    weight: float
    mechanism_spec: MechanismSpec


class BudgetAccountantScope:
    """Context manager grouping the mechanisms of one aggregation.

    On exit, the weights of all mechanisms registered inside the scope are
    normalized to sum to the scope's weight, so one aggregation's budget share
    is independent of how many mechanisms it happens to use internally.
    Parity: budget_accounting.py:273-298.
    """

    def __init__(self, accountant: "BudgetAccountant", weight: float):
        self.accountant = accountant
        self.weight = weight
        self.mechanisms: List[MechanismSpecInternal] = []

    def __enter__(self):
        self.accountant._enter_scope(self)
        return self

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.accountant._exit_scope()
        self._normalize_mechanism_weights()

    def _normalize_mechanism_weights(self):
        if not self.mechanisms:
            return
        total = sum(m.weight for m in self.mechanisms)
        factor = self.weight / total
        for m in self.mechanisms:
            m.weight *= factor


class BudgetAccountant(abc.ABC):
    """Base class: mechanism registry, scopes, aggregation restrictions.

    durable_spend_journal: an optional ``runtime.FileReleaseJournal``
    (or any object with its ``commit(token, kind=)`` contract) that
    persists each mechanism's budget spend as it is committed, so the
    at-most-once spend rule survives process death: a re-exec'd pipeline
    that reaches ``compute_budgets`` with the same accountant
    configuration and journal file raises ``BudgetAccountantError``
    instead of silently re-spending the same epsilon (RESILIENCE.md).
    The spend token is the accountant-relative mechanism identity —
    (totals, index, mechanism type, sensitivity, weight, count) — so two
    runs of the same pipeline collide and two genuinely different
    pipelines sharing one journal file do not.
    """

    def __init__(self, total_epsilon: float, total_delta: float,
                 num_aggregations: Optional[int],
                 aggregation_weights: Optional[list],
                 durable_spend_journal=None):
        input_validators.validate_epsilon_delta(total_epsilon, total_delta,
                                                type(self).__name__)
        self._total_epsilon = total_epsilon
        self._total_delta = total_delta
        self._scopes_stack: List[BudgetAccountantScope] = []
        self._mechanisms: List[MechanismSpecInternal] = []
        self._finalized = False
        if num_aggregations is not None and aggregation_weights is not None:
            raise ValueError(
                "'num_aggregations' and 'aggregation_weights' can not be both "
                "set.")
        if num_aggregations is not None:
            input_validators.validate_positive_int(num_aggregations,
                                                   "num_aggregations",
                                                   type(self).__name__)
        self._expected_num_aggregations = num_aggregations
        self._expected_aggregation_weights = aggregation_weights
        self._actual_aggregation_weights: List[float] = []
        self._spend_journal: List[SpendRecord] = []
        self._durable_spend_journal = durable_spend_journal

    @property
    def spend_journal(self) -> tuple:
        """One SpendRecord per registered mechanism, written exactly once
        when compute_budgets resolves it — the auditable record that each
        epsilon/delta spend was committed once and only once."""
        return tuple(self._spend_journal)

    def _commit_spend(self, index: int,
                      mechanism: "MechanismSpecInternal") -> None:
        spec = mechanism.mechanism_spec
        if self._durable_spend_journal is not None:
            # Durable at-most-once: persist the spend identity (fsync'd
            # WAL append) before acknowledging it in the in-memory
            # journal; a re-exec replaying this spend refuses here.
            from pipelinedp_tpu.runtime import journal as journal_lib
            token = ("budget_spend", float(self._total_epsilon),
                     float(self._total_delta), int(index),
                     str(spec.mechanism_type.value),
                     float(mechanism.sensitivity), float(mechanism.weight),
                     int(spec.count))
            try:
                self._durable_spend_journal.commit(token,
                                                   kind="budget_spend")
            except journal_lib.DoubleReleaseError as e:
                raise BudgetAccountantError(
                    f"mechanism {index} ({spec.mechanism_type.value}) "
                    f"already committed its budget spend in the durable "
                    f"spend journal — a re-executed run is about to "
                    f"replay a committed epsilon/delta spend. Use a "
                    f"fresh journal if a second, separately-accounted "
                    f"run is intended.") from e
        self._spend_journal.append(
            SpendRecord(index=index,
                        mechanism_type=spec.mechanism_type,
                        eps=spec._eps,
                        delta=spec._delta,
                        noise_standard_deviation=spec.
                        _noise_standard_deviation,
                        count=spec.count))

    @property
    def total_epsilon(self) -> float:
        return self._total_epsilon

    @property
    def total_delta(self) -> float:
        return self._total_delta

    @abc.abstractmethod
    def request_budget(self,
                       mechanism_type: MechanismType,
                       sensitivity: float = 1,
                       weight: float = 1,
                       count: int = 1,
                       noise_standard_deviation: Optional[float] = None
                       ) -> MechanismSpec:
        """Registers a mechanism; returns its lazy spec."""

    @abc.abstractmethod
    def compute_budgets(self) -> None:
        """Resolves every registered MechanismSpec in place."""

    def scope(self, weight: float) -> BudgetAccountantScope:
        return BudgetAccountantScope(self, weight)

    def _compute_budget_for_aggregation(self, weight: float) -> Optional[Budget]:
        """Naive-composition estimate of one aggregation's (eps, delta) share.

        Mutates internal state (records the aggregation weight); callable only
        from DPEngine API functions. Parity: budget_accounting.py:189-213.
        """
        self._actual_aggregation_weights.append(weight)
        if self._expected_num_aggregations:
            return Budget(self._total_epsilon / self._expected_num_aggregations,
                          self._total_delta / self._expected_num_aggregations)
        if self._expected_aggregation_weights:
            ratio = weight / sum(self._expected_aggregation_weights)
            return Budget(self._total_epsilon * ratio,
                          self._total_delta * ratio)
        return None

    def _check_aggregation_restrictions(self):
        actual = self._actual_aggregation_weights
        if self._expected_num_aggregations:
            if len(actual) != self._expected_num_aggregations:
                raise ValueError(
                    f"'num_aggregations'({self._expected_num_aggregations}) in "
                    f"the constructor of BudgetAccountant is different from "
                    f"the actual number of aggregations in the pipeline"
                    f"({len(actual)}). If 'num_aggregations' is specified, you "
                    f"must have that many aggregations in the pipeline.")
            if any(w != 1 for w in actual):
                raise ValueError(
                    f"Aggregation weights = {actual}. If 'num_aggregations' is "
                    f"set in the constructor of BudgetAccountant, all "
                    f"aggregation weights have to be 1. If you'd like to have "
                    f"different weights use 'aggregation_weights'.")
        if self._expected_aggregation_weights:
            expected = self._expected_aggregation_weights
            if len(actual) != len(expected):
                raise ValueError(
                    f"Length of 'aggregation_weights' in the constructor of "
                    f"BudgetAccountant is {len(expected)} != {len(actual)} the "
                    f"actual number of aggregations.")
            if any(w1 != w2 for w1, w2 in zip(actual, expected)):
                raise ValueError(
                    f"'aggregation_weights' in the constructor ({expected}) is "
                    f"different from actual aggregation weights ({actual}). If "
                    f"'aggregation_weights' is specified, they must be the "
                    f"same.")

    def _register_mechanism(
            self, mechanism: MechanismSpecInternal) -> MechanismSpecInternal:
        self._mechanisms.append(mechanism)
        for scope in self._scopes_stack:
            scope.mechanisms.append(mechanism)
        return mechanism

    def _enter_scope(self, scope: BudgetAccountantScope):
        self._scopes_stack.append(scope)

    def _exit_scope(self):
        self._scopes_stack.pop()

    def _finalize(self):
        if self._finalized:
            raise BudgetAccountantError(
                "compute_budgets can not be called twice.")
        self._finalized = True

    def _pre_compute_checks(self) -> bool:
        """Shared compute_budgets prologue. Returns False if nothing to do."""
        self._check_aggregation_restrictions()
        self._finalize()
        if not self._mechanisms:
            logging.warning("No budgets were requested.")
            return False
        if self._scopes_stack:
            raise BudgetAccountantError(
                "Cannot call compute_budgets from within a budget scope.")
        return True

    def _check_not_finalized(self):
        if self._finalized:
            raise BudgetAccountantError(
                "request_budget() is called after compute_budgets(). Please "
                "ensure that compute_budgets() is called after DP "
                "aggregations.")


class NaiveBudgetAccountant(BudgetAccountant):
    """Splits (eps, delta) across mechanisms proportionally to their weights.

    Naive (basic) composition: eps_i = eps_total * w_i / sum(w), and delta
    likewise but only across delta-consuming mechanisms.
    Parity: budget_accounting.py:301-408.
    """

    def __init__(self,
                 total_epsilon: float,
                 total_delta: float,
                 num_aggregations: Optional[int] = None,
                 aggregation_weights: Optional[list] = None,
                 durable_spend_journal=None):
        super().__init__(total_epsilon, total_delta, num_aggregations,
                         aggregation_weights,
                         durable_spend_journal=durable_spend_journal)

    def request_budget(self,
                       mechanism_type: MechanismType,
                       sensitivity: float = 1,
                       weight: float = 1,
                       count: int = 1,
                       noise_standard_deviation: Optional[float] = None
                       ) -> MechanismSpec:
        self._check_not_finalized()
        if noise_standard_deviation is not None:
            raise NotImplementedError(
                "Noise standard deviation is not supported by "
                "NaiveBudgetAccountant.request_budget.")
        if (mechanism_type == MechanismType.GAUSSIAN and
                self._total_delta == 0):
            raise ValueError(
                "The Gaussian mechanism requires that the pipeline delta is "
                "greater than 0")
        spec = MechanismSpec(mechanism_type=mechanism_type, _count=count)
        self._register_mechanism(
            MechanismSpecInternal(sensitivity=sensitivity,
                                  weight=weight,
                                  mechanism_spec=spec))
        return spec

    def compute_budgets(self) -> None:
        if not self._pre_compute_checks():
            return
        total_w_eps = sum(m.weight * m.mechanism_spec.count
                          for m in self._mechanisms)
        total_w_delta = sum(m.weight * m.mechanism_spec.count
                            for m in self._mechanisms
                            if m.mechanism_spec.use_delta())
        for i, m in enumerate(self._mechanisms):
            eps = (self._total_epsilon * m.weight /
                   total_w_eps) if total_w_eps else 0.0
            delta = 0.0
            if m.mechanism_spec.use_delta() and total_w_delta:
                delta = self._total_delta * m.weight / total_w_delta
            m.mechanism_spec.set_eps_delta(eps, delta)
            self._commit_spend(i, m)


class PLDBudgetAccountant(BudgetAccountant):
    """Tight accounting via Privacy Loss Distribution composition.

    Finds (by binary search) the minimum common noise multiplier such that
    the composition of all mechanisms' PLDs stays within (eps, delta); each
    mechanism then gets noise std = sensitivity * multiplier / weight.
    Parity: budget_accounting.py:411-619 (semantics preserved; the PLD math
    itself lives in pipelinedp_tpu/pld.py instead of dp_accounting).
    """

    def __init__(self,
                 total_epsilon: float,
                 total_delta: float,
                 pld_discretization: float = 1e-4,
                 num_aggregations: Optional[int] = None,
                 aggregation_weights: Optional[list] = None,
                 durable_spend_journal=None):
        super().__init__(total_epsilon, total_delta, num_aggregations,
                         aggregation_weights,
                         durable_spend_journal=durable_spend_journal)
        self.minimum_noise_std: Optional[float] = None
        self._pld_discretization = pld_discretization

    def request_budget(self,
                       mechanism_type: MechanismType,
                       sensitivity: float = 1,
                       weight: float = 1,
                       count: int = 1,
                       noise_standard_deviation: Optional[float] = None
                       ) -> MechanismSpec:
        self._check_not_finalized()
        if count != 1 or noise_standard_deviation is not None:
            raise NotImplementedError(
                "count != 1 / noise std are not supported by "
                "PLDBudgetAccountant.request_budget.")
        if (mechanism_type == MechanismType.GAUSSIAN and
                self._total_delta == 0):
            raise AssertionError(
                "The Gaussian mechanism requires that the pipeline delta is "
                "greater than 0")
        spec = MechanismSpec(mechanism_type=mechanism_type)
        self._register_mechanism(
            MechanismSpecInternal(sensitivity=sensitivity,
                                  weight=weight,
                                  mechanism_spec=spec))
        return spec

    def compute_budgets(self) -> None:
        if not self._pre_compute_checks():
            return
        if self._total_delta == 0:
            sum_weights = sum(m.weight for m in self._mechanisms)
            minimum_noise_std = sum_weights / self._total_epsilon * math.sqrt(2)
        else:
            minimum_noise_std = self._find_minimum_noise_std()
        self.minimum_noise_std = minimum_noise_std
        for i, m in enumerate(self._mechanisms):
            noise_std = m.sensitivity * minimum_noise_std / m.weight
            m.mechanism_spec.set_noise_standard_deviation(noise_std)
            if m.mechanism_spec.mechanism_type == MechanismType.GENERIC:
                eps0 = math.sqrt(2) / noise_std
                delta0 = eps0 / self._total_epsilon * self._total_delta
                m.mechanism_spec.set_eps_delta(eps0, delta0)
            self._commit_spend(i, m)

    def _find_minimum_noise_std(self) -> float:
        threshold = 1e-4
        low, high = 0.0, self._calculate_max_noise_std()
        while low + threshold < high:
            mid = (low + high) / 2
            eps = self._composed_epsilon(mid)
            if eps <= self._total_epsilon:
                high = mid
            else:
                low = mid
        return high

    def _calculate_max_noise_std(self) -> float:
        max_noise_std = 1.0
        while self._composed_epsilon(max_noise_std * 2) > self._total_epsilon:
            max_noise_std *= 2
        return max_noise_std * 2

    def _composed_epsilon(self, noise_standard_deviation: float) -> float:
        return self._compose_distributions(
            noise_standard_deviation).get_epsilon_for_delta(self._total_delta)

    def _compose_distributions(
            self,
            noise_standard_deviation: float) -> pld_lib.PrivacyLossDistribution:
        composed = None
        for m in self._mechanisms:
            mtype = m.mechanism_spec.mechanism_type
            scale = m.sensitivity * noise_standard_deviation / m.weight
            if mtype == MechanismType.LAPLACE:
                # Laplace scale parameter b = std / sqrt(2).
                pld = pld_lib.from_laplace_mechanism(
                    scale / math.sqrt(2),
                    value_discretization_interval=self._pld_discretization)
            elif mtype == MechanismType.GAUSSIAN:
                pld = pld_lib.from_gaussian_mechanism(
                    scale,
                    value_discretization_interval=self._pld_discretization)
            elif mtype == MechanismType.GENERIC:
                eps0 = math.sqrt(2) / noise_standard_deviation
                delta0 = eps0 / self._total_epsilon * self._total_delta
                pld = pld_lib.from_privacy_parameters(
                    eps0,
                    delta0,
                    value_discretization_interval=self._pld_discretization)
            else:
                raise NotImplementedError(
                    f"PLD accounting for mechanism type {mtype} is not "
                    f"supported.")
            composed = pld if composed is None else composed.compose(pld)
        return composed
