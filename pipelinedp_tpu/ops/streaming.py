"""Streaming (chunked) execution of the fused DP-aggregation kernel.

The columnar engine's end-to-end cost on real hardware is dominated by the
host->device transfer of the row columns, not by the kernel (BASELINE.md
headline workload: ~1.2 GB of columns vs a ~15 s fused kernel). This module
turns the single-shot `columnar.bound_and_aggregate` call into a pipeline of
pid-disjoint chunks so that

  * the transfer of chunk k+1 overlaps the kernel of chunk k (the dispatch
    queue is async end to end),
  * each chunk ships byte-packed to the minimal width its id ranges need
    (privacy ids and partition ids rarely need 4 bytes each), and
  * the `valid` mask is never transferred at all (it is `iota < n` on
    device).

Chunks are made pid-disjoint by hash-sharding rows on the privacy id, which
is what makes the result exact rather than approximate: contribution
bounding (the Linf/L0 sampling of `ops/columnar.py`) only looks at rows of
one privacy id at a time, so bounding each shard independently with the full
caps and summing the per-partition accumulators is *identical in
distribution* to bounding the whole dataset at once (same role as the
per-key sampling of the reference, contribution_bounders.py:62-111 — the
key-space split is just a different iteration order). Privacy-id counts add
across shards because a pid lives in exactly one shard.

The same trick is used across devices by `parallel/sharded.py`; here it is
used across *time* on one device.
"""

from __future__ import annotations

import dataclasses
import functools
import logging
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu.ops import columnar, wirecodec
from pipelinedp_tpu import profiler
from pipelinedp_tpu.obs import trace as obs_trace
from pipelinedp_tpu.runtime import driver as driver_lib

# Knuth multiplicative hash so that structured pid spaces (all-even ids,
# contiguous ranges handed out per site, ...) still shard evenly.
_HASH_MULT = np.uint32(2654435761)

# Row count below which the single-shot path wins (chunking only adds
# dispatch latency when the transfer is small).
MIN_STREAM_ROWS = 2_000_000

# Each chunk re-scatters into the full [num_partitions] accumulators, so
# chunk count multiplies the per-partition segment-sum cost (measured ~1 s
# per 4 chunks at the 100M/1M headline shape) while overlap only needs a
# few slabs in flight. 8 balances the two; 4 made the per-chunk shape so
# large that the tunneled-backend compile blew past 9 minutes.
DEFAULT_NUM_CHUNKS = 8

# Transfers are sized by a byte budget, not a fixed count: small inputs take
# 2 slabs (the minimum that overlaps transfer with compute), huge inputs
# take as many as keep a slab near the budget so peak device residency per
# slab stays bounded.
SLAB_BYTE_BUDGET = 192 * 1024 * 1024

# When the host radix sort rides the slab pipeline (exact RLE entry counts
# known at prep time), finer slabs buy overlap: each slab's sort runs while
# the previous slab's transfer + kernels are in flight, so more slabs hide
# more of the single-core sort. Still bounded below (2) and by the bucket
# count; per-transfer fixed costs keep this from going per-row.
PIPELINED_SLAB_BYTE_BUDGET = 48 * 1024 * 1024

# Tuning knobs (validated in loader.env_int; README "Tuning knobs").
# PIPELINEDP_TPU_SLAB_BYTES overrides BOTH slab byte budgets above;
# PIPELINEDP_TPU_PREFETCH_SLABS bounds the background encode lookahead
# (0 disables prefetch, default 1 slab ahead).
SLAB_BYTES_ENV = "PIPELINEDP_TPU_SLAB_BYTES"
PREFETCH_ENV = "PIPELINEDP_TPU_PREFETCH_SLABS"

# Profiler event counters (profiler.count_event / event_count), counted
# per EXECUTED pass by the unified slab driver (runtime/driver.py, where
# the per-chunk counters are canonical):
#   EVENT_PARTITION_SCATTERS — full-[num_partitions] scatter passes whose
#     input is row/group scale (the expensive kind: one per accumulator
#     per chunk on the legacy path);
#   EVENT_COMPACT_MERGE_SCATTERS — [num_partitions] scatters whose input
#     is the compact per-chunk subtotal columns (once per accumulator per
#     MERGE, not per chunk; counted by the merge closures here);
#   EVENT_COMPACT_CHUNKS — chunks that emitted compact group columns.
EVENT_PARTITION_SCATTERS = driver_lib.EVENT_PARTITION_SCATTERS
EVENT_COMPACT_MERGE_SCATTERS = "ops/compact_merge_scatter_passes"
EVENT_COMPACT_CHUNKS = driver_lib.EVENT_COMPACT_CHUNKS

# compact_merge="auto" engages the compact chunk merge at this partition
# count and above. The merge trades the per-chunk full-[num_partitions]
# scatter passes for a per-chunk compaction (group stage + a [G]-sized
# sort) — a win exactly when the [P]-output passes dominate (the 1M-
# partition headline regime: BASELINE.md round-4 measured ~0.74 s per
# full-partition pass on the bench chip), a loss when P is small and the
# partition passes are nearly free (the CPU smoke at 30k partitions
# measured the compaction overhead at ~2x the whole legacy kernel).
COMPACT_MIN_PARTITIONS = 1 << 17


def finish_wire_plan(fmt, segment_sort, max_run, *, num_partitions: int,
                     row_clip_lo, row_clip_hi, linf_cap, l1_mode: bool,
                     with_quantile_mask: bool = False,
                     group_clip_lo=-np.inf, group_clip_hi=np.inf,
                     need_flags=(True, True, True, True)):
    """Finalizes a wire format for the chunk kernels -> (fmt, int_clip,
    sort_stats). Shared by the single-device slab loop and the mesh chunk
    loop (parallel/sharded.py) so both paths resolve the segment_sort
    knob, the int32-accumulation gate, and the per-chunk sort cost
    identically.

    fmt gains tile geometry and (segment_sort "hash", or "auto" under
    the order-exactness gate) the hash-bin grid of the sortless group
    stage (wirecodec.plan_group_binning — the 4-way
    general/packed/tiled/hash dispatch); int_clip is the int32 row-clip
    pair when VALUE_PLANES chunks may accumulate in int32 bit-identically
    (columnar.int_accumulation_plan), else None; sort_stats is the
    columnar.sort_cost dict one executed chunk kernel credits to the
    ops/sort_* counters (plus the replayed row-mask sort when the chunk
    also feeds quantile histograms), its resolved ``kind``, and — when
    the hash grid is planned — the ``demoted`` stats of the per-chunk
    tiled fallback plus the ``grid_cells`` occupancy denominator.

    "auto" picks the hash-binned stage only when it is provably
    bit-identical to the sorted paths: columnar.hash_exact_gate holds
    (every float32 partial sum is an exact integer, so the different
    accumulation order cannot change a bit), the kernel reads no norm
    columns (mean/variance sums are non-integer), no L1 mode, and the
    grid fits every chunk. segment_sort="hash" forces the stage whenever
    its geometry is computable — exact counts, ULP-close sums outside
    the gate, with the tiled path as the parity oracle.

    segment_sort=False is the full round-8 parity oracle: no tiling, the
    value widens to float32 at decode (f32 sort payload), and the group
    stage accumulates in float32 — so the knob A/Bs this PR's whole
    kernel-side change, not just the tile geometry."""
    if segment_sort is False:
        fmt = dataclasses.replace(fmt, tile_rows=0, tile_slack=0,
                                  hash_bins=0, hash_bin_rows=0,
                                  sort_value_narrow=False)
        clip = None
    else:
        clip = None
        exact = False
        if fmt.value.mode == wirecodec.VALUE_PLANES:
            clip = columnar.int_accumulation_plan(
                fmt.value.lo, fmt.value.scale, fmt.value.bits,
                row_clip_lo, row_clip_hi, linf_cap)
            if (clip is not None and not l1_mode
                    and not (need_flags[2] or need_flags[3])):
                exact = columnar.hash_exact_gate(
                    fmt.value.lo, fmt.value.scale, fmt.value.bits,
                    row_clip_lo, row_clip_hi, linf_cap,
                    group_clip_lo, group_clip_hi, fmt.cap)
        fmt = wirecodec.plan_group_binning(fmt, segment_sort, max_run,
                                           exact=exact)
        if clip is not None:
            clip = (np.int32(clip[0]), np.int32(clip[1]))
    vb = 4
    if (fmt.value.mode == wirecodec.VALUE_PLANES
            and fmt.sort_value_narrow):
        vb = 1 if fmt.value.bits <= 8 else (
            2 if fmt.value.bits <= 16 else 4)

    def cost_stats(hash_bins, hash_bin_rows):
        tiles = ((fmt.tile_rows, fmt.tile_slack) if fmt.pid_sorted
                 else (0, 0))
        kw = dict(num_partitions=num_partitions,
                  max_segments=fmt.ucap if fmt.pid_sorted else None,
                  pid_sorted=fmt.pid_sorted, tile_rows=tiles[0],
                  tile_slack=tiles[1], hash_bins=hash_bins,
                  hash_bin_rows=hash_bin_rows, l1_mode=l1_mode)
        cost = columnar.sort_cost(fmt.cap, value_bytes=vb, **kw)
        out = {name: cost[name]
               for name in ("rows", "tiles", "operand_bytes")}
        if with_quantile_mask:
            mask = columnar.sort_cost(fmt.cap, has_value=False,
                                      need_order=True, **kw)
            for name in ("rows", "tiles", "operand_bytes"):
                out[name] += mask[name]
        out["kind"] = cost["kind"]
        return out

    hb = (fmt.hash_bins, fmt.hash_bin_rows) if fmt.pid_sorted else (0, 0)
    stats = cost_stats(*hb)
    if hb[0]:
        stats["demoted"] = cost_stats(0, 0)
        stats["grid_cells"] = hb[0] * hb[1]
    return fmt, clip, stats


def resolved_sampler_desc(fmt, segment_sort, max_run, *,
                          num_partitions: int, row_clip_lo, row_clip_hi,
                          linf_cap, l1_mode: bool, group_clip_lo,
                          group_clip_hi, need_flags) -> str:
    """Opaque identity of the RESOLVED sampler a query config runs —
    the sampler kind plus the finished wire-format geometry (tile/hash
    fields, narrow-payload flag) the chunk kernels compile against.

    Two knob settings that resolve to the same kernel get the same
    descriptor; the same knob string resolving differently (e.g. "auto"
    picking hash under the exactness gate vs tiled outside it) gets a
    different one. The serving bound cache keys on this instead of the
    raw knob string, so flipping ``segment_sort`` between queries can
    never alias a cached accumulator across samplers (the checkpoint
    path gets the same guarantee from ``repr(fmt)`` riding the wire
    fingerprint).
    """
    fmt2, int_clip, stats = finish_wire_plan(
        fmt, segment_sort, max_run, num_partitions=num_partitions,
        row_clip_lo=row_clip_lo, row_clip_hi=row_clip_hi,
        linf_cap=linf_cap, l1_mode=l1_mode, group_clip_lo=group_clip_lo,
        group_clip_hi=group_clip_hi, need_flags=tuple(need_flags))
    return f"{stats['kind']}:{fmt2!r}"


def _count_sort_stats(stats) -> None:
    """Credits one executed chunk kernel's sort cost to the ops/sort_*
    profiler counters (columnar.sort_cost model — the jitted kernels
    cannot count per execution, so the drivers do it per dispatched
    chunk)."""
    profiler.count_event(columnar.EVENT_SORT_ROWS, int(stats["rows"]))
    profiler.count_event(columnar.EVENT_SORT_TILES, int(stats["tiles"]))
    profiler.count_event(columnar.EVENT_SORT_BYTES,
                         int(stats["operand_bytes"]))


def _compact_enabled(compact_merge, num_partitions: int) -> bool:
    """Resolves the compact_merge knob (True / False / "auto")."""
    if compact_merge is True:
        return True
    if compact_merge == "auto":
        return num_partitions >= COMPACT_MIN_PARTITIONS
    return False


def prefetch_depth() -> int:
    """Validated PIPELINEDP_TPU_PREFETCH_SLABS (0..4, default 1): how many
    slab windows the background encoder may run ahead of the transfer."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(PREFETCH_ENV, 1, 0, 4)


def slab_byte_budget(pipelined: bool) -> int:
    """The slab byte budget, honoring the PIPELINEDP_TPU_SLAB_BYTES
    override (1 MiB .. 4 GiB)."""
    from pipelinedp_tpu.native import loader
    default = PIPELINED_SLAB_BYTE_BUDGET if pipelined else SLAB_BYTE_BUDGET
    return loader.env_int(SLAB_BYTES_ENV, default, 1 << 20, 1 << 32)


def _num_chunks(n_rows: int) -> int:
    # ~8 MB of packed bytes per chunk minimum, capped at the default.
    return int(min(DEFAULT_NUM_CHUNKS, max(2, n_rows // 1_000_000)))


def _num_transfers(total_bytes: int, k: int,
                   budget: int = SLAB_BYTE_BUDGET) -> int:
    want = -(-total_bytes // budget)  # ceil
    return int(max(2, min(k, want)))


# Encoding choice: the wire codec was measured faster end-to-end than the
# legacy fixed-width packing at BOTH link extremes on the bench host (slow
# 35 MB/s link: 3x fewer bytes dominate; fast 1.4 GB/s link: the codec's
# contiguous bit-plane decode beats the legacy layout's strided byte
# unpack on device, 29.4 s vs 35.9 s at the 100M headline shape) — so
# "auto" is simply the codec. "bytes" stays available explicitly.


def _int_bytes(max_value: int) -> int:
    """Bytes needed to carry values in [0, max_value]."""
    for nbytes in (1, 2, 3, 4):
        if max_value < (1 << (8 * nbytes)):
            return nbytes
    raise ValueError(f"{max_value} does not fit in 4 bytes")


def _pack_ints(out: np.ndarray, col: np.ndarray, offset: int,
               nbytes: int) -> None:
    """Little-endian byte-split of an int column into out[:, offset:...]."""
    col = col.astype(np.uint32, copy=False)
    for b in range(nbytes):
        out[:, offset + b] = (col >> (8 * b)).astype(np.uint8)


def _unpack_ints(buf: jnp.ndarray, offset: int, nbytes: int) -> jnp.ndarray:
    """Device-side inverse of _pack_ints -> int32."""
    acc = buf[:, offset].astype(jnp.int32)
    for b in range(1, nbytes):
        acc = acc | (buf[:, offset + b].astype(jnp.int32) << (8 * b))
    return acc


def _unpack_value(buf: jnp.ndarray, offset: int,
                  is_f16: bool) -> jnp.ndarray:
    if is_f16:
        u16 = (buf[:, offset].astype(jnp.uint16) |
               (buf[:, offset + 1].astype(jnp.uint16) << 8))
        return jax.lax.bitcast_convert_type(u16, jnp.float16).astype(
            jnp.float32)
    u32 = (buf[:, offset].astype(jnp.uint32) |
           (buf[:, offset + 1].astype(jnp.uint32) << 8) |
           (buf[:, offset + 2].astype(jnp.uint32) << 16) |
           (buf[:, offset + 3].astype(jnp.uint32) << 24))
    return jax.lax.bitcast_convert_type(u32, jnp.float32)


@functools.partial(
    jax.jit,
    static_argnames=("num_partitions", "bytes_pid", "bytes_pk", "value_f16",
                     "need_flags", "has_group_clip"),
    donate_argnums=(3,))
def _chunk_step(key, buf, n_valid, accs, linf_cap, l0_cap, row_clip_lo,
                row_clip_hi, middle, group_clip_lo, group_clip_hi,
                l1_cap=None, *,
                num_partitions: int, bytes_pid: int, bytes_pk: int,
                value_f16: bool, need_flags=(True, True, True, True),
                has_group_clip: bool = True):
    """Unpack one byte-packed chunk, bound+aggregate it, add into accs.

    Chunks are pid-disjoint, so the optional L1 (max_contributions) sample
    inside the kernel is exact per chunk.
    """
    pid = _unpack_ints(buf, 0, bytes_pid)
    pk = _unpack_ints(buf, bytes_pid, bytes_pk)
    value = _unpack_value(buf, bytes_pid + bytes_pk, value_f16)
    valid = jnp.arange(buf.shape[0], dtype=jnp.int32) < n_valid
    chunk_accs = columnar.bound_and_aggregate(
        key, pid, pk, value, valid,
        num_partitions=num_partitions,
        linf_cap=linf_cap,
        l0_cap=l0_cap,
        row_clip_lo=row_clip_lo,
        row_clip_hi=row_clip_hi,
        middle=middle,
        group_clip_lo=group_clip_lo,
        group_clip_hi=group_clip_hi,
        l1_cap=l1_cap,
        need_count=need_flags[0],
        need_sum=need_flags[1],
        need_norm=need_flags[2],
        need_norm_sq=need_flags[3],
        has_group_clip=has_group_clip)
    return columnar.PartitionAccumulators(
        *(a + c for a, c in zip(accs, chunk_accs)))


def _decode_for_kernel(row, n_valid, n_uniq, fmt):
    """Shared decode of the wire chunk steps: VALUE_PLANES chunks keep the
    narrow int32 plane index through the kernel's sort (widened after it
    with the identical reconstruction expression — bit-for-bit the same
    released values); other modes decode to float32 as before. Returns
    (pid, pk, value, valid, value_kwargs-for-the-kernel)."""
    value_as_index = (fmt.value.mode == wirecodec.VALUE_PLANES
                      and fmt.sort_value_narrow)
    pid, pk, value, valid = wirecodec.decode_bucket(
        row, n_valid, n_uniq, fmt, value_as_index=value_as_index)
    if value is None:
        value = jnp.zeros((fmt.cap,), dtype=jnp.float32)
        value_as_index = False
    kwargs = dict(
        tile_rows=fmt.tile_rows if fmt.pid_sorted else 0,
        tile_slack=fmt.tile_slack if fmt.pid_sorted else 0,
        hash_bins=fmt.hash_bins if fmt.pid_sorted else 0,
        hash_bin_rows=fmt.hash_bin_rows if fmt.pid_sorted else 0,
        value_is_index=value_as_index,
        value_lo=np.float32(fmt.value.lo),
        value_scale=np.float32(fmt.value.scale),
        value_sort_bits=fmt.value.bits if value_as_index else 0)
    return pid, pk, value, valid, kwargs


@functools.partial(
    jax.jit,
    static_argnames=("num_partitions", "fmt", "need_flags",
                     "has_group_clip", "int_accumulate"),
    donate_argnums=(4,))
def _chunk_step_rle(key, row, n_valid, n_uniq, accs, linf_cap, l0_cap,
                    row_clip_lo, row_clip_hi, middle, group_clip_lo,
                    group_clip_hi, l1_cap=None, int_clip=None, *,
                    num_partitions: int, fmt: wirecodec.WireFormat,
                    need_flags=(True, True, True, True),
                    has_group_clip: bool = True,
                    int_accumulate: bool = False):
    """Decode one wire-codec bucket, bound+aggregate it, add into accs.

    Buckets are pid-disjoint, so bounding each independently with the full
    caps and summing accumulators is exact (see module docstring). In
    PID_RLE mode the decoded rows are pid-sorted by construction, so the
    kernel runs its cheaper presorted sampler — tiled into bounded-span
    segment-local sorts when fmt carries tile geometry (fmt.pid_sorted
    plumbs the invariant; fmt.ucap bounds the distinct pids per bucket).
    """
    pid, pk, value, valid, vkw = _decode_for_kernel(row, n_valid, n_uniq,
                                                    fmt)
    chunk_accs = columnar.bound_and_aggregate(
        key, pid, pk, value, valid,
        num_partitions=num_partitions,
        linf_cap=linf_cap,
        l0_cap=l0_cap,
        row_clip_lo=row_clip_lo,
        row_clip_hi=row_clip_hi,
        middle=middle,
        group_clip_lo=group_clip_lo,
        group_clip_hi=group_clip_hi,
        l1_cap=l1_cap,
        need_count=need_flags[0],
        need_sum=need_flags[1],
        need_norm=need_flags[2],
        need_norm_sq=need_flags[3],
        has_group_clip=has_group_clip,
        pid_sorted=fmt.pid_sorted,
        max_segments=fmt.ucap if fmt.pid_sorted else None,
        int_accumulate=int_accumulate,
        int_clip_lo=int_clip[0] if int_clip is not None else None,
        int_clip_hi=int_clip[1] if int_clip is not None else None,
        **vkw)
    return columnar.PartitionAccumulators(
        *(a + c for a, c in zip(accs, chunk_accs)))


@functools.partial(
    jax.jit,
    static_argnames=("num_partitions", "fmt", "max_groups", "need_flags",
                     "has_group_clip", "int_accumulate"))
def _chunk_step_rle_compact(key, row, n_valid, n_uniq, linf_cap, l0_cap,
                            row_clip_lo, row_clip_hi, middle, group_clip_lo,
                            group_clip_hi, l1_cap=None, int_clip=None, *,
                            num_partitions: int, fmt: wirecodec.WireFormat,
                            max_groups: int,
                            need_flags=(True, True, True, True),
                            has_group_clip: bool = True,
                            int_accumulate: bool = False):
    """_chunk_step_rle that emits compact per-group columns instead of
    scattering into the full [num_partitions] accumulators.

    Same decode, same sampler (identical statics and key), same group
    accumulators — but the chunk's contribution leaves the kernel as at
    most ``max_groups`` (pk, subtotal) pairs per accumulator
    (columnar.CompactGroups); ONE final merge scatters every chunk
    (columnar.merge_compact_chunks). Nothing is donated, so a failed
    dispatch can never poison the running state.
    """
    pid, pk, value, valid, vkw = _decode_for_kernel(row, n_valid, n_uniq,
                                                    fmt)
    return columnar.bound_and_aggregate_compact(
        key, pid, pk, value, valid,
        num_partitions=num_partitions,
        max_groups=max_groups,
        linf_cap=linf_cap,
        l0_cap=l0_cap,
        row_clip_lo=row_clip_lo,
        row_clip_hi=row_clip_hi,
        middle=middle,
        group_clip_lo=group_clip_lo,
        group_clip_hi=group_clip_hi,
        l1_cap=l1_cap,
        need_count=need_flags[0],
        need_sum=need_flags[1],
        need_norm=need_flags[2],
        need_norm_sq=need_flags[3],
        has_group_clip=has_group_clip,
        pid_sorted=fmt.pid_sorted,
        max_segments=fmt.ucap if fmt.pid_sorted else None,
        int_accumulate=int_accumulate,
        int_clip_lo=int_clip[0] if int_clip is not None else None,
        int_clip_hi=int_clip[1] if int_clip is not None else None,
        **vkw)


def _merge_pending(accs, pending, num_partitions, need_flags):
    """Folds a list of CompactGroups into the dense accumulators with one
    scatter per accumulator column; validates the static group bound."""
    max_kept = int(jax.device_get(
        jnp.max(jnp.stack([p.n_kept for p in pending]))))
    max_groups = pending[0].pk.shape[0]
    if max_kept > max_groups:
        raise RuntimeError(
            f"compact merge: a chunk kept {max_kept} groups, above the "
            f"static bound {max_groups} — the pid-sorted wire contract "
            f"was violated; refusing to release truncated accumulators")
    profiler.count_event(EVENT_COMPACT_MERGE_SCATTERS,
                         1 + sum(bool(f) for f in need_flags))
    stacked = [jnp.stack([p[i] for p in pending]) for i in range(6)]
    return columnar.merge_compact_chunks(
        accs, *stacked, num_partitions=num_partitions,
        need_flags=tuple(need_flags))


def _credit_chunk_stats(stats, n_valid) -> None:
    """Per-executed-chunk counter crediting: the sort-cost model plus
    the hash-bin pass/occupancy counters (the drivers' host-side twin
    of the jitted kernels, which cannot count per execution)."""
    if stats is None:
        return
    _count_sort_stats(stats)
    if stats.get("kind") == "hash":
        profiler.count_event(columnar.EVENT_HASH_PASSES)
        cells = max(int(stats.get("grid_cells", 0)), 1)
        profiler.count_event(columnar.EVENT_HASH_OCCUPANCY,
                             min(100, (100 * int(n_valid)) // cells))


def _build_chunk_steps(key, fmt, int_clip, *, num_partitions, linf_cap,
                       l0_cap, row_clip_lo, row_clip_hi, middle,
                       group_clip_lo, group_clip_hi, l1_cap, need_flags,
                       has_group_clip, quantile_spec, compact_merge,
                       sort_stats=None):
    """(step_chunk, compact_step, merge_fn) for one finished wire format.

    The single place the per-chunk kernel closures are built, shared by
    the cold streaming path (stream_bound_and_aggregate) and the
    resident-wire replay path (replay_resident_wire), so both fold the
    identical kernels under the identical ``fold_in(key, c)`` schedule —
    the warm-path bit-parity contract of SERVING.md rests on this.

    When fmt plans the hash-binned group stage, the per-chunk demotion
    lives here: a chunk whose RLE entry count exceeds the static bin
    count runs the tiled kernel instead (a second compile of the same
    step with the hash fields zeroed) — decided on HOST data that is
    part of the wire fingerprint, so cold runs, warm replays and
    resumes demote identically and released bits never depend on it.

    sort_stats (finish_wire_plan) makes the steps credit the executed
    sort-cost model and hash-bin counters per chunk — per-chunk because
    demoted chunks must credit the fallback cost, which the driver's
    single on_chunk hook cannot distinguish.

    compact_step/merge_fn are None when the compact merge does not apply
    (knob off, too few partitions, PID_PLANES wire — no per-chunk pid
    bound — or quantile histograms, which stay on the legacy fold).
    """
    hash_on = fmt.hash_bins > 0 and fmt.pid_sorted
    fmt_demoted = (dataclasses.replace(fmt, hash_bins=0, hash_bin_rows=0)
                   if hash_on else fmt)

    def chunk_plan(n_uniq_c, n_valid):
        if hash_on and n_uniq_c > fmt.hash_bins:
            profiler.count_event(columnar.EVENT_HASH_DEMOTIONS)
            demoted = (sort_stats or {}).get("demoted")
            _credit_chunk_stats(demoted, n_valid)
            return fmt_demoted
        _credit_chunk_stats(sort_stats, n_valid)
        return fmt

    def step_chunk(c, bucket_row, accs, qhist, n_valid, n_uniq_c):
        use_fmt = chunk_plan(n_uniq_c, n_valid)
        if quantile_spec is not None:
            return _chunk_step_rle_quantile(
                jax.random.fold_in(key, c), bucket_row, n_valid,
                n_uniq_c, accs, qhist, linf_cap, l0_cap, row_clip_lo,
                row_clip_hi, middle, group_clip_lo, group_clip_hi,
                quantile_spec[1], quantile_spec[2], l1_cap,
                num_partitions=num_partitions, fmt=use_fmt,
                num_leaves=quantile_spec[0],
                need_flags=tuple(need_flags),
                has_group_clip=has_group_clip)
        return _chunk_step_rle(
            jax.random.fold_in(key, c), bucket_row, n_valid, n_uniq_c,
            accs, linf_cap, l0_cap, row_clip_lo, row_clip_hi, middle,
            group_clip_lo, group_clip_hi, l1_cap, int_clip,
            num_partitions=num_partitions, fmt=use_fmt,
            need_flags=tuple(need_flags),
            has_group_clip=has_group_clip,
            int_accumulate=int_clip is not None), qhist

    compact_step = merge_fn = None
    if (_compact_enabled(compact_merge, num_partitions)
            and quantile_spec is None
            and fmt.pid_mode == wirecodec.PID_RLE):
        max_groups = columnar.compact_group_bound(fmt.cap, fmt.ucap, l0_cap)
        if max_groups is not None:

            def compact_step(c, bucket_row, n_valid, n_uniq_c):
                use_fmt = chunk_plan(n_uniq_c, n_valid)
                return _chunk_step_rle_compact(
                    jax.random.fold_in(key, c), bucket_row, n_valid,
                    n_uniq_c, linf_cap, l0_cap, row_clip_lo, row_clip_hi,
                    middle, group_clip_lo, group_clip_hi, l1_cap, int_clip,
                    num_partitions=num_partitions, fmt=use_fmt,
                    max_groups=max_groups, need_flags=tuple(need_flags),
                    has_group_clip=has_group_clip,
                    int_accumulate=int_clip is not None)

            def merge_fn(accs, pending):
                return _merge_pending(accs, pending, num_partitions,
                                      tuple(need_flags))

    return step_chunk, compact_step, merge_fn


@functools.partial(
    jax.jit,
    static_argnames=("num_partitions", "fmt", "num_leaves", "need_flags",
                     "has_group_clip"),
    donate_argnums=(4, 5))
def _chunk_step_rle_quantile(key, row, n_valid, n_uniq, accs, qhist,
                             linf_cap, l0_cap, row_clip_lo, row_clip_hi,
                             middle, group_clip_lo, group_clip_hi,
                             q_lower, q_upper, l1_cap=None, *,
                             num_partitions: int, fmt: wirecodec.WireFormat,
                             num_leaves: int,
                             need_flags=(True, True, True, True),
                             has_group_clip: bool = True):
    """_chunk_step_rle plus the quantile-tree leaf histogram.

    Leaf counts are additive across pid-disjoint chunks, and the row keep
    mask derives from the same per-chunk PRNG key as the accumulator
    kernel, so the histogrammed contributions are exactly the rows the
    aggregation kept (columnar.bound_row_mask shares
    _sample_rows_and_groups with bound_and_aggregate).
    """
    from pipelinedp_tpu.ops import quantiles as quantile_ops
    pid, pk, value, valid, vkw = _decode_for_kernel(row, n_valid, n_uniq,
                                                    fmt)
    chunk_accs = columnar.bound_and_aggregate(
        key, pid, pk, value, valid,
        num_partitions=num_partitions,
        linf_cap=linf_cap,
        l0_cap=l0_cap,
        row_clip_lo=row_clip_lo,
        row_clip_hi=row_clip_hi,
        middle=middle,
        group_clip_lo=group_clip_lo,
        group_clip_hi=group_clip_hi,
        l1_cap=l1_cap,
        need_count=need_flags[0],
        need_sum=need_flags[1],
        need_norm=need_flags[2],
        need_norm_sq=need_flags[3],
        has_group_clip=has_group_clip,
        pid_sorted=fmt.pid_sorted,
        max_segments=fmt.ucap if fmt.pid_sorted else None,
        **vkw)
    # Same pid_sorted/tile/hash statics as the aggregation kernel, so the
    # replayed sampling decisions stay identical (shared packed-key sort
    # or hash-binned selection).
    row_keep = columnar.bound_row_mask(
        key, pid, pk, valid, linf_cap, l0_cap, l1_cap=l1_cap,
        pid_sorted=fmt.pid_sorted,
        max_segments=fmt.ucap if fmt.pid_sorted else None,
        num_partitions=num_partitions,
        tile_rows=vkw["tile_rows"], tile_slack=vkw["tile_slack"],
        hash_bins=vkw["hash_bins"], hash_bin_rows=vkw["hash_bin_rows"])
    if vkw["value_is_index"]:
        # The leaf histogram buckets float values; reconstruct with the
        # decode expression (bit-exact twin of the non-index decode).
        value = (jnp.float32(fmt.value.lo)
                 + value.astype(jnp.float32) * jnp.float32(fmt.value.scale))
    chunk_hist = quantile_ops.leaf_histograms(pk, value, row_keep,
                                              num_partitions=num_partitions,
                                              num_leaves=num_leaves,
                                              lower=q_lower, upper=q_upper)
    return (columnar.PartitionAccumulators(
        *(a + c for a, c in zip(accs, chunk_accs))), qhist + chunk_hist)


def stream_bound_and_aggregate(
    key: jax.Array,
    pid: np.ndarray,
    pk: np.ndarray,
    value: Optional[np.ndarray],
    *,
    num_partitions: int,
    linf_cap,
    l0_cap,
    row_clip_lo,
    row_clip_hi,
    middle,
    group_clip_lo,
    group_clip_hi,
    l1_cap=None,
    n_chunks: Optional[int] = None,
    value_transfer_dtype: Optional[np.dtype] = None,
    need_flags=(True, True, True, True),
    has_group_clip: bool = True,
    n_transfers: Optional[int] = None,
    transfer_encoding: str = "auto",
    quantile_spec: Optional[Tuple[int, float, float]] = None,
    resilience=None,
    resume_from=None,
    compact_merge="auto",
    segment_sort="auto",
) -> columnar.PartitionAccumulators:
    """Chunked, transfer-overlapped twin of columnar.bound_and_aggregate.

    pid: integer numpy array, any range (NOT required to be dense ids — the
      kernel only compares privacy ids for equality, so raw integer ids are
      shipped as-is after a shift-to-zero; this is what lets the engine skip
      privacy-id factorization entirely on the hot path).
    pk: dense int32 ids in [0, num_partitions).
    value: float array or None (COUNT-style).
    value_transfer_dtype: np.float16 to halve the value transfer bytes
      (opt-in: the f16 rounding of individual contributions is far below
      any DP noise scale, but it is a lossy ingest step so the caller must
      ask for it).
    transfer_encoding: "auto" (the lossless RLE/bit-plane wire codec,
      ops/wirecodec.py) or "bytes" (the legacy fixed-width byte packing).
      Both are exact; "auto" ships a fraction of the bytes.
    quantile_spec: optional (num_leaves, lower, upper) — also accumulate
      the [num_partitions, num_leaves] quantile-tree leaf histogram across
      chunks (PERCENTILE metrics on the streamed path; wire-codec
      encoding only). When set the return value is (accs, hist).
    resilience: optional runtime.StreamResilience — retry/degradation
      policy, fault injection and checkpointing for the slab loop (see
      pipelinedp_tpu/runtime/ and RESILIENCE.md). None = fail-fast, the
      historical behavior.
    resume_from: optional runtime.StreamCheckpoint to resume the slab
      loop from (fingerprint-validated; overrides any checkpoint found in
      resilience.checkpoint_policy.store). A resumed run is bit-identical
      to an uninterrupted one — per-chunk keys are fold_in(key, c) and
      accumulators are mergeable.
    compact_merge: each chunk emits compact per-group subtotal columns
      (bounded by the wire format's per-chunk pid capacity * l0_cap) and
      ONE final set of [num_partitions] scatters merges all chunks,
      instead of every chunk re-paying the full partition scatters.
      Applies to the pid-sorted wire-codec path without quantile_spec.
      "auto" (default) engages at >= COMPACT_MIN_PARTITIONS partitions —
      the regime where the [P]-output passes dominate; True forces it,
      False restores the legacy per-chunk scatters (the parity oracle).
      With group-level sum clipping active the released accumulators are
      bit-identical to the legacy path; without it they agree in exact
      arithmetic (float32 association may differ in the last ulp).
    segment_sort: the bucketed segment-local sort inside the chunk kernel
      (columnar tiled sampler; wirecodec.plan_segment_tiling), plus the
      narrow-dtype sort payload and int32 group accumulation that ride
      with it. "auto" (default) engages on the pid-sorted wire when the
      tile heuristic wins; True forces tiling whenever geometry permits;
      False restores the full round-8 kernel (global packed sort, f32
      payload, float accumulation — the parity oracle). BIT-identical
      released values in every mode — the knob is pure kernel geometry.

    Returns per-partition accumulators on device, identical in distribution
    to the single-shot kernel.
    """
    n = len(pid)
    if resume_from is not None:
        if resilience is None:
            from pipelinedp_tpu import runtime as runtime_lib
            resilience = runtime_lib.StreamResilience()
        resilience = dataclasses.replace(resilience, resume_from=resume_from)
    if quantile_spec is not None and transfer_encoding == "bytes":
        raise ValueError(
            "quantile_spec requires the wire-codec transfer encoding")
    if n == 0:
        zeros = jnp.zeros((num_partitions,), dtype=jnp.float32)
        accs0 = columnar.PartitionAccumulators(zeros, zeros, zeros, zeros,
                                               zeros)
        if quantile_spec is not None:
            return accs0, jnp.zeros((num_partitions, quantile_spec[0]),
                                    dtype=jnp.float32)
        return accs0
    k = n_chunks or _num_chunks(n)
    pid = np.asarray(pid)

    if transfer_encoding != "bytes":
        # Shared prologue with the mesh streaming path (pid-span
        # validation, width/bit planning, value plan, pid wire mode,
        # native encoder).
        with profiler.stage("dp/wire_prep"):
            enc, info = wirecodec.make_encoder(
                pid, pk, value, num_partitions=num_partitions, k=k,
                value_transfer_dtype=value_transfer_dtype)

        # `fmt`, `int_clip` and `sort_stats` are late-bound from the
        # enclosing scope: both encode branches below run
        # _finish_wire_plan before the slab loop makes the first call.
        def _finish_wire_plan(wire_fmt):
            return finish_wire_plan(
                wire_fmt, segment_sort, info.max_run,
                num_partitions=num_partitions, row_clip_lo=row_clip_lo,
                row_clip_hi=row_clip_hi, linf_cap=linf_cap,
                l1_mode=l1_cap is not None,
                with_quantile_mask=quantile_spec is not None,
                group_clip_lo=group_clip_lo, group_clip_hi=group_clip_hi,
                need_flags=tuple(need_flags))

        def build_steps(fmt, int_clip, sort_stats):
            return _build_chunk_steps(
                key, fmt, int_clip, num_partitions=num_partitions,
                linf_cap=linf_cap, l0_cap=l0_cap, row_clip_lo=row_clip_lo,
                row_clip_hi=row_clip_hi, middle=middle,
                group_clip_lo=group_clip_lo, group_clip_hi=group_clip_hi,
                l1_cap=l1_cap, need_flags=need_flags,
                has_group_clip=has_group_clip, quantile_spec=quantile_spec,
                compact_merge=compact_merge, sort_stats=sort_stats)

        scatter_passes = 1 + sum(bool(f) for f in need_flags)

        if enc is not None:
            # Pipelined encode. Every slab shares ONE wire format (one
            # XLA compile for the chunk kernel). Three schedules, best
            # first:
            #   * PID_PLANES: no host sort at all — emit ships arrival-
            #     order pid planes, the device sorts (it sorts anyway).
            #   * PID_RLE with prep-time entry counts: the format is known
            #     before any sorting, so the per-bucket radix sort runs
            #     INSIDE the slab loop — slab s+1 sorts on the host CPU
            #     while slab s's device_put + kernels are in flight. This
            #     takes the single-core sort off the e2e critical path.
            #   * PID_RLE without entry counts (huge pid span): upfront
            #     sort to learn the RLE entry max, as before.
            with enc:
                counts = enc.counts
                cap = wirecodec._round8(int(counts.max()))
                pipelined_sort = (info.pid_mode == wirecodec.PID_RLE
                                  and enc.entry_counts is not None)
                if info.pid_mode == wirecodec.PID_PLANES:
                    fmt = wirecodec.WireFormat(
                        bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
                        cap=cap, ucap=8, value=info.plan,
                        pid_mode=wirecodec.PID_PLANES,
                        bits_pid=info.bits_pid)
                    n_uniq = np.zeros(k, dtype=np.int64)
                elif pipelined_sort:
                    n_uniq = enc.entry_counts
                    fmt = wirecodec.WireFormat(
                        bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
                        cap=cap,
                        ucap=wirecodec.round_ucap(int(n_uniq.max())),
                        value=info.plan)
                else:
                    # Distinct stage name: an upfront sort serializes
                    # ahead of the pipeline (bench reports it as
                    # non-overlapped host encode).
                    with profiler.stage("dp/wire_sort_upfront"):
                        n_uniq = enc.sort_range(0, k)
                    fmt = wirecodec.WireFormat(
                        bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
                        cap=cap,
                        ucap=wirecodec.round_ucap(int(n_uniq.max())),
                        value=info.plan)
                fmt, int_clip, sort_stats = _finish_wire_plan(fmt)
                budget = slab_byte_budget(pipelined_sort)
                n_t = n_transfers or _num_transfers(fmt.width * k, k,
                                                    budget)

                def prepare_slab(s0, s1):
                    if pipelined_sort:
                        with profiler.stage("dp/wire_sort"):
                            sorted_uniq = enc.sort_range(s0, s1)
                        if not np.array_equal(sorted_uniq, n_uniq[s0:s1]):
                            # Analytic prep counts must equal the
                            # post-sort RLE counts; a mismatch means
                            # corrupted input (e.g. mutated between
                            # prep and sort) and must not decode.
                            raise RuntimeError(
                                "wirecodec: prep-time RLE entry "
                                "counts disagree with the sorted "
                                "buckets")
                    return enc.emit_range(s0, s1, fmt)

                step_chunk, compact_step, merge_fn = build_steps(
                    fmt, int_clip, sort_stats)
                accs, qhist = _drive_slab_windows(
                    key, k, counts, n_uniq, fmt, prepare_slab, step_chunk,
                    n_t, num_partitions, quantile_spec, resilience,
                    lambda: _input_digest(pid, pk, value),
                    compact_step=compact_step, merge_fn=merge_fn,
                    scatter_passes=scatter_passes)
        else:
            with profiler.stage("dp/wire_encode"):
                slab, counts, n_uniq, fmt = wirecodec.encode_buckets_numpy(
                    pid, pk, value, pid_lo=info.pid_lo, k=k,
                    bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
                    plan=info.plan, pid_mode=info.pid_mode,
                    bits_pid=info.bits_pid)
            fmt, int_clip, sort_stats = _finish_wire_plan(fmt)
            n_t = n_transfers or _num_transfers(slab.nbytes, k)
            step_chunk, compact_step, merge_fn = build_steps(
                fmt, int_clip, sort_stats)
            accs, qhist = _drive_slab_windows(
                key, k, counts, n_uniq, fmt,
                lambda s0, s1: slab[s0:s1], step_chunk,
                n_t, num_partitions, quantile_spec, resilience,
                lambda: _input_digest(pid, pk, value),
                compact_step=compact_step, merge_fn=merge_fn,
                scatter_passes=scatter_passes)
        if quantile_spec is not None:
            return accs, qhist
        return accs

    # Legacy fixed-width byte packing (explicit transfer_encoding="bytes").
    pid_lo = int(pid.min())
    pid_span = int(pid.max()) - pid_lo
    if pid_span >= np.iinfo(np.int32).max - 1:
        raise ValueError(
            f"privacy-id span {pid_span} does not fit int32; factorize the "
            f"ids to dense int32 before streaming")
    bytes_pid = _int_bytes(pid_span)
    bytes_pk = _int_bytes(max(num_partitions - 1, 0))
    value_f16 = (value_transfer_dtype is not None
                 and np.dtype(value_transfer_dtype) == np.float16)
    bytes_value = 2 if value_f16 else 4
    width = bytes_pid + bytes_pk + bytes_value
    packed = _pack_native(pid, pk, value, pid_lo, k, bytes_pid, bytes_pk,
                          value_f16, width)
    if packed is None:
        packed = _pack_numpy(pid, pk, value, pid_lo, k, bytes_pid, bytes_pk,
                             value_f16, width, bytes_value)
    buckets, counts = packed

    # Transfers go in a few large slabs while execution stays per-bucket
    # (device slices of the slab): host->device links with a high
    # per-transfer fixed cost (PCIe doorbells, tunneled links) would eat
    # the pipeline if every bucket shipped separately, and the slab after
    # this one still overlaps the current slab's kernels (async dispatch).
    n_t = n_transfers or _num_transfers(buckets.nbytes, k)

    def step_chunk_bytes(c, bucket_row, accs, qhist, n_valid, _n_uniq_c):
        return _chunk_step(jax.random.fold_in(key, c), bucket_row,
                           n_valid, accs,
                           linf_cap, l0_cap, row_clip_lo,
                           row_clip_hi, middle, group_clip_lo,
                           group_clip_hi, l1_cap,
                           num_partitions=num_partitions,
                           bytes_pid=bytes_pid,
                           bytes_pk=bytes_pk,
                           value_f16=value_f16,
                           need_flags=tuple(need_flags),
                           has_group_clip=has_group_clip), qhist

    bytes_cost = columnar.sort_cost(int(buckets.shape[1]),
                                    num_partitions=num_partitions,
                                    l1_mode=l1_cap is not None)
    accs, _ = _drive_slab_windows(
        key, k, counts, None,
        ("bytes", bytes_pid, bytes_pk, value_f16, width),
        lambda s0, s1: buckets[s0:s1], step_chunk_bytes,
        n_t, num_partitions, None, resilience,
        lambda: _input_digest(pid, pk, value),
        scatter_passes=1 + sum(bool(f) for f in need_flags),
        sort_stats={name: bytes_cost[name]
                    for name in ("rows", "tiles", "operand_bytes")})
    return accs


def input_digest(pid, pk, value) -> str:
    """Content digest of one (pid, pk, value) column triple — the same
    identity ``ResidentWire.data_digest`` carries, exposed for callers
    that digest batches before ingesting them (the serving append WAL
    keys its idempotency on this)."""
    from pipelinedp_tpu.runtime import checkpoint as checkpoint_lib

    return checkpoint_lib.array_digest(pid, pk, value)


_input_digest = input_digest


def _snapshot_host(accs, qhist):
    """Host copies of the slab-loop accumulator state for a checkpoint
    snapshot (shared by the single-device and mesh placements)."""
    # dplint: disable=DPL007 — checkpoint snapshot of pre-noise accumulators: never released, consumed only by fingerprint-validated resume (RESILIENCE.md)
    host_accs, host_q = jax.device_get((tuple(accs), qhist))
    return (tuple(np.asarray(a) for a in host_accs),
            None if host_q is None else np.asarray(host_q))


class _SingleDevicePlacement(driver_lib.DevicePlacement):
    """Single-device strategy for the unified slab driver
    (runtime/driver.py owns the loop; this class owns how slabs land on
    the one device and how chunk steps fold).

    The chunk steps (``_chunk_step*``) donate the accumulator buffers
    into the kernel — five distinct zero buffers at init, fresh host
    copies on restore, so donated buffers are never aliased — and device
    OOM is recoverable by halving the slab window (the slab byte budget
    is ours to choose, unlike the mesh's fixed chunk granularity).
    """

    stage_prefix = "dp/stream_slab_"
    prefetch_prefix = "pdp-slab-prefetch"
    degradable = True
    donates = True

    def __init__(self, *, num_partitions, counts, n_uniq, step_chunk,
                 compact_step=None, merge_fn=None, quantile_leaves=None):
        self._num_partitions = num_partitions
        self._counts = counts
        self._n_uniq = n_uniq
        self._step_chunk = step_chunk
        self._compact_fn = compact_step
        self._merge_fn = merge_fn
        self._quantile_leaves = quantile_leaves
        self.compact = compact_step is not None and merge_fn is not None

    def init_state(self):
        # Five distinct buffers: the accumulators are donated into each
        # chunk step, and a donated buffer must not be aliased.
        accs = columnar.PartitionAccumulators(
            *(jnp.zeros((self._num_partitions,), dtype=jnp.float32)
              for _ in range(5)))
        qhist = (jnp.zeros((self._num_partitions, self._quantile_leaves),
                           dtype=jnp.float32)
                 if self._quantile_leaves is not None else None)
        return accs, qhist

    def transfer(self, slab, s0, s1):
        return jax.device_put(slab)

    def _chunk_meta(self, c):
        n_valid = int(self._counts[c])
        n_uniq_c = int(self._n_uniq[c]) if self._n_uniq is not None else 0
        return n_valid, n_uniq_c

    def step(self, c, payload, offset, accs, qhist):
        n_valid, n_uniq_c = self._chunk_meta(c)
        return self._step_chunk(c, payload[offset], accs, qhist, n_valid,
                                n_uniq_c)

    def compact_step(self, c, payload, offset):
        n_valid, n_uniq_c = self._chunk_meta(c)
        return self._compact_fn(c, payload[offset], n_valid, n_uniq_c)

    def merge_pending(self, accs, pending):
        return self._merge_fn(accs, pending)

    def snapshot(self, accs, qhist):
        # dplint: disable=DPL007 — checkpoint snapshot of pre-noise accumulators: never released, consumed only by fingerprint-validated resume (RESILIENCE.md; same by-design transfer _snapshot_host suppresses)
        return _snapshot_host(accs, qhist)

    def restore(self, cp, expects_qhist):
        return _restore_checkpoint(cp, expects_qhist=expects_qhist)


def _drive_slab_windows(key, k, counts, n_uniq, fmt_desc, prepare_slab,
                        step_chunk, n_transfers, num_partitions,
                        quantile_spec, resilience, data_digest_fn=None, *,
                        compact_step=None, merge_fn=None, scatter_passes=5,
                        sort_stats=None):
    """Runs the single-device streaming schedule on the unified slab
    driver (runtime.SlabDriver — checkpoint/resume, retry + OOM window
    degradation, lookahead prefetch, compact merge, fault injection and
    the dispatch watchdog all live there, shared with the mesh path).

    ``prepare_slab(s0, s1)`` produces the host slab (sort+emit for the
    native codec, an array slice otherwise) and
    ``step_chunk(c, row, accs, qhist, n_valid, n_uniq_c)`` folds each
    chunk into the running accumulators with its ``fold_in(key, c)``
    key. Returns (accs, qhist); qhist is None when quantile_spec is
    None.
    """
    placement = _SingleDevicePlacement(
        num_partitions=num_partitions, counts=counts, n_uniq=n_uniq,
        step_chunk=step_chunk, compact_step=compact_step,
        merge_fn=merge_fn,
        quantile_leaves=(quantile_spec[0] if quantile_spec is not None
                         else None))
    plan = driver_lib.SlabPlan(
        n_chunks=k,
        window_chunks=max(1, (k + n_transfers - 1) // n_transfers),
        fmt_desc=repr(fmt_desc),
        counts=counts,
        n_uniq=n_uniq,
        scatter_passes=scatter_passes,
        quantile=quantile_spec is not None,
        data_digest_fn=data_digest_fn,
        on_chunk=((lambda: _count_sort_stats(sort_stats))
                  if sort_stats is not None else None),
        prefetch_depth=prefetch_depth())
    return driver_lib.SlabDriver(placement, plan, prepare_slab, key,
                                 resilience).run()


def _restore_checkpoint(cp, expects_qhist: bool = False):
    """(accs, qhist) device state from a validated checkpoint. Fresh
    host copies, so restored buffers never alias store state even after
    the chunk steps donate them."""
    from pipelinedp_tpu.runtime import checkpoint as checkpoint_lib

    if expects_qhist and cp.qhist is None:
        raise checkpoint_lib.CheckpointMismatchError(
            "checkpoint has no quantile histogram but this run streams "
            "PERCENTILE metrics")
    accs = columnar.PartitionAccumulators(
        *(jnp.asarray(np.array(a)) for a in cp.accs))
    qhist = None if cp.qhist is None else jnp.asarray(np.array(cp.qhist))
    return accs, qhist


# Log the native-packer fallback once per process, not once per call
# (count_event("runtime/native_fallback") keeps the per-call tally).
_native_fallback_logged = False


def _count_native_fallback(reason: str) -> None:
    global _native_fallback_logged
    profiler.count_event("runtime/native_fallback")
    if not _native_fallback_logged:
        _native_fallback_logged = True
        logging.info(
            "pipelinedp_tpu streaming: native row packer unavailable (%s); "
            "using the numpy fallback", reason)


def _pack_native(pid, pk, value, pid_lo, k, bytes_pid, bytes_pk, value_f16,
                 width):
    """One multithreaded C++ pass: bucket + byte-pack all rows.

    Returns ([bucket buffers], counts) or None when the native library is
    unavailable or the dtypes don't qualify (the numpy fallback handles
    everything).
    """
    from pipelinedp_tpu.native import loader
    try:
        lib = loader.load_row_packer()
    except loader.LOADER_ERRORS as e:
        # Only loader/codec failures fall back (the packer is an
        # optimization); anything else — including NativeRequiredError
        # under PIPELINEDP_TPU_REQUIRE_NATIVE=1 — propagates.
        _count_native_fallback(f"{type(e).__name__}: {e}")
        return None
    if lib is None:
        _count_native_fallback("build/load failed; see native loader logs")
        return None
    import ctypes

    n = len(pid)
    pid32 = np.ascontiguousarray(pid, dtype=np.int32)
    pk32 = np.ascontiguousarray(pk, dtype=np.int32)
    val32 = (np.ascontiguousarray(value, dtype=np.float32)
             if value is not None else None)
    # Knuth-hashed buckets are near-uniform: pad 2% + slack, retry once
    # with the exact max if an adversarial id distribution overflows.
    cap = n // k + max(n // (k * 50), 4096)
    out = None
    for attempt in range(2):
        # Drop the undersized buffer before allocating the retry size, so
        # peak host RAM stays ~1x the packed input even on skewed ids.
        del out
        out = np.zeros((k, cap, width), dtype=np.uint8)
        counts = np.zeros(k, dtype=np.int64)
        rc = lib.pdp_pack_buckets(
            pid32.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pk32.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            val32.ctypes.data_as(ctypes.c_void_p) if val32 is not None
            else None, n, int(pid_lo), k, bytes_pid, bytes_pk,
            int(value_f16),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), cap,
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if rc == 0:
            return out, counts
        if rc == 2:
            new_cap = int(counts.max())
            logging.warning(
                "pipelinedp_tpu streaming: bucket capacity %d overflowed "
                "(skewed privacy-id distribution; max bucket %d rows); "
                "retrying with the exact size.", cap, new_cap)
            cap = new_cap
            continue
        return None
    return None


def _pack_numpy(pid, pk, value, pid_lo, k, bytes_pid, bytes_pk, value_f16,
                width, bytes_value):
    """Numpy fallback: same [k, cap, width] buckets and byte layout as the
    native packer."""
    shifted = (pid - pid_lo).astype(np.uint32, copy=False)
    bucket = ((shifted * _HASH_MULT) >> np.uint32(16)) % np.uint32(k)
    counts = np.bincount(bucket, minlength=k).astype(np.int64)
    chunk_rows = int(counts.max()) if k else 1
    if value is not None:
        value = np.asarray(value)
        value = value.astype(np.float16 if value_f16 else np.float32,
                             copy=False)
    out = np.zeros((k, chunk_rows, width), dtype=np.uint8)
    for c in range(k):
        idx = np.flatnonzero(bucket == c)
        buf = out[c]
        m = len(idx)
        _pack_ints(buf[:m], shifted[idx], 0, bytes_pid)
        _pack_ints(buf[:m], pk[idx].astype(np.uint32, copy=False),
                   bytes_pid, bytes_pk)
        if value is not None:
            buf[:m, bytes_pid + bytes_pk:] = (
                value[idx].view(np.uint8).reshape(m, bytes_value))
    return out, counts


# ---------------------------------------------------------------------------
# Resident-dataset wire: pay encode + sort once, serve many queries
# (pipelinedp_tpu/serving/; SERVING.md).
# ---------------------------------------------------------------------------

# Profiler event counters of the serving replay paths
# (profiler.count_event / event_count):
#   EVENT_SERVING_LAUNCHES — chunk-kernel dispatches issued by the replay
#     paths; a batched launch covering B configs counts ONCE (the
#     structural evidence that B configs share one launch);
#   EVENT_SERVING_REPLAYS — resident-wire replays executed (cache misses
#     at the session layer land here).
EVENT_SERVING_LAUNCHES = "serving/kernel_dispatches"
EVENT_SERVING_REPLAYS = "serving/wire_replays"


@dataclasses.dataclass
class ResidentWire:
    """The reusable product of one wire-pipeline pass over a dataset.

    Holds the sorted, wire-codec-encoded chunk slab (host copy always;
    device copy on demand) plus everything a chunk kernel needs to run
    over it: per-bucket row counts, RLE entry counts, the BASE wire
    format (no tile geometry — ``finish_wire_plan`` resolves the
    query-dependent sort geometry per replay), and the prep-time max
    single-pid run that sizes tile slack.

    The handle is immutable after ingest. ``fingerprint`` names it —
    chunk count, format, per-bucket counts and the source-column digest
    (wirecodec.resident_fingerprint) — so a serving session can refuse a
    source dataset that was mutated after ingest.

    Replaying the handle under a key is bit-identical to streaming the
    source columns cold with the same key and chunk count: the slab
    bytes are the same bytes ``stream_bound_and_aggregate`` would have
    encoded, and the replay folds them through the same chunk kernels
    under the same ``fold_in(key, c)`` schedule.
    """
    slab: np.ndarray  # [k, width] uint8 — the sorted wire chunks
    counts: np.ndarray  # [k] rows per bucket
    n_uniq: np.ndarray  # [k] RLE entries per bucket (zeros for planes)
    fmt: wirecodec.WireFormat  # base format (tile-free)
    max_run: int  # prep-time max single-pid run (-1 = unknown)
    num_partitions: int
    n_rows: int
    n_dev: int = 1  # buckets per chunk (mesh ingest: mesh device count)
    data_digest: str = ""
    fingerprint: str = ""
    _device_slab: Optional[jax.Array] = dataclasses.field(
        default=None, repr=False, compare=False)

    @property
    def k(self) -> int:
        """Total wire buckets."""
        return int(len(self.counts))

    @property
    def n_chunks(self) -> int:
        """Chunk-key positions (mesh chunks span n_dev buckets)."""
        return self.k // max(self.n_dev, 1)

    @property
    def host_nbytes(self) -> int:
        return int(self.slab.nbytes) if self.slab is not None else 0

    @property
    def device_nbytes(self) -> int:
        if self._device_slab is None or self.slab is None:
            return 0
        return int(self.slab.nbytes)

    @property
    def device_resident(self) -> bool:
        return self._device_slab is not None

    @property
    def loaded(self) -> bool:
        """Whether the slab bytes are in memory (False after ``unload``;
        the serving SessionManager's disk-spill rung)."""
        return self.slab is not None

    def unload(self) -> None:
        """Frees the slab bytes (host AND device) while keeping every
        piece of metadata — counts, format, fingerprint — so a spilled
        handle can be digest-validated back in with :meth:`reload`.
        Replaying an unloaded handle is a caller bug (the serving layer
        re-hydrates before it replays)."""
        self._device_slab = None
        self.slab = None

    def reload(self, slab: np.ndarray) -> None:
        """Restores the slab bytes of an unloaded handle. The caller
        (serving/store.py) has already digest-validated the bytes
        against the fingerprint; this only guards the geometry."""
        slab = np.asarray(slab)
        expected = (self.k, self.fmt.width)
        if slab.shape != expected or slab.dtype != np.uint8:
            raise ValueError(
                f"reload geometry mismatch: got {slab.dtype}{slab.shape}, "
                f"handle expects uint8{expected}")
        self.slab = slab

    def ensure_device(self):
        """Device copy of the whole slab (single-device handles only);
        idempotent. Replays then slice it instead of re-transferring."""
        if self.n_dev != 1:
            raise ValueError(
                "device residency applies to single-device handles; mesh "
                "replays ship each chunk sharded per query")
        if self.slab is None:
            raise ValueError(
                "handle is unloaded (spilled); reload it before asking "
                "for device residency")
        if self._device_slab is None:
            self._device_slab = jax.device_put(self.slab)
        return self._device_slab

    def drop_device(self) -> None:
        """Frees the device copy (the host slab stays authoritative)."""
        self._device_slab = None


class _IngestPlacement(driver_lib.DevicePlacement):
    """No-op placement for retain-wire ingest: the driver runs the host
    encode schedule (prefetch pool, watchdog, fault injection) and the
    retain sink keeps every prepared slab; nothing lands on a device and
    no chunk kernels run."""

    stage_prefix = "dp/ingest_slab_"
    prefetch_prefix = "pdp-ingest-prefetch"
    degradable = False
    donates = False

    def init_state(self):
        return None, None

    def transfer(self, slab, s0, s1):
        return slab

    def step(self, c, payload, offset, accs, qhist):
        return accs, qhist

    def snapshot(self, accs, qhist):
        return (), None

    def restore(self, cp, expects_qhist):
        return None, None

    def sync(self, accs, qhist, pending):
        pass


def _empty_resident_wire(num_partitions: int) -> ResidentWire:
    fmt = wirecodec.WireFormat(
        bytes_pid=1,
        bits_pk=max(1, int(max(num_partitions - 1, 0)).bit_length()),
        cap=8, ucap=8, value=wirecodec.ValuePlan(wirecodec.VALUE_NONE))
    counts = np.zeros(0, dtype=np.int64)
    n_uniq = np.zeros(0, dtype=np.int64)
    digest = _input_digest(np.zeros(0, np.int32), np.zeros(0, np.int32),
                           None)
    return ResidentWire(
        slab=np.zeros((0, fmt.width), dtype=np.uint8), counts=counts,
        n_uniq=n_uniq, fmt=fmt, max_run=0, num_partitions=num_partitions,
        n_rows=0, data_digest=digest,
        fingerprint=wirecodec.resident_fingerprint(0, fmt, counts, n_uniq,
                                                   digest))


def ingest_resident_wire(pid: np.ndarray,
                         pk: np.ndarray,
                         value: Optional[np.ndarray],
                         *,
                         num_partitions: int,
                         n_chunks: Optional[int] = None,
                         n_dev: int = 1,
                         value_transfer_dtype=None,
                         n_transfers: Optional[int] = None,
                         resilience=None) -> ResidentWire:
    """Runs the wire pipeline once — encode, per-bucket radix sort, emit —
    and RETAINS the sorted chunks instead of discarding them after the
    fold (the SlabDriver's retain-wire mode).

    The schedule is byte-identical to what stream_bound_and_aggregate
    (n_dev == 1) or the mesh streaming path (n_dev == mesh device count)
    would have encoded for the same chunk count, so replaying the handle
    is bit-identical to the cold path. No chunk kernels run: ingest is
    pure host encode (multithreaded native sort + lookahead prefetch)
    plus one pass of the slab loop with no-op steps.
    """
    if (resilience is not None
            and getattr(resilience, "checkpoint_policy", None) is not None):
        raise ValueError(
            "ingest does not checkpoint (it folds no accumulators); give "
            "the checkpoint policy to the queries, not the ingest")
    pid = np.asarray(pid)
    n = len(pid)
    if n == 0:
        return _empty_resident_wire(num_partitions)
    if n_dev > 1:
        n_c = n_chunks or _num_chunks(max(n // n_dev, 1))
        k = n_c * n_dev
    else:
        k = n_chunks or _num_chunks(n)
    with profiler.stage("dp/wire_prep"):
        enc, info = wirecodec.make_encoder(
            pid, pk, value, num_partitions=num_partitions, k=k,
            value_transfer_dtype=value_transfer_dtype)
    if enc is None:
        with profiler.stage("dp/wire_encode"):
            slab, counts, n_uniq, fmt = wirecodec.encode_buckets_numpy(
                pid, pk, value, pid_lo=info.pid_lo, k=k,
                bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
                plan=info.plan, pid_mode=info.pid_mode,
                bits_pid=info.bits_pid)
        slab = np.ascontiguousarray(slab)
    else:
        with enc:
            counts = enc.counts
            cap = wirecodec._round8(int(counts.max()))
            pipelined_sort = (info.pid_mode == wirecodec.PID_RLE
                              and enc.entry_counts is not None)
            if info.pid_mode == wirecodec.PID_PLANES:
                fmt = wirecodec.WireFormat(
                    bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
                    cap=cap, ucap=8, value=info.plan,
                    pid_mode=wirecodec.PID_PLANES, bits_pid=info.bits_pid)
                n_uniq = np.zeros(k, dtype=np.int64)
            elif pipelined_sort:
                n_uniq = enc.entry_counts
                fmt = wirecodec.WireFormat(
                    bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
                    cap=cap, ucap=wirecodec.round_ucap(int(n_uniq.max())),
                    value=info.plan)
            else:
                with profiler.stage("dp/wire_sort_upfront"):
                    n_uniq = enc.sort_range(0, k)
                fmt = wirecodec.WireFormat(
                    bytes_pid=info.bytes_pid, bits_pk=info.bits_pk,
                    cap=cap, ucap=wirecodec.round_ucap(int(n_uniq.max())),
                    value=info.plan)

            def prepare_slab(s0, s1):
                if pipelined_sort:
                    with profiler.stage("dp/wire_sort"):
                        sorted_uniq = enc.sort_range(s0, s1)
                    if not np.array_equal(sorted_uniq, n_uniq[s0:s1]):
                        raise RuntimeError(
                            "wirecodec: prep-time RLE entry counts "
                            "disagree with the sorted buckets")
                return enc.emit_range(s0, s1, fmt)

            slab = np.zeros((k, fmt.width), dtype=np.uint8)

            def retain(s0, s1, window_slab):
                slab[s0:s1] = window_slab

            budget = slab_byte_budget(pipelined_sort)
            n_t = n_transfers or _num_transfers(fmt.width * k, k, budget)
            plan = driver_lib.SlabPlan(
                n_chunks=k,
                window_chunks=max(1, (k + n_t - 1) // n_t),
                fmt_desc=repr(fmt),
                counts=counts,
                n_uniq=n_uniq,
                scatter_passes=0,
                retain_sink=retain,
                prefetch_depth=prefetch_depth())
            driver_lib.SlabDriver(_IngestPlacement(), plan, prepare_slab,
                                  None, resilience).run()
    digest = _input_digest(pid, pk, value)
    counts = np.asarray(counts, dtype=np.int64)
    n_uniq = np.asarray(n_uniq, dtype=np.int64)
    return ResidentWire(
        slab=slab, counts=counts, n_uniq=n_uniq, fmt=fmt,
        max_run=info.max_run, num_partitions=num_partitions, n_rows=n,
        n_dev=n_dev, data_digest=digest,
        fingerprint=wirecodec.resident_fingerprint(k, fmt, counts, n_uniq,
                                                   digest))


class _ResidentReplayPlacement(_SingleDevicePlacement):
    """Single-device placement replaying a retained wire: when the
    handle holds a device copy of the slab the transfer is a device-side
    slice (no host->device bytes at all); otherwise the host slab window
    ships like a cold slab. Chunk dispatches credit the serving launch
    counter."""

    stage_prefix = "dp/replay_slab_"
    prefetch_prefix = "pdp-replay-prefetch"

    def __init__(self, device_slab=None, **kw):
        super().__init__(**kw)
        self._device_slab = device_slab

    def transfer(self, slab, s0, s1):
        if self._device_slab is not None:
            return self._device_slab[s0:s1]
        return jax.device_put(slab)

    def step(self, c, payload, offset, accs, qhist):
        profiler.count_event(EVENT_SERVING_LAUNCHES)
        return super().step(c, payload, offset, accs, qhist)

    def compact_step(self, c, payload, offset):
        profiler.count_event(EVENT_SERVING_LAUNCHES)
        return super().compact_step(c, payload, offset)


def _zero_accs(num_partitions: int, quantile_spec):
    zeros = jnp.zeros((num_partitions,), dtype=jnp.float32)
    accs = columnar.PartitionAccumulators(zeros, zeros, zeros, zeros, zeros)
    if quantile_spec is not None:
        return accs, jnp.zeros((num_partitions, quantile_spec[0]),
                               dtype=jnp.float32)
    return accs, None


def replay_resident_wire(key: jax.Array,
                         wire: ResidentWire,
                         *,
                         linf_cap,
                         l0_cap,
                         row_clip_lo,
                         row_clip_hi,
                         middle,
                         group_clip_lo,
                         group_clip_hi,
                         l1_cap=None,
                         need_flags=(True, True, True, True),
                         has_group_clip: bool = True,
                         quantile_spec: Optional[Tuple[int, float,
                                                       float]] = None,
                         segment_sort="auto",
                         compact_merge="auto",
                         n_transfers: Optional[int] = None,
                         resilience=None):
    """Answers one query from a retained wire: kernel + fold only — no
    encode, no sort, and (device-resident handles) no transfer.

    Bit-identical to stream_bound_and_aggregate(key, <source columns>,
    n_chunks=wire.n_chunks, ...) with the same knobs: the same chunk
    kernels fold the same slab bytes under the same ``fold_in(key, c)``
    schedule (shared _build_chunk_steps). Returns accs, or (accs, qhist)
    when quantile_spec is set.
    """
    if wire.n_dev != 1:
        raise ValueError(
            "this handle was ingested for a mesh; replay it through "
            "parallel.sharded.replay_resident_wire")
    num_partitions = wire.num_partitions
    if wire.n_rows == 0:
        accs, qhist = _zero_accs(num_partitions, quantile_spec)
        return (accs, qhist) if quantile_spec is not None else accs
    profiler.count_event(EVENT_SERVING_REPLAYS)
    obs_trace.event("wire_replay", n_chunks=wire.n_chunks,
                    device_resident=wire.device_resident)
    fmt, int_clip, sort_stats = finish_wire_plan(
        wire.fmt, segment_sort, wire.max_run,
        num_partitions=num_partitions, row_clip_lo=row_clip_lo,
        row_clip_hi=row_clip_hi, linf_cap=linf_cap,
        l1_mode=l1_cap is not None,
        with_quantile_mask=quantile_spec is not None,
        group_clip_lo=group_clip_lo, group_clip_hi=group_clip_hi,
        need_flags=tuple(need_flags))
    step_chunk, compact_step, merge_fn = _build_chunk_steps(
        key, fmt, int_clip, num_partitions=num_partitions,
        linf_cap=linf_cap, l0_cap=l0_cap, row_clip_lo=row_clip_lo,
        row_clip_hi=row_clip_hi, middle=middle,
        group_clip_lo=group_clip_lo, group_clip_hi=group_clip_hi,
        l1_cap=l1_cap, need_flags=need_flags,
        has_group_clip=has_group_clip, quantile_spec=quantile_spec,
        compact_merge=compact_merge, sort_stats=sort_stats)
    k = wire.k
    placement = _ResidentReplayPlacement(
        device_slab=wire._device_slab,
        num_partitions=num_partitions, counts=wire.counts,
        n_uniq=wire.n_uniq, step_chunk=step_chunk,
        compact_step=compact_step, merge_fn=merge_fn,
        quantile_leaves=(quantile_spec[0] if quantile_spec is not None
                         else None))
    n_t = n_transfers or _num_transfers(wire.slab.nbytes, k)
    plan = driver_lib.SlabPlan(
        n_chunks=k,
        window_chunks=max(1, (k + n_t - 1) // n_t),
        fmt_desc=repr(fmt),
        counts=wire.counts,
        n_uniq=wire.n_uniq,
        scatter_passes=1 + sum(bool(f) for f in need_flags),
        quantile=quantile_spec is not None)
    accs, qhist = driver_lib.SlabDriver(
        placement, plan, lambda s0, s1: wire.slab[s0:s1], key,
        resilience).run()
    if quantile_spec is not None:
        return accs, qhist
    return accs


@functools.partial(
    jax.jit,
    static_argnames=("num_partitions", "fmt", "need_flags",
                     "has_group_clip"))
def _chunk_step_rle_batch(c, keys, row, n_valid, n_uniq_c, accs, linf_caps,
                          l0_caps, row_clip_los, row_clip_his, middles,
                          group_clip_los, group_clip_his, l1_caps=None, *,
                          num_partitions: int, fmt: wirecodec.WireFormat,
                          need_flags=(True, True, True, True),
                          has_group_clip: bool = True):
    """One wire chunk folded for a whole BATCH of query configs in one
    launch: the chunk is decoded once, then the bounding kernel vmaps
    over the per-config (key, caps, clip bounds) with the decoded rows
    broadcast. Accumulators are [B, num_partitions].

    Per-config results are the same values the unbatched
    ``_chunk_step_rle`` produces for that config alone (the sampling
    sorts are exact and the per-config accumulations are independent
    lanes of the batched kernel); the per-config key schedule is the
    engine's own ``fold_in(key_b, c)``.

    ``l1_caps`` (per-config total-contribution caps, [B] int32 or None)
    rides an extra vmapped lane; None keeps the l1-free kernel shape.
    """
    pid, pk, value, valid, vkw = _decode_for_kernel(row, n_valid, n_uniq_c,
                                                    fmt)

    def one(key, acc, linf_cap, l0_cap, row_clip_lo, row_clip_hi, middle,
            group_clip_lo, group_clip_hi, l1_cap=None):
        chunk_accs = columnar.bound_and_aggregate(
            jax.random.fold_in(key, c), pid, pk, value, valid,
            num_partitions=num_partitions,
            linf_cap=linf_cap,
            l0_cap=l0_cap,
            row_clip_lo=row_clip_lo,
            row_clip_hi=row_clip_hi,
            middle=middle,
            group_clip_lo=group_clip_lo,
            group_clip_hi=group_clip_hi,
            l1_cap=l1_cap,
            need_count=need_flags[0],
            need_sum=need_flags[1],
            need_norm=need_flags[2],
            need_norm_sq=need_flags[3],
            has_group_clip=has_group_clip,
            pid_sorted=fmt.pid_sorted,
            max_segments=fmt.ucap if fmt.pid_sorted else None,
            **vkw)
        return columnar.PartitionAccumulators(
            *(a + ch for a, ch in zip(acc, chunk_accs)))

    if l1_caps is not None:
        return jax.vmap(one)(keys, accs, linf_caps, l0_caps, row_clip_los,
                             row_clip_his, middles, group_clip_los,
                             group_clip_his, l1_caps)
    return jax.vmap(one)(keys, accs, linf_caps, l0_caps, row_clip_los,
                         row_clip_his, middles, group_clip_los,
                         group_clip_his)


def replay_resident_wire_batched(keys,
                                 wire: ResidentWire,
                                 *,
                                 linf_caps,
                                 l0_caps,
                                 row_clip_los,
                                 row_clip_his,
                                 middles,
                                 group_clip_los,
                                 group_clip_his,
                                 l1_caps=None,
                                 need_flags=(True, True, True, True),
                                 has_group_clip: bool = True,
                                 n_transfers: Optional[int] = None
                                 ) -> columnar.PartitionAccumulators:
    """Folds the retained wire for B query configs in ONE launch per
    chunk: configs that share the sorted wire but differ in caps / clip
    bounds / keys ride a vmapped bounding kernel instead of B sequential
    passes over the same bytes.

    keys: sequence of B chunk-kernel keys (one per config, the engine's
    k_kernel); caps/bounds: length-B sequences. Returns [B,
    num_partitions] PartitionAccumulators. Per-config lanes match the
    config's sequential replay (and therefore its cold run): the batched
    kernel uses the parity-oracle statics — untiled packed sort, float32
    payload and accumulation — which PR 7 pins bit-identical to every
    other segment_sort mode.
    """
    num_partitions = wire.num_partitions
    B = len(linf_caps)
    if wire.n_dev != 1:
        raise ValueError("batched replay supports single-device handles")
    keys = jnp.stack([jnp.asarray(k) for k in keys])
    accs = columnar.PartitionAccumulators(
        *(jnp.zeros((B, num_partitions), dtype=jnp.float32)
          for _ in range(5)))
    if wire.n_rows == 0:
        return accs
    profiler.count_event(EVENT_SERVING_REPLAYS)
    # Parity-oracle statics: tile-free packed sort, wide payload, no
    # hash bins. PR 7's parity matrix pins the sorted segment_sort modes
    # bit-identical (and the hash-binned stage matches them under its
    # exactness gate — the only regime the auto dispatch picks it in),
    # so the batched lanes match sequential replays at any knob setting.
    fmt = dataclasses.replace(wire.fmt, tile_rows=0, tile_slack=0,
                              hash_bins=0, hash_bin_rows=0,
                              sort_value_narrow=False)
    linf = jnp.asarray(np.asarray(linf_caps, dtype=np.int32))
    l0 = jnp.asarray(np.asarray(l0_caps, dtype=np.int32))
    rlo = jnp.asarray(np.asarray(row_clip_los, dtype=np.float32))
    rhi = jnp.asarray(np.asarray(row_clip_his, dtype=np.float32))
    mid = jnp.asarray(np.asarray(middles, dtype=np.float32))
    glo = jnp.asarray(np.asarray(group_clip_los, dtype=np.float32))
    ghi = jnp.asarray(np.asarray(group_clip_his, dtype=np.float32))
    l1 = (None if l1_caps is None
          else jnp.asarray(np.asarray(l1_caps, dtype=np.int32)))
    k = wire.k
    n_t = n_transfers or _num_transfers(wire.slab.nbytes, k)
    window = max(1, (k + n_t - 1) // n_t)
    cost = columnar.sort_cost(
        fmt.cap, num_partitions=num_partitions,
        max_segments=fmt.ucap if fmt.pid_sorted else None,
        pid_sorted=fmt.pid_sorted, l1_mode=l1 is not None)
    for s0 in range(0, k, window):
        s1 = min(s0 + window, k)
        if wire._device_slab is not None:
            payload = wire._device_slab[s0:s1]
        else:
            payload = jax.device_put(wire.slab[s0:s1])
        for c in range(s0, s1):
            accs = _chunk_step_rle_batch(
                c, keys, payload[c - s0], int(wire.counts[c]),
                int(wire.n_uniq[c]), accs, linf, l0, rlo, rhi, mid, glo,
                ghi, l1, num_partitions=num_partitions, fmt=fmt,
                need_flags=tuple(need_flags),
                has_group_clip=has_group_clip)
            # ONE launch covers all B configs; the sort model runs B
            # lanes over the chunk's rows.
            profiler.count_event(EVENT_SERVING_LAUNCHES)
            profiler.count_event(columnar.EVENT_SORT_ROWS,
                                 int(cost["rows"]) * B)
            profiler.count_event(columnar.EVENT_SORT_BYTES,
                                 int(cost["operand_bytes"]) * B)
    return accs
