"""Vectorized private partition selection for the columnar engine.

One call decides every partition at once (vs. the reference's per-partition
C++ strategy objects inside a filter, dp_engine.py:335-371). The
truncated-geometric keep probabilities use the same closed forms as
pipelinedp_tpu/partition_selection.py, with the segment constants
precomputed on host and passed as runtime scalars so the kernel never
recompiles across budgets.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu import partition_selection as ps_lib
from pipelinedp_tpu.aggregate_params import PartitionSelectionStrategy

TRUNCATED_GEOMETRIC = 0
LAPLACE_THRESHOLDING = 1
GAUSSIAN_THRESHOLDING = 2


@dataclasses.dataclass
class SelectionParams:
    """Runtime scalars describing a selection strategy for the device kernel.

    ``kind`` is static (selects the code path); everything else is traced.
    """
    kind: int
    # Truncated geometric (segment constants):
    eps_p: float = 0.0
    delta_p: float = 0.0
    n1: float = 0.0
    pi_n1: float = 0.0
    pi_inf: float = 0.0
    # Thresholding:
    noise_scale: float = 0.0  # Laplace scale b or Gaussian sigma
    threshold_shifted: float = 0.0
    # Common:
    pre_threshold_shift: float = 0.0  # pre_threshold - 1, or 0


def selection_params_from_strategy(
        strategy: ps_lib.PartitionSelection) -> SelectionParams:
    """Extracts device-kernel scalars from a host strategy object."""
    shift = float((strategy.pre_threshold or 1) - 1)
    if isinstance(strategy, ps_lib.TruncatedGeometricPartitionSelection):
        return SelectionParams(
            kind=TRUNCATED_GEOMETRIC,
            eps_p=strategy._eps_p,
            delta_p=strategy._delta_p,
            n1=float(strategy._n1),
            pi_n1=float(strategy._pi_n1),
            pi_inf=float(strategy._pi_inf),
            pre_threshold_shift=shift,
        )
    if isinstance(strategy, ps_lib.LaplaceThresholdingPartitionSelection):
        return SelectionParams(
            kind=LAPLACE_THRESHOLDING,
            noise_scale=strategy._scale,
            threshold_shifted=strategy._threshold_shifted,
            pre_threshold_shift=shift,
        )
    if isinstance(strategy, ps_lib.GaussianThresholdingPartitionSelection):
        return SelectionParams(
            kind=GAUSSIAN_THRESHOLDING,
            noise_scale=strategy.sigma,
            threshold_shifted=strategy._threshold_shifted,
            pre_threshold_shift=shift,
        )
    raise TypeError(f"Unknown strategy type: {type(strategy)}")


def create_selection_params(strategy: PartitionSelectionStrategy, eps: float,
                            delta: float, max_partitions_contributed: int,
                            pre_threshold: Optional[int]) -> SelectionParams:
    host = ps_lib.create_partition_selection_strategy(
        strategy, eps, delta, max_partitions_contributed, pre_threshold)
    return selection_params_from_strategy(host)


def pack_operands(params: SelectionParams) -> np.ndarray:
    """The strategy's dynamic scalars as one float32 operand vector.

    The static ``kind`` travels separately (e.g. in a FinalizePlan) so a
    compiled kernel keyed on it never recompiles across budgets — the
    (eps, delta)-derived constants here stay runtime operands.
    """
    return np.asarray([
        params.eps_p, params.delta_p, params.n1, params.pi_n1, params.pi_inf,
        params.noise_scale, params.threshold_shifted,
        params.pre_threshold_shift
    ],
                      dtype=np.float32)


def unpack_operands(kind: int, floats) -> SelectionParams:
    """Rebuilds SelectionParams from pack_operands output (floats may be
    traced inside jit; kind must be a static Python int)."""
    return SelectionParams(kind=kind,
                           eps_p=floats[0],
                           delta_p=floats[1],
                           n1=floats[2],
                           pi_n1=floats[3],
                           pi_inf=floats[4],
                           noise_scale=floats[5],
                           threshold_shifted=floats[6],
                           pre_threshold_shift=floats[7])


def truncated_geometric_keep_prob(pid_counts: jnp.ndarray, eps_p, delta_p, n1,
                                  pi_n1, pi_inf) -> jnp.ndarray:
    """pi(n) via the two closed-form segments (floats in, probs out)."""
    n = pid_counts.astype(jnp.float32)
    seg_a = delta_p * jnp.expm1(jnp.minimum(n, n1) * eps_p) / jnp.expm1(eps_p)
    seg_b = pi_inf - (pi_inf - pi_n1) * jnp.exp(-(n - n1) * eps_p)
    probs = jnp.where(n <= n1, seg_a, seg_b)
    return jnp.clip(probs, 0.0, 1.0)


def select_partitions(key: jax.Array, pid_counts: jnp.ndarray,
                      params: SelectionParams, valid: jnp.ndarray):
    """Returns (keep_mask, noised_counts).

    ``pid_counts``: per-partition privacy-unit counts (float or int array).
    ``valid``: mask of partitions that exist in the data.
    ``noised_counts`` is meaningful for thresholding strategies (the DP
    privacy-id count estimate); for truncated geometric it echoes the raw
    count (no noised value is defined — parity with PyDP).
    """
    n = pid_counts.astype(jnp.float32) - params.pre_threshold_shift
    positive = (n > 0) & valid
    if params.kind == TRUNCATED_GEOMETRIC:
        probs = truncated_geometric_keep_prob(jnp.maximum(n, 1.0),
                                              params.eps_p, params.delta_p,
                                              params.n1, params.pi_n1,
                                              params.pi_inf)
        uniforms = jax.random.uniform(key, pid_counts.shape)
        keep = positive & (uniforms < probs)
        return keep, pid_counts.astype(jnp.float32)
    if params.kind == LAPLACE_THRESHOLDING:
        noise = jax.random.laplace(key, pid_counts.shape) * params.noise_scale
    elif params.kind == GAUSSIAN_THRESHOLDING:
        noise = jax.random.normal(key, pid_counts.shape) * params.noise_scale
    else:
        raise ValueError(f"Unknown selection kind: {params.kind}")
    noised = n + noise
    keep = positive & (noised >= params.threshold_shifted)
    return keep, noised + params.pre_threshold_shift


@functools.partial(jax.jit, static_argnums=(2,))
def _select_partitions_compiled(key, pid_counts, kind, floats, valid):
    return select_partitions(key, pid_counts, unpack_operands(kind, floats),
                             valid)


def select_partitions_jit(key: jax.Array, pid_counts: jnp.ndarray,
                          params: SelectionParams, valid: jnp.ndarray):
    """Compiled top-level entry for select_partitions.

    XLA may FMA-contract the noise multiply into the threshold addition
    when the kernel compiles as one computation, flipping keep decisions
    at the boundary relative to op-by-op eager execution. Engine call
    sites use this entry so selection bits match the fused finalization
    epilogue (ops/finalize.py), which inlines the same formula in its own
    jit. The strategy kind is the static key; the (eps, delta)-derived
    floats stay runtime operands (no recompiles across budgets).
    """
    return _select_partitions_compiled(key, jnp.asarray(pid_counts),
                                       params.kind, pack_operands(params),
                                       valid)


def probability_of_keep_np(strategy: ps_lib.PartitionSelection,
                           counts: np.ndarray) -> np.ndarray:
    """Host-side reference for testing the device path."""
    return strategy.probability_of_keep_vec(counts)
