"""The fused columnar DP-aggregation kernels.

This is the TPU-native replacement for the reference's per-row dataflow
(contribution_bounders.py + combiners.py + the per-key shuffle of
pipeline_backend.py): the whole bound-and-aggregate stage is ONE sort plus a
handful of segment reductions over fixed-shape arrays, entirely inside jit.

Dataflow (bound_and_aggregate):
  1. lexsort rows by (privacy_id, group_hash, partition_key, uniform),
     where group_hash is a keyed 32-bit mix of (pid, pk): within each
     privacy id the (pid, pk) groups land in hash order — a uniform random
     permutation of the groups — and within each group the rows land in
     uniform-tiebreak order. One sort therefore provides BOTH sampling
     permutations (the reference's two sample_fixed_per_key passes).
  2. rank rows within (pid, pk) via a cummax over group-start indices; keep
     rank < max_contributions_per_partition  (Linf bounding).
  3. rank groups within pid via the group counter minus its value at the
     pid's first row; keep rank < max_partitions_contributed (L0 bounding)
     — no second sort: group order within a pid is already random.
  4. reduce rows -> (pid, pk) group accumulators with per-column
     segment-sums over the sorted (hence monotone) group ids.
  5. reduce kept groups -> per-partition accumulators (count, clipped sum,
     normalized sum, normalized sum of squares, privacy-id count) with
     per-column segment-sums into [num_partitions] arrays.

The round-4 profile attributed the kernel plateau to pass count, not sort
cost (each 100M-row segment-sum/gather is a full HBM round trip at ~1s on
v5e; the 3-key sort itself is 0.8s): this layout runs 1 sort + the minimal
set of reductions (static need_* flags drop the accumulators a metric set
does not read) instead of 2 sorts + ~10 unconditional reductions. Columns
stay separate [N] arrays: a "fused" [N, k] operand is tile-padded k -> 128
lanes on TPU (a 20x memory blowup measured slower, not faster).

Sampling exactness: the group permutation is uniform iff group hashes are
i.i.d. uniform; the keyed murmur3-style finalizer gives 32-bit avalanche
mixing, and ties (probability ~m^2/2^33 per privacy id with m groups) fall
back to pk order — a negligible, documented bias. Row order within groups
uses an exact uniform tiebreak as before.

All shapes static; caps and clip bounds are runtime scalars. Padding rows
(for sharding) carry valid=False and are routed to the end of the sort.

Pre-sorted ingest (pid_sorted=True): the wire codec delivers rows already
sorted by privacy id within each bucket (ops/wirecodec.py RLE requires it),
so the arrival order IS the primary sort key. The presorted sampler packs
(dense pid-segment index, group_hash, pk, random tiebreak) into THREE
uint32 keys (bit-concatenated, so the 3-key comparison is exactly the
4-field lexicographic order) and carries the value as the only payload —
4 sort operands instead of the general path's 7, and validity becomes
positional (padding is a suffix, so no valid or order operands ride the
sort). Same sampling distribution, cheaper sort: this is the ~2x-headroom
item of BASELINE.md's round-4 floor analysis.
"""

from __future__ import annotations

import functools
import math
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

_INT32_MAX = jnp.iinfo(jnp.int32).max


class PartitionAccumulators(NamedTuple):
    """Per-partition accumulators, each of shape [num_partitions]."""
    pid_count: jnp.ndarray  # distinct privacy units contributing
    count: jnp.ndarray  # kept contributions
    sum: jnp.ndarray  # clipped sum
    norm_sum: jnp.ndarray  # sum of (clip(v) - middle)
    norm_sq_sum: jnp.ndarray  # sum of (clip(v) - middle)^2


def _segment_rank(sorted_is_start: jnp.ndarray) -> jnp.ndarray:
    """Rank of each element within its (contiguous) segment."""
    n = sorted_is_start.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    seg_start = jax.lax.cummax(jnp.where(sorted_is_start, idx, 0))
    return idx - seg_start


class SampledRows(NamedTuple):
    """The Linf/L0 sampling decisions, in (pid, ghash, pk, uniform)-sorted
    order.

    The single source of truth for contribution bounding: every kernel
    (scalar, vector, row-mask) derives from this so their sampling stays
    bit-identical for the same PRNG key.
    """
    order: Optional[jnp.ndarray]  # row permutation (None when not needed)
    spid: jnp.ndarray  # sorted pid keys (padding -> INT32_MAX)
    spk: jnp.ndarray  # sorted pk keys (padding -> INT32_MAX)
    svalid: jnp.ndarray  # sorted validity
    is_start: jnp.ndarray  # (pid, pk)-group start marker
    group_id: jnp.ndarray  # dense (pid, pk)-group index per sorted row
    keep_row: jnp.ndarray  # Linf sampling decision per sorted row
    keep_group_row: jnp.ndarray  # L0 decision of the row's group, per row
    sval: Optional[jnp.ndarray]  # sorted values (when passed to the sort)


def _group_hash(pid: jnp.ndarray, pk: jnp.ndarray,
                salt: jnp.ndarray) -> jnp.ndarray:
    """Keyed 32-bit mix of (pid, pk): the random group order within each
    privacy id (murmur3-style finalizer for avalanche; salt from the PRNG
    key so sampling differs between kernel invocations)."""
    x = pid.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
    x = x ^ (pk.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B)) ^ salt
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x7FEB352D)
    x = x ^ (x >> 15)
    x = x * jnp.uint32(0x846CA68B)
    x = x ^ (x >> 16)
    return x


def _l1_sample_mask(key: jax.Array, pid: jnp.ndarray, valid: jnp.ndarray,
                    l1_cap) -> jnp.ndarray:
    """Keeps a uniform sample of at most l1_cap rows per privacy id.

    Exact replication of the reference's per-privacy-id L1 bounding
    (SamplingPerPrivacyIdContributionBounder,
    contribution_bounders.py:114-156): sort rows by (pid, uniform) — each
    privacy id's rows land in random order — and keep rank < l1_cap.
    """
    n = pid.shape[0]
    pid_key = jnp.where(valid, pid, _INT32_MAX)
    tiebreak = jax.random.uniform(key, (n,))
    order = jnp.lexsort((tiebreak, pid_key))
    spid = pid_key[order]
    is_start = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), spid[1:] != spid[:-1]])
    keep_sorted = valid[order] & (_segment_rank(is_start) < l1_cap)
    return jnp.zeros((n,), dtype=bool).at[order].set(keep_sorted)


def _sample_rows_and_groups(key: jax.Array, pid: jnp.ndarray,
                            pk: jnp.ndarray, valid: jnp.ndarray, linf_cap,
                            l0_cap, l1_cap=None,
                            value: Optional[jnp.ndarray] = None,
                            need_order: bool = True) -> SampledRows:
    """ONE sort of rows by (pid, group_hash, pk, uniform); samples Linf
    rows and L0 groups from it (module docstring steps 1-3).

    The uniform tiebreak makes each (pid, pk) group a random permutation,
    so "rank < cap" is exact sampling without replacement; the keyed group
    hash makes the groups of each privacy id a random permutation, so
    "group rank within pid < cap" is the cross-partition sample — the
    reference's two sample_fixed_per_key passes from a single sort.

    l1_cap (max_contributions mode): when given, a uniform sample of at
    most l1_cap rows per privacy id is taken FIRST — the total-contribution
    bound whose L1 sensitivity the noise is calibrated to. Passing
    linf/l0 caps >= the data bounds alongside reproduces the reference's
    L1-only bounding exactly.
    """
    n = pid.shape[0]
    k1, k2 = jax.random.split(key)
    if l1_cap is not None:
        valid = _l1_sample_mask(jax.random.fold_in(key, 3), pid, valid,
                                l1_cap)

    # Padding rows sort to the very end (pid is the primary key).
    pid_key = jnp.where(valid, pid, _INT32_MAX)
    pk_key = jnp.where(valid, pk, _INT32_MAX)
    salt = jax.random.bits(k2, (), dtype=jnp.uint32)
    ghash = _group_hash(pid_key, pk_key, salt)

    tiebreak = jax.random.uniform(k1, (n,))
    # One variadic sort carries every payload along: on TPU the sort moves
    # data far cheaper than post-hoc random-access gathers (a single 100M
    # gather costs more than the whole 4-key sort). The order payload rides
    # only for callers that map decisions back to input order (row-mask,
    # vector gather) — the scalar aggregation never reads it, and dropping
    # the operand cannot change the permutation (is_stable fixes tie
    # resolution from the keys alone).
    operands = [pid_key, ghash, pk_key, tiebreak, valid]
    if need_order:
        operands.append(jnp.arange(n, dtype=jnp.int32))
    if value is not None:
        operands.append(value)
    # is_stable: float32 tiebreak collisions must resolve identically in
    # every kernel sharing a PRNG key (bound_row_mask sorts one operand
    # fewer than bound_and_aggregate; an unstable sort could order tied
    # rows differently between the two programs, breaking the replayed
    # sampling guarantee).
    sorted_ops = jax.lax.sort(operands, num_keys=4, is_stable=True)
    spid, sgh, spk, _, svalid = sorted_ops[:5]
    order = sorted_ops[5] if need_order else None
    sval = sorted_ops[-1] if value is not None else None
    is_start = jnp.concatenate([
        jnp.ones((1,), dtype=bool),
        (spid[1:] != spid[:-1]) | (sgh[1:] != sgh[:-1]) |
        (spk[1:] != spk[:-1])
    ])
    keep_row = svalid & (_segment_rank(is_start) < linf_cap)
    group_id = (jnp.cumsum(is_start) - 1).astype(jnp.int32)

    # -- L0 sampling: rank of the row's group within its pid --------------
    # group_id is nondecreasing, so a cummax over the pid-start markers
    # yields the pid's first group id without a gather.
    is_pid_start = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), spid[1:] != spid[:-1]])
    first_group_of_pid = jax.lax.cummax(
        jnp.where(is_pid_start, group_id, 0))
    group_rank = group_id - first_group_of_pid
    keep_group_row = svalid & (group_rank < l0_cap)
    return SampledRows(order, spid, spk, svalid, is_start, group_id,
                       keep_row, keep_group_row, sval)


# -- presorted-pid fast path -------------------------------------------------
#
# Minimum random tiebreak bits for the packed-key sort. Ties fall back to
# stable (arrival) order like the general path's float32 tiebreak ties; 8
# bits would make ties common, so below this the presorted path refuses and
# the caller falls back to the general 4-key sort.
_MIN_RAND_BITS = 12
_KEY_BITS = 96  # three uint32 sort keys

# Profiler event counters for the bounding sorts (counted per EXECUTED
# chunk kernel by the streaming drivers and by bench.py, from the static
# sort_cost model below — the kernels themselves are jitted and cannot
# count per execution):
#   ops/sort_rows          rows entering the sampler sort (incl. tile pad)
#   ops/sort_tiles         independently sorted tiles (1 for global sorts)
#   ops/sort_operand_bytes modeled bytes the O(rows * log span) sort
#                          network moves: rows * bytes_per_row * log2(span).
#                          The tiled path shrinks it through the log factor
#                          (span = tile width, not chunk rows) and the
#                          narrowed value payload; the hash-binned group
#                          stage makes ZERO sort passes, so it credits 0.
EVENT_SORT_ROWS = "ops/sort_rows"
EVENT_SORT_TILES = "ops/sort_tiles"
EVENT_SORT_BYTES = "ops/sort_operand_bytes"

# Hash-binned (sortless) group-stage counters, per EXECUTED chunk:
#   ops/hash_bin_passes              chunks that ran the hash-binned stage
#   ops/hash_bin_occupancy_pct      cumulative per-chunk grid occupancy in
#                                    percent (divide by passes for the mean)
#   ops/hash_bin_overflow_demotions chunks whose RLE entry count exceeded
#                                    the static bin count and were demoted
#                                    to the tiled sort by the host driver
EVENT_HASH_PASSES = "ops/hash_bin_passes"
EVENT_HASH_OCCUPANCY = "ops/hash_bin_occupancy_pct"
EVENT_HASH_DEMOTIONS = "ops/hash_bin_overflow_demotions"


def packed_key_layout(n: int, num_partitions: int,
                      max_segments: Optional[int] = None
                      ) -> Tuple[int, int, int, int]:
    """(segbits, pkbits, randbits, padbits) of the packed 3-key layout.

    The single source of truth shared by ``presorted_fits`` and the
    packed/tiled samplers — they previously duplicated these formulas, so
    a drift in one silently broke the fit check at the capacity edge.
    randbits/padbits are only meaningful when the layout fits
    (``presorted_fits``); randbits is clamped to [0, 32].
    """
    seg_cap = int(max_segments) if max_segments is not None else int(n)
    segbits = max(1, seg_cap.bit_length())
    pkbits = max(1, int(max(num_partitions - 1, 0)).bit_length())
    randbits = min(32, max(0, _KEY_BITS - segbits - 32 - pkbits))
    padbits = max(0, _KEY_BITS - segbits - 32 - pkbits - randbits)
    return segbits, pkbits, randbits, padbits


def presorted_fits(n: int, num_partitions: int,
                   max_segments: Optional[int] = None) -> bool:
    """Whether the packed 3-key presorted sort has enough bits for the
    (segment, ghash, pk, rand) fields at this shape."""
    segbits, pkbits, randbits, _ = packed_key_layout(n, num_partitions,
                                                     max_segments)
    return randbits >= _MIN_RAND_BITS


def sort_cost(n: int, *, num_partitions: int,
              max_segments: Optional[int] = None, pid_sorted: bool = False,
              tile_rows: int = 0, tile_slack: int = 0,
              has_value: bool = True, value_bytes: int = 4,
              need_order: bool = False, l1_mode: bool = False,
              hash_bins: int = 0, hash_bin_rows: int = 0) -> dict:
    """Static cost model of the sampler sort one kernel execution runs.

    Mirrors _dispatch_sampler's trace-time dispatch exactly, so host
    drivers can account the compiled kernel's sort without instrumenting
    jitted code. Returns {kind, rows, span, tiles, bytes_per_row,
    operand_bytes}: ``operand_bytes`` is the O(rows * log span) traffic
    model ``rows * bytes_per_row * max(1, ceil(log2(span)))`` — the bytes
    an O(N log N) sort network moves — credited to the profiler counters
    EVENT_SORT_ROWS / EVENT_SORT_TILES / EVENT_SORT_BYTES per executed
    chunk by the streaming drivers and bench.py.

    kind "hash" (the sortless hash-binned group stage) reports its grid
    geometry in rows/span/tiles but ZERO operand_bytes — the group stage
    makes no sort pass over the wire at all.
    """
    if n <= 0:
        return {"kind": "empty", "rows": 0, "span": 1, "tiles": 0,
                "bytes_per_row": 0, "operand_bytes": 0}
    packed = (pid_sorted and not l1_mode
              and presorted_fits(n, num_partitions, max_segments))
    if packed:
        bpr = 12 + (value_bytes if has_value else 0) + (4 if need_order
                                                        else 0)
        if hash_bins and hash_bin_rows:
            return {"kind": "hash", "rows": hash_bins * hash_bin_rows,
                    "span": hash_bin_rows, "tiles": hash_bins,
                    "bytes_per_row": 0, "operand_bytes": 0}
        if tile_rows and tile_rows + tile_slack < n:
            w = tile_rows + tile_slack
            tiles = -(-n // tile_rows)
            rows = tiles * w
            return {"kind": "tiled", "rows": rows, "span": w,
                    "tiles": tiles, "bytes_per_row": bpr,
                    "operand_bytes":
                        rows * bpr * max(1, (w - 1).bit_length())}
        return {"kind": "packed", "rows": n, "span": n, "tiles": 1,
                "bytes_per_row": bpr,
                "operand_bytes": n * bpr * max(1, (n - 1).bit_length())}
    # General 4-key sort: pid/ghash/pk/tiebreak keys + valid payload
    # (+ order, + value); max_contributions mode pays the L1 pre-sample
    # lexsort (2 keys + the implicit iota payload) on top.
    bpr = 17 + (4 if need_order else 0) + (value_bytes if has_value else 0)
    cost = n * bpr * max(1, (n - 1).bit_length())
    if l1_mode:
        cost += n * 12 * max(1, (n - 1).bit_length())
    return {"kind": "general", "rows": n, "span": n, "tiles": 1,
            "bytes_per_row": bpr, "operand_bytes": cost}


def _pack_key_bits(fields) -> list:
    """Concatenates (uint32 array, nbits) fields MSB-first into uint32 keys.

    Lexicographic comparison of the returned key list equals lexicographic
    comparison of the field tuple (bit concatenation preserves order).
    Total bits must not exceed _KEY_BITS; a trailing partial key is
    left-aligned (zero-padded on the right, same order).
    """
    keys = []
    acc = None
    filled = 0
    for arr, nbits in fields:
        arr = arr.astype(jnp.uint32)
        remaining = nbits
        while remaining > 0:
            if acc is None:
                acc = jnp.zeros(arr.shape, dtype=jnp.uint32)
                filled = 0
            take = min(32 - filled, remaining)
            part = (arr >> jnp.uint32(remaining - take)) & jnp.uint32(
                (1 << take) - 1)
            acc = (acc << jnp.uint32(take)) | part if filled else part
            filled += take
            remaining -= take
            if filled == 32:
                keys.append(acc)
                acc = None
    if acc is not None:
        keys.append(acc << jnp.uint32(32 - filled))
    return keys


def _extract_key_bits(keys, start: int, nbits: int) -> jnp.ndarray:
    """Reads bit field [start, start+nbits) back out of packed keys.

    Bit 0 is the MSB of keys[0] (the packing order of _pack_key_bits).
    nbits must be < 32.
    """
    out = None
    end = start + nbits
    for i, kk in enumerate(keys):
        k_lo, k_hi = 32 * i, 32 * i + 32
        lo, hi = max(start, k_lo), min(end, k_hi)
        if lo >= hi:
            continue
        part = (kk >> jnp.uint32(k_hi - hi)) & jnp.uint32(
            (1 << (hi - lo)) - 1)
        out = part if out is None else (out << jnp.uint32(hi - lo)) | part
    return out


def _prefix_changed(keys, prefix_bits: int) -> jnp.ndarray:
    """bool[n]: row's first prefix_bits differ from the previous row's
    (row 0 -> True). Used to find group/pid boundaries in packed-key
    sorted order without re-deriving the fields."""
    changed = None
    remaining = prefix_bits
    for kk in keys:
        if remaining <= 0:
            break
        if remaining >= 32:
            part = kk
        else:
            part = kk >> jnp.uint32(32 - remaining)
        c = part[1:] != part[:-1]
        changed = c if changed is None else (changed | c)
        remaining -= 32
    return jnp.concatenate([jnp.ones((1,), dtype=bool), changed])


def _sampler_randomness(key: jax.Array, n: int, randbits: int):
    """(salt, rand): the PRNG draws of the presorted samplers.

    Shared by the packed/tiled sort-key construction AND the hash-binned
    stage — draw-for-draw the same derivation (salt from the second split,
    per-row tiebreak bits from the first, truncated to the packed layout's
    rand field), so every sampler keyed the same way makes identical
    sampling decisions."""
    k1, k2 = jax.random.split(key)
    salt = jax.random.bits(k2, (), dtype=jnp.uint32)
    rand = jax.random.bits(k1, (n,), dtype=jnp.uint32)
    if randbits < 32:
        rand = rand >> jnp.uint32(32 - randbits)
    return salt, rand


def _packed_sort_fields(key: jax.Array, pid: jnp.ndarray, pk: jnp.ndarray,
                        valid: jnp.ndarray, *, num_partitions: int,
                        max_segments: int):
    """Shared key construction of the packed and tiled presorted samplers.

    Returns (keys, is_new_pid, segbits, pkbits): the three uint32 sort
    keys (padding rows already forced to all-ones, sorting strictly last)
    and the pid-boundary mask the tiled path bins from. Both samplers MUST
    derive their keys here — the tiled path's bit-parity contract is that
    its key sequence (and therefore every downstream sampling decision) is
    identical to the packed global sort's.
    """
    n = pid.shape[0]
    segbits, pkbits, randbits, padbits = packed_key_layout(
        n, num_partitions, max_segments)
    salt, rand = _sampler_randomness(key, n, randbits)
    ghash = _group_hash(pid, pk, salt)

    is_new_pid = valid & jnp.concatenate(
        [jnp.ones((1,), dtype=bool), pid[1:] != pid[:-1]])
    seg = jnp.maximum(jnp.cumsum(is_new_pid.astype(jnp.int32)) - 1,
                      0).astype(jnp.uint32)
    fields = [(seg, segbits), (ghash, 32),
              (pk.astype(jnp.uint32), pkbits), (rand, randbits)]
    if padbits:
        fields.append((jnp.zeros((n,), dtype=jnp.uint32), padbits))
    keys = _pack_key_bits(fields)
    # Padding rows sort strictly last: all-ones keys, and a valid row's
    # segment field is <= max_segments - 1 < 2^segbits - 1.
    ones = jnp.uint32(0xFFFFFFFF)
    keys = [jnp.where(valid, kk, ones) for kk in keys]
    return keys, is_new_pid, segbits, pkbits


def _sampled_from_packed(skeys, n: int, n_valid, segbits: int, pkbits: int,
                         linf_cap, l0_cap, sval, order) -> SampledRows:
    """Shared epilogue over packed-key-sorted rows: field extraction,
    segment/group boundaries, Linf/L0 sampling. Validity is positional
    (padding keys are all-ones, strictly above any valid key)."""
    svalid = jnp.arange(n, dtype=jnp.int32) < n_valid
    sseg = _extract_key_bits(skeys, 0, segbits).astype(jnp.int32)
    spk = _extract_key_bits(skeys, segbits + 32, pkbits).astype(jnp.int32)

    is_start = _prefix_changed(skeys, segbits + 32 + pkbits)
    keep_row = svalid & (_segment_rank(is_start) < linf_cap)
    group_id = (jnp.cumsum(is_start) - 1).astype(jnp.int32)
    is_pid_start = _prefix_changed(skeys, segbits)
    first_group_of_pid = jax.lax.cummax(
        jnp.where(is_pid_start, group_id, 0))
    group_rank = group_id - first_group_of_pid
    keep_group_row = svalid & (group_rank < l0_cap)
    return SampledRows(order, sseg, spk, svalid, is_start, group_id,
                       keep_row, keep_group_row, sval)


def _sample_rows_and_groups_presorted(key: jax.Array, pid: jnp.ndarray,
                                      pk: jnp.ndarray, valid: jnp.ndarray,
                                      linf_cap, l0_cap, *,
                                      num_partitions: int,
                                      max_segments: int,
                                      value: Optional[jnp.ndarray] = None,
                                      need_order: bool = False
                                      ) -> SampledRows:
    """The presorted-ingest twin of _sample_rows_and_groups.

    Contract (guaranteed structurally by wirecodec.decode_bucket):
      * valid is a prefix mask (valid == iota < n_valid);
      * pid is nondecreasing over the valid prefix;
      * the number of distinct pids among valid rows is <= max_segments.

    Because arrival order is already pid-major, the privacy id never rides
    the sort: rows get a dense pid-segment index (one cumsum), and
    (segment, group_hash, pk, random tiebreak) are bit-packed into three
    uint32 keys whose 3-key lexicographic comparison equals the general
    path's 4-field order. The value is the only payload, so the sort moves
    4 operands instead of 7. Validity is positional after the sort
    (padding keys are all-ones, strictly above any valid key), and ghash
    collisions resolve exactly like the general path: equal (seg, ghash)
    keys compare by the pk field, then the tiebreak, then stable order.

    Returned SampledRows: spid holds the segment index (the kernels only
    use pid equality structure); order is None unless need_order.
    """
    n = pid.shape[0]
    keys, _, segbits, pkbits = _packed_sort_fields(
        key, pid, pk, valid, num_partitions=num_partitions,
        max_segments=max_segments)

    operands = list(keys)
    if value is not None:
        operands.append(value)
    if need_order:
        operands.append(jnp.arange(n, dtype=jnp.int32))
    sorted_ops = jax.lax.sort(operands, num_keys=3, is_stable=True)
    skeys = sorted_ops[:3]
    sval = sorted_ops[3] if value is not None else None
    order = sorted_ops[-1] if need_order else None

    n_valid = jnp.sum(valid.astype(jnp.int32))
    return _sampled_from_packed(skeys, n, n_valid, segbits, pkbits,
                                linf_cap, l0_cap, sval, order)


def _sample_rows_and_groups_tiled(key: jax.Array, pid: jnp.ndarray,
                                  pk: jnp.ndarray, valid: jnp.ndarray,
                                  linf_cap, l0_cap, *,
                                  num_partitions: int,
                                  max_segments: int,
                                  tile_rows: int,
                                  tile_slack: int,
                                  value: Optional[jnp.ndarray] = None,
                                  need_order: bool = False
                                  ) -> SampledRows:
    """Bucketed segment-local twin of _sample_rows_and_groups_presorted.

    Same contract, same packed keys, BIT-IDENTICAL sampling decisions —
    but the sort runs over fixed-width tiles instead of the whole chunk,
    dropping sort cost from O(n log n) to O(n log B):

      1. one-pass hash-bucket binning: each row's pid-segment START index
         (a cummax over the pid boundaries, the same machinery as
         _segment_rank) assigns the whole segment to tile
         ``start // tile_rows`` — so no segment ever straddles a tile and
         tile t's segments all precede tile t+1's;
      2. rows gather into a [n_tiles, tile_rows + tile_slack] grid at slot
         ``row - tile * tile_rows`` (injective; slack absorbs a segment
         that begins near a tile's end — the caller guarantees no pid has
         more than tile_slack rows, derived from the wire's prep-time
         per-pid run counts), empty slots carrying all-ones keys;
      3. ONE batched stable 3-key sort along the tile axis — slots are in
         arrival order within each tile, so stable per-tile ties resolve
         exactly like the global stable sort's;
      4. tiles compact back to [n] by concatenating their valid prefixes
         (per-tile valid counts -> offsets -> a near-sequential gather).

    Equal keys never span tiles (equal seg => same segment => same tile),
    and segments are tile-ordered, so the concatenation IS the globally
    sorted sequence: identical keys, identical tie order, therefore
    identical SampledRows bits to the packed global sort.

    Contract violation backstop: if a segment exceeds tile_slack rows
    (corrupt wire metadata — the drivers' prep-count guard fires first on
    the native path), overflowing rows drop from the grid; the binned-row
    count then disagrees with n_valid and every row is invalidated, so a
    violated contract yields empty accumulators rather than a silently
    re-sampled release.
    """
    n = pid.shape[0]
    keys, is_new_pid, segbits, pkbits = _packed_sort_fields(
        key, pid, pk, valid, num_partitions=num_partitions,
        max_segments=max_segments)

    b = int(tile_rows)
    w = int(tile_rows + tile_slack)
    t = -(-n // b)
    idx = jnp.arange(n, dtype=jnp.int32)
    seg_start = jax.lax.cummax(jnp.where(is_new_pid, idx, 0))
    tile_of = seg_start // jnp.int32(b)

    # Grid gather: candidate source row of slot (tile, j) is
    # tile * tile_rows + j; it belongs there iff its segment starts in
    # this tile. Near-sequential reads (each row is probed by at most two
    # tiles), no scatter.
    src = (jnp.arange(t, dtype=jnp.int32)[:, None] * b
           + jnp.arange(w, dtype=jnp.int32)[None, :])
    srcc = jnp.minimum(src, n - 1)
    slot_valid = ((src < n) & valid[srcc]
                  & (tile_of[srcc]
                     == jnp.arange(t, dtype=jnp.int32)[:, None]))
    ones = jnp.uint32(0xFFFFFFFF)
    operands = [jnp.where(slot_valid, kk[srcc], ones) for kk in keys]
    if value is not None:
        operands.append(
            jnp.where(slot_valid, value[srcc],
                      jnp.zeros((), dtype=value.dtype)))
    if need_order:
        operands.append(jnp.where(slot_valid, srcc, n - 1))
    sorted_ops = jax.lax.sort(operands, dimension=1, num_keys=3,
                              is_stable=True)

    # Compaction: tile t's valid rows sorted to its prefix of length m[t];
    # output row i lives at (tile t*, slot i - offset[t*]).
    m = jnp.sum(slot_valid.astype(jnp.int32), axis=1)
    cum = jnp.cumsum(m)
    t_star = jnp.minimum(
        jnp.searchsorted(cum, idx, side="right").astype(jnp.int32), t - 1)
    j_star = idx - (cum[t_star] - m[t_star])
    flat = jnp.clip(t_star * w + j_star, 0, t * w - 1)

    n_valid = jnp.sum(valid.astype(jnp.int32))
    # Contract backstop (docstring): dropped rows invalidate everything.
    n_valid = jnp.where(cum[-1] == n_valid, n_valid, 0)
    tail = idx >= n_valid
    skeys = [jnp.where(tail, ones, op.reshape(-1)[flat])
             for op in sorted_ops[:3]]
    pos = 3
    sval = None
    if value is not None:
        sval = jnp.where(tail, jnp.zeros((), dtype=value.dtype),
                         sorted_ops[3].reshape(-1)[flat])
        pos = 4
    order = None
    if need_order:
        # Tail rows point at themselves: under the prefix-validity
        # contract those are exactly the padding input rows, so the
        # scatter-back in bound_row_mask never collides with a valid row.
        order = jnp.where(tail, idx, sorted_ops[pos].reshape(-1)[flat])
    return _sampled_from_packed(skeys, n, n_valid, segbits, pkbits,
                                linf_cap, l0_cap, sval, order)


class BinnedRows(NamedTuple):
    """The hash-binned (sortless) twin of SampledRows
    (``segment_sort="hash"``).

    Rows never ride a sort: each pid segment occupies one row of a
    ``[hash_bins, hash_bin_rows]`` grid (cells in arrival order), and
    the Linf/L0 sampling decisions come from keyed-priority selection
    inside each bin — pairwise comparisons against the SAME salt /
    truncated-rand draws the packed sort uses as its keys
    (``_sampler_randomness``), so the sampled row multiset is identical
    to the sort path's prefix-take for the same PRNG key.

    Row-domain fields (original arrival order, [n]): keep_row /
    keep_group_row are the Linf / L0 decisions; lead_row marks each
    KEPT group's leader (its first row in arrival order) — the slot the
    group's accumulator columns live at.

    Grid-domain fields ([hash_bins, hash_bin_rows] or [.., .., W]):
    trace-time context for the group reduce — ``same`` is the
    group-membership pairwise mask, ``contrib`` additionally gates the
    contributor by its Linf decision, ``cell`` maps each row to its
    flat grid cell, ``sval`` is the value gathered into the grid.

    ``ok`` is the contract backstop: False (a row failed to bin — the
    per-segment width contract was violated by corrupt wire metadata)
    empties every decision, so a violated contract yields empty
    accumulators rather than a silently re-sampled release, exactly
    like the tiled sampler's slack backstop.
    """
    keep_row: jnp.ndarray  # [n] Linf decision per row
    keep_group_row: jnp.ndarray  # [n] L0 decision of the row's group
    lead_row: jnp.ndarray  # [n] kept-group leader marker
    cell: jnp.ndarray  # [n] flat grid cell of each row
    same: jnp.ndarray  # [S, W, W] same-group pairwise mask
    contrib: jnp.ndarray  # [S, W, W] same-group & contributor-kept
    grid_valid: jnp.ndarray  # [S, W] occupied-cell mask
    spk: jnp.ndarray  # [S, W] partition ids on the grid
    sval: Optional[jnp.ndarray]  # [S, W] value on the grid
    ok: jnp.ndarray  # scalar backstop


def _bin_rows_and_groups_hash(key: jax.Array, pid: jnp.ndarray,
                              pk: jnp.ndarray, valid: jnp.ndarray,
                              linf_cap, l0_cap, *, num_partitions: int,
                              max_segments: int, hash_bins: int,
                              hash_bin_rows: int,
                              value: Optional[jnp.ndarray] = None
                              ) -> BinnedRows:
    """The sortless group-stage sampler: one scatter into per-segment
    bins, keyed-priority selection inside each bin, ZERO sort passes.

    Same presorted-ingest contract as the packed/tiled samplers (valid
    prefix, pid nondecreasing, distinct pids <= max_segments) plus the
    host-sized grid geometry: hash_bins >= the chunk's pid segments
    (the driver demotes chunks that do not fit — n_uniq > hash_bins —
    to the tiled kernel) and hash_bin_rows >= the longest single-pid
    run (row_packer prep stats, like tile_slack).

    Sampling-parity argument (the load-bearing contract): the packed
    sort orders rows by (segment, ghash, pk, rand, arrival) and takes
    per-group / per-segment prefixes. Here every decision is the rank
    form of the same order — a row's Linf rank is the count of
    same-group rows with smaller (rand, arrival), a group's L0 rank is
    the count of distinct same-segment groups with smaller (ghash, pk)
    — over the identical salt/rand draws (_sampler_randomness). The
    kept row multiset and kept group set are therefore IDENTICAL to the
    sort path's for the same key; only the accumulation order differs
    (which the int-exactness gate makes bit-invisible).
    """
    n = pid.shape[0]
    s_bins = int(hash_bins)
    w = int(hash_bin_rows)
    _, _, randbits, _ = packed_key_layout(n, num_partitions, max_segments)
    salt, rand = _sampler_randomness(key, n, randbits)
    ghash = _group_hash(pid, pk, salt)

    idx = jnp.arange(n, dtype=jnp.int32)
    is_new_pid = valid & jnp.concatenate(
        [jnp.ones((1,), dtype=bool), pid[1:] != pid[:-1]])
    seg = jnp.maximum(jnp.cumsum(is_new_pid.astype(jnp.int32)) - 1, 0)
    seg_start = jax.lax.cummax(jnp.where(is_new_pid, idx, 0))

    # Bin scatter: segment s's rows land in grid row s at their
    # within-segment position (injective; segments are arrival-
    # contiguous so the grid gather below is near-sequential). Segments
    # beyond hash_bins drop and trip the ok backstop.
    starts = jnp.zeros((s_bins,), jnp.int32).at[seg].max(seg_start,
                                                         mode="drop")
    src = starts[:, None] + jnp.arange(w, dtype=jnp.int32)[None, :]
    srcc = jnp.minimum(src, n - 1)
    grid_valid = ((src < n) & valid[srcc]
                  & (seg[srcc]
                     == jnp.arange(s_bins, dtype=jnp.int32)[:, None]))
    n_valid = jnp.sum(valid.astype(jnp.int32))
    ok = jnp.sum(grid_valid.astype(jnp.int32)) == n_valid

    ones32 = jnp.uint32(0xFFFFFFFF)
    bg = jnp.where(grid_valid, ghash[srcc], ones32)
    bpk = jnp.where(grid_valid, pk[srcc], _INT32_MAX).astype(jnp.int32)
    brand = jnp.where(grid_valid, rand[srcc], ones32)
    sval = None
    if value is not None:
        sval = jnp.where(grid_valid, value[srcc],
                         jnp.zeros((), dtype=value.dtype))

    # Pairwise keyed-priority selection, j = target cell, k = the other
    # cell of the same bin (XLA fuses each mask chain into its reduce —
    # nothing [S, W, W]-sized is materialized).
    cv_j = grid_valid[:, :, None]
    cv_k = grid_valid[:, None, :]
    tri = (jnp.arange(w, dtype=jnp.int32)[None, :, None]
           > jnp.arange(w, dtype=jnp.int32)[None, None, :])  # k before j
    same = (cv_j & cv_k
            & (bg[:, :, None] == bg[:, None, :])
            & (bpk[:, :, None] == bpk[:, None, :]))
    # Linf: rank within the group by (rand, arrival) — the packed
    # sort's tiebreak key and its stable tie order.
    before_in_group = same & ((brand[:, None, :] < brand[:, :, None])
                              | ((brand[:, None, :] == brand[:, :, None])
                                 & tri))
    keep_row_grid = grid_valid & (jnp.sum(before_in_group, axis=2)
                                  < linf_cap)
    is_leader = grid_valid & ~jnp.any(same & tri, axis=2)
    # L0: rank of the row's group among the segment's distinct groups
    # ordered by (ghash, pk) — count the leaders with a smaller key
    # (evaluated at every member: its key equals its leader's).
    gkey_lt = (is_leader[:, None, :] & cv_j
               & ((bg[:, None, :] < bg[:, :, None])
                  | ((bg[:, None, :] == bg[:, :, None])
                     & (bpk[:, None, :] < bpk[:, :, None]))))
    keep_group_grid = grid_valid & (jnp.sum(gkey_lt, axis=2) < l0_cap)
    contrib = same & keep_row_grid[:, None, :]

    # Gather the decisions back to the (smaller) row domain: each valid
    # row's cell is (seg, idx - seg_start); the backstop masks
    # everything when any row failed to bin.
    cell = jnp.clip(seg * w + (idx - seg_start), 0, s_bins * w - 1)
    rv = valid & ok
    keep_row = rv & keep_row_grid.reshape(-1)[cell]
    keep_group_row = rv & keep_group_grid.reshape(-1)[cell]
    lead_row = (rv & is_leader.reshape(-1)[cell]
                & keep_group_grid.reshape(-1)[cell])
    return BinnedRows(keep_row, keep_group_row, lead_row, cell, same,
                      contrib, grid_valid, bpk, sval, ok)


def _hash_group_sum(b: BinnedRows, col: jnp.ndarray) -> jnp.ndarray:
    """[n] per-group sum of a grid column over the group's KEPT rows,
    gathered back to each row (meaningful at lead_row slots). One fused
    mask-multiply-reduce over the bins — the sortless replacement for
    the sorted path's group segment-sum."""
    g = jnp.sum(jnp.where(b.contrib, col[:, None, :],
                          jnp.zeros((), dtype=col.dtype)), axis=2)
    return g.reshape(-1)[b.cell]


def _dispatch_sampler(key, pid, pk, valid, linf_cap, l0_cap, l1_cap, *,
                      num_partitions, max_segments, pid_sorted, tile_rows,
                      tile_slack, value, need_order=False,
                      hash_bins=0, hash_bin_rows=0):
    """Trace-time sampler dispatch shared by every bounding kernel.

    pid_sorted/max_segments/tile_*/hash_* are static and `l1_cap is
    None` is a pytree-structure (not value) test — the branch is
    deliberately resolved at trace time, like the need_* flags. All
    samplers produce the same sampling distribution; the packed, tiled
    and hash-binned presorted samplers additionally make BIT-identical
    sampling decisions (the hash path returns them as a
    :class:`BinnedRows` rank view instead of a sorted sequence —
    callers branch on the type at trace time).

    Dispatch order on presorted ingest: hash-binned (sortless group
    stage) when the grid geometry is set, else tiled, else the packed
    global sort; the general 4-key sort otherwise.
    """
    n = pid.shape[0]
    # dplint: disable=DPL003 — static/structural branch, resolved per compile
    if (pid_sorted and l1_cap is None
            and presorted_fits(n, num_partitions, max_segments)):
        max_seg = int(max_segments) if max_segments else n
        if hash_bins and hash_bin_rows:
            return _bin_rows_and_groups_hash(
                key, pid, pk, valid, linf_cap, l0_cap,
                num_partitions=num_partitions, max_segments=max_seg,
                hash_bins=hash_bins, hash_bin_rows=hash_bin_rows,
                value=value)
        if tile_rows and tile_rows + tile_slack < n:
            return _sample_rows_and_groups_tiled(
                key, pid, pk, valid, linf_cap, l0_cap,
                num_partitions=num_partitions, max_segments=max_seg,
                tile_rows=tile_rows, tile_slack=tile_slack, value=value,
                need_order=need_order)
        return _sample_rows_and_groups_presorted(
            key, pid, pk, valid, linf_cap, l0_cap,
            num_partitions=num_partitions, max_segments=max_seg,
            value=value, need_order=need_order)
    return _sample_rows_and_groups(key, pid, pk, valid, linf_cap, l0_cap,
                                   l1_cap, value=value,
                                   need_order=need_order)


def _narrow_sort_value(value, value_is_index: bool, value_sort_bits: int):
    """Value operand as it rides the sort: index payloads narrow to the
    smallest dtype their plane count fits (uint8/uint16), halving or
    quartering the payload bytes the sort moves."""
    if not value_is_index or value is None or not value_sort_bits:
        return value
    if value_sort_bits <= 8:
        return value.astype(jnp.uint8)
    if value_sort_bits <= 16:
        return value.astype(jnp.uint16)
    return value


def _widen_sorted_value(sval, value_is_index: bool, value_lo, value_scale):
    """(float value column, int32 index column or None) post-sort.

    The float expression mirrors wirecodec.decode_bucket's plane
    reconstruction bit for bit, so moving the widening to after the sort
    cannot change any released value.
    """
    if not value_is_index:
        return sval, None
    sval_i = sval.astype(jnp.int32)
    sval_f = (jnp.float32(value_lo)
              + sval_i.astype(jnp.float32) * jnp.float32(value_scale))
    return sval_f, sval_i


def _int_plan_bounds(plan_lo, plan_scale, plan_bits: int, row_clip_lo,
                     row_clip_hi, linf_cap
                     ) -> Optional[Tuple[int, int, float]]:
    """(int clip lo, int clip hi, max |clipped row value|) under the
    int-exactness gate, or None — the shared core of
    int_accumulation_plan and hash_exact_gate."""
    try:
        linf = int(linf_cap)
    except (TypeError, ValueError):
        return None
    lo, scale = float(plan_lo), float(plan_scale)
    if linf < 1 or not lo.is_integer() or not scale.is_integer():
        return None
    max_idx = (1 << int(plan_bits)) - 1
    if abs(lo) + max_idx * abs(scale) >= (1 << 24):
        return None
    bounds = [abs(lo), abs(lo + max_idx * scale)]
    iclo, ichi = -(2**31) + 1, 2**31 - 1
    for bound, is_lo in ((float(row_clip_lo), True),
                        (float(row_clip_hi), False)):
        if math.isfinite(bound):
            if not bound.is_integer():
                return None
            bounds.append(abs(bound))
            if is_lo:
                iclo = int(bound)
            else:
                ichi = int(bound)
        elif math.isnan(bound):
            return None
    if linf * max(bounds) >= (1 << 24):
        return None
    return iclo, ichi, float(max(bounds))


def int_accumulation_plan(plan_lo, plan_scale, plan_bits: int, row_clip_lo,
                          row_clip_hi, linf_cap
                          ) -> Optional[Tuple[int, int]]:
    """(int-domain row clip bounds) when the group-stage count and sum
    columns may accumulate in int32 BIT-IDENTICALLY to the float32 path,
    else None.

    Exactness argument: when the value grid (lo + idx * scale) and any
    finite row clip bound are integers, AND |lo| + max_idx * |scale| <
    2^24 (so the float32 reconstruction's intermediate product and sum
    are themselves exactly representable integers — without this a
    product >= 2^24 can round, e.g. lo=-16777215, scale=3, idx=5592407
    reconstructs 5.0 in float32 but 6 in int32), every per-row clipped
    value is the same exact integer in float32 AND int32; with at most
    linf_cap kept rows per group and linf_cap * max|value| < 2^24, every
    float32 partial sum of the legacy group segment-sum is an exactly
    representable integer — so the int32 sums widen to the same float32
    bits at the partition fold. Requires a concrete (host) linf_cap; a
    traced cap cannot be bounded statically.
    """
    r = _int_plan_bounds(plan_lo, plan_scale, plan_bits, row_clip_lo,
                         row_clip_hi, linf_cap)
    return None if r is None else (r[0], r[1])


def hash_exact_gate(plan_lo, plan_scale, plan_bits: int, row_clip_lo,
                    row_clip_hi, linf_cap, group_clip_lo, group_clip_hi,
                    cap_rows) -> bool:
    """Whether the hash-binned group stage is BIT-identical to the
    sorted paths at this configuration, regardless of reduction order.

    Strengthens the int_accumulation_plan gate so that EVERY float32
    partial sum anywhere in the kernel — group stage and partition fold,
    in any association — is an exactly representable integer, making
    the accumulation order (the only thing that differs between the
    hash-binned and sorted group stages; the sampled multiset is
    identical) bit-invisible:

      * the int plan holds (integer grid, integer row clips,
        linf_cap * max|v| < 2^24 — group partials exact);
      * finite group-sum clip bounds are integers (clipped group sums
        stay integers) — a clip can RAISE a magnitude (clip(5, 1000,
        inf) = 1000), so its bounds enter the partition bound below;
      * cap_rows < 2^24 (partition counts / pid-counts exact) and
        cap_rows * max(|v|, |finite group clips|) < 2^24 (partition
        sums exact: at most cap_rows groups, each bounded by the row
        total or its clip).

    The norm columns (mean/variance) are non-integer, so this gate
    only certifies kernels that do not read them — the auto dispatch
    additionally requires need_norm = need_norm_sq = False.
    """
    r = _int_plan_bounds(plan_lo, plan_scale, plan_bits, row_clip_lo,
                         row_clip_hi, linf_cap)
    if r is None:
        return False
    vmax = r[2]
    bound = vmax
    for b in (group_clip_lo, group_clip_hi):
        fb = float(b)
        if math.isnan(fb):
            return False
        if math.isfinite(fb):
            if not fb.is_integer():
                return False
            bound = max(bound, abs(fb))
    try:
        cap = int(cap_rows)
    except (TypeError, ValueError):
        return False
    return cap < (1 << 24) and cap * bound < (1 << 24)


def _hash_partition_accumulators(s: BinnedRows, pk: jnp.ndarray, *,
                                 num_partitions: int, row_clip_lo,
                                 row_clip_hi, middle, group_clip_lo,
                                 group_clip_hi, need_count, need_sum,
                                 need_norm, need_norm_sq, has_group_clip,
                                 value_is_index, value_lo, value_scale
                                 ) -> PartitionAccumulators:
    """Partition accumulators straight out of the hash bins: per-group
    sums at leader rows, then ONE stacked scatter covering every
    accumulator column ([num_partitions, n_cols] with a [n, n_cols]
    update set — the "one scatter per accumulator" shape, fused).

    The accumulation order differs from the sorted paths (row order vs
    group-sorted order), which the hash_exact_gate makes bit-invisible;
    outside the gate counts stay exact and sums are ULP-close.
    """
    sval, _ = _widen_sorted_value(s.sval, value_is_index, value_lo,
                                  value_scale)
    dtype = jnp.promote_types(sval.dtype, jnp.float32)
    vclip = jnp.clip(sval, row_clip_lo, row_clip_hi).astype(dtype)
    vnorm = vclip - middle
    gw = s.lead_row.astype(dtype)
    cols = [gw]  # pid_count: one per kept group
    if need_count:
        cols.append(_hash_group_sum(s, jnp.ones_like(vclip)) * gw)
    if need_sum:
        g_sum = _hash_group_sum(s, vclip)
        if has_group_clip:
            g_sum = jnp.clip(g_sum, group_clip_lo, group_clip_hi)
        cols.append(g_sum * gw)
    if need_norm:
        cols.append(_hash_group_sum(s, vnorm) * gw)
    if need_norm_sq:
        cols.append(_hash_group_sum(s, vnorm * vnorm) * gw)

    tgt = jnp.where(s.lead_row, pk, num_partitions).astype(jnp.int32)
    out = jnp.zeros((num_partitions, len(cols)), dtype).at[tgt].add(
        jnp.stack(cols, axis=-1), mode="drop")
    zeros = jnp.zeros((num_partitions,), dtype=dtype)
    slot = iter(range(1, len(cols)))
    return PartitionAccumulators(
        pid_count=out[:, 0],
        count=out[:, next(slot)] if need_count else zeros,
        sum=out[:, next(slot)] if need_sum else zeros,
        norm_sum=out[:, next(slot)] if need_norm else zeros,
        norm_sq_sum=out[:, next(slot)] if need_norm_sq else zeros,
    )


@functools.partial(jax.jit,
                   static_argnames=("num_partitions", "need_count",
                                    "need_sum", "need_norm",
                                    "need_norm_sq", "has_group_clip",
                                    "pid_sorted", "max_segments",
                                    "tile_rows", "tile_slack",
                                    "hash_bins", "hash_bin_rows",
                                    "value_is_index", "value_sort_bits",
                                    "int_accumulate"))
def bound_and_aggregate(key: jax.Array,
                        pid: jnp.ndarray,
                        pk: jnp.ndarray,
                        value: jnp.ndarray,
                        valid: jnp.ndarray,
                        *,
                        num_partitions: int,
                        linf_cap,
                        l0_cap,
                        row_clip_lo,
                        row_clip_hi,
                        middle,
                        group_clip_lo,
                        group_clip_hi,
                        l1_cap=None,
                        need_count: bool = True,
                        need_sum: bool = True,
                        need_norm: bool = True,
                        need_norm_sq: bool = True,
                        has_group_clip: bool = True,
                        pid_sorted: bool = False,
                        max_segments: Optional[int] = None,
                        tile_rows: int = 0,
                        tile_slack: int = 0,
                        hash_bins: int = 0,
                        hash_bin_rows: int = 0,
                        value_is_index: bool = False,
                        value_lo=0.0,
                        value_scale=1.0,
                        value_sort_bits: int = 0,
                        int_accumulate: bool = False,
                        int_clip_lo=None,
                        int_clip_hi=None
                        ) -> PartitionAccumulators:
    """Contribution bounding + per-partition aggregation, fully fused.

    Args:
      key: PRNG key for the sampling tiebreaks.
      pid, pk: int32[N] dense ids; pk in [0, num_partitions).
      value: float32[N].
      valid: bool[N] — False for padding rows.
      num_partitions: static partition-vocabulary size.
      linf_cap: max contributions kept per (pid, pk) — pass N to disable.
      l0_cap: max partitions kept per pid.
      row_clip_lo/hi: per-contribution clip bounds (+-inf to disable).
      middle: normalization midpoint for mean/variance sums.
      group_clip_lo/hi: per-partition-sum clip bounds (+-inf to disable) —
        the min/max_sum_per_partition mode of SumCombiner.
      l1_cap: max_contributions mode — uniform per-privacy-id total sample
        applied before everything else (pass linf/l0 caps >= data bounds).
      pid_sorted: the input satisfies the presorted-ingest contract (pid
        nondecreasing over a valid prefix — see
        _sample_rows_and_groups_presorted); the sampler then runs the
        cheaper packed-3-key sort. Same sampling distribution, different
        draws. Ignored in L1 mode (the L1 pre-sample breaks the
        prefix-validity invariant).
      max_segments: static upper bound on distinct pids among valid rows
        (presorted path only; tightens the packed segment field — the wire
        decode path passes its RLE entry capacity).
      tile_rows/tile_slack: static tile geometry of the bucketed
        segment-local sort (_sample_rows_and_groups_tiled); 0 keeps the
        global packed sort. Requires pid_sorted and tile_slack >= the
        longest single-pid run (the drivers derive it from the wire's
        prep-time per-pid counts). Bit-identical sampling either way.
      hash_bins/hash_bin_rows: static grid geometry of the sortless
        hash-binned group stage (_bin_rows_and_groups_hash;
        segment_sort="hash") — takes precedence over tile geometry.
        Requires pid_sorted, hash_bins >= the chunk's distinct pids and
        hash_bin_rows >= the longest single-pid run (both host-derived
        from the wire's prep stats; the drivers demote chunks that do
        not fit back to the tiled kernel). Identical sampled multiset;
        bit-identical released values under columnar.hash_exact_gate,
        ULP-close sums (exact counts) otherwise. int_accumulate is
        ignored on this path — under the gate its float32 sums are
        already exact integers, which is the same bits.
      value_is_index: the value column arrives as the int32 affine plane
        index of the wire codec (VALUE_PLANES); it rides the sort narrow
        (value_sort_bits picks uint8/uint16 when the plane count fits)
        and widens to float32 AFTER the sort with
        value_lo + idx * value_scale — the exact decode expression, so
        released values are unchanged.
      int_accumulate: accumulate the group-stage count and sum columns in
        int32, widening to float32 only at the partition fold. Only valid
        under the int_accumulation_plan gate (host-verified exactness —
        bit-identical to the float32 path); int_clip_lo/hi are the
        int-domain row clip bounds the plan returned. Ignored without a
        group stage.
    """
    n = pid.shape[0]
    if n == 0:
        # Same dtype contract as the non-empty path, which accumulates in
        # at least float32 regardless of the value dtype.
        zeros = jnp.zeros((num_partitions,), dtype=jnp.float32)
        return PartitionAccumulators(zeros, zeros, zeros, zeros, zeros)
    s = _dispatch_sampler(
        key, pid, pk, valid, linf_cap, l0_cap, l1_cap,
        num_partitions=num_partitions, max_segments=max_segments,
        pid_sorted=pid_sorted, tile_rows=tile_rows, tile_slack=tile_slack,
        hash_bins=hash_bins, hash_bin_rows=hash_bin_rows,
        value=_narrow_sort_value(value, value_is_index, value_sort_bits))
    if isinstance(s, BinnedRows):
        # Sortless group stage: per-group sums inside the bins, one
        # stacked scatter straight to the partition accumulators.
        return _hash_partition_accumulators(
            s, pk, num_partitions=num_partitions, row_clip_lo=row_clip_lo,
            row_clip_hi=row_clip_hi, middle=middle,
            group_clip_lo=group_clip_lo, group_clip_hi=group_clip_hi,
            need_count=need_count, need_sum=need_sum, need_norm=need_norm,
            need_norm_sq=need_norm_sq, has_group_clip=has_group_clip,
            value_is_index=value_is_index, value_lo=value_lo,
            value_scale=value_scale)
    sval, sval_i = _widen_sorted_value(s.sval, value_is_index, value_lo,
                                       value_scale)

    # -- rows -> (pid, pk) group accumulators ------------------------------
    # Separate scalar segment-sums over the sorted (monotone) group ids:
    # on TPU a narrow [N, k] operand is tile-padded k -> 128 lanes (a 20x
    # memory blowup), so per-column passes with indices_are_sorted=True are
    # the fast layout. The normalized columns are reduced directly (not
    # derived from sum/count algebra) so large-magnitude values keep full
    # float precision — (v - middle) is small even when v is not.
    # Accumulate in at least float32: a float16 value column must not
    # degrade counts, sums, or routing (only individual contributions may
    # carry reduced precision).
    dtype = jnp.promote_types(sval.dtype, jnp.float32)
    w = s.keep_row.astype(dtype)
    vclip = jnp.clip(sval, row_clip_lo, row_clip_hi).astype(dtype)
    vnorm = vclip - middle
    start_w = (s.is_start & s.svalid).astype(dtype)
    zeros = jnp.zeros((num_partitions,), dtype=dtype)
    if not has_group_clip:
        # No per-(pid, pk) group clipping: every accumulator is additive
        # over rows, so rows scatter STRAIGHT into partitions — the whole
        # group stage (and its per-column [N] passes) disappears.
        # Identical results: keep_group_row is constant within a group, so
        # sum_groups gw * (sum_rows w*x) == sum_rows (w * kg * x).
        kg = s.keep_group_row.astype(dtype)
        wk = w * kg
        spk_safe = jnp.where(s.svalid & s.keep_group_row, s.spk,
                             0).astype(jnp.int32)
        prow = functools.partial(jax.ops.segment_sum,
                                 segment_ids=spk_safe,
                                 num_segments=num_partitions)
        return PartitionAccumulators(
            pid_count=prow(start_w * kg),
            count=prow(wk) if need_count else zeros,
            sum=prow(vclip * wk) if need_sum else zeros,
            norm_sum=prow(vnorm * wk) if need_norm else zeros,
            norm_sq_sum=prow(vnorm * vnorm * wk)
            if need_norm_sq else zeros,
        )
    keepg_start = (s.is_start & s.svalid & s.keep_group_row).astype(dtype)
    gseg = functools.partial(jax.ops.segment_sum,
                             segment_ids=s.group_id,
                             num_segments=n,
                             indices_are_sorted=True)
    # Each gated-off accumulator saves one full-HBM group pass and one
    # partition pass (the kernel is pass-count bound; module docstring).
    if int_accumulate and sval_i is not None:
        # Narrow-dtype group accumulation (gate: int_accumulation_plan).
        # Counts and clipped sums are exact integers in both domains, so
        # the int32 sums widen to the legacy float32 bits at the fold.
        w_i = s.keep_row.astype(jnp.int32)
        vclip_i = jnp.clip(
            jnp.asarray(value_lo).astype(jnp.int32)
            + sval_i * jnp.asarray(value_scale).astype(jnp.int32),
            int_clip_lo, int_clip_hi)
        g_count = gseg(w_i).astype(dtype) if need_count else None
        g_sum = (jnp.clip(gseg(vclip_i * w_i).astype(dtype),
                          group_clip_lo, group_clip_hi)
                 if need_sum else None)
    else:
        g_count = gseg(w) if need_count else None
        g_sum = (jnp.clip(gseg(vclip * w), group_clip_lo, group_clip_hi)
                 if need_sum else None)
    g_norm = gseg(vnorm * w) if need_norm else None
    g_norm_sq = gseg(vnorm * vnorm * w) if need_norm_sq else None
    g_pk = _group_pk(s, num_partitions, gseg)
    g_keep = gseg(keepg_start)
    gw = (g_keep > 0).astype(dtype)

    # -- kept groups -> per-partition accumulators -------------------------
    g_pk_safe = jnp.where(g_keep > 0, g_pk, 0).astype(jnp.int32)
    pseg = functools.partial(jax.ops.segment_sum,
                             segment_ids=g_pk_safe,
                             num_segments=num_partitions)
    return PartitionAccumulators(
        pid_count=pseg(gw),
        count=pseg(g_count * gw) if need_count else zeros,
        sum=pseg(g_sum * gw) if need_sum else zeros,
        norm_sum=pseg(g_norm * gw) if need_norm else zeros,
        norm_sq_sum=pseg(g_norm_sq * gw) if need_norm_sq else zeros,
    )


class CompactGroups(NamedTuple):
    """One streamed chunk's per-partition subtotals in compact form.

    Instead of scattering the chunk's kept groups into the full
    [num_partitions] accumulators (a full-HBM partition pass per
    accumulator per chunk), the chunk emits its subtotals as at most
    ``max_groups`` (pk, value) pairs: every distinct partition the chunk
    touches contributes ONE entry per accumulator, already reduced in the
    chunk's group order. ``merge_compact_chunks`` folds any number of
    chunks into the dense accumulators with ONE scatter per accumulator
    column — bit-identical to the legacy per-chunk scatters when the
    group stage is active (the fold order per partition is the same:
    within-chunk group order, then chunk order).

    pk: int32[max_groups]; entries >= num_partitions (padding sentinel)
    or negative (empty runs) are dropped by the merge. The five value
    columns are float32[max_groups]; n_kept is the kept-group count (its
    contract is n_kept <= max_groups — the driver asserts it).
    """
    pk: jnp.ndarray
    pid_count: jnp.ndarray
    count: jnp.ndarray
    sum: jnp.ndarray
    norm_sum: jnp.ndarray
    norm_sq_sum: jnp.ndarray
    n_kept: jnp.ndarray


def _compact_from_groups(kept, g_pk_safe, cols, *, max_groups: int,
                         num_partitions: int, dtype) -> CompactGroups:
    """Compacts per-group accumulator columns (any layout: the sorted
    paths' [n] group slots or the hash path's [n] leader rows) into
    CompactGroups: kept entries pack to a [max_groups] prefix, a stable
    [max_groups] sort by pk groups equal partitions, and a run
    reduction emits ONE subtotal per partition — kept-entry order is
    preserved within a partition, so the sorted paths reproduce the
    legacy scatter's fold order bitwise."""
    g = max_groups
    pos = (jnp.cumsum(kept.astype(jnp.int32)) - 1)
    idx = jnp.where(kept, pos, g)
    cpk = jnp.full((g,), num_partitions, dtype=jnp.int32)
    cpk = cpk.at[idx].set(g_pk_safe, mode="drop")
    ccols = [jnp.zeros((g,), dtype=dtype).at[idx].set(c, mode="drop")
             for c in cols]

    # Stable sort by pk: equal-pk groups stay in kept order, so the run
    # reduction below adds them in exactly the legacy scatter's order.
    sorted_ops = jax.lax.sort([cpk] + ccols, num_keys=1, is_stable=True)
    spk_c = sorted_ops[0]
    is_run_start = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), spk_c[1:] != spk_c[:-1]])
    run_id = (jnp.cumsum(is_run_start) - 1).astype(jnp.int32)
    rseg = functools.partial(jax.ops.segment_sum, segment_ids=run_id,
                             num_segments=g, indices_are_sorted=True)
    run_pk = jax.ops.segment_max(spk_c, run_id, num_segments=g,
                                 indices_are_sorted=True)
    subtot = [rseg(c) for c in sorted_ops[1:]]
    n_kept = jnp.sum(kept.astype(jnp.int32))
    return CompactGroups(run_pk, subtot[0], subtot[1], subtot[2],
                         subtot[3], subtot[4], n_kept)


def _hash_compact_groups(s: BinnedRows, pk: jnp.ndarray, *,
                         num_partitions: int, max_groups: int,
                         row_clip_lo, row_clip_hi, middle, group_clip_lo,
                         group_clip_hi, need_count, need_sum, need_norm,
                         need_norm_sq, has_group_clip, value_is_index,
                         value_lo, value_scale) -> CompactGroups:
    """Compact per-group columns straight out of the hash bins (the
    compact-merge twin of _hash_partition_accumulators): group sums at
    leader rows compact to the shared CompactGroups shape, so the
    merge-side machinery (PR 5) is reused unchanged. Kept-group order
    is row (arrival) order rather than the sorted paths' group order —
    bit-invisible under hash_exact_gate, ULP-only otherwise."""
    sval, _ = _widen_sorted_value(s.sval, value_is_index, value_lo,
                                  value_scale)
    dtype = jnp.promote_types(sval.dtype, jnp.float32)
    vclip = jnp.clip(sval, row_clip_lo, row_clip_hi).astype(dtype)
    vnorm = vclip - middle
    gw = s.lead_row.astype(dtype)
    zeros_n = jnp.zeros_like(gw)
    g_sum = zeros_n
    if need_sum:
        g_sum = _hash_group_sum(s, vclip)
        if has_group_clip:
            g_sum = jnp.clip(g_sum, group_clip_lo, group_clip_hi)
    cols = (gw,
            _hash_group_sum(s, jnp.ones_like(vclip)) * gw
            if need_count else zeros_n,
            g_sum * gw if need_sum else zeros_n,
            _hash_group_sum(s, vnorm) * gw if need_norm else zeros_n,
            _hash_group_sum(s, vnorm * vnorm) * gw
            if need_norm_sq else zeros_n)
    g_pk_safe = jnp.where(s.lead_row, pk, 0).astype(jnp.int32)
    return _compact_from_groups(s.lead_row, g_pk_safe, cols,
                                max_groups=max_groups,
                                num_partitions=num_partitions, dtype=dtype)


@functools.partial(jax.jit,
                   static_argnames=("num_partitions", "max_groups",
                                    "need_count", "need_sum", "need_norm",
                                    "need_norm_sq", "has_group_clip",
                                    "pid_sorted", "max_segments",
                                    "tile_rows", "tile_slack",
                                    "hash_bins", "hash_bin_rows",
                                    "value_is_index", "value_sort_bits",
                                    "int_accumulate"))
def bound_and_aggregate_compact(key: jax.Array,
                                pid: jnp.ndarray,
                                pk: jnp.ndarray,
                                value: jnp.ndarray,
                                valid: jnp.ndarray,
                                *,
                                num_partitions: int,
                                max_groups: int,
                                linf_cap,
                                l0_cap,
                                row_clip_lo,
                                row_clip_hi,
                                middle,
                                group_clip_lo,
                                group_clip_hi,
                                l1_cap=None,
                                need_count: bool = True,
                                need_sum: bool = True,
                                need_norm: bool = True,
                                need_norm_sq: bool = True,
                                has_group_clip: bool = True,
                                pid_sorted: bool = False,
                                max_segments: Optional[int] = None,
                                tile_rows: int = 0,
                                tile_slack: int = 0,
                                hash_bins: int = 0,
                                hash_bin_rows: int = 0,
                                value_is_index: bool = False,
                                value_lo=0.0,
                                value_scale=1.0,
                                value_sort_bits: int = 0,
                                int_accumulate: bool = False,
                                int_clip_lo=None,
                                int_clip_hi=None
                                ) -> CompactGroups:
    """bound_and_aggregate that stops BEFORE the partition scatter.

    Identical sampling to bound_and_aggregate (same sampler, same
    statics, same key) and identical group accumulators; but instead of
    the final [num_partitions] segment-sums it compacts the kept groups
    (<= distinct pids * l0_cap, bounded statically by ``max_groups``),
    stable-sorts them by partition id and reduces each partition's run to
    ONE subtotal — in the kept groups' original order, which is exactly
    the order the legacy partition scatter adds them in. The caller
    merges any number of chunks with merge_compact_chunks.

    With has_group_clip=False the group stage still runs (no clip
    applied); the result equals the legacy direct row->partition scatter
    in exact arithmetic but may differ in float32 ULPs (different
    association), unlike the has_group_clip=True mode which is bitwise.
    """
    n = pid.shape[0]
    # Same trace-time sampler dispatch as bound_and_aggregate (shared
    # _dispatch_sampler) so the sampling decisions replay bitwise.
    s = _dispatch_sampler(
        key, pid, pk, valid, linf_cap, l0_cap, l1_cap,
        num_partitions=num_partitions, max_segments=max_segments,
        pid_sorted=pid_sorted, tile_rows=tile_rows, tile_slack=tile_slack,
        hash_bins=hash_bins, hash_bin_rows=hash_bin_rows,
        value=_narrow_sort_value(value, value_is_index, value_sort_bits))
    if isinstance(s, BinnedRows):
        return _hash_compact_groups(
            s, pk, num_partitions=num_partitions, max_groups=max_groups,
            row_clip_lo=row_clip_lo, row_clip_hi=row_clip_hi,
            middle=middle, group_clip_lo=group_clip_lo,
            group_clip_hi=group_clip_hi, need_count=need_count,
            need_sum=need_sum, need_norm=need_norm,
            need_norm_sq=need_norm_sq, has_group_clip=has_group_clip,
            value_is_index=value_is_index, value_lo=value_lo,
            value_scale=value_scale)
    sval, sval_i = _widen_sorted_value(s.sval, value_is_index, value_lo,
                                       value_scale)

    dtype = jnp.promote_types(sval.dtype, jnp.float32)
    w = s.keep_row.astype(dtype)
    vclip = jnp.clip(sval, row_clip_lo, row_clip_hi).astype(dtype)
    vnorm = vclip - middle
    keepg_start = (s.is_start & s.svalid & s.keep_group_row).astype(dtype)
    gseg = functools.partial(jax.ops.segment_sum,
                             segment_ids=s.group_id,
                             num_segments=n,
                             indices_are_sorted=True)
    zeros_n = jnp.zeros((n,), dtype=dtype)
    if int_accumulate and sval_i is not None:
        # Same narrow-dtype group accumulation as bound_and_aggregate
        # (gate: int_accumulation_plan; bit-identical widening).
        w_i = s.keep_row.astype(jnp.int32)
        vclip_i = jnp.clip(
            jnp.asarray(value_lo).astype(jnp.int32)
            + sval_i * jnp.asarray(value_scale).astype(jnp.int32),
            int_clip_lo, int_clip_hi)
        g_count = gseg(w_i).astype(dtype) if need_count else None
        g_sum = gseg(vclip_i * w_i).astype(dtype) if need_sum else None
    else:
        g_count = gseg(w) if need_count else None
        g_sum = gseg(vclip * w) if need_sum else None
    if need_sum and has_group_clip:
        g_sum = jnp.clip(g_sum, group_clip_lo, group_clip_hi)
    g_norm = gseg(vnorm * w) if need_norm else None
    g_norm_sq = gseg(vnorm * vnorm * w) if need_norm_sq else None
    g_pk = _group_pk(s, num_partitions, gseg)
    g_keep = gseg(keepg_start)
    gw = (g_keep > 0).astype(dtype)
    g_pk_safe = jnp.where(g_keep > 0, g_pk, 0).astype(jnp.int32)

    # The same scatter operands the legacy partition pass would feed
    # (value * gw, in group order) — compacted instead of scattered.
    cols = (gw,
            g_count * gw if need_count else zeros_n,
            g_sum * gw if need_sum else zeros_n,
            g_norm * gw if need_norm else zeros_n,
            g_norm_sq * gw if need_norm_sq else zeros_n)

    return _compact_from_groups(g_keep > 0, g_pk_safe, cols,
                                max_groups=max_groups,
                                num_partitions=num_partitions, dtype=dtype)


@functools.partial(jax.jit, static_argnames=("num_partitions",
                                             "need_flags"))
def merge_compact_chunks(accs: PartitionAccumulators,
                         pk: jnp.ndarray,
                         pid_count: jnp.ndarray,
                         count: jnp.ndarray,
                         sum_: jnp.ndarray,
                         norm_sum: jnp.ndarray,
                         norm_sq_sum: jnp.ndarray,
                         *,
                         num_partitions: int,
                         need_flags=(True, True, True, True)
                         ) -> PartitionAccumulators:
    """ONE [num_partitions] scatter per accumulator merges every chunk.

    Inputs are [n_chunks, max_groups] stacks of CompactGroups columns.
    The flatten is chunk-major, so per partition the scatter adds the
    chunk subtotals in chunk order on top of ``accs`` — reproducing the
    legacy loop's ``accs = accs + chunk_scatter`` fold bitwise (each
    chunk contributes at most one entry per partition). Sentinel /
    negative pk entries drop.
    """
    flat_pk = pk.reshape(-1)

    def scat(base, col):
        return base.at[flat_pk].add(col.reshape(-1), mode="drop")

    return PartitionAccumulators(
        pid_count=scat(accs.pid_count, pid_count),
        count=scat(accs.count, count) if need_flags[0] else accs.count,
        sum=scat(accs.sum, sum_) if need_flags[1] else accs.sum,
        norm_sum=(scat(accs.norm_sum, norm_sum)
                  if need_flags[2] else accs.norm_sum),
        norm_sq_sum=(scat(accs.norm_sq_sum, norm_sq_sum)
                     if need_flags[3] else accs.norm_sq_sum),
    )


def compact_group_bound(cap: int, ucap: int, l0_cap) -> Optional[int]:
    """Static kept-group bound for one chunk, or None when unavailable.

    Kept groups per pid-disjoint chunk <= distinct pids * l0_cap, and the
    RLE wire format bounds distinct pids per bucket by its entry capacity
    (ucap); total groups are also <= the row capacity (cap). Requires a
    concrete (host) l0_cap — a traced value cannot size a static shape.
    """
    try:
        l0 = int(l0_cap)
    except (TypeError, ValueError):
        return None
    if l0 < 1:
        return None
    bound = min(int(cap), int(ucap) * l0)
    return max(8, (bound + 7) & ~7)


def _group_pk(s: SampledRows, num_partitions: int, gseg) -> jnp.ndarray:
    """Each group slot's partition id: a float32-reduced column when ids
    fit float32 exactly (< 2^24), an integer pass otherwise. Always
    float32 regardless of the value dtype — a narrower accumulation dtype
    (e.g. float16 values) must never round partition ids. Single
    definition so the scalar and vector kernels can never diverge on the
    precision threshold or the padding mask."""
    if num_partitions < (1 << 24):
        start_w = (s.is_start & s.svalid).astype(jnp.float32)
        return gseg(start_w *
                    jnp.where(s.svalid, s.spk, 0).astype(jnp.float32))
    start_w_i = (s.is_start & s.svalid).astype(jnp.int32)
    return gseg(jnp.where(s.svalid, s.spk, 0) * start_w_i)


@functools.partial(jax.jit, static_argnames=("num_partitions", "norm_ord",
                                             "pid_sorted", "max_segments"))
def bound_and_aggregate_vector(key: jax.Array,
                               pid: jnp.ndarray,
                               pk: jnp.ndarray,
                               value: jnp.ndarray,
                               valid: jnp.ndarray,
                               *,
                               num_partitions: int,
                               linf_cap,
                               l0_cap,
                               max_norm,
                               norm_ord: int,
                               l1_cap=None,
                               pid_sorted: bool = False,
                               max_segments: Optional[int] = None
                               ) -> tuple[jnp.ndarray, PartitionAccumulators]:
    """VECTOR_SUM path: per-row norm clipping + the same two-stage sampling.

    value: float32[N, D]. norm_ord: 0 => Linf clip per coordinate, 1/2 =>
    L1/L2 norm scaling. Returns (vector_sums [num_partitions, D],
    scalar PartitionAccumulators) — the scalar accumulators ride along so
    callers never need a second pass over the rows.

    pid_sorted: the presorted-ingest contract of
    _sample_rows_and_groups_presorted holds (pid nondecreasing over a
    valid prefix); the sampler then runs the packed 3-key sort shared
    with the scalar path (_pack_key_bits layout) carrying only the row
    order — 4 sort operands instead of the general path's 7; the [N, D]
    vector payload is gathered once by the sorted order either way. Same
    sampling distribution, different draws; ignored in L1 mode.
    """
    n = pid.shape[0]
    d = value.shape[1]
    if n == 0:
        zeros = jnp.zeros((num_partitions,), dtype=value.dtype)
        return (jnp.zeros((num_partitions, d), dtype=value.dtype),
                PartitionAccumulators(zeros, zeros, zeros, zeros, zeros))
    s = _dispatch_sampler(key, pid, pk, valid, linf_cap, l0_cap, l1_cap,
                          num_partitions=num_partitions,
                          max_segments=max_segments,
                          pid_sorted=pid_sorted, tile_rows=0, tile_slack=0,
                          value=None, need_order=True)
    sval = value[s.order]

    if norm_ord == 0:
        sval = jnp.clip(sval, -max_norm, max_norm)
    else:
        norms = jnp.linalg.norm(sval, ord=norm_ord, axis=1, keepdims=True)
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norms, 1e-30))
        sval = sval * scale

    dtype = jnp.promote_types(sval.dtype, jnp.float32)
    sval = sval.astype(dtype)
    w1 = s.keep_row.astype(dtype)
    keepg_start = (s.is_start & s.svalid & s.keep_group_row).astype(dtype)
    gseg = functools.partial(jax.ops.segment_sum,
                             segment_ids=s.group_id,
                             num_segments=n,
                             indices_are_sorted=True)
    # The [N, D] vector payload is one segment-sum (D is a real data axis,
    # already tile-friendly); scalar columns go per pass like the scalar
    # kernel.
    g_vec = gseg(sval * w1[:, None])
    g_count = gseg(w1)
    g_pk = _group_pk(s, num_partitions, gseg)
    g_keep = gseg(keepg_start)
    gw = (g_keep > 0).astype(dtype)
    g_pk_safe = jnp.where(g_keep > 0, g_pk, 0).astype(jnp.int32)
    pseg = functools.partial(jax.ops.segment_sum,
                             segment_ids=g_pk_safe,
                             num_segments=num_partitions)
    vector_sums = pseg(g_vec * gw[:, None])
    zeros = jnp.zeros((num_partitions,), dtype=dtype)
    accs = PartitionAccumulators(pid_count=pseg(gw),
                                 count=pseg(g_count * gw),
                                 sum=zeros,
                                 norm_sum=zeros,
                                 norm_sq_sum=zeros)
    return vector_sums, accs


@functools.partial(jax.jit,
                   static_argnames=("pid_sorted", "max_segments",
                                    "num_partitions", "tile_rows",
                                    "tile_slack", "hash_bins",
                                    "hash_bin_rows"))
def bound_row_mask(key: jax.Array, pid: jnp.ndarray, pk: jnp.ndarray,
                   valid: jnp.ndarray, linf_cap, l0_cap,
                   l1_cap=None, *, pid_sorted: bool = False,
                   max_segments: Optional[int] = None,
                   num_partitions: Optional[int] = None,
                   tile_rows: int = 0,
                   tile_slack: int = 0,
                   hash_bins: int = 0,
                   hash_bin_rows: int = 0) -> jnp.ndarray:
    """Per-row keep mask (original row order) after Linf + L0 bounding.

    Identical sampling decisions to bound_and_aggregate for the same key —
    guaranteed structurally: all bounding kernels derive from the shared
    _sample_rows_and_groups pipeline (pass the SAME pid_sorted /
    max_segments / num_partitions statics as the aggregation kernel so the
    two sort with identical keys). This one returns which rows survive
    instead of aggregates — the row-level view needed by consumers that
    histogram individual contributions (e.g. the batched quantile trees of
    ops/quantiles.py).
    """
    n = pid.shape[0]
    if n == 0:
        return jnp.zeros((0,), dtype=bool)
    # Same trace-time sampler dispatch as bound_and_aggregate (shared
    # _dispatch_sampler, incl. the tiled path) so replayed sampling stays
    # identical.
    s = _dispatch_sampler(
        key, pid, pk, valid, linf_cap, l0_cap, l1_cap,
        num_partitions=num_partitions if num_partitions is not None else 0,
        max_segments=max_segments,
        pid_sorted=pid_sorted and num_partitions is not None,
        tile_rows=tile_rows, tile_slack=tile_slack,
        hash_bins=hash_bins, hash_bin_rows=hash_bin_rows, value=None,
        need_order=True)
    if isinstance(s, BinnedRows):
        # The hash-binned decisions are already in original row order.
        return s.keep_row & s.keep_group_row
    keep_sorted_rows = s.keep_row & s.keep_group_row
    return jnp.zeros((n,), dtype=bool).at[s.order].set(keep_sorted_rows)


@functools.partial(jax.jit, static_argnames=("num_partitions",))
def count_distinct_pids_per_partition(pid: jnp.ndarray, pk: jnp.ndarray,
                                      valid: jnp.ndarray, key: jax.Array,
                                      l0_cap, *,
                                      num_partitions: int) -> jnp.ndarray:
    """select_partitions fast path: L0-bounded distinct-pid counts per pk."""
    accs = bound_and_aggregate(key,
                               pid,
                               pk,
                               jnp.zeros_like(pid, dtype=jnp.float32),
                               valid,
                               num_partitions=num_partitions,
                               linf_cap=1,
                               l0_cap=l0_cap,
                               row_clip_lo=-jnp.inf,
                               row_clip_hi=jnp.inf,
                               middle=0.0,
                               group_clip_lo=-jnp.inf,
                               group_clip_hi=jnp.inf,
                               need_count=False,
                               need_sum=False,
                               need_norm=False,
                               need_norm_sq=False,
                               has_group_clip=False)
    return accs.pid_count
