"""The fused columnar DP-aggregation kernels.

This is the TPU-native replacement for the reference's per-row dataflow
(contribution_bounders.py + combiners.py + the per-key shuffle of
pipeline_backend.py): the whole bound-and-aggregate stage is two sorts and a
handful of segment reductions over fixed-shape arrays, entirely inside jit.

Dataflow (bound_and_aggregate):
  1. lexsort rows by (privacy_id, partition_key, uniform) — the uniform
     tiebreak makes each (pid, pk) group a random permutation, so "rank <
     cap" is exact sampling without replacement (the sample_fixed_per_key of
     the reference, done once for all keys).
  2. rank rows within (pid, pk) via a cummax over group-start indices; keep
     rank < max_contributions_per_partition  (Linf bounding).
  3. reduce rows -> (pid, pk) group accumulators with segment sums.
  4. lexsort groups by (pid, uniform); rank within pid; keep rank <
     max_partitions_contributed  (L0 bounding).
  5. reduce kept groups -> per-partition accumulators (count, clipped sum,
     normalized sum, normalized sum of squares, privacy-id count) with
     segment sums into [num_partitions] arrays.

All shapes static; caps and clip bounds are runtime scalars. Padding rows
(for sharding) carry valid=False and are routed to the end of the sort.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

_INT32_MAX = jnp.iinfo(jnp.int32).max


class PartitionAccumulators(NamedTuple):
    """Per-partition accumulators, each of shape [num_partitions]."""
    pid_count: jnp.ndarray  # distinct privacy units contributing
    count: jnp.ndarray  # kept contributions
    sum: jnp.ndarray  # clipped sum
    norm_sum: jnp.ndarray  # sum of (clip(v) - middle)
    norm_sq_sum: jnp.ndarray  # sum of (clip(v) - middle)^2


def _segment_rank(sorted_is_start: jnp.ndarray) -> jnp.ndarray:
    """Rank of each element within its (contiguous) segment."""
    n = sorted_is_start.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    seg_start = jax.lax.cummax(jnp.where(sorted_is_start, idx, 0))
    return idx - seg_start


class SampledRows(NamedTuple):
    """The Linf/L0 sampling decisions, in (pid, pk, uniform)-sorted order.

    The single source of truth for contribution bounding: every kernel
    (scalar, vector, row-mask) derives from this so their sampling stays
    bit-identical for the same PRNG key.
    """
    order: jnp.ndarray  # row permutation into sorted order
    spid: jnp.ndarray  # sorted pid keys (padding -> INT32_MAX)
    spk: jnp.ndarray  # sorted pk keys (padding -> INT32_MAX)
    svalid: jnp.ndarray  # sorted validity
    is_start: jnp.ndarray  # (pid, pk)-group start marker
    group_id: jnp.ndarray  # dense (pid, pk)-group index per sorted row
    keep_row: jnp.ndarray  # Linf sampling decision per sorted row
    keep_group: jnp.ndarray  # L0 sampling decision per group slot
    g_valid: jnp.ndarray  # group slot holds a real group


def _l1_sample_mask(key: jax.Array, pid: jnp.ndarray, valid: jnp.ndarray,
                    l1_cap) -> jnp.ndarray:
    """Keeps a uniform sample of at most l1_cap rows per privacy id.

    Exact replication of the reference's per-privacy-id L1 bounding
    (SamplingPerPrivacyIdContributionBounder,
    contribution_bounders.py:114-156): sort rows by (pid, uniform) — each
    privacy id's rows land in random order — and keep rank < l1_cap.
    """
    n = pid.shape[0]
    pid_key = jnp.where(valid, pid, _INT32_MAX)
    tiebreak = jax.random.uniform(key, (n,))
    order = jnp.lexsort((tiebreak, pid_key))
    spid = pid_key[order]
    is_start = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), spid[1:] != spid[:-1]])
    keep_sorted = valid[order] & (_segment_rank(is_start) < l1_cap)
    return jnp.zeros((n,), dtype=bool).at[order].set(keep_sorted)


def _sample_rows_and_groups(key: jax.Array, pid: jnp.ndarray,
                            pk: jnp.ndarray, valid: jnp.ndarray, linf_cap,
                            l0_cap, l1_cap=None) -> SampledRows:
    """Sorts rows by (pid, pk, uniform) and samples Linf rows / L0 groups.

    The uniform tiebreak makes each (pid, pk) group a random permutation,
    so "rank < cap" is exact sampling without replacement (the
    sample_fixed_per_key of the reference, done once for all keys).

    l1_cap (max_contributions mode): when given, a uniform sample of at
    most l1_cap rows per privacy id is taken FIRST — the total-contribution
    bound whose L1 sensitivity the noise is calibrated to. Passing
    linf/l0 caps >= the data bounds alongside reproduces the reference's
    L1-only bounding exactly.
    """
    n = pid.shape[0]
    k1, k2 = jax.random.split(key)
    if l1_cap is not None:
        valid = _l1_sample_mask(jax.random.fold_in(key, 3), pid, valid,
                                l1_cap)

    # Padding rows sort to the very end.
    pid_key = jnp.where(valid, pid, _INT32_MAX)
    pk_key = jnp.where(valid, pk, _INT32_MAX)

    # -- sort rows by (pid, pk, uniform), rank within (pid, pk) -----------
    tiebreak = jax.random.uniform(k1, (n,))
    order = jnp.lexsort((tiebreak, pk_key, pid_key))
    spid = pid_key[order]
    spk = pk_key[order]
    svalid = valid[order]
    is_start = jnp.concatenate([
        jnp.ones((1,), dtype=bool),
        (spid[1:] != spid[:-1]) | (spk[1:] != spk[:-1])
    ])
    keep_row = svalid & (_segment_rank(is_start) < linf_cap)
    group_id = (jnp.cumsum(is_start) - 1).astype(jnp.int32)

    # -- L0 sampling over (pid, pk) groups ---------------------------------
    start_w = (is_start & svalid).astype(jnp.int32)
    g_pid = jax.ops.segment_sum(spid * start_w, group_id, num_segments=n)
    g_valid = jax.ops.segment_sum(start_w, group_id, num_segments=n) > 0
    g_rand = jax.random.uniform(k2, (n,))
    g_pid_key = jnp.where(g_valid, g_pid, _INT32_MAX)
    order2 = jnp.lexsort((g_rand, g_pid_key))
    sg_pid = g_pid_key[order2]
    is_start2 = jnp.concatenate(
        [jnp.ones((1,), dtype=bool), sg_pid[1:] != sg_pid[:-1]])
    keep_sorted = _segment_rank(is_start2) < l0_cap
    keep_group = jnp.zeros((n,), dtype=bool).at[order2].set(keep_sorted)
    keep_group = keep_group & g_valid
    return SampledRows(order, spid, spk, svalid, is_start, group_id,
                       keep_row, keep_group, g_valid)


@functools.partial(jax.jit, static_argnames=("num_partitions",))
def bound_and_aggregate(key: jax.Array,
                        pid: jnp.ndarray,
                        pk: jnp.ndarray,
                        value: jnp.ndarray,
                        valid: jnp.ndarray,
                        *,
                        num_partitions: int,
                        linf_cap,
                        l0_cap,
                        row_clip_lo,
                        row_clip_hi,
                        middle,
                        group_clip_lo,
                        group_clip_hi,
                        l1_cap=None) -> PartitionAccumulators:
    """Contribution bounding + per-partition aggregation, fully fused.

    Args:
      key: PRNG key for the sampling tiebreaks.
      pid, pk: int32[N] dense ids; pk in [0, num_partitions).
      value: float32[N].
      valid: bool[N] — False for padding rows.
      num_partitions: static partition-vocabulary size.
      linf_cap: max contributions kept per (pid, pk) — pass N to disable.
      l0_cap: max partitions kept per pid.
      row_clip_lo/hi: per-contribution clip bounds (+-inf to disable).
      middle: normalization midpoint for mean/variance sums.
      group_clip_lo/hi: per-partition-sum clip bounds (+-inf to disable) —
        the min/max_sum_per_partition mode of SumCombiner.
      l1_cap: max_contributions mode — uniform per-privacy-id total sample
        applied before everything else (pass linf/l0 caps >= data bounds).
    """
    n = pid.shape[0]
    if n == 0:
        zeros = jnp.zeros((num_partitions,), dtype=value.dtype)
        return PartitionAccumulators(zeros, zeros, zeros, zeros, zeros)
    s = _sample_rows_and_groups(key, pid, pk, valid, linf_cap, l0_cap,
                                l1_cap)
    sval = value[s.order]

    # -- rows -> (pid, pk) group accumulators ------------------------------
    w = s.keep_row.astype(sval.dtype)
    vclip = jnp.clip(sval, row_clip_lo, row_clip_hi)
    vnorm = vclip - middle
    seg = functools.partial(jax.ops.segment_sum,
                            segment_ids=s.group_id,
                            num_segments=n)
    g_count = seg(w)
    g_sum = jnp.clip(seg(vclip * w), group_clip_lo, group_clip_hi)
    g_norm = seg(vnorm * w)
    g_norm_sq = seg(vnorm * vnorm * w)
    start_w = (s.is_start & s.svalid).astype(jnp.int32)
    g_pk = seg(s.spk * start_w)

    # -- kept groups -> per-partition accumulators -------------------------
    gw = s.keep_group.astype(sval.dtype)
    g_pk_safe = jnp.where(s.keep_group, g_pk, 0).astype(jnp.int32)
    pseg = functools.partial(jax.ops.segment_sum,
                             segment_ids=g_pk_safe,
                             num_segments=num_partitions)
    return PartitionAccumulators(
        pid_count=pseg(gw),
        count=pseg(g_count * gw),
        sum=pseg(g_sum * gw),
        norm_sum=pseg(g_norm * gw),
        norm_sq_sum=pseg(g_norm_sq * gw),
    )


@functools.partial(jax.jit, static_argnames=("num_partitions", "norm_ord"))
def bound_and_aggregate_vector(key: jax.Array,
                               pid: jnp.ndarray,
                               pk: jnp.ndarray,
                               value: jnp.ndarray,
                               valid: jnp.ndarray,
                               *,
                               num_partitions: int,
                               linf_cap,
                               l0_cap,
                               max_norm,
                               norm_ord: int,
                               l1_cap=None
                               ) -> tuple[jnp.ndarray, PartitionAccumulators]:
    """VECTOR_SUM path: per-row norm clipping + the same two-stage sampling.

    value: float32[N, D]. norm_ord: 0 => Linf clip per coordinate, 1/2 =>
    L1/L2 norm scaling. Returns (vector_sums [num_partitions, D],
    scalar PartitionAccumulators) — the scalar accumulators ride along so
    callers never need a second pass over the rows.
    """
    n = pid.shape[0]
    d = value.shape[1]
    if n == 0:
        zeros = jnp.zeros((num_partitions,), dtype=value.dtype)
        return (jnp.zeros((num_partitions, d), dtype=value.dtype),
                PartitionAccumulators(zeros, zeros, zeros, zeros, zeros))
    s = _sample_rows_and_groups(key, pid, pk, valid, linf_cap, l0_cap,
                                l1_cap)
    sval = value[s.order]

    if norm_ord == 0:
        sval = jnp.clip(sval, -max_norm, max_norm)
    else:
        norms = jnp.linalg.norm(sval, ord=norm_ord, axis=1, keepdims=True)
        scale = jnp.minimum(1.0, max_norm / jnp.maximum(norms, 1e-30))
        sval = sval * scale

    group_id = s.group_id
    w1 = s.keep_row.astype(sval.dtype)
    w = w1[:, None]
    g_vec = jax.ops.segment_sum(sval * w, group_id, num_segments=n)
    g_count = jax.ops.segment_sum(w1, group_id, num_segments=n)
    start_w = (s.is_start & s.svalid).astype(jnp.int32)
    g_pk = jax.ops.segment_sum(s.spk * start_w, group_id, num_segments=n)

    keep_group = s.keep_group
    gw = keep_group.astype(sval.dtype)
    g_pk_safe = jnp.where(keep_group, g_pk, 0).astype(jnp.int32)
    pseg = functools.partial(jax.ops.segment_sum,
                             segment_ids=g_pk_safe,
                             num_segments=num_partitions)
    vector_sums = pseg(g_vec * gw[:, None])
    zeros = jnp.zeros((num_partitions,), dtype=sval.dtype)
    accs = PartitionAccumulators(pid_count=pseg(gw),
                                 count=pseg(g_count * gw),
                                 sum=zeros,
                                 norm_sum=zeros,
                                 norm_sq_sum=zeros)
    return vector_sums, accs


@functools.partial(jax.jit)
def bound_row_mask(key: jax.Array, pid: jnp.ndarray, pk: jnp.ndarray,
                   valid: jnp.ndarray, linf_cap, l0_cap,
                   l1_cap=None) -> jnp.ndarray:
    """Per-row keep mask (original row order) after Linf + L0 bounding.

    Identical sampling decisions to bound_and_aggregate for the same key —
    guaranteed structurally: all bounding kernels derive from the shared
    _sample_rows_and_groups pipeline. This one returns which rows survive
    instead of aggregates — the row-level view needed by consumers that
    histogram individual contributions (e.g. the batched quantile trees of
    ops/quantiles.py).
    """
    n = pid.shape[0]
    if n == 0:
        return jnp.zeros((0,), dtype=bool)
    s = _sample_rows_and_groups(key, pid, pk, valid, linf_cap, l0_cap,
                                l1_cap)
    keep_sorted_rows = s.keep_row & s.keep_group[s.group_id]
    return jnp.zeros((n,), dtype=bool).at[s.order].set(keep_sorted_rows)


@functools.partial(jax.jit, static_argnames=("num_partitions",))
def count_distinct_pids_per_partition(pid: jnp.ndarray, pk: jnp.ndarray,
                                      valid: jnp.ndarray, key: jax.Array,
                                      l0_cap, *,
                                      num_partitions: int) -> jnp.ndarray:
    """select_partitions fast path: L0-bounded distinct-pid counts per pk."""
    accs = bound_and_aggregate(key,
                               pid,
                               pk,
                               jnp.zeros_like(pid, dtype=jnp.float32),
                               valid,
                               num_partitions=num_partitions,
                               linf_cap=1,
                               l0_cap=l0_cap,
                               row_clip_lo=-jnp.inf,
                               row_clip_hi=jnp.inf,
                               middle=0.0,
                               group_clip_lo=-jnp.inf,
                               group_clip_hi=jnp.inf)
    return accs.pid_count
