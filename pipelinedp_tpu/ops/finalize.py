"""Fused DP finalization epilogue: plan construction + one dispatch.

Everything that happens after the fused bound-and-aggregate kernel —
private partition selection, every combiner's noise draw, mean/variance
arithmetic, post-aggregation thresholding, keep-mask application and the
mesh-padding trim — used to run as a host-side Python loop over combiners,
one tiny device op per metric interleaved with blocking ``np.asarray``
syncs (jax_engine._compute_combiner_metrics). This module collapses that
epilogue into:

  * a static :class:`FinalizePlan`, derived from the compound combiner
    list: which accumulator columns feed which metrics, the noise mode per
    metric, the selection strategy kind, thresholding, the public-partition
    mask and the output-stddev flags. The plan is hashable and contains no
    budget-dependent values, so it doubles as the jit cache key;
  * per-execution :class:`FinalizeScalars`: noise scales / granularities /
    selection constants read off the *resolved* mechanism specs. They enter
    the compiled epilogue as dynamic operands, so the lazy-budget contract
    survives jit — recompilation never depends on budgets;
  * one compiled epilogue (:func:`epilogue_body` under ``jax.jit``) for the
    device-noise path, with all per-combiner draws batched into stacked
    ``[n_metrics, num_out]`` noise kernels (ops/noise.add_noise_batched):
    the per-metric keys reproduce the legacy
    ``split(fold_in(k_noise, i), 3)`` derivation bit-for-bit, so seeded
    device-noise runs are unchanged across the fusion (pinned by
    tests/finalize_test.py);
  * a float64 host twin (:func:`host_epilogue`) for the secure-host-noise
    path that keeps noise_core's full granularity snapping but consumes the
    accumulators from ONE batched device→host transfer instead of one
    blocking sync per metric, drawing host noise in the exact legacy order
    (so the seeded fallback RNG sequence is also unchanged);
  * an engine-level :class:`EpilogueCache` keyed on
    ``(plan, shapes, dtypes, mesh)``: a second ``aggregate`` call with the
    same query shape reuses the compiled executable with zero retraces
    (counted via profiler.count_event — see :func:`trace_count`).

Noise stddev outputs ride the plan as *scalars* and are expanded to
columns only at :func:`materialize` time (one ``np.full`` per released
dict instead of one per combiner per call).
"""

from __future__ import annotations

import dataclasses
import functools
import math
import threading
from collections import OrderedDict
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu import combiners as combiners_lib
from pipelinedp_tpu import dp_computations
from pipelinedp_tpu import noise_core
from pipelinedp_tpu import partition_selection as ps_lib
from pipelinedp_tpu import profiler
from pipelinedp_tpu.obs import trace as obs_trace
from pipelinedp_tpu.aggregate_params import NoiseKind
from pipelinedp_tpu.ops import noise as noise_ops
from pipelinedp_tpu.ops import selection as selection_ops

# Selection sentinels (plan.selection_kind). Non-negative values are
# ops/selection strategy kinds (TRUNCATED_GEOMETRIC / *_THRESHOLDING).
SEL_PUBLIC = -1  # keep the first num_partitions rows (public partitions)
SEL_EXISTS = -2  # keep partitions with data (post-agg thresholding prunes)

# Noise slot modes. 'select' is the branchless two-draw kernel
# (ops/noise.add_noise: laplace + gaussian drawn, one selected — the
# additive-mechanism path); 'laplace'/'gaussian' are the single-draw
# kernels (variance / vector sums, where the kind is static in the
# params); 'none' passes the accumulator through un-noised (zero
# sensitivity).
MODE_SELECT = "select"
MODE_LAPLACE = "laplace"
MODE_GAUSSIAN = "gaussian"
MODE_NONE = "none"

_TRACE_EVENT = "dp/finalize_traces"
_CACHE_HIT_EVENT = "dp/finalize_cache_hits"
_CACHE_MISS_EVENT = "dp/finalize_cache_misses"
_CACHE_EVICT_EVENT = "dp/finalize_cache_evictions"

# Max compiled executables the (default) EpilogueCache retains; LRU
# beyond it. Env knob PIPELINEDP_TPU_EPILOGUE_CACHE (README "Tuning
# knobs") — a serving deployment cycling through more than this many
# distinct query plans should raise it.
DEFAULT_CACHE_ENTRIES = 64
CACHE_ENTRIES_ENV = "PIPELINEDP_TPU_EPILOGUE_CACHE"


def cache_max_entries() -> int:
    """Validated PIPELINEDP_TPU_EPILOGUE_CACHE (default 64)."""
    from pipelinedp_tpu.native import loader
    return loader.env_int(CACHE_ENTRIES_ENV, DEFAULT_CACHE_ENTRIES, 1,
                          1 << 16)


@dataclasses.dataclass(frozen=True)
class NoiseSlot:
    """One noise draw of the epilogue.

    The key derivation replays the legacy per-combiner loop exactly:
    ``sub_key = fold_in(k_noise, comb_idx)`` then
    ``split(sub_key, 3)[split_idx]`` — so fused device noise is
    bit-identical to the unfused path for the same engine seed.
    """
    comb_idx: int  # index into compound.combiners
    split_idx: int  # which of split(sub_key, 3) keys the draw consumes
    source: str  # accumulator column ('count', 'norm_sum', ...) or 'vector'
    mode: str  # MODE_SELECT / MODE_LAPLACE / MODE_GAUSSIAN / MODE_NONE


@dataclasses.dataclass(frozen=True)
class FinalizePlan:
    """Static description of the whole post-aggregation path.

    Hashable (all-tuple payloads) and free of budget-dependent values:
    (eps, delta)-derived scales live in FinalizeScalars and enter the
    compiled epilogue as runtime operands.
    """
    ops: Tuple[tuple, ...]  # per-combiner op descriptors, in combiner order
    slots: Tuple[NoiseSlot, ...]
    out_columns: Tuple[tuple, ...]  # ordered ('col'|'qcol'|'stddev', name, i)
    selection_kind: int  # SEL_PUBLIC / SEL_EXISTS / ops.selection kind
    thresh_kind: int  # selection kind of post-agg thresholding, or -1
    thresh_comb_idx: int  # combiner index of the thresholding combiner
    num_partitions: int  # trim target (mesh padding is dropped here)
    has_vector: bool


@dataclasses.dataclass
class FinalizeScalars:
    """Per-execution dynamic values, read off resolved mechanism specs."""
    slot_isg: Tuple[bool, ...] = ()
    slot_scale: Tuple[float, ...] = ()
    slot_gran: Tuple[float, ...] = ()
    sel_strategy: Optional[ps_lib.PartitionSelection] = None
    sel_params: Optional[selection_ops.SelectionParams] = None
    thresh_strategy: Optional[ps_lib.PartitionSelection] = None
    thresh_params: Optional[selection_ops.SelectionParams] = None
    max_rows_per_pid: float = 1.0
    mean_middle: float = 0.0
    var_shift: float = 0.0
    stddevs: Dict[str, float] = dataclasses.field(default_factory=dict)


def _mechanism_noise_params(spec, sensitivities):
    """(is_gaussian, scale_or_std, granularity) for a resolved spec."""
    mech = dp_computations.create_additive_mechanism(spec, sensitivities)
    if mech.noise_kind == NoiseKind.GAUSSIAN:
        return True, mech.std, noise_core.gaussian_granularity(mech.std)
    return False, mech.noise_parameter, noise_core.laplace_granularity(
        mech.noise_parameter)


def _released_stddev(is_gaussian: bool, scale_or_std: float) -> float:
    """Stddev of the released additive noise (Laplace: b*sqrt(2))."""
    return (float(scale_or_std)
            if is_gaussian else float(scale_or_std) * math.sqrt(2.0))


def build_plan(combiners: Sequence[combiners_lib.Combiner],
               params,
               selection_spec,
               *,
               is_public: bool,
               num_partitions: int,
               max_rows_per_pid: int = 1
               ) -> Tuple[FinalizePlan, FinalizeScalars]:
    """Derives (plan, scalars) from a compound combiner list.

    Must run after BudgetAccountant.compute_budgets() — the scalars read
    eps/delta off the resolved specs (the lazy-budget contract: reading an
    unresolved spec raises). The plan itself is structural and would be
    identical across budgets.
    """
    ops: list = []
    slots: list = []
    out_columns: list = []
    scalars = FinalizeScalars()
    slot_isg: list = []
    slot_scale: list = []
    slot_gran: list = []
    stddevs: Dict[str, float] = {}
    thresh_kind = -1
    thresh_comb_idx = -1
    has_vector = False

    def add_slot(comb_idx, split_idx, source, mode, is_g, scale, gran) -> int:
        slots.append(NoiseSlot(comb_idx, split_idx, source, mode))
        slot_isg.append(bool(is_g))
        slot_scale.append(float(scale))
        slot_gran.append(float(gran))
        return len(slots) - 1

    for i, combiner in enumerate(combiners):
        if isinstance(combiner, combiners_lib.CountCombiner):
            is_g, scale, gran = _mechanism_noise_params(
                combiner.mechanism_spec(), combiner.sensitivities())
            slot = add_slot(i, 0, "count", MODE_SELECT, is_g, scale, gran)
            ops.append(("count", slot))
            out_columns.append(("col", "count", None))
            if params.output_noise_stddev:
                stddevs["count_noise_stddev"] = _released_stddev(is_g, scale)
                out_columns.append(("stddev", "count_noise_stddev", None))
        elif isinstance(combiner, combiners_lib.SumCombiner):
            is_g, scale, gran = _mechanism_noise_params(
                combiner.mechanism_spec(), combiner.sensitivities())
            slot = add_slot(i, 0, "sum", MODE_SELECT, is_g, scale, gran)
            ops.append(("sum", slot))
            out_columns.append(("col", "sum", None))
            if params.output_noise_stddev:
                stddevs["sum_noise_stddev"] = _released_stddev(is_g, scale)
                out_columns.append(("stddev", "sum_noise_stddev", None))
        elif isinstance(combiner, combiners_lib.PrivacyIdCountCombiner):
            is_g, scale, gran = _mechanism_noise_params(
                combiner.mechanism_spec(), combiner.sensitivities())
            slot = add_slot(i, 0, "pid_count", MODE_SELECT, is_g, scale,
                            gran)
            ops.append(("privacy_id_count", slot))
            out_columns.append(("col", "privacy_id_count", None))
            if params.output_noise_stddev:
                stddevs["privacy_id_count_noise_stddev"] = _released_stddev(
                    is_g, scale)
                out_columns.append(
                    ("stddev", "privacy_id_count_noise_stddev", None))
        elif isinstance(combiner,
                        combiners_lib.PostAggregationThresholdingCombiner):
            thresh = dp_computations.create_thresholding_mechanism(
                combiner.mechanism_spec(), combiner.sensitivities(),
                params.pre_threshold)
            scalars.thresh_strategy = thresh.strategy
            scalars.thresh_params = (
                selection_ops.selection_params_from_strategy(thresh.strategy))
            thresh_kind = scalars.thresh_params.kind
            thresh_comb_idx = i
            ops.append(("post_thresh",))
            out_columns.append(("col", "privacy_id_count", None))
            if params.output_noise_stddev:
                stddevs["privacy_id_count_noise_stddev"] = float(
                    thresh.strategy.noise_stddev)
                out_columns.append(
                    ("stddev", "privacy_id_count_noise_stddev", None))
        elif isinstance(combiner, combiners_lib.MeanCombiner):
            count_spec, sum_spec = combiner.mechanism_spec()
            cg, cs, cgr = _mechanism_noise_params(
                count_spec, combiner._count_sensitivities)
            sg, ss, sgr = _mechanism_noise_params(
                sum_spec, combiner._sum_sensitivities)
            c_slot = add_slot(i, 0, "count", MODE_SELECT, cg, cs, cgr)
            s_slot = add_slot(i, 1, "norm_sum", MODE_SELECT, sg, ss, sgr)
            scalars.mean_middle = dp_computations.compute_middle(
                params.min_value, params.max_value)
            names = combiner.metrics_names()
            ops.append(("mean", c_slot, s_slot, "count" in names,
                        "sum" in names))
            out_columns.append(("col", "mean", None))
            if "count" in names:
                out_columns.append(("col", "count", None))
            if "sum" in names:
                out_columns.append(("col", "sum", None))
        elif isinstance(combiner, combiners_lib.VarianceCombiner):
            p = combiner._params
            b_count, b_sum, b_sq = dp_computations.equally_split_budget(
                p.eps, p.delta, 3)
            l0 = params.max_partitions_contributed
            linf = params.max_contributions_per_partition
            middle = dp_computations.compute_middle(params.min_value,
                                                    params.max_value)
            sq_lo, sq_hi = dp_computations.compute_squares_interval(
                params.min_value, params.max_value)
            sq_middle = dp_computations.compute_middle(sq_lo, sq_hi)
            is_gaussian = params.noise_kind == NoiseKind.GAUSSIAN

            def var_slot(split_idx, source, eps_delta, linf_sens):
                if linf_sens == 0:
                    return add_slot(i, split_idx, source, MODE_NONE,
                                    is_gaussian, 0.0, 0.0)
                if is_gaussian:
                    sigma = noise_core.analytic_gaussian_sigma(
                        eps_delta[0], eps_delta[1],
                        dp_computations.compute_l2_sensitivity(l0, linf_sens))
                    return add_slot(i, split_idx, source, MODE_GAUSSIAN,
                                    True, sigma,
                                    noise_core.gaussian_granularity(sigma))
                scale = noise_core.laplace_diversity(
                    eps_delta[0],
                    dp_computations.compute_l1_sensitivity(l0, linf_sens))
                return add_slot(i, split_idx, source, MODE_LAPLACE, False,
                                scale, noise_core.laplace_granularity(scale))

            c_slot = var_slot(0, "count", b_count, linf)
            s_slot = var_slot(1, "norm_sum", b_sum,
                              linf * abs(middle - params.min_value))
            q_slot = var_slot(2, "norm_sq_sum", b_sq,
                              linf * abs(sq_middle - sq_lo))
            scalars.var_shift = (middle if params.min_value !=
                                 params.max_value else 0.0)
            names = combiner.metrics_names()
            ops.append(("variance", c_slot, s_slot, q_slot, "mean" in names,
                        "count" in names, "sum" in names))
            out_columns.append(("col", "variance", None))
            if "mean" in names:
                out_columns.append(("col", "mean", None))
            if "count" in names:
                out_columns.append(("col", "count", None))
            if "sum" in names:
                out_columns.append(("col", "sum", None))
        elif isinstance(combiner, combiners_lib.QuantileCombiner):
            # Quantile columns are finished before the epilogue (the
            # histogram/tree walk pipeline, ops/quantiles.py); the plan
            # just routes them into the released dict, in order.
            ops.append(("quantile",))
            for j, name in enumerate(combiner.metrics_names()):
                out_columns.append(("qcol", name, j))
        elif isinstance(combiner, combiners_lib.VectorSumCombiner):
            noise_params = combiner._params.additive_vector_noise_params
            if noise_params.noise_kind == NoiseKind.LAPLACE:
                l1 = (noise_params.l0_sensitivity *
                      noise_params.linf_sensitivity)
                scale = l1 / noise_params.eps_per_coordinate
                slot = add_slot(i, 0, "vector", MODE_LAPLACE, False, scale,
                                noise_core.laplace_granularity(scale))
                std = _released_stddev(False, scale)
            else:
                l2 = (math.sqrt(noise_params.l0_sensitivity) *
                      noise_params.linf_sensitivity)
                sigma = noise_core.analytic_gaussian_sigma(
                    noise_params.eps_per_coordinate,
                    noise_params.delta_per_coordinate, l2)
                slot = add_slot(i, 0, "vector", MODE_GAUSSIAN, True, sigma,
                                noise_core.gaussian_granularity(sigma))
                std = _released_stddev(True, sigma)
            has_vector = True
            ops.append(("vector_sum", slot))
            out_columns.append(("col", "vector_sum", None))
            if params.output_noise_stddev:
                stddevs["vector_sum_noise_stddev"] = std
                out_columns.append(
                    ("stddev", "vector_sum_noise_stddev", None))
        else:
            raise NotImplementedError(
                f"Combiner {type(combiner).__name__} is not supported on "
                f"the columnar engine.")

    if is_public:
        selection_kind = SEL_PUBLIC
    elif selection_spec is not None:
        declared_l0 = (params.max_partitions_contributed
                       or params.max_contributions or 1)
        strategy = ps_lib.create_partition_selection_strategy(
            params.partition_selection_strategy, selection_spec.eps,
            selection_spec.delta, declared_l0, params.pre_threshold)
        scalars.sel_strategy = strategy
        scalars.sel_params = selection_ops.selection_params_from_strategy(
            strategy)
        selection_kind = scalars.sel_params.kind
        scalars.max_rows_per_pid = float(max_rows_per_pid)
    else:
        selection_kind = SEL_EXISTS

    scalars.slot_isg = tuple(slot_isg)
    scalars.slot_scale = tuple(slot_scale)
    scalars.slot_gran = tuple(slot_gran)
    scalars.stddevs = stddevs
    plan = FinalizePlan(ops=tuple(ops),
                        slots=tuple(slots),
                        out_columns=tuple(out_columns),
                        selection_kind=selection_kind,
                        thresh_kind=thresh_kind,
                        thresh_comb_idx=thresh_comb_idx,
                        num_partitions=int(num_partitions),
                        has_vector=has_vector)
    return plan, scalars


# -- operand packing ---------------------------------------------------------


def device_operands(plan: FinalizePlan, scalars: FinalizeScalars, accs,
                    vector_sums, k_select, k_noise) -> dict:
    """The dynamic operand pytree for the compiled epilogue.

    Keys present depend only on the (static) plan, so the pytree structure
    is stable per plan and never forces a retrace. All scale-like values
    ship as float32 — the dtype the legacy eager path's weak-typed Python
    floats resolved to inside the kernels, keeping the fusion bit-exact.
    """
    op = {
        "accs": accs,
        "k_noise": k_noise,
        "slot_isg": np.asarray(scalars.slot_isg, dtype=bool),
        "slot_scale": np.asarray(scalars.slot_scale, dtype=np.float32),
        "slot_gran": np.asarray(scalars.slot_gran, dtype=np.float32),
    }
    if plan.has_vector:
        op["vector_sums"] = vector_sums
    if plan.selection_kind >= 0:
        op["k_select"] = k_select
        op["sel_floats"] = selection_ops.pack_operands(scalars.sel_params)
        op["max_rows_per_pid"] = np.float32(scalars.max_rows_per_pid)
    if plan.thresh_kind >= 0:
        op["thresh_floats"] = selection_ops.pack_operands(
            scalars.thresh_params)
    if any(entry[0] == "mean" for entry in plan.ops):
        op["mean_middle"] = np.float32(scalars.mean_middle)
    if any(entry[0] == "variance" for entry in plan.ops):
        op["var_shift"] = np.float32(scalars.var_shift)
    return op


def _slot_key(k_noise, slot: NoiseSlot):
    sub_key = jax.random.fold_in(k_noise, slot.comb_idx)
    return jax.random.split(sub_key, 3)[slot.split_idx]


@jax.jit
def variance_from_moments(dp_mean_sq, dp_mean_normalized):
    """DP variance from the two noised normalized moments.

    Compiled so the mul-into-sub pair FMA-contracts identically whether
    called standalone (the legacy per-combiner loop) or inlined in the
    fused epilogue's jit — eager op-by-op execution rounds the square
    separately and can differ in the last ulp (see
    ops/noise.add_noise_compiled).
    """
    return dp_mean_sq - dp_mean_normalized**2


# -- the fused device epilogue ----------------------------------------------


def epilogue_body(plan: FinalizePlan, op: dict):
    """Traced body of the fused epilogue: selection, batched noise,
    combiner arithmetic and post-aggregation thresholding in one
    executable. Returns (metric_columns, keep_mask) over the full
    (possibly mesh-padded) partition axis; materialize() trims and masks.
    """
    profiler.count_event(_TRACE_EVENT)
    accs = op["accs"]
    num_out = accs.pid_count.shape[0]
    partition_exists = accs.pid_count > 0

    if plan.selection_kind == SEL_PUBLIC:
        keep = jnp.arange(num_out) < plan.num_partitions
    elif plan.selection_kind == SEL_EXISTS:
        keep = partition_exists
    else:
        pid_counts_est = jnp.ceil(accs.pid_count / op["max_rows_per_pid"])
        sel_params = selection_ops.unpack_operands(plan.selection_kind,
                                                   op["sel_floats"])
        keep, _ = selection_ops.select_partitions(op["k_select"],
                                                  pid_counts_est, sel_params,
                                                  partition_exists)

    def source_of(slot: NoiseSlot):
        if slot.source == "vector":
            return op["vector_sums"]
        return getattr(accs, slot.source)

    # Batched noise: all scalar-column draws of one mode stack into a
    # single [n_metrics, num_out] kernel; vector sums (different shape)
    # draw individually. 'none' slots pass through un-noised.
    noised: Dict[int, jnp.ndarray] = {}
    groups: Dict[str, list] = {
        MODE_SELECT: [],
        MODE_LAPLACE: [],
        MODE_GAUSSIAN: []
    }
    for idx, slot in enumerate(plan.slots):
        if slot.mode == MODE_NONE:
            noised[idx] = source_of(slot)
        elif slot.source == "vector":
            vec_key = _slot_key(op["k_noise"], slot)
            if slot.mode == MODE_LAPLACE:
                noised[idx] = noise_ops.add_laplace_noise(
                    vec_key, op["vector_sums"], op["slot_scale"][idx],
                    op["slot_gran"][idx])
            else:
                noised[idx] = noise_ops.add_gaussian_noise(
                    vec_key, op["vector_sums"], op["slot_scale"][idx],
                    op["slot_gran"][idx])
        else:
            groups[slot.mode].append(idx)
    for mode, idxs in groups.items():
        if not idxs:
            continue
        keys = jnp.stack([_slot_key(op["k_noise"], plan.slots[i])
                          for i in idxs])
        values = jnp.stack([source_of(plan.slots[i]) for i in idxs])
        scales = jnp.stack([op["slot_scale"][i] for i in idxs])
        grans = jnp.stack([op["slot_gran"][i] for i in idxs])
        if mode == MODE_SELECT:
            is_g = jnp.stack([op["slot_isg"][i] for i in idxs])
            outs = noise_ops.add_noise_batched(keys, values, is_g, scales,
                                               grans)
        elif mode == MODE_LAPLACE:
            outs = noise_ops.add_laplace_noise_batched(keys, values, scales,
                                                       grans)
        else:
            outs = noise_ops.add_gaussian_noise_batched(keys, values, scales,
                                                        grans)
        for j, i in enumerate(idxs):
            noised[i] = outs[j]

    columns: Dict[str, jnp.ndarray] = {}
    for entry in plan.ops:
        tag = entry[0]
        if tag in ("count", "sum", "privacy_id_count", "vector_sum"):
            columns[tag] = noised[entry[1]]
        elif tag == "mean":
            _, c_slot, s_slot, emit_count, emit_sum = entry
            dp_count = noised[c_slot]
            dp_mean = op["mean_middle"] + noised[s_slot] / jnp.maximum(
                1.0, dp_count)
            columns["mean"] = dp_mean
            if emit_count:
                columns["count"] = dp_count
            if emit_sum:
                columns["sum"] = dp_mean * dp_count
        elif tag == "variance":
            _, c_slot, s_slot, q_slot, emit_mean, emit_count, emit_sum = entry
            dp_count = noised[c_slot]
            count_clamped = jnp.maximum(1.0, dp_count)
            dp_mean_normalized = noised[s_slot] / count_clamped
            dp_mean_sq = noised[q_slot] / count_clamped
            columns["variance"] = variance_from_moments(
                dp_mean_sq, dp_mean_normalized)
            dp_mean = dp_mean_normalized + op["var_shift"]
            if emit_mean:
                columns["mean"] = dp_mean
            if emit_count:
                columns["count"] = dp_count
            if emit_sum:
                columns["sum"] = dp_mean * dp_count
        elif tag == "post_thresh":
            thresh_params = selection_ops.unpack_operands(
                plan.thresh_kind, op["thresh_floats"])
            thresh_key = jax.random.fold_in(op["k_noise"],
                                            plan.thresh_comb_idx)
            thresh_keep, thresh_noised = selection_ops.select_partitions(
                thresh_key, accs.pid_count, thresh_params, partition_exists)
            keep = keep & thresh_keep
            columns["privacy_id_count"] = thresh_noised
        # 'quantile' entries route finished host columns in materialize().
    return columns, keep


# -- the float64 host epilogue ----------------------------------------------


def host_epilogue(plan: FinalizePlan, scalars: FinalizeScalars, accs,
                  vector_sums):
    """Secure-host-noise twin: float64 finalization over numpy
    accumulators that arrived in ONE batched device→host transfer.

    The draw order (selection uniforms, then per-combiner noise in
    combiner order) replays the legacy loop exactly, so a seeded fallback
    RNG produces the identical release.
    """
    pid_count = np.asarray(accs.pid_count)
    partition_exists = pid_count > 0

    if plan.selection_kind == SEL_PUBLIC:
        keep = np.arange(len(pid_count)) < plan.num_partitions
    elif plan.selection_kind == SEL_EXISTS:
        keep = partition_exists
    else:
        # float32 division + ceil to match the legacy device-computed
        # estimate bit-for-bit before the host selection draw.
        pid_counts_est = np.ceil(
            pid_count.astype(np.float32) /
            np.float32(scalars.max_rows_per_pid))
        sel_keep, _ = scalars.sel_strategy.select_vec(pid_counts_est)
        keep = sel_keep & partition_exists

    def source_of(slot: NoiseSlot):
        if slot.source == "vector":
            return np.asarray(vector_sums)
        return np.asarray(getattr(accs, slot.source))

    def draw(slot_idx: int):
        slot = plan.slots[slot_idx]
        values = source_of(slot)
        if slot.mode == MODE_NONE:
            return values
        if slot.mode == MODE_SELECT:
            return noise_core.add_noise_array(values,
                                              scalars.slot_isg[slot_idx],
                                              scalars.slot_scale[slot_idx])
        if slot.mode == MODE_LAPLACE:
            return noise_core.add_laplace_noise_array(
                values, scalars.slot_scale[slot_idx])
        return noise_core.add_gaussian_noise_array(
            values, scalars.slot_scale[slot_idx])

    columns: Dict[str, np.ndarray] = {}
    for entry in plan.ops:
        tag = entry[0]
        if tag in ("count", "sum", "privacy_id_count", "vector_sum"):
            columns[tag] = draw(entry[1])
        elif tag == "mean":
            _, c_slot, s_slot, emit_count, emit_sum = entry
            dp_count = draw(c_slot)
            dp_norm_sum = draw(s_slot)
            dp_mean = scalars.mean_middle + dp_norm_sum / np.maximum(
                1.0, dp_count)
            columns["mean"] = dp_mean
            if emit_count:
                columns["count"] = dp_count
            if emit_sum:
                columns["sum"] = dp_mean * dp_count
        elif tag == "variance":
            _, c_slot, s_slot, q_slot, emit_mean, emit_count, emit_sum = entry
            dp_count = draw(c_slot)
            count_clamped = np.maximum(1.0, dp_count)
            dp_mean_normalized = draw(s_slot) / count_clamped
            dp_mean_sq = draw(q_slot) / count_clamped
            columns["variance"] = dp_mean_sq - dp_mean_normalized**2
            dp_mean = dp_mean_normalized + scalars.var_shift
            if emit_mean:
                columns["mean"] = dp_mean
            if emit_count:
                columns["count"] = dp_count
            if emit_sum:
                columns["sum"] = dp_mean * dp_count
        elif tag == "post_thresh":
            thresh_keep, thresh_noised = scalars.thresh_strategy.select_vec(
                pid_count)
            keep = keep & (thresh_keep & partition_exists)
            columns["privacy_id_count"] = thresh_noised
    return columns, keep


# -- materialization ---------------------------------------------------------


def materialize(plan: FinalizePlan, scalars: FinalizeScalars,
                metric_cols: Dict[str, Any], keep_mask,
                quantile_cols=None) -> dict:
    """Final released dict: trim mesh padding to num_partitions, expand
    stddev scalars to columns, splice quantile columns, NaN-mask non-kept
    partitions — preserving the legacy column insertion order (the
    MetricsTuple field order consumers iterate)."""
    n = plan.num_partitions
    keep = np.asarray(keep_mask)[:n]
    out: dict = {}
    for kind, name, payload in plan.out_columns:
        if kind == "col":
            arr = np.asarray(metric_cols[name])[:n]
        elif kind == "qcol":
            arr = np.asarray(quantile_cols[:, payload])[:n]
        else:  # 'stddev': plan-scalar expanded only here
            arr = np.full(n, scalars.stddevs[name], dtype=np.float64)
        mask = keep if arr.ndim == 1 else keep[:, None]
        out[name] = np.where(mask, arr, np.nan)
    out["partition_id"] = np.arange(n, dtype=np.int32)
    out["keep_mask"] = keep
    return out


# -- the executable cache ----------------------------------------------------


def _abstract_signature(operands) -> tuple:
    leaves, treedef = jax.tree_util.tree_flatten(operands)
    return (treedef,
            tuple((tuple(np.shape(leaf)), str(np.asarray(leaf).dtype)
                   if not hasattr(leaf, "dtype") else str(leaf.dtype))
                  for leaf in leaves))


def _jit_entry(plan: FinalizePlan, op: dict):
    return epilogue_body(plan, op)


class EpilogueCache:
    """Engine-level executable cache for the fused epilogue.

    Keyed on (plan, operand shapes/dtypes, mesh): a second aggregate call
    with an identical query shape reuses the compiled executable with zero
    retraces (jit's own cache handles shapes/dtypes; this layer keeps one
    jitted callable per (plan, mesh) so the callable identity — and with
    it the jit cache — survives across engines). Hit/miss counts are
    exposed for the bench and mirrored into profiler event counters.

    Bounded and thread-safe: concurrent session queries
    (pipelinedp_tpu/serving/) share one cache, so lookups and insertions
    run under a lock, and the executable map LRU-evicts past
    ``max_entries`` (PIPELINEDP_TPU_EPILOGUE_CACHE; evicting an
    executable drops its jit cache with it — the next use of that plan
    recompiles). Evictions are counted (``evictions`` attribute and the
    dp/finalize_cache_evictions profiler counter). The seen-signature
    set behind the hit/miss counters is bounded to a multiple of
    max_entries, so the counters are approximate only once a plan has
    been evicted and returns.
    """

    # Signature-set bound per executable entry: each (plan, mesh) is
    # typically exercised at a handful of shapes.
    _SIGS_PER_ENTRY = 8

    def __init__(self, max_entries: Optional[int] = None):
        self._max_entries = (int(max_entries) if max_entries is not None
                             else cache_max_entries())
        if self._max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self._lock = threading.Lock()
        self._executables: "OrderedDict[tuple, Any]" = OrderedDict()
        self._seen_signatures: "OrderedDict[tuple, None]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    @property
    def max_entries(self) -> int:
        return self._max_entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._executables)

    def get(self, plan: FinalizePlan, mesh, operands, builder=None):
        """The compiled epilogue for (plan, mesh); counts whether this
        exact operand signature was seen before. builder(plan) supplies a
        mesh-aware jit (parallel/sharded.build_finalize_epilogue)."""
        signature = (plan, mesh, _abstract_signature(operands))
        key = (plan, mesh)
        with self._lock:
            if signature in self._seen_signatures:
                self._seen_signatures.move_to_end(signature)
                self.hits += 1
                profiler.count_event(_CACHE_HIT_EVENT)
            else:
                self.misses += 1
                self._seen_signatures[signature] = None
                while len(self._seen_signatures) > (
                        self._max_entries * self._SIGS_PER_ENTRY):
                    self._seen_signatures.popitem(last=False)
                profiler.count_event(_CACHE_MISS_EVENT)
                # A miss on the serving path usually means a retrace is
                # about to happen — exactly the "why was THIS query
                # slow" evidence a span wants.
                obs_trace.event("epilogue_cache_miss")
            fn = self._executables.get(key)
            if fn is None:
                if builder is not None:
                    fn = builder(plan)
                else:
                    fn = jax.jit(functools.partial(_jit_entry, plan))
                self._executables[key] = fn
                while len(self._executables) > self._max_entries:
                    self._executables.popitem(last=False)
                    self.evictions += 1
                    profiler.count_event(_CACHE_EVICT_EVENT)
            else:
                self._executables.move_to_end(key)
            return fn


_DEFAULT_CACHE: Optional[EpilogueCache] = None
_DEFAULT_CACHE_LOCK = threading.Lock()


def default_cache() -> EpilogueCache:
    """The process-wide cache engines share by default (so repeated
    queries from fresh engine instances still hit warm executables).
    Built lazily so the PIPELINEDP_TPU_EPILOGUE_CACHE knob is read (and
    validated) on first use, not at import."""
    global _DEFAULT_CACHE
    with _DEFAULT_CACHE_LOCK:
        if _DEFAULT_CACHE is None:
            _DEFAULT_CACHE = EpilogueCache()
        return _DEFAULT_CACHE


def trace_count() -> int:
    """How many times the fused epilogue has been traced (compiled) in
    this process. Steady-state serving must not move this counter."""
    return profiler.event_count(_TRACE_EVENT)


# -- at-most-once release ----------------------------------------------------


def release_token(key_stream_fingerprint: str,
                  key_counter: int = -1) -> tuple:
    """The identity of one noise release, tied to the KeyStream state.

    Two computations release "the same noise" exactly when they draw from
    the same key material — i.e. the same engine root key at the same
    KeyStream position, which is exactly (root fingerprint, counter)
    (jax_engine.KeyStream.fingerprint / .counter; every epilogue noise
    key derives from that pair). The engine commits this token to its
    ReleaseJournal (runtime/journal.py) immediately *before*
    finalization: a resumed or retried run that would re-draw
    already-released noise raises DoubleReleaseError instead of silently
    spending the same budget twice (see RESILIENCE.md).
    """
    return ("noise_release", str(key_stream_fingerprint), int(key_counter))
