"""Batched per-partition DP quantile trees for the columnar engine.

The reference computes PERCENTILE with one C++ QuantileTree object per
partition, built row by row and noised node by node during the quantile
walk (combiners.py:590-669 via PyDP). The TPU-native formulation builds
EVERY partition's tree at once: the leaf level is a single
[num_partitions, branching**height] histogram produced by one segment-sum
over the (already contribution-bounded) rows, upper levels are reshape-sums
of the leaf level, and each level gets one batched noise call. The quantile
walk is then pure post-processing of DP-released node counts — no privacy
left in it — so it runs as vectorized numpy over all partitions and all
requested quantiles at once.

Budget semantics match pipelinedp_tpu/quantile_tree.py (the host twin, and
through it the PyDP algorithm): eps/delta split evenly across tree levels;
per-level noise uses L1 sensitivity l0*linf (Laplace) or L2 sensitivity
sqrt(l0)*linf (Gaussian), since each contribution increments exactly one
node per level.
"""

from __future__ import annotations

import functools
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu import noise_core
from pipelinedp_tpu.ops import noise as noise_ops

# Guard for the dense [num_partitions, leaves] layout: above this many
# histogram elements (2^28 floats = 1 GiB), fall back to the host engine.
MAX_HISTOGRAM_ELEMENTS = 2**28


@functools.partial(jax.jit, static_argnames=("num_partitions", "num_leaves"))
def leaf_histograms(pk: jnp.ndarray, value: jnp.ndarray,
                    weights: jnp.ndarray, *, num_partitions: int,
                    num_leaves: int, lower, upper) -> jnp.ndarray:
    """[num_partitions, num_leaves] leaf counts of every partition's tree.

    ``weights`` is the per-row keep mask from contribution bounding
    (columnar.bound_row_mask); lower/upper are runtime scalars.
    """
    frac = (jnp.clip(value, lower, upper) - lower) / (upper - lower)
    leaf = jnp.minimum((frac * num_leaves).astype(jnp.int32), num_leaves - 1)
    seg = pk * num_leaves + leaf
    counts = jax.ops.segment_sum(weights.astype(jnp.float32), seg,
                                 num_segments=num_partitions * num_leaves)
    return counts.reshape(num_partitions, num_leaves)


def level_counts(leaf_hist: np.ndarray, branching: int,
                 height: int) -> List[np.ndarray]:
    """Per-level node counts derived from the leaf level by reshape-sums.

    Level l (0-based, children of the root first) has branching**(l+1)
    nodes per partition — same convention as QuantileTree._level_counts.
    """
    num_partitions = leaf_hist.shape[0]
    levels = []
    for level in range(height):
        nodes = branching**(level + 1)
        levels.append(
            leaf_hist.reshape(num_partitions, nodes, -1).sum(axis=2))
    return levels


def walk_quantiles(noised_levels: Sequence[np.ndarray],
                   quantiles: Sequence[float], lower: float, upper: float,
                   branching: int) -> np.ndarray:
    """[num_partitions, num_quantiles] quantile estimates from noised levels.

    Vectorized twin of QuantileTree._locate_quantile: descend level by
    level following the target rank; partitions whose subtree total drops
    to <= 0 resolve to the middle of their current range.
    """
    b = branching
    num_partitions = noised_levels[0].shape[0]
    num_q = len(quantiles)
    node = np.zeros((num_partitions, num_q), dtype=np.int64)
    lo = np.full((num_partitions, num_q), lower, dtype=np.float64)
    hi = np.full((num_partitions, num_q), upper, dtype=np.float64)
    target = np.tile(np.asarray(quantiles, dtype=np.float64),
                     (num_partitions, 1))
    dead = np.zeros((num_partitions, num_q), dtype=bool)
    dead_result = np.zeros((num_partitions, num_q), dtype=np.float64)

    for level_nodes in noised_levels:
        lvl = np.maximum(np.asarray(level_nodes, dtype=np.float64), 0.0)
        idx = node[:, :, None] * b + np.arange(b)  # [P, Q, b]
        children = np.take_along_axis(lvl[:, None, :], idx, axis=2)
        total = children.sum(axis=2)
        newly_dead = ~dead & (total <= 0)
        dead_result = np.where(newly_dead, lo + (hi - lo) / 2, dead_result)
        dead |= newly_dead
        cum = np.cumsum(children, axis=2)
        rank = target * total
        # searchsorted(cum, rank, side="right"), clipped to the last child.
        child = np.minimum((cum <= rank[:, :, None]).sum(axis=2), b - 1)
        child_count = np.take_along_axis(children, child[:, :, None],
                                         axis=2)[:, :, 0]
        below = np.take_along_axis(cum, child[:, :, None],
                                   axis=2)[:, :, 0] - child_count
        target = np.where(child_count > 0,
                          (rank - below) / np.maximum(child_count, 1e-300),
                          0.5)
        target = np.clip(target, 0.0, 1.0)
        width = (hi - lo) / b
        lo = lo + child * width
        hi = lo + width
        node = node * b + child
    out = lo + target * (hi - lo)
    return np.where(dead, dead_result, out)


def noised_levels_host(levels: Sequence[np.ndarray], eps: float, delta: float,
                       l0: int, linf: float,
                       is_gaussian: bool) -> List[np.ndarray]:
    """Secure host noise per level (float64, granularity-snapped sampler) —
    identical budget math to QuantileTree._noise_counts."""
    height = len(levels)
    eps_l, delta_l = eps / height, delta / height
    out = []
    for counts in levels:
        counts = np.asarray(counts, dtype=np.float64)
        if is_gaussian:
            sigma = noise_core.analytic_gaussian_sigma(
                eps_l, delta_l, np.sqrt(l0) * linf)
            out.append(counts + noise_core.sample_gaussian(
                sigma, counts.shape))
        else:
            scale = noise_core.laplace_diversity(eps_l, l0 * linf)
            out.append(counts + noise_core.sample_laplace(
                scale, counts.shape))
    return out


def noised_levels_device(key: jax.Array, levels: Sequence[jnp.ndarray],
                         eps: float, delta: float, l0: int, linf: float,
                         is_gaussian: bool) -> List[np.ndarray]:
    """Device-side batched noise per level (fast mode)."""
    height = len(levels)
    eps_l, delta_l = eps / height, delta / height
    if is_gaussian:
        sigma = noise_core.analytic_gaussian_sigma(eps_l, delta_l,
                                                   np.sqrt(l0) * linf)
        gran = noise_core.gaussian_granularity(sigma)
    else:
        scale = noise_core.laplace_diversity(eps_l, l0 * linf)
        gran = noise_core.laplace_granularity(scale)
    out = []
    for i, counts in enumerate(levels):
        k = jax.random.fold_in(key, i)
        if is_gaussian:
            out.append(np.asarray(
                noise_ops.add_gaussian_noise(k, counts, sigma, gran)))
        else:
            out.append(np.asarray(
                noise_ops.add_laplace_noise(k, counts, scale, gran)))
    return out
