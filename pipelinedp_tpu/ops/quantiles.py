"""Batched per-partition DP quantile trees for the columnar engine.

The reference computes PERCENTILE with one C++ QuantileTree object per
partition, built row by row and noised node by node during the quantile
walk (combiners.py:590-669 via PyDP). The TPU-native formulation builds
EVERY partition's tree at once: the leaf level is a single
[num_partitions, branching**height] histogram produced by one segment-sum
over the (already contribution-bounded) rows, upper levels are reshape-sums
of the leaf level, and each level gets one batched noise call. The quantile
walk is then pure post-processing of DP-released node counts — no privacy
left in it — so it runs as vectorized numpy over all partitions and all
requested quantiles at once.

Budget semantics match pipelinedp_tpu/quantile_tree.py (the host twin, and
through it the PyDP algorithm): eps/delta split evenly across tree levels;
per-level noise uses L1 sensitivity l0*linf (Laplace) or L2 sensitivity
sqrt(l0)*linf (Gaussian), since each contribution increments exactly one
node per level.

Sampling-replay contract: the per-row keep mask feeding leaf_histograms
comes from columnar.bound_row_mask called with the SAME key and the SAME
sort statics as the aggregation kernel of the run — including the
pid_sorted/max_segments flags and, since round 9, the tile_rows/tile_slack
geometry of the bucketed segment-local sort (streaming
._chunk_step_rle_quantile plumbs all four from the chunk's WireFormat).
The packed 3-key sort is where the sampling randomness lives, so any
divergence in sort configuration between the two kernels would silently
de-correlate "rows kept for COUNT/SUM" from "rows kept for PERCENTILE" of
one release. The tiled and global packed sorts are bit-identical by
construction (ops/columnar._sample_rows_and_groups_tiled), which is what
lets segment_sort="auto" flip geometry per chunk without touching the
replayed masks.
"""

from __future__ import annotations

import functools
from typing import List, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu import noise_core
from pipelinedp_tpu.ops import noise as noise_ops

# Dense [num_partitions, leaves] budget (2^28 floats = 1 GiB). Larger
# partition counts are processed in partition blocks of this many elements:
# rows are sorted by partition once, each block slices its row range and
# histograms into a [block, leaves] array — same released values, bounded
# memory.
MAX_HISTOGRAM_ELEMENTS = 2**28


def blocked_quantile_columns(spk: jnp.ndarray, sval: jnp.ndarray,
                             skeep: jnp.ndarray, row_bounds: np.ndarray, *,
                             num_partitions: int, num_leaves: int, lower,
                             upper, num_quantiles: int, finish_fn
                             ) -> np.ndarray:
    """[num_partitions, n_quantiles] DP quantiles, block by block.

    spk/sval/skeep: device arrays sorted by partition id (skeep is the
    contribution-bounding row mask, already permuted); row_bounds[p] is the
    host-side row offset of partition p in the sorted order. finish_fn
    turns one [block, num_leaves] histogram (device array) into the
    block's [block, n_quantiles] DP quantiles — noise + tree walk in
    whichever mode the engine runs (the eps/delta split is per tree, so
    per-block noising is identical to one global call: blocks partition
    the node space).
    """
    block_p = max(1, MAX_HISTOGRAM_ELEMENTS // num_leaves)
    starts = list(range(0, num_partitions, block_p))
    n_rows = int(spk.shape[0])
    out = np.zeros((num_partitions, num_quantiles), dtype=np.float64)
    for p0 in starts:
        p1 = min(p0 + block_p, num_partitions)
        rows_b = int(row_bounds[p1] - row_bounds[p0])
        if rows_b == 0 or n_rows == 0:
            # No contributions: zero trees (noise in finish_fn may still
            # release nonzero counts — same as dense on empty partitions).
            hist = jnp.zeros((block_p, num_leaves), dtype=jnp.float32)
        else:
            # Slice size = rows rounded up to a power of two, so skewed
            # blocks cost work proportional to their own rows while the
            # kernel compiles at most log2(n) shapes. The start clamp near
            # the array end is harmless: the in-block partition mask drops
            # neighbouring rows the padded window picks up.
            # rows_b <= n_rows, so the clamp never shrinks below rows_b.
            size = 1 << (rows_b - 1).bit_length()
            size = min(max(size, 1024), n_rows)
            start = min(int(row_bounds[p0]), n_rows - size)
            bpk = jax.lax.dynamic_slice_in_dim(spk, start, size)
            bval = jax.lax.dynamic_slice_in_dim(sval, start, size)
            bkeep = jax.lax.dynamic_slice_in_dim(skeep, start, size)
            weights = bkeep & (bpk >= p0) & (bpk < p1)
            hist = leaf_histograms(bpk - p0, bval, weights,
                                   num_partitions=block_p,
                                   num_leaves=num_leaves,
                                   lower=lower,
                                   upper=upper)
        # Full [block_p, leaves] shape even for the tail block, so the
        # noise/walk kernels compile once; only the output is trimmed.
        # (The extra padding partitions burn a little noise, not budget —
        # noise is per released node, and padding nodes are discarded.)
        out[p0:p1] = finish_fn(hist)[:p1 - p0]
    return out


@functools.partial(jax.jit, static_argnames=("num_partitions", "num_leaves"))
def leaf_histograms(pk: jnp.ndarray, value: jnp.ndarray,
                    weights: jnp.ndarray, *, num_partitions: int,
                    num_leaves: int, lower, upper) -> jnp.ndarray:
    """[num_partitions, num_leaves] leaf counts of every partition's tree.

    ``weights`` is the per-row keep mask from contribution bounding
    (columnar.bound_row_mask); lower/upper are runtime scalars.
    """
    frac = (jnp.clip(value, lower, upper) - lower) / (upper - lower)
    leaf = jnp.minimum((frac * num_leaves).astype(jnp.int32), num_leaves - 1)
    seg = pk * num_leaves + leaf
    counts = jax.ops.segment_sum(weights.astype(jnp.float32), seg,
                                 num_segments=num_partitions * num_leaves)
    return counts.reshape(num_partitions, num_leaves)


def level_counts(leaf_hist: np.ndarray, branching: int,
                 height: int) -> List[np.ndarray]:
    """Per-level node counts derived from the leaf level by reshape-sums.

    Level l (0-based, children of the root first) has branching**(l+1)
    nodes per partition — same convention as QuantileTree._level_counts.
    """
    num_partitions = leaf_hist.shape[0]
    levels = []
    for level in range(height):
        nodes = branching**(level + 1)
        levels.append(
            leaf_hist.reshape(num_partitions, nodes, -1).sum(axis=2))
    return levels


def _walk_impl(xp, noised_levels, quantiles_arr, lower, upper,
               branching: int, dtype, tiny):
    """The tree descent, shared by the host and device walks (xp = numpy
    or jax.numpy): descend level by level following the target rank;
    partitions whose subtree total drops to <= 0 resolve to the middle of
    their current range. Vectorized twin of
    QuantileTree._locate_quantile."""
    b = branching
    num_partitions = noised_levels[0].shape[0]
    num_q = quantiles_arr.shape[0]
    node = xp.zeros((num_partitions, num_q), dtype=xp.int32)
    lo = xp.full((num_partitions, num_q), lower, dtype=dtype)
    hi = xp.full((num_partitions, num_q), upper, dtype=dtype)
    target = xp.tile(quantiles_arr.astype(dtype), (num_partitions, 1))
    dead = xp.zeros((num_partitions, num_q), dtype=bool)
    dead_result = xp.zeros((num_partitions, num_q), dtype=dtype)

    for level_nodes in noised_levels:
        lvl = xp.maximum(level_nodes.astype(dtype), 0.0)
        idx = node[:, :, None] * b + xp.arange(b, dtype=xp.int32)  # [P,Q,b]
        children = xp.take_along_axis(lvl[:, None, :], idx, axis=2)
        total = children.sum(axis=2)
        newly_dead = ~dead & (total <= 0)
        dead_result = xp.where(newly_dead, lo + (hi - lo) / 2, dead_result)
        dead = dead | newly_dead
        cum = xp.cumsum(children, axis=2)
        rank = target * total
        # searchsorted(cum, rank, side="right"), clipped to the last child.
        child = xp.minimum((cum <= rank[:, :, None]).sum(axis=2), b - 1)
        child_count = xp.take_along_axis(children, child[:, :, None],
                                         axis=2)[:, :, 0]
        below = xp.take_along_axis(cum, child[:, :, None],
                                   axis=2)[:, :, 0] - child_count
        target = xp.where(child_count > 0,
                          (rank - below) / xp.maximum(child_count, tiny),
                          0.5)
        target = xp.clip(target, 0.0, 1.0)
        width = (hi - lo) / b
        lo = lo + child * width
        hi = lo + width
        node = node * b + child
    out = lo + target * (hi - lo)
    return xp.where(dead, dead_result, out)


def walk_quantiles(noised_levels: Sequence[np.ndarray],
                   quantiles: Sequence[float], lower: float, upper: float,
                   branching: int) -> np.ndarray:
    """[num_partitions, num_quantiles] quantile estimates (host, float64)."""
    levels = [np.asarray(lvl, dtype=np.float64) for lvl in noised_levels]
    return _walk_impl(np, levels, np.asarray(quantiles, dtype=np.float64),
                      lower, upper, branching, np.float64, 1e-300)


@functools.partial(jax.jit, static_argnames=("branching",))
def walk_quantiles_device(noised_levels, quantiles_arr: jnp.ndarray,
                          lower, upper, *, branching: int) -> jnp.ndarray:
    """Device twin of walk_quantiles (same _walk_impl descent, jnp ops,
    float32) so the O(partitions x nodes) noised levels never leave the
    device — only the [partitions, quantiles] result does."""
    return _walk_impl(jnp, noised_levels, quantiles_arr, lower, upper,
                      branching, jnp.float32, 1e-30)


def noised_levels_host(levels: Sequence[np.ndarray], eps: float, delta: float,
                       l0: int, linf: float,
                       is_gaussian: bool) -> List[np.ndarray]:
    """Secure host noise per level (float64, granularity-snapped sampler) —
    identical budget math to QuantileTree._noise_counts."""
    height = len(levels)
    eps_l, delta_l = eps / height, delta / height
    out = []
    for counts in levels:
        counts = np.asarray(counts, dtype=np.float64)
        if is_gaussian:
            sigma = noise_core.analytic_gaussian_sigma(
                eps_l, delta_l, np.sqrt(l0) * linf)
            out.append(counts + noise_core.sample_gaussian(
                sigma, counts.shape))
        else:
            scale = noise_core.laplace_diversity(eps_l, l0 * linf)
            out.append(counts + noise_core.sample_laplace(
                scale, counts.shape))
    return out


def noised_levels_device(key: jax.Array, levels: Sequence[jnp.ndarray],
                         eps: float, delta: float, l0: int, linf: float,
                         is_gaussian: bool) -> List[jnp.ndarray]:
    """Device-side batched noise per level (fast mode). Returns device
    arrays — feed them to walk_quantiles_device so the O(partitions x
    nodes) level counts never cross the host link."""
    height = len(levels)
    eps_l, delta_l = eps / height, delta / height
    if is_gaussian:
        sigma = noise_core.analytic_gaussian_sigma(eps_l, delta_l,
                                                   np.sqrt(l0) * linf)
        gran = noise_core.gaussian_granularity(sigma)
    else:
        scale = noise_core.laplace_diversity(eps_l, l0 * linf)
        gran = noise_core.laplace_granularity(scale)
    out = []
    for i, counts in enumerate(levels):
        k = jax.random.fold_in(key, i)
        if is_gaussian:
            out.append(
                noise_ops.add_gaussian_noise(k, jnp.asarray(counts), sigma,
                                             gran))
        else:
            out.append(
                noise_ops.add_laplace_noise(k, jnp.asarray(counts), scale,
                                            gran))
    return out
