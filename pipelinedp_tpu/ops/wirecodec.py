"""Lossless wire codec for the host->device row columns.

The end-to-end cost of the streaming engine is the host->device transfer
(BASELINE.md round-4 e2e analysis: ~1 GB of byte-packed columns over the
bench link vs a 3.25 s kernel). This module shrinks the bytes on the wire
*losslessly* by exploiting the structure the byte-packed layout ignores:

  * privacy ids repeat (~rows/users times each). Rows are stably sorted by
    pid inside each pid-disjoint bucket, so the pid column becomes a
    run-length list (unique id + uint16 run length; runs longer than 65535
    are split). 3 bytes/row -> ~0.3 bits/row at the benchmark shape.
  * partition keys are dense ids in [0, P): they need exactly
    ceil(log2(P)) bits, not a whole number of bytes. They ship as LSB-first
    bit-planes (bit j of 8 consecutive rows per byte) and are rebuilt on
    device with shifts and ors only — no gathers.
  * values are frequently discrete (the reference's north-star workload is
    movie ratings — /root/reference/examples/movie_view_ratings/
    run_without_frameworks.py: integer star ratings). `plan_value_encoding`
    detects an exact affine-integer representation v = lo + idx * scale,
    VERIFIES bit-exact float32 round-trip on the host, and ships idx as
    bit-planes. Values that fail the check ship as raw float32 (or float16
    under the existing lossy opt-in) — the codec never loses bits.

Everything for one bucket is flattened into a single row of a [k, W] uint8
slab, so a slab still ships as ONE device_put (per-transfer fixed costs on
tunneled links made many small puts strictly worse — see streaming.py).

Decode is elementwise + one cumsum + one small gather per bucket, far below
the kernel cost, and overlaps the next slab's transfer like the kernel does.

Host encode has two implementations that produce bit-identical buffers: the
multithreaded C++ packer (native/row_packer.cc, pdp_pack_buckets_rle) and
the numpy reference below (used as fallback and as the test oracle).

Role vs the reference: this is the TPU answer to the loader/shuffle layer
the reference delegates to Beam/Spark native runners
(pipeline_backend.py:38-195) — columnar, entropy-aware, and exact.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pipelinedp_tpu import profiler

# Same Knuth multiplicative hash as streaming.py's bucketing (buckets must
# stay pid-disjoint and identical across the codec and the legacy packer).
_HASH_MULT = np.uint32(2654435761)

# Value transfer modes (wire format tag; also the C++ ABI contract).
VALUE_NONE = 0  # COUNT-style: no value bytes on the wire
VALUE_PLANES = 1  # affine-integer bit-planes (lossless, host-verified)
VALUE_F32 = 2  # raw little-endian float32
VALUE_F16 = 3  # raw float16 (lossy ingest, existing opt-in)

# Privacy-id wire modes. PID_RLE requires the host radix sort (rows arrive
# on device pid-sorted per bucket — the load-bearing invariant the fused
# kernel's presorted sampler exploits); PID_PLANES ships the shifted ids as
# LSB-first bit-planes in arrival order and skips the host sort entirely —
# chosen when the RLE gain is small (near-unique ids), where the planes are
# BOTH fewer bytes and zero host sort (the device kernel sorts anyway).
PID_RLE = 0
PID_PLANES = 1

_MAX_VALUE_BITS = 20  # beyond ~1M distinct levels the planes stop paying
_RUN_SPLIT = 65535  # uint16 run-length limit; longer runs split


@dataclasses.dataclass(frozen=True)
class ValuePlan:
    """How the value column ships. lo/scale only meaningful for PLANES."""
    mode: int
    bits: int = 0
    lo: float = 0.0
    scale: float = 1.0


@dataclasses.dataclass(frozen=True)
class WireFormat:
    """Static shape/layout info shared by encoder and decoder.

    All fields are jit-static: one compile serves every bucket of a call.
    pid_mode PID_RLE lays out [uniq ids | uint16 runs | pk planes | value];
    PID_PLANES lays out [pid planes | pk planes | value] (bits_pid planes,
    arrival order, no sortedness guarantee).

    tile_rows/tile_slack describe the bucketed segment-local sort the
    kernel may run over the decoded rows (columnar tiled sampler): tiles
    of tile_rows rows, slack >= the longest single-pid run in any bucket
    (known on host from the prep-time per-pid counts). They are sort
    GEOMETRY, not wire layout — the byte offsets above are unaffected, and
    per-bucket tile offsets are derived on device in one pass from the
    RLE segment starts (offset arrays could not ride this dataclass: it
    must stay hashable/jit-static). 0 = untiled (global packed sort).
    """
    bytes_pid: int
    bits_pk: int
    cap: int  # padded rows per bucket, multiple of 8
    ucap: int  # padded RLE entries per bucket (PID_RLE only)
    value: ValuePlan
    pid_mode: int = PID_RLE
    bits_pid: int = 0  # pid bit-planes per row (PID_PLANES only)
    tile_rows: int = 0  # segment-local sort tile width (0 = untiled)
    tile_slack: int = 0  # per-tile slack >= max single-pid run
    # Sortless hash-binned group stage (segment_sort="hash";
    # plan_group_binning): per-segment bin count and bin width. Like the
    # tile fields this is kernel geometry, not wire layout; 0 = off.
    # Chunks whose RLE entry count exceeds hash_bins are demoted to the
    # tiled kernel per chunk by the drivers (never wrong bits).
    hash_bins: int = 0
    hash_bin_rows: int = 0  # bin width >= max single-pid run
    # VALUE_PLANES chunks ride the kernel sort as the narrow plane index
    # (widened to float32 after it — bit-identical releases). False
    # restores the round-8 widen-at-decode kernel; like the tile fields
    # this is kernel geometry, not wire layout (segment_sort=False).
    sort_value_narrow: bool = True

    @property
    def cap_bytes(self) -> int:
        return self.cap // 8

    @property
    def pid_sorted(self) -> bool:
        """Whether decoded rows are pid-sorted (the presorted-kernel
        invariant): structural for PID_RLE, never for PID_PLANES."""
        return self.pid_mode == PID_RLE

    @property
    def _offsets(self) -> Tuple[int, int, int, int]:
        if self.pid_mode == PID_PLANES:
            o_cnt = self.bits_pid * self.cap_bytes
            o_pk = o_cnt
        else:
            o_cnt = self.ucap * self.bytes_pid
            o_pk = o_cnt + self.ucap * 2
        o_val = o_pk + self.bits_pk * self.cap_bytes
        if self.value.mode == VALUE_PLANES:
            end = o_val + self.value.bits * self.cap_bytes
        elif self.value.mode == VALUE_F32:
            end = o_val + self.cap * 4
        elif self.value.mode == VALUE_F16:
            end = o_val + self.cap * 2
        else:
            end = o_val
        return o_cnt, o_pk, o_val, end

    @property
    def width(self) -> int:
        """Bytes per bucket row of the flat slab."""
        return self._offsets[3]


_SCALE_LADDER = (1.0, 0.5, 0.25, 0.125, 0.1, 0.05, 0.025, 0.01)


def _plan_preamble(value, value_f16):
    """Shared trivial-case handling. Returns (final_plan, None, ...) when
    the mode is decided without looking at scales, else
    (None, value_f32, lo, lo64, sample)."""
    if value is None:
        return ValuePlan(VALUE_NONE), None, None, None, None
    if value_f16:
        return ValuePlan(VALUE_F16), None, None, None, None
    value = np.asarray(value, dtype=np.float32)
    if value.size == 0:
        return ValuePlan(VALUE_F32), None, None, None, None
    lo64 = float(np.min(value))
    if not math.isfinite(lo64):
        return ValuePlan(VALUE_F32), None, None, None, None
    return None, value, np.float32(lo64), lo64, value[:65536]


def _gated_scales(sample, lo, lo64):
    """Scales from the ladder that pass the cheap 64k-sample gate (range
    check + bit-exact float32 reconstruction on the sample)."""
    for scale in _SCALE_LADDER:
        s = np.float32(scale)
        sidx = np.rint((sample.astype(np.float64) - lo64) / scale)
        if (sidx.max(initial=0.0) >= (1 << _MAX_VALUE_BITS)
                or sidx.min(initial=0.0) < 0):
            continue
        if np.array_equal(lo + sidx.astype(np.float32) * s, sample):
            yield scale, s


def plan_and_index(value: Optional[np.ndarray],
                   value_f16: bool = False
                   ) -> Tuple[ValuePlan, Optional[np.ndarray]]:
    """Chooses the value wire mode, verifying losslessness on the host.

    Tries v = lo + idx * scale for scale in a small dyadic/decimal ladder
    (a cheap sample-first check gates the full-array verification). The
    reconstruction check is done in float32 with the exact expression the
    device uses, so PLANES is bit-exact by construction. NaN/inf anywhere
    falls through to raw (NaN != NaN fails the check).

    Returns (plan, idx int32 array when plan is PLANES else None) — the
    index is computed once here and reused by the encoders (this host is
    single-pass-precious: one core, see BASELINE.md).
    """
    final, value, lo, lo64, sample = _plan_preamble(value, value_f16)
    if final is not None:
        return final, None
    for scale, s in _gated_scales(sample, lo, lo64):
        idx = _verified_index(value, lo, s, lo64, scale)
        if idx is not None:
            bits = max(1, int(idx.max(initial=0)).bit_length())
            return (ValuePlan(VALUE_PLANES, bits=bits, lo=float(lo),
                              scale=float(s)), idx)
    return ValuePlan(VALUE_F32), None


def _verified_index(value: np.ndarray, lo: np.float32, s: np.float32,
                    lo64: float, scale: float) -> Optional[np.ndarray]:
    """idx with lo + idx*scale == value verified bit-exact, or None.

    Chunked: the float64 intermediates live per-chunk (a full-array pass
    at 100M rows allocates multiple 800 MB temporaries and was measured
    ~6x slower than this on the single-core bench host).
    """
    n = len(value)
    out = np.empty(n, dtype=np.int32)
    step = 1 << 22
    for c0 in range(0, n, step):
        chunk = value[c0:c0 + step]
        idx = np.rint((chunk.astype(np.float64) - lo64) / scale)
        if (idx.max(initial=0.0) >= (1 << _MAX_VALUE_BITS)
                or idx.min(initial=0.0) < 0):
            return None
        idx32 = idx.astype(np.int32)
        if not np.array_equal(lo + idx32.astype(np.float32) * s, chunk):
            return None
        out[c0:c0 + step] = idx32
    return out


def plan_value_encoding(value: Optional[np.ndarray],
                        value_f16: bool = False) -> ValuePlan:
    """plan_and_index without the index (compatibility surface)."""
    return plan_and_index(value, value_f16)[0]


def _pack_le(out: np.ndarray, col: np.ndarray, nbytes: int) -> None:
    """Little-endian byte split of an int column into out[:, :nbytes]."""
    col = col.astype(np.uint32, copy=False)
    for b in range(nbytes):
        out[:, b] = (col >> np.uint32(8 * b)).astype(np.uint8)


def _pack_planes(out: np.ndarray, col: np.ndarray, bits: int) -> None:
    """LSB-first bit-planes: out[j, r >> 3] bit (r & 7) = bit j of col[r].

    out: [bits, cap // 8] uint8 (zeroed); col: [m] nonneg ints, m <= cap.
    """
    m = len(col)
    if m == 0:
        return
    col = col.astype(np.uint32, copy=False)
    cap8 = out.shape[1]
    for j in range(bits):
        bitvals = ((col >> np.uint32(j)) & np.uint32(1)).astype(np.uint8)
        padded = np.zeros(cap8 * 8, dtype=np.uint8)
        padded[:m] = bitvals
        # LSB-first within each byte (np.packbits is MSB-first -> bitorder).
        out[j, :] = np.packbits(padded, bitorder="little")


def encode_buckets_numpy(
    pid: np.ndarray,
    pk: np.ndarray,
    value: Optional[np.ndarray],
    *,
    pid_lo: int,
    k: int,
    bytes_pid: int,
    bits_pk: int,
    plan: ValuePlan,
    pid_mode: int = PID_RLE,
    bits_pid: int = 0,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, WireFormat]:
    """Numpy reference encoder. Returns (slab [k, W] uint8, n_rows [k],
    n_uniq [k], fmt). Bit-identical to the native packer's output.

    pid_mode PID_PLANES ships the shifted pid column as bits_pid bit-planes
    with rows grouped (stably) by the pid low byte — the same arrival order
    the native prep scatter produces, so the two encoders stay
    bit-identical in this mode too.
    """
    n = len(pid)
    shifted = (np.asarray(pid) - pid_lo).astype(np.uint32, copy=False)
    bucket = ((shifted * _HASH_MULT) >> np.uint32(16)) % np.uint32(k)
    counts = np.bincount(bucket, minlength=k).astype(np.int64)
    cap = _round8(int(counts.max()) if n else 8)

    vidx = None
    if plan.mode == VALUE_PLANES:
        vidx = np.rint(
            (np.asarray(value, dtype=np.float64) - float(plan.lo))
            / float(plan.scale)).astype(np.int64)

    if pid_mode == PID_PLANES:
        fmt = WireFormat(bytes_pid=bytes_pid, bits_pk=bits_pk, cap=cap,
                         ucap=8, value=plan, pid_mode=PID_PLANES,
                         bits_pid=bits_pid)
        slab = np.zeros((k, fmt.width), dtype=np.uint8)
        o_cnt, o_pk, o_val, _ = fmt._offsets
        for c in range(k):
            rows = np.flatnonzero(bucket == c)
            # Match the native prep scatter order (radix pass 0): stable
            # grouping by the pid low byte.
            order = rows[np.argsort(shifted[rows] & np.uint32(0xFF),
                                    kind="stable")]
            row = slab[c]
            pid_planes = row[:o_cnt].reshape(bits_pid, fmt.cap_bytes)
            _pack_planes(pid_planes, shifted[order], bits_pid)
            _emit_pk_and_value(row, fmt, plan, np.asarray(pk), value, vidx,
                               order, o_pk, o_val)
        return slab, counts, np.zeros(k, dtype=np.int64), fmt

    # Pass 1: per-bucket stable pid sort + RLE to size ucap exactly.
    orders, uniq_cols, cnt_cols = [], [], []
    for c in range(k):
        rows = np.flatnonzero(bucket == c)
        order = rows[np.argsort(shifted[rows], kind="stable")]
        orders.append(order)
        u, cts = _rle_split(shifted[order])
        uniq_cols.append(u)
        cnt_cols.append(cts)
    n_uniq = np.array([len(u) for u in uniq_cols], dtype=np.int64)
    ucap = _round8(int(n_uniq.max()) if n else 8)
    fmt = WireFormat(bytes_pid=bytes_pid, bits_pk=bits_pk, cap=cap,
                     ucap=ucap, value=plan)

    slab = np.zeros((k, fmt.width), dtype=np.uint8)
    o_cnt, o_pk, o_val, _ = fmt._offsets
    for c in range(k):
        order, u, cts = orders[c], uniq_cols[c], cnt_cols[c]
        row = slab[c]
        _pack_le(row[:len(u) * bytes_pid].reshape(-1, bytes_pid), u,
                 bytes_pid)
        _pack_le(row[o_cnt:o_cnt + len(cts) * 2].reshape(-1, 2), cts, 2)
        _emit_pk_and_value(row, fmt, plan, np.asarray(pk), value, vidx,
                           order, o_pk, o_val)
    return slab, counts, n_uniq, fmt


def _emit_pk_and_value(row, fmt, plan, pk, value, vidx, order, o_pk,
                       o_val) -> None:
    """Shared pk-planes + value tail of both numpy bucket layouts."""
    pk_planes = row[o_pk:o_pk + fmt.bits_pk * fmt.cap_bytes].reshape(
        fmt.bits_pk, fmt.cap_bytes)
    _pack_planes(pk_planes, pk[order], fmt.bits_pk)
    if plan.mode == VALUE_PLANES:
        val_planes = row[o_val:o_val + plan.bits * fmt.cap_bytes].reshape(
            plan.bits, fmt.cap_bytes)
        _pack_planes(val_planes, vidx[order], plan.bits)
    elif plan.mode == VALUE_F32:
        m = len(order)
        row[o_val:o_val + m * 4] = (np.asarray(
            value, dtype=np.float32)[order].view(np.uint8))
    elif plan.mode == VALUE_F16:
        m = len(order)
        row[o_val:o_val + m * 2] = (np.asarray(
            value, dtype=np.float32)[order].astype(
                np.float16).view(np.uint8))


def _round8(x: int) -> int:
    return max(8, (x + 7) & ~7)


def _rle_split(sorted_ids: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Run-length encode a sorted id column, splitting runs at _RUN_SPLIT."""
    if len(sorted_ids) == 0:
        return (np.zeros(0, dtype=np.uint32), np.zeros(0, dtype=np.uint32))
    change = np.flatnonzero(np.diff(sorted_ids)) + 1
    starts = np.concatenate([[0], change])
    ends = np.concatenate([change, [len(sorted_ids)]])
    u_out, c_out = [], []
    for s, e in zip(starts, ends):
        run = int(e - s)
        uid = sorted_ids[s]
        while run > _RUN_SPLIT:
            u_out.append(uid)
            c_out.append(_RUN_SPLIT)
            run -= _RUN_SPLIT
        u_out.append(uid)
        c_out.append(run)
    return (np.asarray(u_out, dtype=np.uint32),
            np.asarray(c_out, dtype=np.uint32))


# ---------------------------------------------------------------------------
# Device-side decode (all inside jit; fmt fields are static).
# ---------------------------------------------------------------------------


def _unpack_le(buf: jnp.ndarray, nbytes: int) -> jnp.ndarray:
    """[m, nbytes] uint8 -> int32 (little-endian)."""
    acc = buf[:, 0].astype(jnp.int32)
    for b in range(1, nbytes):
        acc = acc | (buf[:, b].astype(jnp.int32) << (8 * b))
    return acc


def _unpack_planes(planes: jnp.ndarray, bits: int, cap: int) -> jnp.ndarray:
    """[bits, cap//8] uint8 bit-planes -> int32 [cap]. Elementwise only."""
    shifts = jnp.arange(8, dtype=jnp.uint8)
    acc = jnp.zeros((cap,), dtype=jnp.int32)
    for j in range(bits):
        b = ((planes[j][:, None] >> shifts) & jnp.uint8(1)).reshape(cap)
        acc = acc | (b.astype(jnp.int32) << j)
    return acc


def decode_bucket(
    row: jnp.ndarray,
    n_valid: jnp.ndarray,
    n_uniq: jnp.ndarray,
    fmt: WireFormat,
    value_as_index: bool = False,
) -> Tuple[jnp.ndarray, jnp.ndarray, Optional[jnp.ndarray], jnp.ndarray]:
    """Decode one bucket row of the slab -> (pid, pk, value|None, valid).

    pid is the shifted (pid - pid_lo) id. In PID_RLE mode rows come back in
    the bucket's pid-sorted order — nondecreasing by construction (sorted
    RLE entries expanded in sequence, padding repeating the last id), which
    is the invariant the fused kernel's presorted sampler relies on. In
    PID_PLANES mode rows are in arrival order (no sortedness guarantee).
    Rows >= n_valid are garbage with valid=False.

    value_as_index (VALUE_PLANES only): return the raw int32 plane index
    instead of the reconstructed float32 — the kernel then carries the
    narrow index through its sort and widens with the identical
    ``lo + idx * scale`` float32 expression afterwards, so released
    values are bit-for-bit unchanged.
    """
    o_cnt, o_pk, o_val, _ = fmt._offsets
    cap, ucap = fmt.cap, fmt.ucap

    if fmt.pid_mode == PID_PLANES:
        pid = _unpack_planes(
            row[:o_cnt].reshape(fmt.bits_pid, fmt.cap_bytes), fmt.bits_pid,
            cap)
    else:
        uniq = _unpack_le(row[:o_cnt].reshape(ucap, fmt.bytes_pid),
                          fmt.bytes_pid)
        cnts = _unpack_le(row[o_cnt:o_pk].reshape(ucap, 2), 2)
        uvalid = jnp.arange(ucap, dtype=jnp.int32) < n_uniq
        cnts = jnp.where(uvalid, cnts, 0)
        starts = jnp.cumsum(cnts) - cnts
        # Padded entries scatter out of range and are dropped.
        starts = jnp.where(uvalid, starts, cap)
        run_of_row = jnp.cumsum(
            jnp.zeros((cap,), jnp.int32).at[starts].add(1, mode="drop")) - 1
        run_of_row = jnp.clip(run_of_row, 0, ucap - 1)
        pid = uniq[run_of_row]

    pk = _unpack_planes(
        row[o_pk:o_val].reshape(fmt.bits_pk, fmt.cap_bytes), fmt.bits_pk,
        cap)

    plan = fmt.value
    if plan.mode == VALUE_PLANES:
        idx = _unpack_planes(
            row[o_val:o_val + plan.bits * fmt.cap_bytes].reshape(
                plan.bits, fmt.cap_bytes), plan.bits, cap)
        if value_as_index:
            valid = jnp.arange(cap, dtype=jnp.int32) < n_valid
            return pid, pk, idx, valid
        # Must mirror the host verification expression exactly (f32 ops).
        value = (jnp.float32(plan.lo)
                 + idx.astype(jnp.float32) * jnp.float32(plan.scale))
    elif plan.mode == VALUE_F32:
        b = row[o_val:o_val + cap * 4].reshape(cap, 4)
        u32 = (b[:, 0].astype(jnp.uint32)
               | (b[:, 1].astype(jnp.uint32) << 8)
               | (b[:, 2].astype(jnp.uint32) << 16)
               | (b[:, 3].astype(jnp.uint32) << 24))
        value = jax.lax.bitcast_convert_type(u32, jnp.float32)
    elif plan.mode == VALUE_F16:
        b = row[o_val:o_val + cap * 2].reshape(cap, 2)
        u16 = (b[:, 0].astype(jnp.uint16)
               | (b[:, 1].astype(jnp.uint16) << 8))
        value = jax.lax.bitcast_convert_type(u16, jnp.float16).astype(
            jnp.float32)
    else:
        value = None

    valid = jnp.arange(cap, dtype=jnp.int32) < n_valid
    return pid, pk, value, valid


# ---------------------------------------------------------------------------
# Native dispatch.
# ---------------------------------------------------------------------------


def _load_packer():
    """The native row-packer library, or None (cached by the loader).

    Only loader/build failures fall back (the codec is an optimization;
    loader.LOADER_ERRORS is the typed set) — anything else, including
    NativeRequiredError under PIPELINEDP_TPU_REQUIRE_NATIVE=1, must
    propagate rather than silently downgrade to the numpy encoder (the
    `_pack_native` pattern, ops/streaming.py)."""
    from pipelinedp_tpu.native import loader
    try:
        lib = loader.load_row_packer()
    except loader.LOADER_ERRORS:
        profiler.count_event("runtime/native_fallback")
        return None
    if lib is None or not hasattr(lib, "pdp_rle_prep"):
        return None
    return lib


class NativeRleEncoder:
    """Stateful handle over the native prep/sort/emit codec.

    The split API exists for pipelining: `sort_range`+`emit_range` of slab
    s+1 runs on the host CPU while slab s's async device_put is still on
    the wire (ops/streaming.py drives this). When `entry_counts` is
    available (prep counted RLE entries exactly without sorting), the wire
    format can be fixed up front and the expensive per-bucket radix sort
    itself joins the pipeline — sort slab s+1 while slab s is in flight.
    Use as a context manager or call close(); create() returns None when
    the native library is unavailable (callers fall back to
    encode_buckets_numpy).
    """

    def __init__(self, lib, handle, counts, k, plan, entry_counts=None,
                 max_run: int = -1):
        self._lib = lib
        self._handle = handle
        self.counts = counts
        self._k = k
        self._plan = plan
        # Exact per-bucket RLE entry counts from prep (pre-sort), or None
        # when the pid span exceeded the native count-table budget.
        self.entry_counts = entry_counts
        # Max rows of any single pid (same count table; -1 = uncounted).
        self.max_run = max_run

    @property
    def plan(self) -> ValuePlan:
        """The value plan in effect (inline-vidx preps correct the bit
        width to the observed max index)."""
        return self._plan

    @classmethod
    def create(cls, pid, pk, value, vidx, *, pid_lo: int, k: int,
               plan: ValuePlan,
               inline_vidx: bool = False,
               out_status: Optional[dict] = None,
               pid_span: int = -1
               ) -> Optional["NativeRleEncoder"]:
        """inline_vidx: for PLANES plans, let the C++ prep compute AND
        bit-verify the value index during its scatter pass (vidx must be
        None). On verification failure returns None and sets
        out_status["inline_failed"] = True — callers re-plan. The
        returned encoder's plan carries the true bit width (from the
        observed max index).

        pid_span: max(pid) - pid_lo; when >= 0 and within the native
        count-table budget, prep also returns exact per-bucket RLE entry
        counts (encoder.entry_counts) without sorting."""
        lib = _load_packer()
        if lib is None:
            return None
        import ctypes

        n = len(pid)
        pid32 = np.ascontiguousarray(pid, dtype=np.int32)
        pk32 = np.ascontiguousarray(pk, dtype=np.int32)
        use_inline = inline_vidx and plan.mode == VALUE_PLANES
        val32 = (np.ascontiguousarray(value, dtype=np.float32)
                 if value is not None
                 and (use_inline or plan.mode in (VALUE_F32, VALUE_F16))
                 else None)
        vidx32 = (np.ascontiguousarray(vidx, dtype=np.int32)
                  if plan.mode == VALUE_PLANES and not use_inline else None)
        counts = np.zeros(k, dtype=np.int64)
        entries = np.zeros(k, dtype=np.int64)
        # stats: [0] inline verification failed, [1] max value index,
        # [2] max rows of any single pid (ABI 7; -1 when uncounted).
        stats = np.zeros(3, dtype=np.int64)
        handle = lib.pdp_rle_prep(
            pid32.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            pk32.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            val32.ctypes.data_as(ctypes.c_void_p) if val32 is not None
            else None,
            vidx32.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))
            if vidx32 is not None else None,
            float(plan.lo), float(plan.scale),
            n, int(pid_lo), k, int(plan.mode), int(pid_span),
            entries.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            stats.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if not handle:
            if out_status is not None and use_inline and stats[0] == 1:
                out_status["inline_failed"] = True
            return None
        if use_inline:
            plan = dataclasses.replace(
                plan, bits=max(1, int(stats[1]).bit_length()))
        entry_counts = None if entries[0] < 0 else entries
        return cls(lib, handle, counts, k, plan, entry_counts,
                   max_run=int(stats[2]))

    def sort_range(self, b0: int, b1: int) -> np.ndarray:
        """Sorts buckets [b0, b1) by pid; returns their RLE entry counts."""
        import ctypes
        n_uniq = np.zeros(b1 - b0, dtype=np.int64)
        rc = self._lib.pdp_rle_sort_range(
            self._handle, b0, b1,
            n_uniq.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
        if rc != 0:
            raise RuntimeError(f"pdp_rle_sort_range failed (rc={rc})")
        return n_uniq

    def emit_range(self, b0: int, b1: int, fmt: WireFormat) -> np.ndarray:
        """Writes the flat [b1-b0, fmt.width] slab: sorted RLE rows in
        PID_RLE mode, arrival-order pid bit-planes in PID_PLANES mode."""
        import ctypes
        out = np.empty((b1 - b0, fmt.width), dtype=np.uint8)
        rc = self._lib.pdp_rle_emit_range(
            self._handle, b0, b1, int(fmt.pid_mode), fmt.bytes_pid,
            int(fmt.bits_pid), fmt.bits_pk,
            int(self._plan.bits), fmt.cap, fmt.ucap,
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)), fmt.width)
        if rc != 0:
            raise RuntimeError(f"pdp_rle_emit_range failed (rc={rc})")
        return out

    def close(self):
        if self._handle:
            self._lib.pdp_rle_free(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):
        self.close()


def encode_buckets_native(
    pid: np.ndarray,
    pk: np.ndarray,
    value: Optional[np.ndarray],
    *,
    pid_lo: int,
    k: int,
    bytes_pid: int,
    bits_pk: int,
    plan: ValuePlan,
    vidx: Optional[np.ndarray] = None,
):
    """C++ fast path (single shot over all buckets); returns the same
    tuple as encode_buckets_numpy, or None when unavailable."""
    if plan.mode == VALUE_PLANES and vidx is None:
        vidx = np.rint(
            (np.asarray(value, dtype=np.float64) - float(plan.lo))
            / float(plan.scale)).astype(np.int32)
    enc = NativeRleEncoder.create(pid, pk, value, vidx, pid_lo=pid_lo, k=k,
                                  plan=plan)
    if enc is None:
        return None
    with enc:
        n = len(pid)
        n_uniq = enc.sort_range(0, k)
        fmt = WireFormat(bytes_pid=bytes_pid, bits_pk=bits_pk,
                         cap=_round8(int(enc.counts.max()) if n else 8),
                         ucap=_round8(int(n_uniq.max()) if n else 8),
                         value=plan)
        slab = enc.emit_range(0, k, fmt)
        return slab, enc.counts, n_uniq, fmt


def encode_buckets(pid, pk, value, *, pid_lo, k, bytes_pid, bits_pk, plan,
                   vidx=None):
    """Native encoder with numpy fallback; identical outputs either way."""
    out = encode_buckets_native(pid, pk, value, pid_lo=pid_lo, k=k,
                                bytes_pid=bytes_pid, bits_pk=bits_pk,
                                plan=plan, vidx=vidx)
    if out is None:
        out = encode_buckets_numpy(pid, pk, value, pid_lo=pid_lo, k=k,
                                   bytes_pid=bytes_pid, bits_pk=bits_pk,
                                   plan=plan)
    return out


# Largest (pid_span + 1) for which the numpy fallback counts exact RLE
# entries before sorting (mirrors kMaxEntryCountSpan in row_packer.cc; the
# extra 4*n guard keeps the span pass proportional to the data).
_MAX_ENTRY_COUNT_SPAN = 1 << 26


def rle_entry_stats_numpy(pid, pid_lo: int, k: int, pid_span: int
                          ) -> Tuple[Optional[np.ndarray], int]:
    """(per-bucket RLE entry counts, max rows of any single pid) WITHOUT
    sorting, or (None, -1) when the pid span is too large to count
    cheaply.

    A pid hashes to exactly one bucket, so bucket b's post-sort entry
    count is sum(ceil(rows_of_pid / 65535)) over the pids landing in b —
    computable from a per-pid bincount. This is what lets the caller fix
    the wire format before any sort and pipeline the sort per slab. The
    max per-pid row count from the same bincount bounds every pid-segment
    span in every bucket — the tile-slack input of the segment-local
    tiled sort (plan_segment_tiling).
    """
    n = len(pid)
    if pid_span < 0 or pid_span + 1 > min(_MAX_ENTRY_COUNT_SPAN,
                                          max(4 * n, 1 << 22)):
        return None, -1
    shifted = (np.asarray(pid) - pid_lo).astype(np.int64, copy=False)
    per = np.bincount(shifted, minlength=pid_span + 1)
    nz = np.flatnonzero(per)
    bucket = ((nz.astype(np.uint32) * _HASH_MULT) >> np.uint32(16)) % \
        np.uint32(k)
    entries = -(-per[nz] // _RUN_SPLIT)
    counts = np.bincount(bucket, weights=entries,
                         minlength=k).astype(np.int64)
    return counts, int(per.max()) if n else 0


def rle_entry_counts_numpy(pid, pid_lo: int, k: int,
                           pid_span: int) -> Optional[np.ndarray]:
    """rle_entry_stats_numpy without the max-run stat (compat surface)."""
    return rle_entry_stats_numpy(pid, pid_lo, k, pid_span)[0]


def plan_segment_tiling(fmt: WireFormat, segment_sort,
                        max_run: int) -> WireFormat:
    """Resolves the ``segment_sort`` knob into tile geometry on ``fmt``.

    segment_sort: False disables; True forces tiling whenever the
    geometry is non-degenerate; "auto" additionally requires enough tiles
    per bucket (>= 8) that the shorter sort span pays for the binning and
    compaction passes. Tiling needs the max single-pid run (``max_run``,
    from prep-time per-pid counts — tile_slack must bound every segment;
    unknown/-1 disables) and pid-sorted arrival (PID_RLE).

    Tile width: the smallest power of two >= 4 * max_run (so slack stays
    <= ~25% of a tile) and >= 1024 (smaller tiles are all padding).
    """
    if segment_sort is False or fmt.pid_mode != PID_RLE:
        return fmt
    if max_run is None or max_run <= 0:
        return fmt
    slack = _round8(max_run)
    tile = 1 << max(10, (4 * max_run - 1).bit_length())
    if tile + slack >= fmt.cap:
        return fmt
    if segment_sort == "auto" and tile > fmt.cap // 8:
        return fmt
    return dataclasses.replace(fmt, tile_rows=tile, tile_slack=slack)


# Hash-binned group-stage geometry limits (plan_group_binning). The bin
# width bounds the O(W^2) pairwise selection per segment — beyond
# HASH_MAX_BIN_ROWS the quadratic term loses to the tiled sort, so auto
# declines (forced "hash" tolerates up to HASH_FORCED_MAX_BIN_ROWS, the
# compile-sanity ceiling). HASH_GRID_BLOWUP bounds the [bins, width]
# grid relative to the chunk's rows: bins beyond the budget are not
# allocated — chunks needing them demote to the tiled kernel per chunk.
HASH_MAX_BIN_ROWS = 128
HASH_FORCED_MAX_BIN_ROWS = 1024
HASH_GRID_BLOWUP = 4


def plan_group_binning(fmt: WireFormat, segment_sort, max_run: int, *,
                       exact: bool = False) -> WireFormat:
    """plan_segment_tiling extended to the 4-way sampler plan: resolves
    the ``segment_sort`` knob into tile geometry AND, for the sortless
    group stage, the ``[hash_bins, hash_bin_rows]`` bin grid.

    segment_sort="hash" forces the hash-binned stage whenever its
    geometry is computable (pid-sorted wire, known max_run, bin width
    within the forced ceiling); "auto" additionally requires ``exact``
    (the caller-evaluated columnar.hash_exact_gate — bit-identity to
    the sorted paths), the auto bin-width ceiling, and bins for every
    chunk within the grid budget (so auto never mixes kernels). The
    tile geometry is always resolved too: it is the per-chunk demotion
    target when a chunk's RLE entry count exceeds hash_bins.

    Bin sizing from the row_packer prep stats: width = the max
    single-pid run rounded up (a segment can never overflow its bin —
    only corrupt wire metadata can, and the kernel backstop empties the
    accumulators then), bins = the per-bucket RLE entry capacity
    (every segment gets a bin) capped by the grid byte budget.
    """
    fmt = plan_segment_tiling(fmt, segment_sort, max_run)
    if segment_sort is False or fmt.pid_mode != PID_RLE:
        return fmt
    if max_run is None or max_run <= 0:
        return fmt
    forced = segment_sort == "hash"
    if not forced and not (segment_sort == "auto" and exact):
        return fmt
    w = _round8(max_run)
    if w > (HASH_FORCED_MAX_BIN_ROWS if forced else HASH_MAX_BIN_ROWS):
        return fmt
    budget = max(8, (HASH_GRID_BLOWUP * fmt.cap) // w)
    bins = min(_round8(fmt.ucap), _round8(budget))
    if bins < 8:
        return fmt
    if not forced and bins < fmt.ucap:
        # auto never plans a grid some chunks would overflow (mixed
        # hash/tiled execution is the forced knob's explicit trade).
        return fmt
    return dataclasses.replace(fmt, hash_bins=int(bins),
                               hash_bin_rows=int(w))


def choose_pid_mode(n: int, pid_span: int, bytes_pid: int,
                    entry_counts: Optional[np.ndarray]) -> Tuple[int, int]:
    """(pid_mode, bits_pid) for this dataset.

    PID_PLANES wins when the arrival-order bit-planes are strictly smaller
    on the wire than the RLE entries — near-unique privacy ids — since it
    also skips the host radix sort entirely (the device sorts anyway).
    With repetitive ids (the headline movie-ratings shape: ~10 rows/user,
    RLE ~0.3 bits/row vs 24 plane bits) RLE stays, and it additionally
    hands the kernel the pid-sorted arrival order (presorted sampler).
    Unknown entry counts (huge span) keep RLE with the upfront sort.
    """
    bits_pid = max(1, int(pid_span).bit_length())
    if entry_counts is None:
        return PID_RLE, bits_pid
    plane_bits = n * bits_pid
    rle_bits = int(entry_counts.sum()) * (8 * bytes_pid + 16)
    return (PID_PLANES if plane_bits < rle_bits else PID_RLE), bits_pid


def _sample_plan(value: Optional[np.ndarray],
                 value_f16: bool) -> ValuePlan:
    """Tentative plan from the 64k-sample gate only (one cheap pass plus
    the global min). A PLANES result is provisional: the native prep
    verifies the full array bit-exactly during its scatter pass. Shares
    the scale ladder and gate with plan_and_index."""
    final, value, lo, lo64, sample = _plan_preamble(value, value_f16)
    if final is not None:
        return final
    for scale, s in _gated_scales(sample, lo, lo64):
        return ValuePlan(VALUE_PLANES, bits=1, lo=float(lo),
                         scale=float(s))
    return ValuePlan(VALUE_F32)


@dataclasses.dataclass(frozen=True)
class EncodeInfo:
    """Everything the streaming drivers need to build wire formats and
    schedule the encode pipeline (make_encoder's planning output)."""
    plan: ValuePlan
    vidx: Optional[np.ndarray]  # value index (numpy fallback PLANES only)
    pid_lo: int
    pid_span: int
    bytes_pid: int
    bits_pk: int
    pid_mode: int  # PID_RLE or PID_PLANES
    bits_pid: int  # pid plane count (PID_PLANES)
    # Exact per-bucket RLE entry counts known BEFORE sorting, or None
    # (then PID_RLE callers must learn ucap from an upfront sort).
    entry_counts: Optional[np.ndarray]
    # Max rows of any single pid (bounds every pid segment in every
    # bucket — the tile-slack input of plan_segment_tiling), or -1 when
    # the span was too large to count.
    max_run: int = -1


def make_encoder(pid: np.ndarray, pk, value, *, num_partitions: int, k: int,
                 value_transfer_dtype=None
                 ) -> Tuple[Optional[NativeRleEncoder], EncodeInfo]:
    """Shared encode prologue of the single-device and mesh streaming
    paths: pid-span validation, width/bit planning, value plan + index,
    the pid wire-mode decision, and the native encoder (None -> numpy
    fallback).

    With the native library, the full-array value verification happens
    INSIDE the C++ scatter pass (no separate host pass); without it, the
    chunked host verification of plan_and_index runs for the numpy
    fallback.

    Returns (enc_or_None, EncodeInfo).
    """
    pid = np.asarray(pid)
    pid_lo = int(pid.min())
    pid_span = int(pid.max()) - pid_lo
    if pid_span >= np.iinfo(np.int32).max - 1:
        # The kernel reserves INT32_MAX as its padding sentinel; a shifted
        # pid colliding with it would be silently dropped.
        raise ValueError(
            f"privacy-id span {pid_span} does not fit int32; factorize the "
            f"ids to dense int32 before streaming")
    bytes_pid = 1
    while pid_span >= (1 << (8 * bytes_pid)):
        bytes_pid += 1
    bits_pk = max(1, int(max(num_partitions - 1, 0)).bit_length())
    value_f16 = (value_transfer_dtype is not None
                 and np.dtype(value_transfer_dtype) == np.float16)

    def info_for(plan, vidx, entry_counts, max_run=-1):
        pid_mode, bits_pid = choose_pid_mode(len(pid), pid_span, bytes_pid,
                                             entry_counts)
        return EncodeInfo(plan=plan, vidx=vidx, pid_lo=pid_lo,
                          pid_span=pid_span, bytes_pid=bytes_pid,
                          bits_pk=bits_pk, pid_mode=pid_mode,
                          bits_pid=bits_pid, entry_counts=entry_counts,
                          max_run=max_run)

    def fallback_info():
        plan, vidx = plan_and_index(value, value_f16)
        entries, max_run = rle_entry_stats_numpy(pid, pid_lo, k, pid_span)
        return info_for(plan, vidx, entries, max_run)

    if _load_packer() is None:
        # Numpy fallback: needs the fully verified plan and index on the
        # host (and must not pay the sample pass twice).
        return None, fallback_info()

    tentative = _sample_plan(value, value_f16)
    status: dict = {}
    enc = NativeRleEncoder.create(pid, pk, value, None, pid_lo=pid_lo, k=k,
                                  plan=tentative, inline_vidx=True,
                                  out_status=status, pid_span=pid_span)
    if enc is not None:
        return enc, info_for(enc.plan, None, enc.entry_counts, enc.max_run)
    if status.get("inline_failed"):
        # The sample-chosen scale failed the full array: re-plan with the
        # full chunked host verification (which tries the other scales)
        # and retry — rare, and only costs the fallback pass.
        plan, vidx = plan_and_index(value, value_f16)
        enc = NativeRleEncoder.create(pid, pk, value, vidx, pid_lo=pid_lo,
                                      k=k, plan=plan, pid_span=pid_span)
        if enc is not None:
            return enc, info_for(plan, vidx, enc.entry_counts, enc.max_run)
        entries, max_run = rle_entry_stats_numpy(pid, pid_lo, k, pid_span)
        return None, info_for(plan, vidx, entries, max_run)
    return None, fallback_info()


def resident_fingerprint(k: int, fmt: WireFormat, counts: np.ndarray,
                         n_uniq: Optional[np.ndarray],
                         data_digest: str = "") -> str:
    """Identity of a retained wire handle (streaming.ResidentWire).

    Reuses the checkpoint wire-fingerprint path — chunk count, format,
    per-bucket row/entry counts, plus the source-column digest
    (runtime.checkpoint.array_digest) — so a resident-dataset session
    names its handle exactly the way a resumed slab loop names its wire,
    and a source dataset mutated after ingest is refused on the same
    evidence a mutated checkpoint input is.
    """
    from pipelinedp_tpu.runtime import checkpoint as checkpoint_lib

    return checkpoint_lib.wire_fingerprint(k, repr(fmt), counts, n_uniq,
                                           data_digest=data_digest)


def round_ucap(umax: int) -> int:
    """Rounds an RLE entry count up with ~12.5% granularity so slab shapes
    recur across slabs/runs (each distinct shape is a fresh XLA compile)."""
    umax = max(umax, 8)
    g = max(8, 1 << max(3, umax.bit_length() - 3))
    return -(-umax // g) * g
