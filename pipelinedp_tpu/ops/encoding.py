"""Host-side dictionary encoding of arbitrary keys to dense int32 ids.

The columnar engine works on fixed-shape integer arrays; arbitrary privacy
ids and partition keys (strings, tuples, ...) are encoded on host to dense
ids (SURVEY.md §7 "String keys"). Public-partition filtering becomes a
vocabulary-membership test during encoding, so non-public rows never reach
the device.

Three input shapes, fastest first:
  * EncodedColumns — ids already dense int32: zero host work.
  * ColumnarData — raw numpy columns: vectorized np.unique factorization.
  * Python rows + extractors — per-row extraction, vectorized encoding of
    the extracted columns.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class Vocabulary:
    """Bidirectional key <-> dense id mapping.

    Vocabularies built from a distinct-keys array (`from_unique`) stay as
    that array; the Python dict for reverse lookup is materialized only if
    `lookup`/`add` is actually called — encoding a 100M-row dataset must
    not pay for a multi-million-entry dict it never reads.
    """

    def __init__(self, keys: Optional[Sequence[Any]] = None):
        self._key_to_id: Optional[Dict[Any, int]] = {}
        self._keys: List[Any] = []
        self._unique_arr: Optional[np.ndarray] = None
        if keys is not None:
            for key in keys:
                self.add(key)

    @classmethod
    def from_unique(cls, unique_keys: np.ndarray) -> "Vocabulary":
        """Wraps an array of distinct keys; id i maps to unique_keys[i]."""
        vocab = cls()
        vocab._unique_arr = np.asarray(unique_keys)
        vocab._key_to_id = None  # built lazily
        return vocab

    def _materialize(self) -> None:
        if self._unique_arr is not None:
            self._keys = [k.item() if hasattr(k, "item") else k
                          for k in self._unique_arr]
            self._unique_arr = None
        if self._key_to_id is None:
            self._key_to_id = {k: i for i, k in enumerate(self._keys)}

    def add(self, key: Any) -> int:
        self._materialize()
        idx = self._key_to_id.get(key)
        if idx is None:
            idx = len(self._keys)
            self._key_to_id[key] = idx
            self._keys.append(key)
        return idx

    def lookup(self, key: Any) -> int:
        """Returns the id or -1 if unknown."""
        self._materialize()
        return self._key_to_id.get(key, -1)

    def decode(self, idx: int) -> Any:
        if self._unique_arr is not None:
            key = self._unique_arr[idx]
            return key.item() if hasattr(key, "item") else key
        return self._keys[idx]

    def decode_all(self, ids: Sequence[int]) -> List[Any]:
        if self._unique_arr is not None:
            picked = self._unique_arr[np.asarray(ids, dtype=np.int64)]
            return [k.item() if hasattr(k, "item") else k for k in picked]
        return [self._keys[i] for i in ids]

    @property
    def keys(self) -> List[Any]:
        if self._unique_arr is not None:
            return [k.item() if hasattr(k, "item") else k
                    for k in self._unique_arr]
        return list(self._keys)

    def __len__(self) -> int:
        if self._unique_arr is not None:
            return len(self._unique_arr)
        return len(self._keys)


@dataclasses.dataclass
class ColumnarData:
    """Raw columnar input: one entry per contribution.

    ``pid``/``pk`` may be any numpy-comparable dtype (ints, strings, ...);
    they are factorized to dense ids with vectorized np.unique. ``value``
    may be None (COUNT-style metrics), float[N], or float[N, D] for
    VECTOR_SUM.
    """
    pid: np.ndarray
    pk: np.ndarray
    value: Optional[np.ndarray] = None


@dataclasses.dataclass
class EncodedColumns:
    """Pre-encoded columnar input: ids are already dense int32.

    ``pid`` in [0, num_privacy_units), ``pk`` in [0, num_partitions). The
    partition vocabulary maps ids back to user-facing keys; identity if
    omitted. This is the zero-host-cost path for data that already lives
    in dense-id form (e.g. the output of a previous pipeline stage).
    """
    pid: np.ndarray
    pk: np.ndarray
    num_partitions: int
    value: Optional[np.ndarray] = None
    pk_keys: Optional[Sequence[Any]] = None  # id -> key, identity if None


_SCALAR_KEY_TYPES = (int, float, str, bytes, bool, np.generic)


def _column_from_list(values: List[Any]) -> np.ndarray:
    """Column array from extracted keys, preserving composite keys.

    np.asarray would splat tuple keys into a 2-D array and coerce mixed
    int/str keys to strings; keys must stay whole, so anything that is not
    uniformly a scalar type becomes a 1-D object array.
    """
    types = {type(v) for v in values}
    if len(types) == 1 and issubclass(next(iter(types)), _SCALAR_KEY_TYPES):
        return np.asarray(values)
    arr = np.empty(len(values), dtype=object)
    arr[:] = values
    return arr


def _factorize(column: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(dense int32 ids, unique keys). Vectorized for non-object dtypes."""
    column = np.asarray(column)
    if column.dtype == object:
        # Mixed/unhashable-by-numpy keys: dict-based single pass.
        vocab: Dict[Any, int] = {}
        ids = np.empty(len(column), dtype=np.int32)
        for i, key in enumerate(column):
            idx = vocab.setdefault(key, len(vocab))
            ids[i] = idx
        uniques = np.empty(len(vocab), dtype=object)
        for key, idx in vocab.items():
            uniques[idx] = key
        return ids, uniques
    if np.issubdtype(column.dtype, np.integer) and len(column):
        lo = int(column.min())
        hi = int(column.max())
        span = hi - lo + 1
        # Presence-table factorization: O(N + span) beats the O(N log N)
        # sort when the id range is not much larger than the data.
        if 0 < span <= max(4 * len(column), 1 << 20):
            shifted = column - lo if lo else column
            present = np.zeros(span, dtype=bool)
            present[shifted] = True
            if present.all():
                # Ids are already dense on [lo, hi]: identity mapping, no
                # remap gather (2 s saved at the 100M-row benchmark shape).
                # The ids array must be a FRESH buffer: results are
                # computed lazily, so aliasing the caller's column would
                # let a later caller-side mutation corrupt the encoded
                # ids (shifted aliases `column` when lo == 0).
                ids = (shifted.astype(np.int32, copy=True)
                       if shifted is column else
                       shifted.astype(np.int32, copy=False))
                return ids, np.arange(lo, hi + 1, dtype=column.dtype)
            ids_map = np.cumsum(present, dtype=np.int32) - 1
            ids = ids_map[shifted]
            uniques = np.flatnonzero(present) + lo
            return ids, uniques.astype(column.dtype)
    uniques, inverse = np.unique(column, return_inverse=True)
    return inverse.astype(np.int32), uniques


def _lookup_ids(column: np.ndarray, vocab: Vocabulary) -> np.ndarray:
    """ids of column entries under an existing vocabulary (-1 = unknown),
    vectorized via sorted search against the vocabulary keys."""
    column = np.asarray(column)
    if column.dtype == object or len(vocab) == 0:
        return np.fromiter((vocab.lookup(k) for k in column),
                           dtype=np.int32,
                           count=len(column))
    keys = np.asarray(vocab.keys)
    order = np.argsort(keys, kind="stable")
    sorted_keys = keys[order]
    pos = np.searchsorted(sorted_keys, column)
    pos = np.clip(pos, 0, len(keys) - 1)
    found = sorted_keys[pos] == column
    ids = np.where(found, order[pos], -1)
    return ids.astype(np.int32)


_INT32_SENTINEL_SAFE = np.iinfo(np.int32).max - 1


def _pid_passthrough(pid_col: np.ndarray) -> Optional[np.ndarray]:
    """Raw integer privacy ids shifted to [0, span], or None if unusable.

    The kernels only compare privacy ids for equality, so dense
    factorization is pure overhead when the input ids are already integers
    — a shift-to-zero keeps them inside int32 (the kernel reserves
    INT32_MAX as its padding sentinel, hence the safety margin).

    Read-only contract: when the input is already int32 with lo == 0 the
    returned array ALIASES the caller's column (this is the hot path; a
    defensive copy would cost ~0.2 s at the 100M-row shape). Engine call
    sites treat encoded pid columns as immutable.
    """
    if not np.issubdtype(pid_col.dtype, np.integer) or len(pid_col) == 0:
        return None
    lo = int(pid_col.min())
    span = int(pid_col.max()) - lo
    if span >= _INT32_SENTINEL_SAFE:
        return None
    shifted = pid_col - lo if lo else pid_col
    return shifted.astype(np.int32, copy=False)


def encode_columns(
    pid_col,
    pk_col,
    value_col,
    public_partitions: Optional[Sequence[Any]] = None,
    vector_size: Optional[int] = None,
    factorize_pid: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Optional[Vocabulary],
           Vocabulary]:
    """Vectorized encoding of raw columns; same contract as encode_rows.

    ``pid_col`` may be None (contribution_bounds_already_enforced: each row
    becomes its own privacy unit). With ``factorize_pid=False`` integer
    privacy ids skip factorization entirely (returned shifted-to-zero with
    pid_vocab=None) — the kernels never need dense pid ids, only equality.
    """
    pk_col = np.asarray(pk_col)
    if pid_col is not None:
        pid_col = np.asarray(pid_col)
    if public_partitions is not None:
        pk_vocab = Vocabulary(public_partitions)
        pk_ids = _lookup_ids(pk_col, pk_vocab)
        keep = pk_ids >= 0
        pk_ids = pk_ids[keep]
        if pid_col is not None:
            pid_col = pid_col[keep]
        if value_col is not None:
            value_col = np.asarray(value_col)[keep]
    else:
        pk_ids, pk_uniques = _factorize(pk_col)
        pk_vocab = Vocabulary.from_unique(pk_uniques)
    if pid_col is None:
        pid_ids = np.arange(len(pk_ids), dtype=np.int32)
        pid_vocab = Vocabulary.from_unique(np.arange(len(pk_ids)))
    else:
        pid_ids = None if factorize_pid else _pid_passthrough(pid_col)
        if pid_ids is not None:
            pid_vocab = None
        else:
            pid_ids, pid_uniques = _factorize(pid_col)
            pid_vocab = Vocabulary.from_unique(pid_uniques)
    value_arr = _value_array(value_col, len(pk_ids), vector_size)
    return (pid_ids.astype(np.int32, copy=False),
            pk_ids.astype(np.int32, copy=False), value_arr, pid_vocab,
            pk_vocab)


def _value_array(value_col, n: int,
                 vector_size: Optional[int]) -> np.ndarray:
    if value_col is None:
        return np.zeros(n, dtype=np.float32)
    arr = np.asarray(value_col, dtype=np.float32)
    if vector_size is not None:
        return arr.reshape(n, vector_size)
    return arr


def encode_rows(
    rows,
    privacy_id_extractor,
    partition_extractor,
    value_extractor,
    public_partitions: Optional[Sequence[Any]] = None,
    vector_size: Optional[int] = None,
    factorize_pid: bool = True,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Optional[Vocabulary],
           Vocabulary]:
    """Encodes Python rows into (pid_ids, pk_ids, values) numpy columns.

    Columnar inputs (ColumnarData / EncodedColumns) skip the per-row
    extractor loop entirely. With ``public_partitions`` the partition
    vocabulary is frozen up front and rows with non-public partitions are
    dropped (the public-path filter_by_key of the reference graph,
    dp_engine.py:290).
    """
    if isinstance(rows, EncodedColumns):
        return _encode_pre_encoded(rows, public_partitions, vector_size,
                                   use_pid=privacy_id_extractor is not None)
    if isinstance(rows, ColumnarData):
        pid_col = rows.pid if privacy_id_extractor is not None else None
        return encode_columns(pid_col, rows.pk, rows.value,
                              public_partitions, vector_size,
                              factorize_pid=factorize_pid)
    rows = list(rows)
    pk_col = _column_from_list([partition_extractor(row) for row in rows])
    if privacy_id_extractor is not None and privacy_id_extractor is not True:
        pid_col = _column_from_list(
            [privacy_id_extractor(row) for row in rows])
    else:
        pid_col = None
    if value_extractor is not None:
        value_col = [value_extractor(row) for row in rows]
    else:
        value_col = None
    return encode_columns(pid_col, pk_col, value_col, public_partitions,
                          vector_size, factorize_pid=factorize_pid)


def _encode_pre_encoded(cols: EncodedColumns,
                        public_partitions: Optional[Sequence[Any]],
                        vector_size: Optional[int],
                        use_pid: bool = True):
    pid = np.asarray(cols.pid, dtype=np.int32)
    pk = np.asarray(cols.pk, dtype=np.int32)
    if not use_pid:
        # contribution_bounds_already_enforced: each row is its own unit.
        pid = np.arange(len(pk), dtype=np.int32)
    pk_keys = (cols.pk_keys
               if cols.pk_keys is not None else range(cols.num_partitions))
    pk_vocab = Vocabulary.from_unique(np.asarray(pk_keys))
    if len(pk_vocab) != cols.num_partitions:
        raise ValueError(
            f"pk_keys has {len(pk_vocab)} entries, expected "
            f"num_partitions={cols.num_partitions}")
    value = cols.value
    if public_partitions is not None:
        # Re-encode against a public-only vocabulary: non-public ids must
        # not survive into the output partition space.
        public_vocab = Vocabulary(public_partitions)
        table = np.full(cols.num_partitions, -1, dtype=np.int32)
        for new_id, key in enumerate(public_vocab.keys):
            old_id = pk_vocab.lookup(key)
            if old_id >= 0:
                table[old_id] = new_id
        pk = table[pk]
        mask = pk >= 0
        pid, pk = pid[mask], pk[mask]
        if value is not None:
            value = np.asarray(value)[mask]
        pk_vocab = public_vocab
    # Privacy-id vocabulary is identity over the observed id space.
    n_pids = int(pid.max()) + 1 if len(pid) else 0
    pid_vocab = Vocabulary.from_unique(np.arange(n_pids))
    return (pid, pk, _value_array(value, len(pk), vector_size), pid_vocab,
            pk_vocab)
