"""Host-side dictionary encoding of arbitrary keys to dense int32 ids.

The columnar engine works on fixed-shape integer arrays; arbitrary privacy
ids and partition keys (strings, tuples, ...) are encoded on host to dense
ids (SURVEY.md §7 "String keys"). Public-partition filtering becomes a
vocabulary-membership test during encoding, so non-public rows never reach
the device.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np


class Vocabulary:
    """Bidirectional key <-> dense id mapping."""

    def __init__(self, keys: Optional[Sequence[Any]] = None):
        self._key_to_id: Dict[Any, int] = {}
        self._keys: List[Any] = []
        if keys is not None:
            for key in keys:
                self.add(key)

    def add(self, key: Any) -> int:
        idx = self._key_to_id.get(key)
        if idx is None:
            idx = len(self._keys)
            self._key_to_id[key] = idx
            self._keys.append(key)
        return idx

    def lookup(self, key: Any) -> int:
        """Returns the id or -1 if unknown."""
        return self._key_to_id.get(key, -1)

    def decode(self, idx: int) -> Any:
        return self._keys[idx]

    def decode_all(self, ids: Sequence[int]) -> List[Any]:
        return [self._keys[i] for i in ids]

    @property
    def keys(self) -> List[Any]:
        return list(self._keys)

    def __len__(self) -> int:
        return len(self._keys)


def encode_rows(
    rows,
    privacy_id_extractor,
    partition_extractor,
    value_extractor,
    public_partitions: Optional[Sequence[Any]] = None,
    vector_size: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, Vocabulary, Vocabulary]:
    """Encodes Python rows into (pid_ids, pk_ids, values) numpy columns.

    With ``public_partitions`` the partition vocabulary is frozen up front
    and rows with non-public partitions are dropped (the public-path
    filter_by_key of the reference graph, dp_engine.py:290).
    """
    pid_vocab = Vocabulary()
    if public_partitions is not None:
        pk_vocab = Vocabulary(public_partitions)
    else:
        pk_vocab = Vocabulary()
    pids: List[int] = []
    pks: List[int] = []
    values: List[Any] = []
    public = public_partitions is not None
    for row in rows:
        pk = partition_extractor(row)
        if public:
            pk_id = pk_vocab.lookup(pk)
            if pk_id < 0:
                continue
        else:
            pk_id = pk_vocab.add(pk)
        pid = privacy_id_extractor(row) if privacy_id_extractor else len(pids)
        pids.append(pid_vocab.add(pid))
        pks.append(pk_id)
        if value_extractor is not None:
            values.append(value_extractor(row))
        else:
            values.append(0.0)
    pid_arr = np.asarray(pids, dtype=np.int32)
    pk_arr = np.asarray(pks, dtype=np.int32)
    if vector_size is not None:
        value_arr = np.asarray(values, dtype=np.float32).reshape(
            len(values), vector_size)
    else:
        value_arr = np.asarray(values, dtype=np.float32)
    return pid_arr, pk_arr, value_arr, pid_vocab, pk_vocab
