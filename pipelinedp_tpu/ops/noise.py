"""Batched on-device noise with granularity snapping.

The device twin of pipelinedp_tpu/noise_core.py: one `jax.random` call
noises every partition at once (vs. the reference's per-partition C++ calls,
combiners.py:262-263). The same power-of-two granularity snapping is applied
— value and noise are both rounded to a granularity derived from the noise
scale — with JAX's counter-based threefry PRNG supplying the randomness.
Scales and granularities are runtime scalars, so budget resolution never
forces a recompile (SURVEY.md §7 "Lazy budget vs. jit").
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def snap(values: jnp.ndarray, granularity) -> jnp.ndarray:
    return jnp.round(values / granularity) * granularity


def add_laplace_noise(key: jax.Array, values: jnp.ndarray, scale,
                      granularity) -> jnp.ndarray:
    """values snapped + Laplace(scale) noise snapped to granularity.

    Noise is sampled in float32 (TPU-native); snapping quantizes the
    mantissa tail which is the float-attack mitigation (Mironov 2012).
    """
    noise = jax.random.laplace(key, values.shape, dtype=values.dtype) * scale
    return snap(values, granularity) + snap(noise, granularity)


def add_gaussian_noise(key: jax.Array, values: jnp.ndarray, stddev,
                       granularity) -> jnp.ndarray:
    noise = jax.random.normal(key, values.shape, dtype=values.dtype) * stddev
    return snap(values, granularity) + snap(noise, granularity)


def add_noise(key: jax.Array, values: jnp.ndarray, is_gaussian,
              scale_or_std, granularity) -> jnp.ndarray:
    """Branchless noise: is_gaussian selects the distribution.

    All parameters may be traced scalars, so one compiled kernel serves both
    noise kinds and any budget.
    """
    lap = jax.random.laplace(key, values.shape, dtype=values.dtype)
    gauss = jax.random.normal(jax.random.fold_in(key, 1), values.shape,
                              dtype=values.dtype)
    noise = jnp.where(is_gaussian, gauss, lap) * scale_or_std
    return snap(values, granularity) + snap(noise, granularity)
