"""Batched on-device noise with granularity snapping.

The device twin of pipelinedp_tpu/noise_core.py: one `jax.random` call
noises every partition at once (vs. the reference's per-partition C++ calls,
combiners.py:262-263). Scales and granularities are runtime scalars, so
budget resolution never forces a recompile (SURVEY.md §7 "Lazy budget vs.
jit").

Security note — float32 limits. The host path (noise_core.py) snaps value
and noise to a power-of-two granularity ~scale*2^-40 in float64, the
Mironov-2012 mitigation. float32 cannot represent that grid: the integer
`round(x / g)` is exact only for |x| < 2^24 * g, so a 2^-40-relative
granularity would make `snap` an identity and provide no mitigation at all.
The device path therefore clamps the effective granularity to
scale * 2^-18 (`F32_GRANULARITY_BITS`), which keeps the noise grid
representable (Laplace/Gaussian tails stay within 2^6 * scale), and snaps
the *sum* value+noise on that grid. This quantizes the released value to
the same public grid the noise lives on — but values with magnitude above
2^24 * g still round to themselves, so bit-level security for outputs much
larger than ~2^5 * scale is NOT provided by this path. For bit-level
guarantees use the host finalization path (JaxDPEngine's secure_host_noise
mode / noise_core), which runs in float64 and is O(num_partitions), off the
hot path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# Effective relative granularity for float32 device snapping: the grid must
# stay representable (see module docstring).
F32_GRANULARITY_BITS = 18


def effective_granularity(scale_or_std, granularity, dtype) -> jnp.ndarray:
    """Granularity actually usable for snapping in ``dtype``.

    For float32, clamps the host-computed granularity (~scale*2^-40) up to
    scale * 2^-18 so that round(noise / g) is exact. float64 (x64 mode)
    keeps the host granularity.
    """
    if jnp.dtype(dtype) == jnp.float32:
        return jnp.maximum(granularity,
                           scale_or_std * (2.0**-F32_GRANULARITY_BITS))
    return jnp.asarray(granularity)


def snap(values: jnp.ndarray, granularity) -> jnp.ndarray:
    return jnp.round(values / granularity) * granularity


def add_laplace_noise(key: jax.Array, values: jnp.ndarray, scale,
                      granularity) -> jnp.ndarray:
    """(values + Laplace(scale) noise) snapped to the effective granularity.

    See the module docstring for what the float32 snap does and does not
    guarantee.
    """
    g = effective_granularity(scale, granularity, values.dtype)
    noise = jax.random.laplace(key, values.shape, dtype=values.dtype) * scale
    return snap(values + noise, g)


def add_gaussian_noise(key: jax.Array, values: jnp.ndarray, stddev,
                       granularity) -> jnp.ndarray:
    g = effective_granularity(stddev, granularity, values.dtype)
    noise = jax.random.normal(key, values.shape, dtype=values.dtype) * stddev
    return snap(values + noise, g)


def add_noise(key: jax.Array, values: jnp.ndarray, is_gaussian,
              scale_or_std, granularity) -> jnp.ndarray:
    """Branchless noise: is_gaussian selects the distribution.

    All parameters may be traced scalars, so one compiled kernel serves both
    noise kinds and any budget.
    """
    g = effective_granularity(scale_or_std, granularity, values.dtype)
    lap = jax.random.laplace(key, values.shape, dtype=values.dtype)
    gauss = jax.random.normal(jax.random.fold_in(key, 1), values.shape,
                              dtype=values.dtype)
    noise = jnp.where(is_gaussian, gauss, lap) * scale_or_std
    return snap(values + noise, g)


# Compiled top-level entries. XLA's CPU/TPU backends may contract a
# multiply feeding an add into one FMA (single rounding) when a kernel is
# compiled as one computation, so op-by-op eager execution of the same
# formula can differ from the jitted form in the last ulp — which the snap
# then amplifies to a whole granularity step. Every engine call site uses
# these compiled entries so released noise is identical whether a kernel
# runs standalone (the per-combiner legacy loop) or inlined in the fused
# finalization epilogue (ops/finalize.py, which compiles the same
# formulas in one executable — pinned by tests/finalize_test.py).
add_noise_compiled = jax.jit(add_noise)
add_laplace_noise_compiled = jax.jit(add_laplace_noise)
add_gaussian_noise_compiled = jax.jit(add_gaussian_noise)


# -- stacked per-metric batching (the fused epilogue, ops/finalize.py) -------
#
# One noise kernel over a stacked [n_metrics, num_out] array replaces one
# dispatch per metric. The raw draws vmap over the per-metric keys — the
# counter-based PRNG makes that bit-identical to the per-key calls (each
# row's bits depend only on its own key and row shape) — while the
# scale/snap arithmetic runs once on the stacked array with the per-row
# scales broadcast, which is elementwise-identical to the scalar kernels.
# So fusing the epilogue does not change seeded device-noise runs (pinned
# by tests/finalize_test.py).


def _batched_laplace(keys, values: jnp.ndarray) -> jnp.ndarray:
    return jax.vmap(
        lambda k: jax.random.laplace(k, values.shape[1:],
                                     dtype=values.dtype))(keys)


def _batched_normal(keys, values: jnp.ndarray) -> jnp.ndarray:
    return jax.vmap(
        lambda k: jax.random.normal(k, values.shape[1:],
                                    dtype=values.dtype))(keys)


def add_noise_batched(keys, values: jnp.ndarray, is_gaussian, scales,
                      granularities) -> jnp.ndarray:
    """Stacked twin of add_noise: row i of ``values`` [n, m] is noised with
    ``keys[i]``/``scales[i]``, exactly as n separate add_noise calls."""
    g = effective_granularity(scales, granularities, values.dtype)[:, None]
    lap = _batched_laplace(keys, values)
    gauss = _batched_normal(
        jax.vmap(lambda k: jax.random.fold_in(k, 1))(keys), values)
    noise = jnp.where(is_gaussian[:, None], gauss, lap) * scales[:, None]
    return snap(values + noise, g)


def add_laplace_noise_batched(keys, values: jnp.ndarray, scales,
                              granularities) -> jnp.ndarray:
    g = effective_granularity(scales, granularities, values.dtype)[:, None]
    noise = _batched_laplace(keys, values) * scales[:, None]
    return snap(values + noise, g)


def add_gaussian_noise_batched(keys, values: jnp.ndarray, stddevs,
                               granularities) -> jnp.ndarray:
    g = effective_granularity(stddevs, granularities, values.dtype)[:, None]
    noise = _batched_normal(keys, values) * stddevs[:, None]
    return snap(values + noise, g)
