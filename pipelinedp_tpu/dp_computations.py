"""Differential-privacy computations: mechanisms, sensitivities, DP
mean/variance algorithms, exponential mechanism, thresholding.

Parity: pipeline_dp/dp_computations.py (ScalarNoiseParams :28, compute_middle
:71, L1/L2 sensitivity :78-103, compute_sigma :106, Laplace/Gaussian
application :119-151, AdditiveVectorNoiseParams/_clip_vector/add_noise_vector
:186-229, equally_split_budget :232, compute_dp_var :306-365, noise-std
helpers :368-394, AdditiveMechanism :397, LaplaceMechanism :430,
GaussianMechanism :480, MeanMechanism :540-575, Sensitivities :578-618,
create_additive_mechanism :621, create_mean_mechanism :649,
ExponentialMechanism :661-715, compute_sensitivities_* :718-771,
ThresholdingMechanism :774-825, create_thresholding_mechanism :828).

Where the reference calls PyDP C++ mechanism objects, this module calls the
native noise core (pipelinedp_tpu/noise_core.py); batched device-side
equivalents are in pipelinedp_tpu/ops/noise.py.
"""

from __future__ import annotations

import abc
import dataclasses
import math
from typing import Any, List, Optional, Tuple, Union

import numpy as np

from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import noise_core
from pipelinedp_tpu import partition_selection
from pipelinedp_tpu.aggregate_params import (AggregateParams, Metric, Metrics,
                                             NoiseKind, NormKind,
                                             PartitionSelectionStrategy)


@dataclasses.dataclass
class ScalarNoiseParams:
    """Parameters for computing DP count/sum/mean/variance."""

    eps: float
    delta: float
    min_value: Optional[float]
    max_value: Optional[float]
    min_sum_per_partition: Optional[float]
    max_sum_per_partition: Optional[float]
    max_partitions_contributed: int
    max_contributions_per_partition: Optional[int]
    noise_kind: NoiseKind

    def __post_init__(self):
        assert (self.min_value is None) == (self.max_value is None), (
            "min_value and max_value should be both set or both None.")
        assert (self.min_sum_per_partition is None) == (
            self.max_sum_per_partition is None), (
                "min_sum_per_partition and max_sum_per_partition should be "
                "both set or both None.")

    def l0_sensitivity(self) -> int:
        return self.max_partitions_contributed

    @property
    def bounds_per_contribution_are_set(self) -> bool:
        return self.min_value is not None and self.max_value is not None

    @property
    def bounds_per_partition_are_set(self) -> bool:
        return (self.min_sum_per_partition is not None and
                self.max_sum_per_partition is not None)


def compute_squares_interval(min_value: float,
                             max_value: float) -> Tuple[float, float]:
    """Range of x^2 for x in [min_value, max_value]."""
    if min_value < 0 < max_value:
        return 0.0, max(min_value**2, max_value**2)
    return min_value**2, max_value**2


def compute_middle(min_value: float, max_value: float) -> float:
    """Midpoint, computed overflow-safely."""
    return min_value + (max_value - min_value) / 2


def compute_l1_sensitivity(l0_sensitivity: float,
                           linf_sensitivity: float) -> float:
    return l0_sensitivity * linf_sensitivity


def compute_l2_sensitivity(l0_sensitivity: float,
                           linf_sensitivity: float) -> float:
    return math.sqrt(l0_sensitivity) * linf_sensitivity


def compute_sigma(eps: float, delta: float, l2_sensitivity: float) -> float:
    """Optimal Gaussian sigma (analytic Gaussian mechanism)."""
    return noise_core.analytic_gaussian_sigma(eps, delta, l2_sensitivity)


def apply_laplace_mechanism(value: float, eps: float,
                            l1_sensitivity: float) -> float:
    return noise_core.add_laplace_noise(
        value, noise_core.laplace_diversity(eps, l1_sensitivity))


def apply_gaussian_mechanism(value: float, eps: float, delta: float,
                             l2_sensitivity: float) -> float:
    return noise_core.add_gaussian_noise(
        value, compute_sigma(eps, delta, l2_sensitivity))


def _add_random_noise(value: float, eps: float, delta: float,
                      l0_sensitivity: float, linf_sensitivity: float,
                      noise_kind: NoiseKind) -> float:
    if noise_kind == NoiseKind.LAPLACE:
        return apply_laplace_mechanism(
            value, eps, compute_l1_sensitivity(l0_sensitivity,
                                               linf_sensitivity))
    if noise_kind == NoiseKind.GAUSSIAN:
        return apply_gaussian_mechanism(
            value, eps, delta,
            compute_l2_sensitivity(l0_sensitivity, linf_sensitivity))
    raise ValueError("Noise kind must be either Laplace or Gaussian.")


# ---------------------------------------------------------------------------
# Vector sums
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class AdditiveVectorNoiseParams:
    eps_per_coordinate: float
    delta_per_coordinate: float
    max_norm: float
    l0_sensitivity: float
    linf_sensitivity: float
    norm_kind: NormKind
    noise_kind: NoiseKind


def _clip_vector(vec: np.ndarray, max_norm: float,
                 norm_kind: NormKind) -> np.ndarray:
    kind = norm_kind.value
    if kind == "linf":
        return np.clip(vec, -max_norm, max_norm)
    if kind in ("l1", "l2"):
        norm = np.linalg.norm(vec, ord=int(kind[-1]))
        if norm == 0:
            return vec
        return vec * min(1.0, max_norm / norm)
    raise NotImplementedError(
        f"Vector norm of kind '{kind}' is not supported.")


def vector_noise_stddev(noise_params: AdditiveVectorNoiseParams) -> float:
    """Per-coordinate noise stddev of add_noise_vector's mechanism."""
    if noise_params.noise_kind == NoiseKind.LAPLACE:
        scale = noise_core.laplace_diversity(
            noise_params.eps_per_coordinate,
            compute_l1_sensitivity(noise_params.l0_sensitivity,
                                   noise_params.linf_sensitivity))
        return scale * math.sqrt(2.0)
    return compute_sigma(
        noise_params.eps_per_coordinate, noise_params.delta_per_coordinate,
        compute_l2_sensitivity(noise_params.l0_sensitivity,
                               noise_params.linf_sensitivity))


def add_noise_vector(vec: np.ndarray,
                     noise_params: AdditiveVectorNoiseParams) -> np.ndarray:
    """Clips the vector to max_norm and noises each coordinate."""
    vec = _clip_vector(np.asarray(vec, dtype=np.float64),
                       noise_params.max_norm, noise_params.norm_kind)
    return np.array([
        _add_random_noise(v, noise_params.eps_per_coordinate,
                          noise_params.delta_per_coordinate,
                          noise_params.l0_sensitivity,
                          noise_params.linf_sensitivity,
                          noise_params.noise_kind) for v in vec
    ])


def equally_split_budget(eps: float, delta: float,
                         no_mechanisms: int) -> List[Tuple[float, float]]:
    """Splits (eps, delta) into no_mechanisms equal parts; the last part takes
    the floating-point remainder so the parts sum exactly."""
    if no_mechanisms <= 0:
        raise ValueError("The number of mechanisms must be a positive integer.")
    eps_used = delta_used = 0.0
    budgets = []
    for _ in range(no_mechanisms - 1):
        budgets.append((eps / no_mechanisms, delta / no_mechanisms))
        eps_used += eps / no_mechanisms
        delta_used += delta / no_mechanisms
    budgets.append((eps - eps_used, delta - delta_used))
    return budgets


# ---------------------------------------------------------------------------
# DP variance (budget split across count / normalized sum / sum of squares)
# ---------------------------------------------------------------------------


def _compute_mean_for_normalized_sum(dp_count: float, sum_: float,
                                     min_value: float, max_value: float,
                                     eps: float, delta: float,
                                     l0_sensitivity: float,
                                     max_contributions_per_partition: float,
                                     noise_kind: NoiseKind) -> float:
    """DP mean of a normalized sum, dividing by a clamped DP count."""
    if min_value == max_value:
        return min_value
    middle = compute_middle(min_value, max_value)
    linf_sensitivity = max_contributions_per_partition * abs(middle - min_value)
    dp_normalized_sum = _add_random_noise(sum_, eps, delta, l0_sensitivity,
                                          linf_sensitivity, noise_kind)
    return dp_normalized_sum / max(1.0, dp_count)


def compute_dp_var(count: int, normalized_sum: float,
                   normalized_sum_squares: float,
                   dp_params: ScalarNoiseParams):
    """DP (count, sum, mean, variance) from raw moments.

    Budget is split equally between count, normalized sum, and normalized sum
    of squares; variance = E[x^2] - E[x]^2 on the noised normalized moments.
    """
    ((count_eps, count_delta), (sum_eps, sum_delta),
     (sq_eps, sq_delta)) = equally_split_budget(dp_params.eps, dp_params.delta,
                                                3)
    l0 = dp_params.l0_sensitivity()

    dp_count = _add_random_noise(count, count_eps, count_delta, l0,
                                 dp_params.max_contributions_per_partition,
                                 dp_params.noise_kind)
    dp_mean = _compute_mean_for_normalized_sum(
        dp_count, normalized_sum, dp_params.min_value, dp_params.max_value,
        sum_eps, sum_delta, l0, dp_params.max_contributions_per_partition,
        dp_params.noise_kind)
    sq_min, sq_max = compute_squares_interval(dp_params.min_value,
                                              dp_params.max_value)
    dp_mean_squares = _compute_mean_for_normalized_sum(
        dp_count, normalized_sum_squares, sq_min, sq_max, sq_eps, sq_delta,
        l0, dp_params.max_contributions_per_partition, dp_params.noise_kind)
    dp_var = dp_mean_squares - dp_mean**2
    if dp_params.min_value != dp_params.max_value:
        dp_mean += compute_middle(dp_params.min_value, dp_params.max_value)
    return dp_count, dp_mean * dp_count, dp_mean, dp_var


def _compute_noise_std(linf_sensitivity: float,
                       dp_params: ScalarNoiseParams) -> float:
    if dp_params.noise_kind == NoiseKind.LAPLACE:
        l1 = compute_l1_sensitivity(dp_params.l0_sensitivity(),
                                    linf_sensitivity)
        return noise_core.laplace_diversity(dp_params.eps, l1) * math.sqrt(2)
    if dp_params.noise_kind == NoiseKind.GAUSSIAN:
        l2 = compute_l2_sensitivity(dp_params.l0_sensitivity(),
                                    linf_sensitivity)
        return compute_sigma(dp_params.eps, dp_params.delta, l2)
    raise ValueError("Only Laplace and Gaussian noise is supported.")


def compute_dp_count_noise_std(dp_params: ScalarNoiseParams) -> float:
    return _compute_noise_std(dp_params.max_contributions_per_partition,
                              dp_params)


def compute_dp_sum_noise_std(dp_params: ScalarNoiseParams) -> float:
    linf = max(abs(dp_params.min_sum_per_partition),
               abs(dp_params.max_sum_per_partition))
    return _compute_noise_std(linf, dp_params)


# ---------------------------------------------------------------------------
# Mechanism objects
# ---------------------------------------------------------------------------


class AdditiveMechanism(abc.ABC):
    """An additive noise mechanism (Laplace or Gaussian)."""

    @abc.abstractmethod
    def add_noise(self, value: Union[int, float]) -> float:
        """Anonymizes value by adding noise."""

    def add_noise_vectorized(self, values: np.ndarray) -> np.ndarray:
        """Batched add_noise over a numpy array (used by vectorized paths)."""
        return np.array([self.add_noise(float(v)) for v in values])

    @property
    @abc.abstractmethod
    def noise_kind(self) -> NoiseKind:
        ...

    @property
    @abc.abstractmethod
    def noise_parameter(self) -> float:
        """Distribution parameter (Laplace scale b / Gaussian sigma)."""

    @property
    @abc.abstractmethod
    def std(self) -> float:
        ...

    @property
    @abc.abstractmethod
    def sensitivity(self) -> float:
        ...

    @abc.abstractmethod
    def describe(self) -> str:
        """One-line description for explain-computation reports."""


class LaplaceMechanism(AdditiveMechanism):

    def __init__(self, epsilon: float, l1_sensitivity: float):
        self._epsilon = epsilon
        self._l1_sensitivity = l1_sensitivity
        self._scale = noise_core.laplace_diversity(epsilon, l1_sensitivity)

    @classmethod
    def create_from_epsilon(cls, epsilon: float,
                            l1_sensitivity: float) -> "LaplaceMechanism":
        return cls(epsilon, l1_sensitivity)

    @classmethod
    def create_from_std_deviation(cls, normalized_stddev: float,
                                  l1_sensitivity: float) -> "LaplaceMechanism":
        """normalized_stddev: std divided by l1_sensitivity."""
        b = normalized_stddev / math.sqrt(2)
        return cls(1.0 / b, l1_sensitivity)

    def add_noise(self, value: Union[int, float]) -> float:
        return noise_core.add_laplace_noise(float(value), self._scale)

    def add_noise_vectorized(self, values: np.ndarray) -> np.ndarray:
        g = noise_core.laplace_granularity(self._scale)
        snapped = noise_core.round_to_granularity(
            np.asarray(values, dtype=np.float64), g)
        return snapped + noise_core.sample_laplace(self._scale,
                                                   size=snapped.shape)

    @property
    def epsilon(self) -> float:
        return self._epsilon

    @property
    def noise_parameter(self) -> float:
        return self._scale

    @property
    def std(self) -> float:
        return self._scale * math.sqrt(2)

    @property
    def noise_kind(self) -> NoiseKind:
        return NoiseKind.LAPLACE

    @property
    def sensitivity(self) -> float:
        return self._l1_sensitivity

    def describe(self) -> str:
        return (f"Laplace mechanism:  parameter={self.noise_parameter}  eps="
                f"{self._epsilon}  l1_sensitivity={self.sensitivity}")


class GaussianMechanism(AdditiveMechanism):

    def __init__(self, sigma: float, l2_sensitivity: float,
                 epsilon: float = 0.0, delta: float = 0.0):
        self._sigma = sigma
        self._l2_sensitivity = l2_sensitivity
        self._epsilon = epsilon
        self._delta = delta

    @classmethod
    def create_from_epsilon_delta(cls, epsilon: float, delta: float,
                                  l2_sensitivity: float) -> "GaussianMechanism":
        sigma = noise_core.analytic_gaussian_sigma(epsilon, delta,
                                                   l2_sensitivity)
        return cls(sigma, l2_sensitivity, epsilon, delta)

    @classmethod
    def create_from_std_deviation(cls, normalized_stddev: float,
                                  l2_sensitivity: float) -> "GaussianMechanism":
        """normalized_stddev: std divided by l2_sensitivity."""
        return cls(normalized_stddev * l2_sensitivity, l2_sensitivity)

    def add_noise(self, value: Union[int, float]) -> float:
        return noise_core.add_gaussian_noise(float(value), self._sigma)

    def add_noise_vectorized(self, values: np.ndarray) -> np.ndarray:
        g = noise_core.gaussian_granularity(self._sigma)
        snapped = noise_core.round_to_granularity(
            np.asarray(values, dtype=np.float64), g)
        return snapped + noise_core.sample_gaussian(self._sigma,
                                                    size=snapped.shape)

    @property
    def epsilon(self) -> float:
        return self._epsilon

    @property
    def delta(self) -> float:
        return self._delta

    @property
    def noise_kind(self) -> NoiseKind:
        return NoiseKind.GAUSSIAN

    @property
    def noise_parameter(self) -> float:
        return self._sigma

    @property
    def std(self) -> float:
        return self._sigma

    @property
    def sensitivity(self) -> float:
        return self._l2_sensitivity

    def describe(self) -> str:
        if self._epsilon > 0:
            eps_delta_str = f"eps={self._epsilon}  delta={self._delta}  "
        else:
            eps_delta_str = ""
        return (f"Gaussian mechanism:  parameter={self.noise_parameter}"
                f"  {eps_delta_str}l2_sensitivity={self.sensitivity}")


class MeanMechanism:
    """DP mean via the normalized-sum trick.

    normalized_sum = sum(x_i - mid) with mid = (min+max)/2 has Linf
    sensitivity (max-min)/2 * max_contributions — smaller than the raw sum's
    max(|min|,|max|) * max_contributions. dp_mean = mid +
    dp_normalized_sum / max(1, dp_count).
    """

    def __init__(self, range_middle: float, count_mechanism: AdditiveMechanism,
                 sum_mechanism: AdditiveMechanism):
        self._range_middle = range_middle
        self._count_mechanism = count_mechanism
        self._sum_mechanism = sum_mechanism

    def compute_mean(self, count: float, normalized_sum: float):
        dp_count = self._count_mechanism.add_noise(count)
        denominator = max(1.0, dp_count)
        dp_normalized_sum = self._sum_mechanism.add_noise(normalized_sum)
        dp_mean = self._range_middle + dp_normalized_sum / denominator
        return dp_count, dp_mean * dp_count, dp_mean

    def describe(self) -> str:
        return (f"    a. Computed 'normalized_sum' = sum of (value - "
                f"{self._range_middle})\n"
                f"    b. Applied to 'count' {self._count_mechanism.describe()}\n"
                f"    c. Applied to 'normalized_sum' "
                f"{self._sum_mechanism.describe()}")


@dataclasses.dataclass
class Sensitivities:
    """L0/Linf/L1/L2 sensitivities with consistency validation."""
    l0: Optional[int] = None
    linf: Optional[float] = None
    l1: Optional[float] = None
    l2: Optional[float] = None

    def __post_init__(self):
        for name in ("l0", "linf", "l1", "l2"):
            value = getattr(self, name)
            if value is not None and value <= 0:
                raise ValueError(
                    f"{name.capitalize()} must be positive, but {value} given.")
        if (self.l0 is None) != (self.linf is None):
            raise ValueError("l0 and linf sensitivities must be either both "
                             "set or both unset.")
        if self.l0 is not None:
            l1 = compute_l1_sensitivity(self.l0, self.linf)
            if self.l1 is None:
                self.l1 = l1
            elif abs(l1 - self.l1) > 1e-12:
                raise ValueError(f"L1={self.l1} != L0*Linf={l1}")
            l2 = compute_l2_sensitivity(self.l0, self.linf)
            if self.l2 is None:
                self.l2 = l2
            elif abs(l2 - self.l2) > 1e-12:
                raise ValueError(f"L2={self.l2} != sqrt(L0)*Linf={l2}")


def create_additive_mechanism(
        mechanism_spec: budget_accounting.MechanismSpec,
        sensitivities: Sensitivities) -> AdditiveMechanism:
    """Builds the mechanism from a resolved budget spec + sensitivities."""
    noise_kind = mechanism_spec.mechanism_type.to_noise_kind()
    if noise_kind == NoiseKind.LAPLACE:
        if sensitivities.l1 is None:
            raise ValueError("L1 or (L0 and Linf) sensitivities must be set "
                             "for Laplace mechanism.")
        if mechanism_spec.standard_deviation_is_set:
            return LaplaceMechanism.create_from_std_deviation(
                mechanism_spec.noise_standard_deviation, sensitivities.l1)
        return LaplaceMechanism.create_from_epsilon(mechanism_spec.eps,
                                                    sensitivities.l1)
    if noise_kind == NoiseKind.GAUSSIAN:
        if sensitivities.l2 is None:
            raise ValueError("L2 or (L0 and Linf) sensitivities must be set "
                             "for Gaussian mechanism.")
        if mechanism_spec.standard_deviation_is_set:
            return GaussianMechanism.create_from_std_deviation(
                mechanism_spec.noise_standard_deviation, sensitivities.l2)
        return GaussianMechanism.create_from_epsilon_delta(
            mechanism_spec.eps, mechanism_spec.delta, sensitivities.l2)
    raise ValueError(f"{noise_kind} not supported.")


def create_mean_mechanism(
        range_middle: float, count_spec: budget_accounting.MechanismSpec,
        count_sensitivities: Sensitivities,
        normalized_sum_spec: budget_accounting.MechanismSpec,
        normalized_sum_sensitivities: Sensitivities) -> MeanMechanism:
    return MeanMechanism(
        range_middle,
        create_additive_mechanism(count_spec, count_sensitivities),
        create_additive_mechanism(normalized_sum_spec,
                                  normalized_sum_sensitivities))


# ---------------------------------------------------------------------------
# Exponential mechanism
# ---------------------------------------------------------------------------


class ExponentialMechanism:
    """Chooses one of a finite set of candidates with probability
    proportional to exp(eps * score / (2 * sensitivity)) (the factor 2 is
    dropped for monotonic scoring functions). In-memory only."""

    class ScoringFunction(abc.ABC):

        @abc.abstractmethod
        def score(self, k) -> float:
            """Higher score => higher selection probability."""

        @property
        @abc.abstractmethod
        def global_sensitivity(self) -> float:
            ...

        @property
        @abc.abstractmethod
        def is_monotonic(self) -> bool:
            """Whether neighboring datasets move all scores one direction."""

    # Candidate draws are DP releases (calculate_private_contribution_bounds
    # publishes the result), so the uniform comes from noise_core's secure
    # sampler; seed_rng swaps in a seeded numpy Generator for tests.
    _seeded_rng: Optional[np.random.Generator] = None

    @classmethod
    def seed_rng(cls, seed: Optional[int]) -> None:
        """Routes selection draws through a seeded numpy RNG (tests only).

        Pass seed_rng(None) to restore the secure non-replayable source.
        """
        # The default draw in apply() is noise_core.sample_uniform; this
        # generator only exists so tests can replay the candidate choice.
        # dplint: disable=DPL004 — test-only seeded fallback
        cls._seeded_rng = None if seed is None else np.random.default_rng(seed)

    def __init__(self, scoring_function: "ExponentialMechanism.ScoringFunction"):
        self._scoring_function = scoring_function

    def apply(self, eps: float, inputs_to_score_col: List[Any]) -> Any:
        probs = self._calculate_probabilities(eps, inputs_to_score_col)
        if ExponentialMechanism._seeded_rng is not None:
            u = ExponentialMechanism._seeded_rng.random()
        else:
            u = noise_core.sample_uniform()
        # Inverse-CDF draw: first index whose cumulative probability exceeds u.
        index = min(int(np.searchsorted(np.cumsum(probs), u, side="right")),
                    len(probs) - 1)
        return inputs_to_score_col[index]

    def _calculate_probabilities(self, eps: float,
                                 inputs_to_score_col: List[Any]) -> np.ndarray:
        scores = np.array(
            [self._scoring_function.score(k) for k in inputs_to_score_col],
            dtype=np.float64)
        denominator = self._scoring_function.global_sensitivity
        if not self._scoring_function.is_monotonic:
            denominator *= 2
        # Subtract max for numerical stability (invariant under softmax).
        weights = np.exp((scores - scores.max()) * eps / denominator)
        return weights / weights.sum()


# ---------------------------------------------------------------------------
# Per-metric sensitivities
# ---------------------------------------------------------------------------


def compute_sensitivities_for_count(params: AggregateParams) -> Sensitivities:
    if params.max_contributions is not None:
        return Sensitivities(l1=params.max_contributions,
                             l2=params.max_contributions)
    return Sensitivities(l0=params.max_partitions_contributed,
                         linf=params.max_contributions_per_partition)


def compute_sensitivities_for_privacy_id_count(
        params: AggregateParams) -> Sensitivities:
    if params.max_contributions is not None:
        return Sensitivities(l1=params.max_contributions,
                             l2=math.sqrt(params.max_contributions))
    return Sensitivities(l0=params.max_partitions_contributed, linf=1)


def compute_sensitivities_for_sum(params: AggregateParams) -> Sensitivities:
    if params.bounds_per_contribution_are_set:
        max_abs = max(abs(params.min_value), abs(params.max_value))
        if params.max_contributions:
            l1_l2 = max_abs * params.max_contributions
            return Sensitivities(l1=l1_l2, l2=l1_l2)
        linf = max_abs * params.max_contributions_per_partition
    else:
        linf = max(abs(params.min_sum_per_partition),
                   abs(params.max_sum_per_partition))
    return Sensitivities(l0=params.max_partitions_contributed, linf=linf)


def compute_sensitivities(metric: Metric,
                          params: AggregateParams) -> Sensitivities:
    if metric == Metrics.COUNT:
        return compute_sensitivities_for_count(params)
    if metric == Metrics.PRIVACY_ID_COUNT:
        return compute_sensitivities_for_privacy_id_count(params)
    if metric == Metrics.SUM:
        return compute_sensitivities_for_sum(params)
    raise ValueError(f"Sensitivity computations for {metric} not supported")


def compute_sensitivities_for_normalized_sum(
        params: AggregateParams) -> Sensitivities:
    max_abs = (params.max_value - params.min_value) / 2
    if params.max_contributions:
        l1_l2 = max_abs * params.max_contributions
        return Sensitivities(l1=l1_l2, l2=l1_l2)
    return Sensitivities(l0=params.max_partitions_contributed,
                         linf=max_abs * params.max_contributions_per_partition)


# ---------------------------------------------------------------------------
# Thresholding mechanism (post-aggregation partition selection)
# ---------------------------------------------------------------------------


class ThresholdingMechanism:
    """Noises a privacy-unit count and keeps it only above a threshold.

    Steps 2-3 of the (Laplace/Gaussian) thresholding algorithm: noise with
    stddev from (eps, delta, l0_sensitivity), threshold from delta (per
    Delta_For_Thresholding.pdf).
    """

    def __init__(self, epsilon: float, delta: float,
                 strategy: PartitionSelectionStrategy, l0_sensitivity: int,
                 pre_threshold: Optional[int]):
        self._strategy_type = strategy
        self._pre_threshold = pre_threshold
        self._thresholding_strategy = (
            partition_selection.create_partition_selection_strategy(
                strategy, epsilon, delta, l0_sensitivity, pre_threshold))

    def noised_value_if_should_keep(
            self, num_privacy_units: int) -> Optional[float]:
        return self._thresholding_strategy.noised_value_if_should_keep(
            num_privacy_units)

    def describe(self) -> str:
        eps = self._thresholding_strategy.epsilon
        delta = self._thresholding_strategy.delta
        threshold = self._thresholding_strategy.threshold
        text = (f"{self._strategy_type.value} with threshold={threshold:.1f} "
                f"eps={eps} delta={delta}")
        if self._pre_threshold is not None:
            text += f" and pre_threshold={self._pre_threshold}"
        return text

    def threshold(self) -> float:
        return self._thresholding_strategy.threshold

    @property
    def strategy(self) -> partition_selection.PartitionSelection:
        return self._thresholding_strategy


def create_thresholding_mechanism(
        mechanism_spec: budget_accounting.MechanismSpec,
        sensitivities: Sensitivities,
        pre_threshold: Optional[int]) -> ThresholdingMechanism:
    strategy = mechanism_spec.mechanism_type.to_partition_selection_strategy()
    return ThresholdingMechanism(epsilon=mechanism_spec.eps,
                                 delta=mechanism_spec.delta,
                                 strategy=strategy,
                                 l0_sensitivity=sensitivities.l0,
                                 pre_threshold=pre_threshold)
