"""Scalar noise core: calibration + sampling for the host-side (driver) path.

Role in the stack: this module is the Python-visible surface of the L0
"native DP primitives" layer (reference reaches it through PyDP's pybind11
wrapper over Google's C++ differential-privacy library —
dp_computations.py:25, see SURVEY.md §2.4). Noise calibration (sigma for the
analytic Gaussian mechanism, Laplace diversity) lives here in pure float
math; *sampling* is delegated to the native C++ library
(pipelinedp_tpu/native/secure_noise.cc — exact discrete Laplace/Gaussian
samplers over the kernel CSPRNG), auto-installed on the first draw when a
compiler is available; the numpy fallback covers environments without one.

Security note (why a native library exists at all): naive float Laplace
sampling leaks information through the floating-point representation
(Mironov 2012, "On significance of the least significant bits for
differential privacy"). The native mitigation is the snapping/granularity
construction: noise is sampled as an *integer* multiple of a power-of-two
granularity (an exact discrete Laplace / discrete Gaussian, Canonne-Kamath-
Steinke 2020), and the value is rounded to the same granularity before
adding. The numpy fallback implements the same granularity snapping on top
of numpy's float samplers — distributions match, bit-level security
guarantees require the native path (check with using_native_sampling()).

The TPU bulk path (pipelinedp_tpu/ops/noise.py, built alongside the JAX
backend) applies the same snapping scheme with JAX's counter-based threefry
PRNG.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np
from scipy import special
from scipy import stats

# 2^-40: relative granularity for Laplace snapping (matches the construction
# used by Google's C++ library: granularity = next power of two of scale/2^40).
_LAPLACE_GRANULARITY_BITS = 40
# 2^-57 for Gaussian.
_GAUSSIAN_GRANULARITY_BITS = 57


def next_power_of_two(x: float) -> float:
    """Smallest power of two >= x (x > 0). Exact for float64."""
    if x <= 0 or not math.isfinite(x):
        raise ValueError(f"next_power_of_two requires finite x > 0, got {x}")
    mantissa, exponent = math.frexp(x)  # x = mantissa * 2**exponent
    if mantissa == 0.5:
        return x
    return math.ldexp(1.0, exponent)


def laplace_granularity(scale: float) -> float:
    return next_power_of_two(
        max(scale, 2.0**-_LAPLACE_GRANULARITY_BITS) *
        2.0**-_LAPLACE_GRANULARITY_BITS)


def gaussian_granularity(stddev: float) -> float:
    return next_power_of_two(
        max(stddev, 2.0**-_GAUSSIAN_GRANULARITY_BITS) *
        2.0**-_GAUSSIAN_GRANULARITY_BITS)


def round_to_granularity(value, granularity: float):
    return np.round(value / granularity) * granularity


# ---------------------------------------------------------------------------
# Calibration
# ---------------------------------------------------------------------------


def gaussian_delta(sigma: float, eps: float, l2_sensitivity: float) -> float:
    """delta achieved by a Gaussian mechanism (exact analytic expression).

    delta = Phi(s/(2 sigma) - eps sigma/s) - e^eps Phi(-s/(2 sigma) - eps
    sigma/s), per Balle & Wang, "Improving the Gaussian mechanism for
    differential privacy" (arXiv:1805.06530) — the calibration the reference
    uses via PyDP (dp_computations.py:116, cited at
    private_contribution_bounds.py:126).
    """
    s = l2_sensitivity
    a = s / (2.0 * sigma)
    b = eps * sigma / s
    # e^eps Phi(-a-b) in log space: for large eps the exponential overflows
    # while the product stays finite. In fact log_term = eps + log Phi(-a-b)
    # <= eps - (a+b)^2/2 <= eps - 2ab = 0 by AM-GM (2ab = eps), so the
    # product is always <= 1; exp never overflows.
    log_term = eps + special.log_ndtr(-a - b)
    return float(stats.norm.cdf(a - b) - math.exp(log_term))


def analytic_gaussian_sigma(eps: float, delta: float,
                            l2_sensitivity: float) -> float:
    """Minimal sigma with gaussian_delta(sigma) <= delta (binary search)."""
    if eps <= 0:
        raise ValueError(f"eps must be positive, got {eps}")
    if not 0 < delta < 1:
        raise ValueError(f"delta must be in (0, 1), got {delta}")
    if l2_sensitivity <= 0:
        raise ValueError(
            f"l2_sensitivity must be positive, got {l2_sensitivity}")
    # Bracket: classical sigma = sqrt(2 ln(1.25/delta)) * s / eps always works
    # for eps <= 1; double until valid for the general case.
    hi = math.sqrt(2.0 * math.log(1.25 / delta)) * l2_sensitivity / eps
    while gaussian_delta(hi, eps, l2_sensitivity) > delta:
        hi *= 2.0
    lo = hi / 2.0**20
    if gaussian_delta(lo, eps, l2_sensitivity) <= delta:
        return lo
    for _ in range(200):
        mid = 0.5 * (lo + hi)
        if gaussian_delta(mid, eps, l2_sensitivity) <= delta:
            hi = mid
        else:
            lo = mid
        if hi - lo <= 1e-12 * hi:
            break
    return hi


def laplace_diversity(eps: float, l1_sensitivity: float) -> float:
    """Laplace scale parameter b = l1_sensitivity / eps."""
    if eps <= 0:
        raise ValueError(f"eps must be positive, got {eps}")
    return l1_sensitivity / eps


# ---------------------------------------------------------------------------
# Sampling. The hooks below start in "autoload" state: the first draw
# builds/loads the native C++ samplers (pipelinedp_tpu/native) and rebinds
# the hooks — deferred so importing the package never shells out to g++.
# The numpy fallback covers environments without a compiler, with a lock
# because backends may draw noise from worker threads.
# ---------------------------------------------------------------------------

import logging as _logging
import threading as _threading

_rng = np.random.default_rng()
_rng_lock = _threading.Lock()


def seed_fallback_rng(seed: Optional[int]) -> None:
    """Test hook: reseeds the numpy RNG AND routes sampling through the
    (seedable) fallback — secure native noise is deliberately not
    replayable, so deterministic tests must opt out of it. Call
    pipelinedp_tpu.native.install() to restore the native path."""
    global _rng, sample_laplace, sample_gaussian, sample_uniform
    _rng = np.random.default_rng(seed)
    sample_laplace = _fallback_laplace
    sample_gaussian = _fallback_gaussian
    sample_uniform = _fallback_uniform


def _fallback_laplace(scale: float, size=None):
    g = laplace_granularity(scale)
    with _rng_lock:
        raw = _rng.laplace(0.0, scale, size)
    return round_to_granularity(raw, g)


def _fallback_gaussian(stddev: float, size=None):
    g = gaussian_granularity(stddev)
    with _rng_lock:
        raw = _rng.normal(0.0, stddev, size)
    return round_to_granularity(raw, g)


def _fallback_uniform(size=None):
    with _rng_lock:
        return _rng.random() if size is None else _rng.random(size)


_native_attempted = False


def _try_native_install() -> None:
    """One attempt to build/load the native samplers (rebinds the hooks).

    The first draw may shell out to g++ (see native/loader.py); deployments
    that cannot afford that latency on the first DP release should warm up
    explicitly with pipelinedp_tpu.native.install().
    """
    global _native_attempted
    if _native_attempted:
        return
    _native_attempted = True
    from pipelinedp_tpu.native import loader as native_loader
    try:
        ok = native_loader.install()
    except native_loader.LOADER_ERRORS + (ValueError,) as e:
        # Build/load/ctypes failures fall back to the numpy samplers;
        # NativeRequiredError (and anything else) propagates — under
        # PIPELINEDP_TPU_REQUIRE_NATIVE a toolchain regression must be a
        # hard error, not a silent downgrade of the bit-level guarantees.
        _logging.warning(
            "pipelinedp_tpu: native secure-noise install raised %r; "
            "falling back to the seedable numpy samplers "
            "(distributionally equivalent, weaker bit-level guarantees)", e)
    else:
        if not ok:
            _logging.warning(
                "pipelinedp_tpu: native secure-noise library unavailable "
                "(no compiler, or the build failed — details at INFO "
                "level); noise and selection draws use the seedable numpy "
                "fallback. Warm up at startup with "
                "pipelinedp_tpu.native.install() to control when the "
                "build cost is paid, or ship a prebuilt _secure_noise "
                "shared object matching the current ABI.")


def _autoload_laplace(scale: float, size=None):
    global sample_laplace, sample_gaussian, sample_uniform
    _try_native_install()
    if sample_laplace is _autoload_laplace:  # native unavailable
        _bind_fallbacks()
    return sample_laplace(scale, size)


def _autoload_gaussian(stddev: float, size=None):
    global sample_gaussian
    _try_native_install()
    if sample_gaussian is _autoload_gaussian:
        _bind_fallbacks()
    return sample_gaussian(stddev, size)


def _autoload_uniform(size=None):
    global sample_uniform
    _try_native_install()
    if sample_uniform is _autoload_uniform:
        _bind_fallbacks()
    return sample_uniform(size)


def _bind_fallbacks() -> None:
    global sample_laplace, sample_gaussian, sample_uniform
    sample_laplace = _fallback_laplace
    sample_gaussian = _fallback_gaussian
    sample_uniform = _fallback_uniform


# Hook points: rebound to the native C++ samplers on first draw (or to the
# numpy fallback when no native build is possible / after seed_fallback_rng).
sample_laplace = _autoload_laplace
sample_gaussian = _autoload_gaussian
sample_uniform = _autoload_uniform


def using_native_sampling() -> bool:
    return sample_laplace not in (_fallback_laplace, _autoload_laplace)


def add_laplace_noise(value: float, scale: float) -> float:
    """value snapped to granularity + secure Laplace noise."""
    g = laplace_granularity(scale)
    return float(round_to_granularity(value, g) + sample_laplace(scale))


def add_gaussian_noise(value: float, stddev: float) -> float:
    g = gaussian_granularity(stddev)
    return float(round_to_granularity(value, g) + sample_gaussian(stddev))


def add_laplace_noise_array(values: np.ndarray, scale: float) -> np.ndarray:
    """Vectorized float64 host noise (the secure finalization path for the
    columnar engine: O(num_partitions), off the TPU hot path)."""
    g = laplace_granularity(scale)
    values = np.asarray(values, dtype=np.float64)
    return round_to_granularity(values, g) + sample_laplace(scale,
                                                            values.shape)


def add_gaussian_noise_array(values: np.ndarray, stddev: float) -> np.ndarray:
    g = gaussian_granularity(stddev)
    values = np.asarray(values, dtype=np.float64)
    return round_to_granularity(values, g) + sample_gaussian(stddev,
                                                             values.shape)


def add_noise_array(values: np.ndarray, is_gaussian: bool,
                    scale_or_std: float) -> np.ndarray:
    if is_gaussian:
        return add_gaussian_noise_array(values, scale_or_std)
    return add_laplace_noise_array(values, scale_or_std)
