"""Contribution bounders: enforce L0/Linf/L1 sensitivity by per-key sampling.

Parity: pipeline_dp/contribution_bounders.py (ContributionBounder ABC :31,
SamplingCrossAndPerPartitionContributionBounder :62-111,
SamplingPerPrivacyIdContributionBounder :114-156,
SamplingCrossPartitionContributionBounder :159-201, LinfSampler :204-230,
NoOpSampler :233-246, collect_values_per_partition_key_per_privacy_id :249).

These are expressed purely in backend primitives so any backend (local
generators or the columnar JAX backend, which lowers sample_fixed_per_key to
a sort + random-rank kernel) executes them.
"""

from __future__ import annotations

import abc
import collections
from typing import Callable, Iterable

from pipelinedp_tpu import sampling_utils
from pipelinedp_tpu.backends import base


class ContributionBounder(abc.ABC):
    """Bounds each privacy unit's contributions, then aggregates.

    ``bound_contributions`` receives (privacy_id, partition_key, value) rows
    and returns ((privacy_id, partition_key), accumulator) after applying
    ``aggregate_fn`` to the surviving values of each (pid, pk) group.
    """

    @abc.abstractmethod
    def bound_contributions(self, col, params, backend: base.PipelineBackend,
                            report_generator, aggregate_fn: Callable):
        ...


class SamplingCrossAndPerPartitionContributionBounder(ContributionBounder):
    """L0 + Linf bounding: samples values within each (pid, pk) to
    max_contributions_per_partition, then samples partitions per pid to
    max_partitions_contributed."""

    def bound_contributions(self, col, params, backend, report_generator,
                            aggregate_fn):
        max_partitions = params.max_partitions_contributed
        max_per_partition = params.max_contributions_per_partition
        col = backend.map_tuple(
            col, lambda pid, pk, v: ((pid, pk), v),
            "Rekey to ((privacy_id, partition_key), value)")
        col = backend.sample_fixed_per_key(
            col, max_per_partition, "Sample per (privacy_id, partition_key)")
        report_generator.add_stage(
            f"Per-partition contribution bounding: for each privacy_id and "
            f"each partition, randomly select "
            f"max(actual_contributions_per_partition, {max_per_partition}) "
            f"contributions.")
        col = backend.map_values(
            col, aggregate_fn, "Apply aggregate_fn after per partition "
            "bounding")
        # ((pid, pk), accumulator) -> (pid, (pk, accumulator))
        col = backend.map_tuple(
            col, lambda pid_pk, acc: (pid_pk[0], (pid_pk[1], acc)),
            "Rekey to (privacy_id, (partition_key, accumulator))")
        col = backend.sample_fixed_per_key(col, max_partitions,
                                           "Sample per privacy_id")
        report_generator.add_stage(
            f"Cross-partition contribution bounding: for each privacy_id "
            f"randomly select max(actual_partition_contributed, "
            f"{max_partitions}) partitions")

        def unnest(pid, pk_accs):
            return (((pid, pk), acc) for pk, acc in pk_accs)

        return backend.flat_map(
            col, lambda kv: unnest(*kv), "Rekey by privacy_id and unnest")


class SamplingPerPrivacyIdContributionBounder(ContributionBounder):
    """L1 bounding: samples each privacy id's total contributions down to
    max_contributions, across all partitions."""

    def bound_contributions(self, col, params, backend, report_generator,
                            aggregate_fn):
        max_contributions = params.max_contributions
        col = backend.map_tuple(
            col, lambda pid, pk, v: (pid, (pk, v)),
            "Rekey to (privacy_id, (partition_key, value))")
        col = backend.sample_fixed_per_key(col, max_contributions,
                                           "Sample per privacy_id")
        report_generator.add_stage(
            f"User contribution bounding: randomly selected not more than "
            f"{max_contributions} contributions")
        col = collect_values_per_partition_key_per_privacy_id(col, backend)

        def unnest(pid, partition_values):
            return (((pid, pk), values) for pk, values in partition_values)

        col = backend.flat_map(col, lambda kv: unnest(*kv), "Unnest")
        return backend.map_values(
            col, aggregate_fn,
            "Apply aggregate_fn after per privacy_id contribution bounding")


class SamplingCrossPartitionContributionBounder(ContributionBounder):
    """L0-only bounding: samples partitions per privacy id; per-partition
    bounding is the aggregate_fn's responsibility (e.g. per-partition sum
    clipping)."""

    def bound_contributions(self, col, params, backend, report_generator,
                            aggregate_fn):
        col = backend.map_tuple(
            col, lambda pid, pk, v: (pid, (pk, v)),
            "Rekey to (privacy_id, (partition_key, value))")
        col = backend.group_by_key(col, "Group by privacy_id")
        col = collect_values_per_partition_key_per_privacy_id(col, backend)
        sample = sampling_utils.choose_from_list_without_replacement
        sample_size = params.max_partitions_contributed
        col = backend.map_values(col, lambda a: sample(a, sample_size),
                                 "Sample")
        # The reference's twin adds no stage here (contribution_bounders
        # .py:159-201) — an explain-report gap; the bound is real, so
        # report it.
        report_generator.add_stage(
            f"Cross-partition contribution bounding: for each privacy_id "
            f"randomly select max(actual_partition_contributed, "
            f"{sample_size}) partitions")

        def unnest(pid, partition_values):
            return (((pid, pk), values) for pk, values in partition_values)

        col = backend.flat_map(col, lambda kv: unnest(*kv),
                               "Unnest per privacy_id")
        return backend.map_values(
            col, aggregate_fn,
            "Apply aggregate_fn after cross-partition contribution bounding")


class LinfSampler(ContributionBounder):
    """Linf-only bounding: samples values within each (pid, pk)."""

    def bound_contributions(self, col, params, backend, report_generator,
                            aggregate_fn):
        col = backend.map_tuple(
            col, lambda pid, pk, v: ((pid, pk), v),
            "Rekey to ((privacy_id, partition_key), value)")
        col = backend.sample_fixed_per_key(
            col, params.max_contributions_per_partition,
            "Sample per (privacy_id, partition_key)")
        report_generator.add_stage(
            f"Per-partition contribution bounding: for each privacy_id and "
            f"each partition, randomly select "
            f"max(actual_contributions_per_partition, "
            f"{params.max_contributions_per_partition}) contributions.")
        return backend.map_values(col, aggregate_fn, "Apply aggregate_fn")


class NoOpSampler(ContributionBounder):
    """No bounding: groups per (pid, pk) and aggregates everything."""

    def bound_contributions(self, col, params, backend, report_generator,
                            aggregate_fn):
        col = backend.map_tuple(
            col, lambda pid, pk, v: ((pid, pk), v),
            "Rekey to ((privacy_id, partition_key), value)")
        col = backend.group_by_key(col, "Group by (privacy_id, partition_key)")
        return backend.map_values(col, aggregate_fn, "Apply aggregate_fn")


def collect_values_per_partition_key_per_privacy_id(
        col, backend: base.PipelineBackend):
    """(pid, Iterable[(pk, value)]) -> (pid, [(pk, [values])])."""

    def collect(pairs: Iterable):
        grouped = collections.defaultdict(list)
        for key, value in pairs:
            grouped[key].append(value)
        return list(grouped.items())

    return backend.map_values(
        col, collect, "Collect values per privacy_id and partition_key")
