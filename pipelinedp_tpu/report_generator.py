"""Explain Computation reports.

Every DP aggregation collects an ordered list of stage descriptions; stages
may be callables that are resolved only when the report text is rendered —
after ``BudgetAccountant.compute_budgets()`` — because budget numbers are not
known at graph-construction time.

Parity: pipeline_dp/report_generator.py (ReportGenerator :46-89,
ExplainComputationReport :92-115).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Union

from pipelinedp_tpu import aggregate_params as agg
from pipelinedp_tpu import budget_accounting


class ReportGenerator:
    """Accumulates the stages of one DP aggregation and renders the report."""

    def __init__(self,
                 params,
                 method_name: str,
                 is_public_partition: Optional[bool] = None):
        self._params_str: Optional[str] = None
        if params:
            self._params_str = agg.parameters_to_readable_string(
                params, is_public_partition)
        self._method_name = method_name
        self._stages: List[Union[Callable[[], str], str]] = []

    def add_stage(self, stage_description: Union[Callable[[], str],
                                                 str]) -> None:
        """Appends a stage; callables are rendered lazily at report() time."""
        self._stages.append(stage_description)

    def report(self) -> str:
        if not self._params_str:
            return ""
        lines = [f"DPEngine method: {self._method_name}", self._params_str,
                 "Computation graph:"]
        for i, stage in enumerate(self._stages, start=1):
            text = stage() if callable(stage) else stage
            lines.append(f" {i}. {text}")
        return "\n".join(lines)


class ExplainComputationReport:
    """User-facing handle for one aggregation's explain report."""

    def __init__(self):
        self._report_generator: Optional[ReportGenerator] = None

    def _set_report_generator(self, report_generator: ReportGenerator):
        self._report_generator = report_generator

    def text(self) -> str:
        if self._report_generator is None:
            raise ValueError(
                "The report_generator is not set.\nWas this object passed as "
                "an argument to a DP aggregation method?")
        try:
            return self._report_generator.report()
        except (AssertionError, AttributeError, TypeError, ValueError,
                budget_accounting.BudgetAccountantError) as e:
            # The lazy stage callables read budget numbers off the
            # MechanismSpecs; before compute_budgets() those reads raise
            # AssertionError ("not calculated yet" — the reference's
            # pinned contract) or one of these typed errors. Anything
            # else is a bug in a stage renderer and must propagate as-is.
            raise ValueError(
                "Explain computation report failed to be generated.\nWas "
                "BudgetAccountant.compute_budgets() called?") from e
