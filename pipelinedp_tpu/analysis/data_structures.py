"""Configuration dataclasses for the utility analysis.

Parity: analysis/data_structures.py (MultiParameterConfiguration :25,
UtilityAnalysisOptions :100, get_aggregate_params :124,
get_partition_selection_strategy :137). The multi-parameter sweep here is
the leading axis of the vectorized analysis grid (per_partition.py), not a
list of combiner objects.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Iterator, List, Optional, Sequence

from pipelinedp_tpu import input_validators
from pipelinedp_tpu.aggregate_params import (AggregateParams, NoiseKind,
                                             PartitionSelectionStrategy)


@dataclasses.dataclass
class MultiParameterConfiguration:
    """A sweep over AggregateParams attributes.

    Every non-None attribute is a sequence of per-configuration values; all
    set attributes must have equal length. Configuration i is the blueprint
    AggregateParams with attribute i substituted.
    """
    max_partitions_contributed: Optional[Sequence[int]] = None
    max_contributions_per_partition: Optional[Sequence[int]] = None
    min_sum_per_partition: Optional[Sequence[float]] = None
    max_sum_per_partition: Optional[Sequence[float]] = None
    noise_kind: Optional[Sequence[NoiseKind]] = None
    partition_selection_strategy: Optional[
        Sequence[PartitionSelectionStrategy]] = None
    post_aggregation_thresholding: Optional[Sequence[bool]] = None

    def __post_init__(self):
        lengths = {
            len(v)
            for v in dataclasses.asdict(self).values() if v
        }
        if not lengths:
            raise ValueError("MultiParameterConfiguration requires at least "
                             "one non-empty attribute.")
        if len(lengths) > 1:
            raise ValueError("All set MultiParameterConfiguration attributes "
                             "must have the same length.")
        if (self.min_sum_per_partition is None) != (
                self.max_sum_per_partition is None):
            raise ValueError(
                "min_sum_per_partition and max_sum_per_partition must be "
                "both set or both None.")
        self._size = lengths.pop()

    @property
    def size(self) -> int:
        return self._size

    def get_aggregate_params(self, blueprint: AggregateParams,
                             index: int) -> AggregateParams:
        """Blueprint with the index-th swept values substituted."""
        params = copy.copy(blueprint)
        for field in ("max_partitions_contributed",
                      "max_contributions_per_partition",
                      "min_sum_per_partition", "max_sum_per_partition",
                      "noise_kind", "partition_selection_strategy",
                      "post_aggregation_thresholding"):
            values = getattr(self, field)
            if values:
                setattr(params, field, values[index])
        return params


@dataclasses.dataclass
class UtilityAnalysisOptions:
    """Options for the utility analysis.

    use_device_sweep: True runs the multi-parameter error-model sweep as a
      jitted device kernel (analysis/device_sweep.py), False keeps it on
      host numpy, None (default) auto-selects: device when an accelerator
      is present and the [configurations x groups] grid is large enough to
      amortize the launch.
    device_mesh: a jax.sharding.Mesh (parallel/sharded.make_mesh): the
      sweep's group dimension shards over the mesh and the per-partition
      grids ride the same ICI-first reduce-scatter as the aggregation
      kernels. Implies the device sweep.
    """
    epsilon: float
    delta: float
    aggregate_params: AggregateParams
    multi_param_configuration: Optional[MultiParameterConfiguration] = None
    partitions_sampling_prob: float = 1
    pre_aggregated_data: bool = False
    use_device_sweep: Optional[bool] = None
    device_mesh: Optional[object] = None

    def __post_init__(self):
        input_validators.validate_epsilon_delta(self.epsilon, self.delta,
                                                "UtilityAnalysisOptions")
        if not 0 < self.partitions_sampling_prob <= 1:
            raise ValueError("partitions_sampling_prob must be in (0, 1], "
                             f"got {self.partitions_sampling_prob}.")

    @property
    def n_configurations(self) -> int:
        if self.multi_param_configuration is None:
            return 1
        return self.multi_param_configuration.size


def get_aggregate_params(
        options: UtilityAnalysisOptions) -> Iterator[AggregateParams]:
    """Yields the AggregateParams of every configuration in the sweep."""
    config = options.multi_param_configuration
    if config is None:
        yield options.aggregate_params
        return
    for i in range(config.size):
        yield config.get_aggregate_params(options.aggregate_params, i)


def get_partition_selection_strategy(
    options: UtilityAnalysisOptions
) -> List[PartitionSelectionStrategy]:
    """Per-configuration partition selection strategies."""
    config = options.multi_param_configuration
    if config is not None and config.partition_selection_strategy is not None:
        return list(config.partition_selection_strategy)
    n = 1 if config is None else config.size
    return [options.aggregate_params.partition_selection_strategy] * n
