"""Columnar pre-aggregation: (pid, pk, value) rows -> per-(pid, pk) groups.

This is the TPU-native replacement for the reference's
AnalysisContributionBounder + preaggregate (analysis/contribution_bounders
.py:19-77, analysis/pre_aggregation.py:19-61): one lexsort + segment
reductions produce, for every (privacy_id, partition) pair, the
contribution count, contribution sum and the number of distinct partitions
the privacy id touches. Those three arrays are the entire input of the
utility-analysis error models — no per-row combiner objects exist.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional, Tuple

import numpy as np

from pipelinedp_tpu import sampling_utils
from pipelinedp_tpu.data_extractors import DataExtractors
from pipelinedp_tpu.ops import encoding


@dataclasses.dataclass
class PreAggregates:
    """Per-(privacy_id, partition) group columns, all of equal length G.

    pk_ids: dense partition id of each group.
    counts: number of contributions in the group.
    sums: sum of contributed values in the group.
    n_partitions: number of distinct partitions the group's privacy id
      contributes to (the L0 load of that privacy id).
    pk_vocab: id -> partition key.
    """
    pk_ids: np.ndarray
    counts: np.ndarray
    sums: np.ndarray
    n_partitions: np.ndarray
    pk_vocab: encoding.Vocabulary

    @property
    def num_groups(self) -> int:
        return len(self.pk_ids)


def preaggregate_columns(pid: np.ndarray, pk: np.ndarray, value: np.ndarray,
                         pk_vocab: encoding.Vocabulary) -> PreAggregates:
    """Groups encoded columns by (pid, pk) with one lexsort + reduceat."""
    n = len(pid)
    if n == 0:
        empty = np.zeros(0)
        return PreAggregates(empty.astype(np.int32), empty, empty,
                             empty.astype(np.int32), pk_vocab)
    order = np.lexsort((pk, pid))
    spid, spk, sval = pid[order], pk[order], value[order]
    is_start = np.empty(n, dtype=bool)
    is_start[0] = True
    np.not_equal(spid[1:], spid[:-1], out=is_start[1:])
    is_start[1:] |= spk[1:] != spk[:-1]
    starts = np.flatnonzero(is_start)
    counts = np.diff(np.append(starts, n)).astype(np.float64)
    sums = np.add.reduceat(sval.astype(np.float64), starts)
    g_pid = spid[starts]
    g_pk = spk[starts]
    # Distinct partitions per privacy id, broadcast back onto the groups.
    pid_start = np.empty(len(g_pid), dtype=bool)
    pid_start[0] = True
    np.not_equal(g_pid[1:], g_pid[:-1], out=pid_start[1:])
    pid_group = np.cumsum(pid_start) - 1
    partitions_per_pid = np.bincount(pid_group)
    n_partitions = partitions_per_pid[pid_group].astype(np.int32)
    return PreAggregates(g_pk.astype(np.int32), counts, sums, n_partitions,
                         pk_vocab)


def sample_partitions(pre: PreAggregates,
                      sampling_prob: float) -> PreAggregates:
    """Deterministic partition subsampling (ValueSampler keyed by partition
    key): every group of a sampled-out partition is removed."""
    if sampling_prob >= 1:
        return pre
    sampler = sampling_utils.ValueSampler(sampling_prob)
    keep_by_id = np.fromiter(
        (sampler.keep(pre.pk_vocab.decode(i)) for i in range(
            len(pre.pk_vocab))),
        dtype=bool,
        count=len(pre.pk_vocab))
    keep = keep_by_id[pre.pk_ids]
    return PreAggregates(pre.pk_ids[keep], pre.counts[keep], pre.sums[keep],
                         pre.n_partitions[keep], pre.pk_vocab)


def preaggregate_from_rows(col,
                           data_extractors: DataExtractors,
                           public_partitions=None) -> PreAggregates:
    """Encodes rows/ColumnarData and groups them (the analyze entry path)."""
    pid, pk, value, _, pk_vocab = encoding.encode_rows(
        col,
        getattr(data_extractors, "privacy_id_extractor", True),
        getattr(data_extractors, "partition_extractor", None),
        getattr(data_extractors, "value_extractor", None),
        public_partitions=public_partitions)
    return preaggregate_columns(pid, pk, value, pk_vocab)


def preaggregates_from_pre_aggregated_rows(col,
                                           partition_extractor,
                                           preaggregate_extractor,
                                           public_partitions=None
                                           ) -> PreAggregates:
    """Builds PreAggregates from rows that are already
    (partition_key, (count, sum, n_partitions)) shaped (the
    pre_aggregated_data mode; extractors per PreAggregateExtractors)."""
    rows = list(col)
    pk_col = encoding._column_from_list(
        [partition_extractor(row) for row in rows])
    data = [preaggregate_extractor(row) for row in rows]
    counts = np.asarray([d[0] for d in data], dtype=np.float64)
    sums = np.asarray([d[1] for d in data], dtype=np.float64)
    n_partitions = np.asarray([d[2] for d in data], dtype=np.int32)
    if public_partitions is not None:
        pk_vocab = encoding.Vocabulary(public_partitions)
        pk_ids = encoding._lookup_ids(pk_col, pk_vocab)
        keep = pk_ids >= 0
        return PreAggregates(pk_ids[keep], counts[keep], sums[keep],
                             n_partitions[keep], pk_vocab)
    pk_ids, uniques = encoding._factorize(pk_col)
    return PreAggregates(pk_ids, counts, sums, n_partitions,
                         encoding.Vocabulary.from_unique(uniques))


def preaggregate(col,
                 backend=None,
                 data_extractors: Optional[DataExtractors] = None,
                 partitions_sampling_prob: float = 1
                 ) -> List[Tuple[Any, Tuple[int, float, int]]]:
    """Materializes (partition_key, (count, sum, n_partitions)) rows.

    API parity with analysis/pre_aggregation.py:19-61 — the output can be
    fed back through PreAggregateExtractors for repeated analysis runs.
    ``backend`` is accepted for signature compatibility and ignored: the
    computation is columnar.
    """
    del backend
    pre = preaggregate_from_rows(col, data_extractors)
    pre = sample_partitions(pre, partitions_sampling_prob)
    keys = pre.pk_vocab.decode_all(pre.pk_ids)
    return [(keys[i], (int(pre.counts[i]), float(pre.sums[i]),
                       int(pre.n_partitions[i])))
            for i in range(pre.num_groups)]
