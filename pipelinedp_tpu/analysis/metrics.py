"""Result dataclasses of the utility analysis.

Parity: analysis/metrics.py (SumMetrics :23, RawStatistics :62,
PerPartitionMetrics :68, MeanVariance :75, ContributionBoundingErrors :81,
ValueErrors :106, DataDropInfo :172, MetricUtility :191, PartitionsInfo
:219, UtilityReport :248, UtilityReportBin :267). These are plain output
records; the math that fills them lives in per_partition.py /
cross_partition.py as vectorized array code.
"""

from __future__ import annotations

import dataclasses
import math
from typing import List, Optional

from pipelinedp_tpu.aggregate_params import (Metric, NoiseKind,
                                             PartitionSelectionStrategy)


@dataclasses.dataclass
class SumMetrics:
    """Per-partition error decomposition for one additive metric.

    Used for SUM, COUNT and PRIVACY_ID_COUNT alike (COUNT is the sum of
    per-(pid, pk) counts, PRIVACY_ID_COUNT the sum of indicators). The
    invariant the fields satisfy:
      E(dp value) = sum + clipping_to_min_error + clipping_to_max_error
                    + expected_l0_bounding_error  (+ zero-mean noise)
    """
    aggregation: Metric
    sum: float
    clipping_to_min_error: float
    clipping_to_max_error: float
    expected_l0_bounding_error: float
    std_l0_bounding_error: float
    std_noise: float
    noise_kind: NoiseKind


@dataclasses.dataclass
class RawStatistics:
    """Raw (non-DP) per-partition statistics."""
    privacy_id_count: int
    count: int


@dataclasses.dataclass
class PerPartitionMetrics:
    partition_selection_probability_to_keep: float
    raw_statistics: RawStatistics
    metric_errors: Optional[List[SumMetrics]] = None


@dataclasses.dataclass
class MeanVariance:
    mean: float
    var: float


@dataclasses.dataclass
class ContributionBoundingErrors:
    """Error breakdown by bounding stage: l0 (cross-partition, random) and
    linf min/max clipping (per-partition, deterministic)."""
    l0: MeanVariance
    linf_min: float
    linf_max: float

    def to_relative(self, value: float) -> "ContributionBoundingErrors":
        return ContributionBoundingErrors(
            l0=MeanVariance(self.l0.mean / value, self.l0.var / value**2),
            linf_min=self.linf_min / value,
            linf_max=self.linf_max / value)


@dataclasses.dataclass
class ValueErrors:
    """Statistics of (dp_value - actual_value), averaged across partitions.

    The *_with_dropped_partitions variants fold in partitions lost to
    private partition selection: with keep probability p the error is
    p*err + (1-p)*|actual|.
    """
    bounding_errors: ContributionBoundingErrors
    mean: float
    variance: float
    rmse: float
    l1: float
    rmse_with_dropped_partitions: float
    l1_with_dropped_partitions: float

    def to_relative(self, value: float) -> "ValueErrors":
        if value == 0:
            zero_bounding = ContributionBoundingErrors(MeanVariance(0, 0), 0,
                                                       0)
            return ValueErrors(zero_bounding, 0, 0, 0, 0, 0, 0)
        return ValueErrors(
            bounding_errors=self.bounding_errors.to_relative(value),
            mean=self.mean / value,
            variance=self.variance / value**2,
            rmse=self.rmse / value,
            l1=self.l1 / value,
            rmse_with_dropped_partitions=(self.rmse_with_dropped_partitions /
                                          value),
            l1_with_dropped_partitions=(self.l1_with_dropped_partitions /
                                        value))


@dataclasses.dataclass
class DataDropInfo:
    """Ratio of data dropped per DP stage."""
    l0: float
    linf: float
    partition_selection: float


@dataclasses.dataclass
class MetricUtility:
    """Cross-partition utility of one DP metric."""
    metric: Metric
    noise_std: float
    noise_kind: Optional[NoiseKind]
    ratio_data_dropped: Optional[DataDropInfo]
    absolute_error: ValueErrors
    relative_error: ValueErrors


@dataclasses.dataclass
class PartitionsInfo:
    """Aggregate statistics about partitions and partition selection."""
    public_partitions: bool
    num_dataset_partitions: int
    num_non_public_partitions: Optional[int] = None
    num_empty_partitions: Optional[int] = None
    strategy: Optional[PartitionSelectionStrategy] = None
    kept_partitions: Optional[MeanVariance] = None


@dataclasses.dataclass
class UtilityReport:
    """Result of the utility analysis for one parameter configuration."""
    configuration_index: int
    partitions_info: PartitionsInfo
    metric_errors: Optional[List[MetricUtility]] = None
    utility_report_histogram: Optional[List["UtilityReportBin"]] = None


@dataclasses.dataclass
class UtilityReportBin:
    """UtilityReport restricted to partitions whose size falls in
    [partition_size_from, partition_size_to)."""
    partition_size_from: int
    partition_size_to: int
    report: UtilityReport


def rmse_from_moments(bias: float, variance: float) -> float:
    """sqrt(bias^2 + variance) — the per-partition RMSE identity."""
    return math.sqrt(bias * bias + variance)
