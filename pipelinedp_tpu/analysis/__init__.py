"""Utility analysis & parameter tuning for pipelinedp_tpu.

TPU-first redesign of the reference's analysis/ package
(analysis/__init__.py in lagodiuk/PipelineDP): instead of per-row Python
combiner objects multiplied across parameter configurations
(analysis/per_partition_combiners.py:359-451), the whole multi-configuration
sweep is evaluated as vectorized array math over a
[n_configurations, n_partitions] grid on columnar pre-aggregates.
"""

from pipelinedp_tpu.analysis.data_structures import (
    MultiParameterConfiguration,
    UtilityAnalysisOptions,
    get_aggregate_params,
    get_partition_selection_strategy,
)
from pipelinedp_tpu.analysis import metrics
from pipelinedp_tpu.analysis.utility_analysis import perform_utility_analysis
from pipelinedp_tpu.analysis.utility_analysis_engine import (
    UtilityAnalysisEngine,)
from pipelinedp_tpu.analysis.parameter_tuning import (
    MinimizingFunction,
    ParametersToTune,
    TuneOptions,
    TuneResult,
    tune,
)
from pipelinedp_tpu.analysis.dp_strategy_selector import (
    DPStrategy,
    DPStrategySelector,
    DPStrategySelectorFactory,
)
from pipelinedp_tpu.analysis.pre_aggregation import preaggregate
from pipelinedp_tpu.analysis.probability_computations import (
    compute_sum_laplace_gaussian_quantiles,)
from pipelinedp_tpu.analysis.dataset_summary import (
    PublicPartitionsSummary,
    compute_public_partitions_summary,
)

__all__ = [
    "DPStrategy",
    "DPStrategySelector",
    "DPStrategySelectorFactory",
    "MinimizingFunction",
    "MultiParameterConfiguration",
    "ParametersToTune",
    "PublicPartitionsSummary",
    "TuneOptions",
    "TuneResult",
    "UtilityAnalysisEngine",
    "UtilityAnalysisOptions",
    "compute_public_partitions_summary",
    "compute_sum_laplace_gaussian_quantiles",
    "get_aggregate_params",
    "get_partition_selection_strategy",
    "metrics",
    "perform_utility_analysis",
    "preaggregate",
    "tune",
]
