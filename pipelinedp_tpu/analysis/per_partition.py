"""Vectorized per-partition utility-analysis error models.

The TPU-first replacement for the reference's per-row combiner objects
(analysis/per_partition_combiners.py:37-451): all configurations and all
partitions are evaluated at once on a [n_configurations, n_groups] grid of
columnar pre-aggregates, reduced to [n_configurations, n_partitions]
accumulator arrays with bincount segment sums. One Python loop per
configuration never appears on the group axis.

Error model (matching the reference's combiners):
  For each (privacy_id, partition) group with contribution count c, sum s
  and privacy-id partition load m, under config with L0 bound l0:
    q = min(1, l0 / m)               # P(group survives L0 sampling)
    x = clip(v, lo, hi)              # v = s (SUM), c (COUNT), 1 (PID_COUNT)
  Per partition: raw value = sum(v), clipping errors = sum(x - v) split by
  side, E[L0 error] = -sum(x (1-q)), Var[L0 error] = sum(x^2 q (1-q)).
  Partition keep probability = E[pi(N)] where N = sum of Bernoulli(q) over
  the partition's groups (exact Poisson-binomial PGF when the partition has
  <= MAX_EXACT_PROBABILITIES privacy units, refined-normal lattice
  approximation otherwise — analysis/poisson_binomial.py:62).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

import numpy as np

from pipelinedp_tpu import budget_accounting
from pipelinedp_tpu import dp_computations
from pipelinedp_tpu import partition_selection as ps_lib
from pipelinedp_tpu.aggregate_params import (AggregateParams, MechanismType,
                                             Metric, Metrics, NoiseKind,
                                             noise_to_thresholding)
from pipelinedp_tpu.analysis import data_structures
from pipelinedp_tpu.analysis import poisson_binomial
from pipelinedp_tpu.analysis.pre_aggregation import PreAggregates

MAX_EXACT_PROBABILITIES = 100
# Lattice size of the vectorized refined-normal approximation. When the
# +-8 sigma span fits (16 sigma <= lattice), the lattice is integer and the
# result matches the scalar refined-normal PMF exactly.
_APPROX_LATTICE = 160

# The order in which metric error models are computed and reported
# (stable regardless of the order in params.metrics).
METRIC_ORDER = (Metrics.SUM, Metrics.COUNT, Metrics.PRIVACY_ID_COUNT)


@dataclasses.dataclass
class ConfigSpec:
    """One configuration of the sweep with its resolved budget split."""
    index: int
    params: AggregateParams
    selection_spec: Optional[budget_accounting.MechanismSpec]
    metric_specs: Dict[Metric, budget_accounting.MechanismSpec]
    # Private selection happens through PRIVACY_ID_COUNT thresholding
    # (no separate selection budget).
    post_agg_thresholding: bool = False


def resolve_config_budgets(options: data_structures.UtilityAnalysisOptions,
                           public_partitions: bool) -> List[ConfigSpec]:
    """Splits (epsilon, delta) per configuration.

    Each configuration gets its own accountant so different configurations
    can use different mechanisms (parity: the deep-copied accountants of
    analysis/utility_analysis_engine.py:99-143; request order selection ->
    SUM -> COUNT -> PRIVACY_ID_COUNT).
    """
    configs = []
    metrics = options.aggregate_params.metrics or []
    for i, params in enumerate(data_structures.get_aggregate_params(options)):
        accountant = budget_accounting.NaiveBudgetAccountant(
            options.epsilon, options.delta)
        post_agg = (params.post_aggregation_thresholding and
                    not public_partitions)
        if post_agg and Metrics.PRIVACY_ID_COUNT not in metrics:
            # Per-config validation: the sweep can enable the flag per
            # configuration, bypassing the engine-level check on the
            # blueprint params.
            raise ValueError(
                f"Configuration {i}: post_aggregation_thresholding requires "
                f"PRIVACY_ID_COUNT in metrics")
        selection_spec = None
        if not public_partitions and not post_agg:
            # With post-aggregation thresholding, selection rides on the
            # PRIVACY_ID_COUNT thresholding mechanism — no separate budget
            # (parity: the engine requests no GENERIC spec in that mode).
            selection_spec = accountant.request_budget(MechanismType.GENERIC)
        mechanism_type = (params.noise_kind.convert_to_mechanism_type()
                          if params.noise_kind else None)
        metric_specs = {}
        for metric in METRIC_ORDER:
            if metric in metrics:
                if metric == Metrics.PRIVACY_ID_COUNT and post_agg:
                    metric_specs[metric] = accountant.request_budget(
                        noise_to_thresholding(params.noise_kind))
                else:
                    metric_specs[metric] = accountant.request_budget(
                        mechanism_type)
        accountant.compute_budgets()
        configs.append(
            ConfigSpec(i, params, selection_spec, metric_specs,
                       post_agg_thresholding=bool(post_agg)))
    return configs


def _thresholding_strategy(
        config: ConfigSpec) -> ps_lib.PartitionSelection:
    """The post-aggregation thresholding strategy of a config (its keep
    probabilities AND its PRIVACY_ID_COUNT noise, per the engine's
    PostAggregationThresholdingCombiner)."""
    params = config.params
    spec = config.metric_specs[Metrics.PRIVACY_ID_COUNT]
    sensitivities = (
        dp_computations.compute_sensitivities_for_privacy_id_count(params))
    return dp_computations.create_thresholding_mechanism(
        spec, sensitivities, params.pre_threshold).strategy


@dataclasses.dataclass
class MetricErrorArrays:
    """[n_configs, n_partitions] error accumulators for one metric."""
    metric: Metric
    raw: np.ndarray  # non-DP per-partition value
    clip_min_err: np.ndarray
    clip_max_err: np.ndarray
    exp_l0_err: np.ndarray
    var_l0_err: np.ndarray
    std_noise: np.ndarray  # [n_configs]
    noise_kind: List[NoiseKind]  # per config


@dataclasses.dataclass
class PerPartitionArrays:
    """The complete vectorized analysis state.

    device, when set, is the analysis/device_sweep.DeviceSweep holding the
    device-resident grids; metric_errors are then lazy views that pull to
    host numpy on first array access, and the report builder
    (cross_partition.build_reports_with_histogram) reduces on-device
    without ever materializing them.
    """
    n_configs: int
    n_partitions: int
    metric_errors: List[MetricErrorArrays]
    keep_prob: Optional[np.ndarray]  # [n_configs, n_partitions]; None=public
    raw_pid_count: np.ndarray  # [n_partitions]
    raw_count: np.ndarray  # [n_partitions]
    device: Optional[object] = None

    def release_device(self, materialize: bool = True) -> None:
        """Frees the device-resident grids (see DeviceSweep.release);
        no-op for host-computed arrays."""
        if self.device is not None:
            self.device.release(materialize)
            self.device = None


def _metric_values(metric: Metric, pre: PreAggregates) -> np.ndarray:
    """Per-group raw values v of the metric (configuration-independent)."""
    if metric == Metrics.SUM:
        return pre.sums
    if metric == Metrics.COUNT:
        return pre.counts
    if metric == Metrics.PRIVACY_ID_COUNT:
        return (pre.counts > 0).astype(np.float64)
    raise ValueError(f"Unsupported analysis metric: {metric}")


def _metric_bounds(metric: Metric, params: AggregateParams):
    """(clip lo, clip hi) for the metric under one configuration
    (reference combiners: SumCombiner :244, CountCombiner :304,
    PrivacyIdCountCombiner :328)."""
    if metric == Metrics.SUM:
        if params.bounds_per_partition_are_set:
            return params.min_sum_per_partition, params.max_sum_per_partition
        # Per-contribution bounds: the engine clips each contribution to
        # [min_value, max_value] and keeps at most linf of them, so a
        # group's released sum lies in linf-scaled bounds — model
        # clipping there. DELIBERATE DEVIATION from the reference, whose
        # analysis SumCombiner reads only min/max_sum_per_partition and
        # applies NO clipping in this mode
        # (per_partition_combiners.py:250-259: np.clip with None
        # bounds); that under-reports clipping error for groups whose
        # raw sum exceeds the count-scaled bounds. Pinned by
        # tests/analysis_test.py TestSumPerContributionBounds.
        return (params.min_value * params.max_contributions_per_partition,
                params.max_value * params.max_contributions_per_partition)
    if metric == Metrics.COUNT:
        return 0.0, float(params.max_contributions_per_partition)
    if metric == Metrics.PRIVACY_ID_COUNT:
        return 0.0, 1.0
    raise ValueError(f"Unsupported analysis metric: {metric}")


def _metric_values_and_bounds(metric: Metric, pre: PreAggregates,
                              params: AggregateParams):
    """(per-group raw values v, clip lo, clip hi) for the metric under the
    given config."""
    lo, hi = _metric_bounds(metric, params)
    return _metric_values(metric, pre), lo, hi


def _segment(values: np.ndarray, pk_ids: np.ndarray,
             n_partitions: int) -> np.ndarray:
    return np.bincount(pk_ids, weights=values, minlength=n_partitions)


def _metric_noise(configs: List[ConfigSpec], metric: Metric):
    """([n_configs] noise stddevs, per-config noise kinds) — host scalar
    mechanism math, shared by the host and device grid paths."""
    std_noise = np.zeros(len(configs))
    noise_kinds = []
    for c, config in enumerate(configs):
        if (metric == Metrics.PRIVACY_ID_COUNT and
                config.post_agg_thresholding):
            # Post-aggregation thresholding: the released count is the
            # thresholding strategy's noised value.
            std_noise[c] = _thresholding_strategy(config).noise_stddev
        else:
            sensitivities = dp_computations.compute_sensitivities(
                metric, config.params)
            mechanism = dp_computations.create_additive_mechanism(
                config.metric_specs[metric], sensitivities)
            std_noise[c] = mechanism.std
        noise_kinds.append(config.params.noise_kind)
    return std_noise, noise_kinds


def compute_metric_errors(pre: PreAggregates, configs: List[ConfigSpec],
                          metric: Metric,
                          n_partitions: int) -> MetricErrorArrays:
    """Error accumulators for one metric across every configuration."""
    n_configs = len(configs)
    shape = (n_configs, n_partitions)
    raw = np.zeros(shape)
    clip_min = np.zeros(shape)
    clip_max = np.zeros(shape)
    exp_l0 = np.zeros(shape)
    var_l0 = np.zeros(shape)
    for c, config in enumerate(configs):
        params = config.params
        v, lo, hi = _metric_values_and_bounds(metric, pre, params)
        q = np.minimum(1.0, params.max_partitions_contributed /
                       np.maximum(pre.n_partitions, 1))
        x = np.clip(v, lo, hi)
        err = x - v
        raw[c] = _segment(v, pre.pk_ids, n_partitions)
        clip_min[c] = _segment(np.where(v < lo, err, 0.0), pre.pk_ids,
                               n_partitions)
        clip_max[c] = _segment(np.where(v > hi, err, 0.0), pre.pk_ids,
                               n_partitions)
        exp_l0[c] = _segment(-x * (1.0 - q), pre.pk_ids, n_partitions)
        var_l0[c] = _segment(x * x * q * (1.0 - q), pre.pk_ids, n_partitions)
    std_noise, noise_kinds = _metric_noise(configs, metric)
    return MetricErrorArrays(metric=metric,
                             raw=raw,
                             clip_min_err=clip_min,
                             clip_max_err=clip_max,
                             exp_l0_err=exp_l0,
                             var_l0_err=var_l0,
                             std_noise=std_noise,
                             noise_kind=noise_kinds)


# metric -> DeviceSweep metric_kind (analysis/device_sweep.py).
_METRIC_KIND = {
    Metrics.SUM: "sum",
    Metrics.COUNT: "count",
    Metrics.PRIVACY_ID_COUNT: "privacy_id_count",
}


def _build_device_sweep(pre: PreAggregates, configs: List[ConfigSpec],
                        ordered_metrics: List[Metric], n_partitions: int,
                        public_partitions: bool, n_units: np.ndarray,
                        mesh=None):
    """Computes the whole configuration sweep on the device.

    Returns (DeviceSweep, lazy metric_errors, approx_moments or None). The
    grids stay device-resident; LazyMetricErrorArrays materializes them to
    host numpy only when a consumer reads the arrays (the fused report
    reduction in cross_partition never does).
    """
    from pipelinedp_tpu.analysis import device_sweep

    sweep = device_sweep.DeviceSweep(pre.pk_ids, pre.counts, pre.sums,
                                     pre.n_partitions, n_partitions,
                                     len(configs), mesh=mesh)
    l0 = np.asarray(
        [config.params.max_partitions_contributed for config in configs],
        dtype=np.float64)
    kinds, los, his, stds, noise_kind_lists = [], [], [], [], []
    for metric in ordered_metrics:
        bounds = [_metric_bounds(metric, config.params) for config in configs]
        kinds.append(_METRIC_KIND[metric])
        los.append(np.asarray([b[0] for b in bounds], dtype=np.float64))
        his.append(np.asarray([b[1] for b in bounds], dtype=np.float64))
        std_noise, noise_kinds = _metric_noise(configs, metric)
        stds.append(std_noise)
        noise_kind_lists.append(noise_kinds)
    indices = sweep.add_metrics(kinds, los, his, l0, stds)
    metric_errors = [
        device_sweep.LazyMetricErrorArrays(metric, stds[m],
                                           noise_kind_lists[m], sweep,
                                           indices[m])
        for m, metric in enumerate(ordered_metrics)
    ]
    if ordered_metrics:
        # Exact (float64) per-partition sizes for report bucketing: the
        # device raw values are float32 and could land on the other side
        # of a 1-2-5 bucket boundary.
        sweep.exact_sizes = _segment(_metric_values(ordered_metrics[0], pre),
                                     pre.pk_ids, n_partitions)
    approx_moments = None
    if (not public_partitions and pre.num_groups and
            (n_units > MAX_EXACT_PROBABILITIES).any()):
        # The refined-normal keep-probability path needs the moment
        # grids on host (the strategy's pi evaluation is host math).
        sweep.compute_moments(l0)
        approx_moments = sweep.pull_moments()
    # All kernels have run: free the uploaded input columns and the
    # moments grid so only the per-metric grids stay in device memory.
    sweep.drop_inputs()
    return sweep, metric_errors, approx_moments


def _keep_prob_exact(qs: np.ndarray,
                     strategy: ps_lib.PartitionSelection) -> float:
    pmf = poisson_binomial.compute_pmf(qs)
    counts = np.arange(pmf.start, pmf.start + len(pmf.probabilities))
    return float(
        np.dot(pmf.probabilities, strategy.probability_of_keep_vec(counts)))


# Exact-path batch buckets: partitions are grouped by privacy-unit count
# and padded to the bucket upper bound (padding with q=0 units is exact —
# a Bernoulli(0) contributes nothing to the PGF), so each bucket is one
# vectorized convolution instead of a per-partition Python loop.
_EXACT_BUCKETS = (4, 8, 16, 32, 64, MAX_EXACT_PROBABILITIES)


def _keep_prob_exact_batch(q_padded: np.ndarray, shift: np.ndarray,
                           strategy: ps_lib.PartitionSelection) -> np.ndarray:
    """Exact Poisson-binomial keep probabilities for a [P, M] batch.

    Row p holds partition p's *random* (q < 1) per-unit survival
    probabilities, zero-padded; shift[p] is the partition's count of
    deterministic q == 1 units, which translate the PMF instead of being
    convolved. The PMF recurrence runs over the unit axis with all
    partitions in lockstep: pmf_{j+1} = pmf_j (1 - q_j) + shift(pmf_j) q_j
    — identical arithmetic to poisson_binomial.compute_pmf, batched.
    """
    n_rows, m = q_padded.shape
    pmf = np.zeros((n_rows, m + 1))
    pmf[:, 0] = 1.0
    shifted = np.zeros_like(pmf)
    for j in range(m):
        qj = q_padded[:, j:j + 1]
        shifted[:, 1:] = pmf[:, :-1]
        pmf = pmf * (1.0 - qj) + shifted * qj
    counts = shift[:, None] + np.arange(m + 1)[None, :]
    pok = strategy.probability_of_keep_vec(counts.ravel()).reshape(
        counts.shape)
    return np.clip((pmf * pok).sum(axis=1), 0.0, 1.0)


def _keep_prob_approx_vec(mean: np.ndarray, var: np.ndarray, m3: np.ndarray,
                          n_units: np.ndarray,
                          strategy: ps_lib.PartitionSelection) -> np.ndarray:
    """Vectorized refined-normal keep probabilities.

    For each partition, builds a lattice spanning +-8 sigma around the
    mean, computes Edgeworth-corrected CDF differences on the lattice cells
    and dots them with the strategy's keep probabilities. Integer lattices
    (16 sigma <= _APPROX_LATTICE) reproduce the scalar refined-normal PMF
    bin for bin.
    """
    from scipy import stats

    n = len(mean)
    if n == 0:
        return np.zeros(0)
    sigma = np.sqrt(var)
    sigma_safe = np.maximum(sigma, 1e-12)
    skew = np.where(sigma > 0, m3 / sigma_safe**3, 0.0)
    step = np.maximum(1.0, np.ceil(16.0 * sigma / _APPROX_LATTICE))
    start = np.maximum(0.0, np.floor(mean - 8.0 * sigma))
    k = np.arange(_APPROX_LATTICE)
    # Lattice stays unclamped: clamping ns itself would duplicate the
    # boundary cell's probability mass once per clamped point. The count at
    # which pi is evaluated is clamped instead — mass the normal
    # approximation puts beyond n_units belongs to the n_units outcome.
    ns = start[:, None] + step[:, None] * k[None, :]  # [n, K]

    def corrected_cdf(x):
        z = (x - mean[:, None]) / sigma_safe[:, None]
        g = stats.norm.cdf(z) + skew[:, None] * (1 - z * z) * stats.norm.pdf(
            z) / 6.0
        return np.clip(g, 0.0, 1.0)

    cell_prob = (corrected_cdf(ns + step[:, None] / 2.0) -
                 corrected_cdf(ns - step[:, None] / 2.0))
    counts = np.minimum(np.round(ns), n_units[:, None].astype(np.float64))
    pok = strategy.probability_of_keep_vec(
        counts.astype(np.int64).ravel()).reshape(ns.shape)
    probs = (cell_prob * pok).sum(axis=1)
    # Degenerate distributions (sigma == 0): point mass at round(mean).
    degenerate = sigma == 0
    if degenerate.any():
        point = strategy.probability_of_keep_vec(
            np.round(mean[degenerate]).astype(np.int64))
        probs[degenerate] = point
    return np.clip(probs, 0.0, 1.0)


def compute_keep_probabilities(pre: PreAggregates, configs: List[ConfigSpec],
                               n_partitions: int,
                               approx_moments: Optional[np.ndarray] = None,
                               n_units: Optional[np.ndarray] = None
                               ) -> np.ndarray:
    """[n_configs, n_partitions] private-partition keep probabilities.

    approx_moments: optional [3, n_configs, n_partitions] Poisson-binomial
    moment grids (mean, var, m3) precomputed on the device
    (device_sweep.DeviceSweep.compute_moments); when absent the moments
    are segment sums on the host. n_units: optional precomputed
    privacy-unit count per partition (one bincount pass saved on the hot
    path).
    """
    n_configs = len(configs)
    out = np.zeros((n_configs, n_partitions))
    if n_units is None:
        n_units = np.bincount(pre.pk_ids, minlength=n_partitions)
    n_units = n_units.astype(np.int64)
    # Sorted-by-partition group view, for the exact path's padded batches.
    # All of this indexing is config-independent, computed once.
    order = np.argsort(pre.pk_ids, kind="stable")
    spk = pre.pk_ids[order]
    small = np.flatnonzero(
        (n_units > 0) & (n_units <= MAX_EXACT_PROBABILITIES))
    small_set = np.zeros(n_partitions, dtype=bool)
    small_set[small] = True
    sel_small = small_set[spk]
    spk_small = spk[sel_small]
    sq_order = order[sel_small]
    # Keep probabilities depend on the config only through the selection
    # strategy and the L0 bound — NOT through linf or the sum bounds — so
    # sweep configurations differing only in those share one computation.
    cache = {}
    for c, config in enumerate(configs):
        params = config.params
        if config.post_agg_thresholding:
            # Selection = the PRIVACY_ID_COUNT thresholding strategy: the
            # analyzed strategy is exactly what the engine would run.
            strategy = _thresholding_strategy(config)
            spec = config.metric_specs[Metrics.PRIVACY_ID_COUNT]
            key = (True, spec.eps, spec.delta, params.noise_kind,
                   params.max_partitions_contributed, params.pre_threshold)
        else:
            spec = config.selection_spec
            strategy = ps_lib.create_partition_selection_strategy(
                params.partition_selection_strategy, spec.eps, spec.delta,
                params.max_partitions_contributed, params.pre_threshold)
            key = (False, spec.eps, spec.delta,
                   params.partition_selection_strategy,
                   params.max_partitions_contributed, params.pre_threshold)
        if key in cache:
            out[c] = out[cache[key]]
            continue
        cache[key] = c
        q = np.minimum(1.0, params.max_partitions_contributed /
                       np.maximum(pre.n_partitions, 1))
        if len(small):
            out[c, small] = _exact_keep_probs(q[sq_order], spk_small,
                                              n_units, small, n_partitions,
                                              strategy)
        # Vectorized refined-normal for the rest.
        big = np.flatnonzero(n_units > MAX_EXACT_PROBABILITIES)
        if len(big):
            if approx_moments is not None:
                mean = approx_moments[0, c][big]
                var = approx_moments[1, c][big]
                m3 = approx_moments[2, c][big]
            else:
                mean = _segment(q, pre.pk_ids, n_partitions)[big]
                var = _segment(q * (1 - q), pre.pk_ids, n_partitions)[big]
                m3 = _segment(q * (1 - q) * (1 - 2 * q), pre.pk_ids,
                              n_partitions)[big]
            out[c, big] = _keep_prob_approx_vec(mean, var, m3, n_units[big],
                                                strategy)
    return out


def _exact_keep_probs(sq: np.ndarray, spk_small: np.ndarray,
                      n_units: np.ndarray, small: np.ndarray,
                      n_partitions: int,
                      strategy: ps_lib.PartitionSelection) -> np.ndarray:
    """Exact keep probabilities for the small partitions (one config).

    sq: per-unit survival probabilities of the small partitions' units, in
    partition-sorted order; spk_small: their partition ids. Deterministic
    q == 1 units only translate the Poisson-binomial PMF, so partitions are
    bucketed by their count of *random* (q < 1) units — under a generous L0
    bound most units are deterministic and whole buckets collapse to a
    direct probability_of_keep lookup.
    """
    keep = np.zeros(len(small))
    is_random = sq < 1.0
    n_random = np.bincount(spk_small[is_random],
                           minlength=n_partitions)[small]
    n_all = n_units[small]
    # Fully deterministic partitions: N == n_units.
    det = n_random == 0
    if det.any():
        keep[det] = strategy.probability_of_keep_vec(n_all[det])
    # Random positions within each partition's q<1 subset.
    csel = np.flatnonzero(is_random)
    if len(csel):
        spk_r = spk_small[csel]
        starts = np.searchsorted(spk_r, spk_r, side="left")
        pos = np.arange(len(spk_r)) - starts
        # Map partition id -> row in the small/bucket arrays.
        rowmap = np.full(n_partitions, -1)
        lo = 0
        for m in _EXACT_BUCKETS:
            rows = np.flatnonzero((n_random > lo) & (n_random <= m))
            lo = m
            if not len(rows):
                continue
            rowmap[:] = -1
            rowmap[small[rows]] = np.arange(len(rows))
            in_bucket = rowmap[spk_r] >= 0
            q_padded = np.zeros((len(rows), m))
            q_padded[rowmap[spk_r[in_bucket]], pos[in_bucket]] = (
                sq[csel[in_bucket]])
            shift = n_all[rows] - n_random[rows]
            keep[rows] = _keep_prob_exact_batch(q_padded, shift, strategy)
    return keep


def compute_per_partition_arrays(pre: PreAggregates,
                                 configs: List[ConfigSpec],
                                 metrics: List[Metric],
                                 public_partitions: bool,
                                 n_partitions: Optional[int] = None,
                                 use_device: Optional[bool] = None,
                                 mesh=None) -> PerPartitionArrays:
    """Runs every error model over the whole configuration grid.

    use_device: True forces the jitted device sweep
    (analysis/device_sweep.py) — any device failure propagates; False
    forces host numpy; None auto-selects (device when an accelerator is
    present and the grid is large), falling back to host with a warning if
    the device path fails.
    mesh: a jax.sharding.Mesh to shard the sweep over (implies device).
    """
    if n_partitions is None:
        n_partitions = max(len(pre.pk_vocab), 1)
    ordered_metrics = [m for m in METRIC_ORDER if m in metrics]
    from pipelinedp_tpu.analysis import device_sweep
    if mesh is not None:
        use_device = True
    forced_device = use_device is True
    if use_device is None:
        use_device = device_sweep.should_use_device(pre.num_groups,
                                                    len(configs))
    n_units = np.bincount(pre.pk_ids, minlength=n_partitions)
    metric_errors = None
    approx_moments = None
    device_state = None
    if use_device:
        try:
            device_state, metric_errors, approx_moments = (
                _build_device_sweep(pre, configs, ordered_metrics,
                                    n_partitions, public_partitions,
                                    n_units, mesh=mesh))
        except device_sweep.SWEEP_ERRORS:
            if forced_device:
                raise
            device_sweep.logger.warning(
                "Device utility-analysis sweep failed; falling back to the "
                "host path.",
                exc_info=True)
            metric_errors = None
            approx_moments = None
            device_state = None
    if metric_errors is None:
        metric_errors = [
            compute_metric_errors(pre, configs, m, n_partitions)
            for m in ordered_metrics
        ]
    keep_prob = None
    if not public_partitions:
        keep_prob = compute_keep_probabilities(pre, configs, n_partitions,
                                               approx_moments=approx_moments,
                                               n_units=n_units)
    return PerPartitionArrays(
        n_configs=len(configs),
        n_partitions=n_partitions,
        metric_errors=metric_errors,
        keep_prob=keep_prob,
        raw_pid_count=n_units,
        raw_count=_segment(pre.counts, pre.pk_ids, n_partitions),
        device=device_state,
    )
