"""Top-level utility-analysis orchestration.

Parity: analysis/utility_analysis.py:42-145 (perform_utility_analysis
returning (UtilityReports, per-partition metrics)); the packing /
unnesting / combine-per-key dataflow of the reference collapses into
direct vectorized reductions over the analysis arrays
(cross_partition.build_reports_with_histogram).
"""

from __future__ import annotations

from collections.abc import Sequence as _SequenceABC
from typing import Any, List, Sequence, Tuple, Union

from pipelinedp_tpu.data_extractors import (DataExtractors,
                                            PreAggregateExtractors)
from pipelinedp_tpu.analysis import data_structures
from pipelinedp_tpu.analysis import cross_partition
from pipelinedp_tpu.analysis import metrics as metrics_lib
from pipelinedp_tpu.analysis import per_partition
from pipelinedp_tpu.analysis import utility_analysis_engine

BUCKET_BOUNDS = cross_partition.BUCKET_BOUNDS


def perform_utility_analysis(
    col,
    backend=None,
    options: data_structures.UtilityAnalysisOptions = None,
    data_extractors: Union[DataExtractors, PreAggregateExtractors] = None,
    public_partitions=None,
) -> Tuple[List[metrics_lib.UtilityReport], Sequence[Tuple[Tuple[
        Any, int], metrics_lib.PerPartitionMetrics]]]:
    """Runs utility analysis for every parameter configuration.

    Returns:
      (utility_reports, per_partition_result):
        utility_reports — one UtilityReport per configuration, with the
          report-by-partition-size histogram attached;
        per_partition_result — ((partition_key, configuration_index),
          PerPartitionMetrics) for every partition and configuration, as a
          lazily-built list-like Sequence: index/iterate/len plus the
          common list mutators (append/extend/sort/item assignment), all
          of which materialize on first use — so report-only consumers
          never pay for the per-partition grid.
      ``backend`` is accepted for signature parity and ignored (execution
      is columnar).
    """
    del backend
    engine = utility_analysis_engine.UtilityAnalysisEngine()
    analysis_result = engine.analyze(col, options, data_extractors,
                                     public_partitions)
    is_public = public_partitions is not None
    metrics = [
        m for m in per_partition.METRIC_ORDER
        if m in (options.aggregate_params.metrics or [])
    ]
    reports = cross_partition.build_reports_with_histogram(
        analysis_result.arrays, metrics, is_public)
    if not is_public:
        strategies = data_structures.get_partition_selection_strategy(options)
        for report in reports:
            strategy = strategies[report.configuration_index]
            report.partitions_info.strategy = strategy
            for bin_ in report.utility_report_histogram or []:
                bin_.report.partitions_info.strategy = strategy

    return reports, _LazyPerPartitionResult(analysis_result)


class _LazyPerPartitionResult(_SequenceABC):
    """((partition_key, configuration_index), PerPartitionMetrics) rows,
    built on first access.

    perform_utility_analysis always returns them (API parity with the
    reference's per-partition output collection), but materializing them
    pulls the whole [n_configs, n_partitions] grid off the device — so the
    tuning path (parameter_tuning.tune), which reads only the reports,
    never pays for it.
    """

    def __init__(self, analysis_result):
        self._analysis_result = analysis_result
        self._items = None

    def _materialize(self):
        if self._items is None:
            items = []
            for pk, per_config in self._analysis_result:
                for c, ppm in enumerate(per_config):
                    items.append(((pk, c), ppm))
            self._items = items
        return self._items

    def __len__(self):
        return len(self._materialize())

    def __getitem__(self, index):
        return self._materialize()[index]

    # Reference-parity callers treat the result as a plain list; the
    # common mutators materialize and then behave exactly like one.
    def append(self, item):
        self._materialize().append(item)

    def extend(self, items):
        self._materialize().extend(items)

    def sort(self, **kwargs):
        self._materialize().sort(**kwargs)

    def __setitem__(self, index, value):
        self._materialize()[index] = value
