"""Automatic DP-strategy selection (noise kind, partition selection).

Parity: analysis/dp_strategy_selector.py:25-196. Chooses the noise kind
with the smaller standard deviation and the partition-selection strategy
with the smaller release threshold; PRIVACY_ID_COUNT routes to
post-aggregation thresholding with the delta split of
Delta_For_Thresholding.pdf.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from pipelinedp_tpu import dp_computations
from pipelinedp_tpu import input_validators
from pipelinedp_tpu.aggregate_params import (Metric, Metrics, NoiseKind,
                                             PartitionSelectionStrategy,
                                             noise_to_thresholding)


@dataclasses.dataclass
class DPStrategy:
    noise_kind: Optional[NoiseKind]
    partition_selection_strategy: Optional[PartitionSelectionStrategy]
    post_aggregation_thresholding: bool


class DPStrategySelector:
    """Chooses a DPStrategy from budget, metric and sensitivities."""

    def __init__(self, epsilon: float, delta: float, metric: Optional[Metric],
                 is_public_partitions: bool):
        input_validators.validate_epsilon_delta(epsilon, delta,
                                               "DPStrategySelector")
        if delta == 0 and not is_public_partitions:
            raise ValueError("Private partition selection requires delta > 0")
        self._epsilon = epsilon
        self._delta = delta
        self._metric = metric
        self._is_public_partitions = is_public_partitions

    @property
    def is_public_partitions(self) -> bool:
        return self._is_public_partitions

    @property
    def metric(self) -> Optional[Metric]:
        return self._metric

    def get_dp_strategy(
            self,
            sensitivities: dp_computations.Sensitivities) -> DPStrategy:
        if self._metric is None:
            # select_partitions: all budget goes to selection.
            return DPStrategy(
                noise_kind=None,
                partition_selection_strategy=self.
                select_partition_selection_strategy(self._epsilon,
                                                    self._delta,
                                                    sensitivities.l0),
                post_aggregation_thresholding=False)
        if self._is_public_partitions:
            return DPStrategy(noise_kind=self.select_noise_kind(
                self._epsilon, self._delta, sensitivities),
                              partition_selection_strategy=None,
                              post_aggregation_thresholding=False)
        if self.use_post_aggregation_thresholding(self._metric):
            # Delta split per Delta_For_Thresholding.pdf: half to noise,
            # half to the threshold.
            noise_kind = self.select_noise_kind(
                # Predicts the engine's documented thresholding split for
                # strategy scoring; no budget is spent here.
                # dplint: disable=DPL005 — scoring-only mirror of the split
                self._epsilon, self._delta / 2,
                dp_computations.Sensitivities(l0=sensitivities.l0, linf=1))
            return DPStrategy(noise_kind=noise_kind,
                              partition_selection_strategy=noise_to_thresholding(
                                  noise_kind).to_partition_selection_strategy(),
                              post_aggregation_thresholding=True)
        # Private selection: budget halved between noise and selection.
        # This mirrors the accountant's even two-way split for strategy
        # scoring only; the real split stays with the BudgetAccountant.
        # dplint: disable=DPL005 — scoring-only mirror of the split
        half_eps, half_delta = self._epsilon / 2, self._delta / 2
        return DPStrategy(
            noise_kind=self.select_noise_kind(half_eps, half_delta,
                                              sensitivities),
            partition_selection_strategy=self.
            select_partition_selection_strategy(half_eps, half_delta,
                                                sensitivities.l0),
            post_aggregation_thresholding=False)

    def select_noise_kind(
            self, epsilon: float, delta: float,
            sensitivities: dp_computations.Sensitivities) -> NoiseKind:
        """The noise kind with the smaller standard deviation."""
        if delta == 0:
            return NoiseKind.LAPLACE
        gaussian_std = dp_computations.GaussianMechanism.\
            create_from_epsilon_delta(epsilon, delta, sensitivities.l2).std
        laplace_std = dp_computations.LaplaceMechanism.create_from_epsilon(
            epsilon, sensitivities.l1).std
        return (NoiseKind.GAUSSIAN
                if gaussian_std < laplace_std else NoiseKind.LAPLACE)

    def use_post_aggregation_thresholding(self, metric: Metric) -> bool:
        return metric == Metrics.PRIVACY_ID_COUNT

    def select_partition_selection_strategy(
            self, epsilon: float, delta: float,
            l0_sensitivity: int) -> PartitionSelectionStrategy:
        """The strategy with the smaller release threshold.

        Laplace and Gaussian thresholding are compared by threshold; when
        Laplace wins, truncated geometric (strictly better than Laplace
        thresholding) is returned in its place.
        """

        def threshold(strategy: PartitionSelectionStrategy) -> float:
            return dp_computations.ThresholdingMechanism(
                epsilon, delta, strategy, l0_sensitivity,
                pre_threshold=None).threshold()

        laplace_t = threshold(
            PartitionSelectionStrategy.LAPLACE_THRESHOLDING)
        gaussian_t = threshold(
            PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING)
        if laplace_t < gaussian_t:
            return PartitionSelectionStrategy.TRUNCATED_GEOMETRIC
        return PartitionSelectionStrategy.GAUSSIAN_THRESHOLDING


class DPStrategySelectorFactory:

    def create(self, epsilon: float, delta: float, metric: Optional[Metric],
               is_public_partitions: bool) -> DPStrategySelector:
        return DPStrategySelector(epsilon, delta, metric,
                                  is_public_partitions)
