"""Poisson-binomial PMF: exact PGF convolution + refined normal approximation.

Parity: analysis/poisson_binomial.py (compute_pmf :39,
compute_exp_std_skewness :53, compute_pmf_approximation :62). Used by the
partition-selection error model to turn per-privacy-unit keep
probabilities into a distribution over the post-bounding privacy-unit
count.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence, Tuple

import numpy as np
from scipy import stats


@dataclasses.dataclass
class PMF:
    """PMF of an integer distribution: P(X = start + i) = probabilities[i]."""
    start: int
    probabilities: np.ndarray


def compute_pmf(probabilities: Sequence[float]) -> PMF:
    """Exact Poisson-binomial PMF via probability-generating-function
    products: PGF(x) = prod_p (1 - p + p x)."""
    coeffs = np.ones(1)
    for p in probabilities:
        nxt = np.zeros(len(coeffs) + 1)
        nxt[:-1] = coeffs * (1.0 - p)
        nxt[1:] += coeffs * p
        coeffs = nxt
    return PMF(0, coeffs)


def compute_exp_std_skewness(
        probabilities: Sequence[float]) -> Tuple[float, float, float]:
    p = np.asarray(probabilities, dtype=np.float64)
    exp = float(p.sum())
    var = float((p * (1 - p)).sum())
    std = np.sqrt(var)
    skew = float((p * (1 - p) * (1 - 2 * p)).sum()) / std**3 if std else 0.0
    return exp, std, skew


def compute_pmf_approximation(mean: float, sigma: float, skewness: float,
                              n: int) -> PMF:
    """Refined normal approximation (Edgeworth-corrected CDF) of the
    Poisson-binomial PMF; tails below ~1e-15 are truncated at 8 sigma."""
    if sigma == 0:
        return PMF(int(round(mean)), np.ones(1))
    lo = max(0, int(np.floor(mean - 8 * sigma)))
    hi = min(n, int(np.round(mean + 8 * sigma)))
    grid = np.arange(lo - 1, hi + 1)
    z = (grid + 0.5 - mean) / sigma
    cdf = stats.norm.cdf(z) + skewness * (1 - z * z) * stats.norm.pdf(z) / 6.0
    cdf = np.clip(cdf, 0.0, 1.0)
    return PMF(lo, np.diff(cdf))
