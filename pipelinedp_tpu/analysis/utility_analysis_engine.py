"""UtilityAnalysisEngine: per-partition utility analysis, vectorized.

API parity with the reference engine (analysis/utility_analysis_engine
.py:29-185: analyze() takes UtilityAnalysisOptions + extractors + optional
public partitions and yields per-partition error estimates), but the
execution model is columnar: one pre-aggregation pass over the data, then
the whole multi-parameter sweep as array math on a
[n_configurations, n_partitions] grid (per_partition.py) — no per-row
combiner objects and no deep-copied accumulator graphs.
"""

from __future__ import annotations

from typing import Any, Iterator, List, Optional, Tuple, Union

import numpy as np

from pipelinedp_tpu.aggregate_params import Metrics
from pipelinedp_tpu.data_extractors import (DataExtractors,
                                            PreAggregateExtractors)
from pipelinedp_tpu.analysis import data_structures
from pipelinedp_tpu.analysis import metrics as metrics_lib
from pipelinedp_tpu.analysis import per_partition
from pipelinedp_tpu.analysis import pre_aggregation

_SUPPORTED_METRICS = {Metrics.COUNT, Metrics.SUM, Metrics.PRIVACY_ID_COUNT}


class AnalysisResult:
    """Result of analyze(): per-partition metrics for every configuration.

    Iterating yields (partition_key, Tuple[PerPartitionMetrics]) with one
    entry per configuration. `arrays` exposes the underlying
    [n_configurations, n_partitions] error grids for vectorized consumers
    (utility_analysis.py aggregates straight from them).
    """

    def __init__(self, arrays: per_partition.PerPartitionArrays, pk_vocab,
                 ordered_metrics, public_partitions: bool):
        self.arrays = arrays
        self.pk_vocab = pk_vocab
        self.ordered_metrics = ordered_metrics
        self.public_partitions = public_partitions

    def per_partition_metrics(
            self, p: int) -> Tuple[metrics_lib.PerPartitionMetrics, ...]:
        arrays = self.arrays
        result = []
        for c in range(arrays.n_configs):
            keep = (1.0 if arrays.keep_prob is None else float(
                arrays.keep_prob[c, p]))
            errors = [
                metrics_lib.SumMetrics(
                    aggregation=err.metric,
                    sum=float(err.raw[c, p]),
                    clipping_to_min_error=float(err.clip_min_err[c, p]),
                    clipping_to_max_error=float(err.clip_max_err[c, p]),
                    expected_l0_bounding_error=float(err.exp_l0_err[c, p]),
                    std_l0_bounding_error=float(
                        np.sqrt(err.var_l0_err[c, p])),
                    std_noise=float(err.std_noise[c]),
                    noise_kind=err.noise_kind[c])
                for err in arrays.metric_errors
            ]
            result.append(
                metrics_lib.PerPartitionMetrics(
                    partition_selection_probability_to_keep=keep,
                    raw_statistics=metrics_lib.RawStatistics(
                        privacy_id_count=int(arrays.raw_pid_count[p]),
                        count=int(arrays.raw_count[p])),
                    metric_errors=errors))
        return tuple(result)

    def __iter__(
        self
    ) -> Iterator[Tuple[Any, Tuple[metrics_lib.PerPartitionMetrics, ...]]]:
        for p in range(self.arrays.n_partitions):
            if p < len(self.pk_vocab):
                yield self.pk_vocab.decode(p), self.per_partition_metrics(p)


class UtilityAnalysisEngine:
    """Computes error estimates (not DP results) for DP aggregations."""

    def __init__(self, budget_accountant=None, backend=None):
        # Accepted for signature parity; the analysis splits budgets with
        # per-configuration accountants (per_partition.resolve_config_budgets)
        # and executes columnar, so neither is used.
        del budget_accountant, backend

    def aggregate(self, *args, **kwargs):
        raise ValueError(
            "UtilityAnalysisEngine computes error estimates, not DP results: "
            "call analyze(); for DP aggregation use DPEngine/JaxDPEngine.")

    def analyze(self,
                col,
                options: data_structures.UtilityAnalysisOptions,
                data_extractors: Union[DataExtractors,
                                       PreAggregateExtractors],
                public_partitions: Optional[List[Any]] = None
                ) -> AnalysisResult:
        """Per-partition utility analysis over every configuration."""
        _check_analyze_params(options, data_extractors)
        is_public = public_partitions is not None
        if options.pre_aggregated_data:
            pre = pre_aggregation.preaggregates_from_pre_aggregated_rows(
                col, data_extractors.partition_extractor,
                data_extractors.preaggregate_extractor, public_partitions)
        else:
            pre = pre_aggregation.preaggregate_from_rows(
                col, data_extractors, public_partitions)
        pre = pre_aggregation.sample_partitions(
            pre, options.partitions_sampling_prob)
        configs = per_partition.resolve_config_budgets(options, is_public)
        metrics = options.aggregate_params.metrics or []
        ordered = [m for m in per_partition.METRIC_ORDER if m in metrics]
        arrays = per_partition.compute_per_partition_arrays(
            pre, configs, metrics, is_public,
            n_partitions=max(len(pre.pk_vocab), 1),
            use_device=options.use_device_sweep,
            mesh=getattr(options, "device_mesh", None))
        return AnalysisResult(arrays, pre.pk_vocab, ordered, is_public)


def _check_analyze_params(
        options: data_structures.UtilityAnalysisOptions,
        data_extractors: Union[DataExtractors, PreAggregateExtractors]):
    if options.pre_aggregated_data:
        if not isinstance(data_extractors, PreAggregateExtractors):
            raise ValueError(
                "pre_aggregated_data=True requires PreAggregateExtractors.")
    elif not isinstance(data_extractors,
                        (DataExtractors,)) and data_extractors is not None:
        raise ValueError("DataExtractors required for raw data.")
    params = options.aggregate_params
    if params.custom_combiners is not None:
        raise NotImplementedError(
            "Utility analysis of custom combiners is not supported.")
    unsupported = set(params.metrics or []) - _SUPPORTED_METRICS
    if unsupported:
        raise NotImplementedError(
            f"Utility analysis does not support metrics {unsupported}.")
    if params.contribution_bounds_already_enforced:
        raise NotImplementedError(
            "Utility analysis with contribution_bounds_already_enforced is "
            "not supported.")
    if (params.post_aggregation_thresholding and
            Metrics.PRIVACY_ID_COUNT not in (params.metrics or [])):
        # Same validation as DPEngine._check_aggregate_params
        # (dp_engine.py:338-341): the thresholding rides on the
        # PRIVACY_ID_COUNT mechanism.
        raise ValueError("When post_aggregation_thresholding = True, "
                         "PRIVACY_ID_COUNT must be in metrics")
