"""Public-partitions vs dataset overlap statistics.

Parity: analysis/dataset_summary.py:21-108 — the reference's
distinct/flatten/group-by dataflow reduces to two set operations on the
distinct partition keys.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Iterable, Optional

from pipelinedp_tpu.data_extractors import DataExtractors


@dataclasses.dataclass
class PublicPartitionsSummary:
    num_dataset_public_partitions: int
    num_dataset_non_public_partitions: int
    num_empty_public_partitions: int


def compute_public_partitions_summary(
        col,
        backend=None,
        extractors: Optional[DataExtractors] = None,
        public_partitions: Iterable[Any] = None) -> PublicPartitionsSummary:
    """Counts dataset∩public, dataset\\public and public\\dataset partitions.

    ``backend`` accepted for signature parity and ignored.
    """
    del backend
    dataset = {extractors.partition_extractor(row) for row in col}
    public = set(public_partitions)
    return PublicPartitionsSummary(
        num_dataset_public_partitions=len(dataset & public),
        num_dataset_non_public_partitions=len(dataset - public),
        num_empty_public_partitions=len(public - dataset))
