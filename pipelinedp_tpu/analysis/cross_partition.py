"""Vectorized cross-partition aggregation into UtilityReports.

Replaces the reference's dataclass-arithmetic combiner
(analysis/cross_partition_combiners.py:296-343, recursive field add/multiply
:142-191) with direct weighted reductions over the
[n_configurations, n_partitions] error arrays: one numpy sum per report
field instead of one combiner merge per partition.

Semantics (matching the reference):
  * per-partition weight = keep probability (1 for public partitions);
  * every ValueErrors field is the weighted mean over partitions;
  * relative errors divide by the partition's raw value before weighting
    (partitions with raw value 0 contribute 0);
  * data-dropped ratios are summed raw and divided by the total raw value;
  * kept_partitions is the Poisson-binomial mean/variance of the number of
    kept partitions; noise_std passes through unaveraged.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from pipelinedp_tpu.aggregate_params import Metric
from pipelinedp_tpu.analysis import metrics as metrics_lib
from pipelinedp_tpu.analysis.per_partition import (MetricErrorArrays,
                                                   PerPartitionArrays)


def _weighted_mean(values: np.ndarray, weights: np.ndarray,
                   total_weight: float) -> float:
    if total_weight == 0:
        return 0.0
    return float(np.dot(values, weights) / total_weight)


def _metric_utility(err: MetricErrorArrays, c: int, part_mask: np.ndarray,
                    keep_prob: Optional[np.ndarray]) -> metrics_lib.MetricUtility:
    """Cross-partition MetricUtility for configuration c over the masked
    partition subset."""
    raw = err.raw[c][part_mask]
    clip_min = err.clip_min_err[c][part_mask]
    clip_max = err.clip_max_err[c][part_mask]
    exp_l0 = err.exp_l0_err[c][part_mask]
    var_l0 = err.var_l0_err[c][part_mask]
    std_noise = float(err.std_noise[c])
    keep = (np.ones(len(raw))
            if keep_prob is None else keep_prob[c][part_mask])

    bias = exp_l0 + clip_min + clip_max
    variance = var_l0 + std_noise**2
    rmse = np.sqrt(bias**2 + variance)
    rmse_dropped = keep * rmse + (1 - keep) * np.abs(raw)

    weights = keep
    total_weight = float(weights.sum())

    def abs_errors():
        return metrics_lib.ValueErrors(
            bounding_errors=metrics_lib.ContributionBoundingErrors(
                l0=metrics_lib.MeanVariance(
                    _weighted_mean(exp_l0, weights, total_weight),
                    _weighted_mean(var_l0, weights, total_weight)),
                linf_min=_weighted_mean(clip_min, weights, total_weight),
                linf_max=_weighted_mean(clip_max, weights, total_weight)),
            mean=_weighted_mean(bias, weights, total_weight),
            variance=_weighted_mean(variance, weights, total_weight),
            rmse=_weighted_mean(rmse, weights, total_weight),
            l1=0.0,
            rmse_with_dropped_partitions=_weighted_mean(
                rmse_dropped, weights, total_weight),
            l1_with_dropped_partitions=0.0)

    def rel_errors():
        # Divide per-partition values by raw before weighting; raw == 0
        # partitions contribute zero (ValueErrors.to_relative semantics).
        safe_raw = np.where(raw == 0, 1.0, raw)
        nz = (raw != 0).astype(np.float64)
        return metrics_lib.ValueErrors(
            bounding_errors=metrics_lib.ContributionBoundingErrors(
                l0=metrics_lib.MeanVariance(
                    _weighted_mean(exp_l0 / safe_raw * nz, weights,
                                   total_weight),
                    _weighted_mean(var_l0 / safe_raw**2 * nz, weights,
                                   total_weight)),
                linf_min=_weighted_mean(clip_min / safe_raw * nz, weights,
                                        total_weight),
                linf_max=_weighted_mean(clip_max / safe_raw * nz, weights,
                                        total_weight)),
            mean=_weighted_mean(bias / safe_raw * nz, weights, total_weight),
            variance=_weighted_mean(variance / safe_raw**2 * nz, weights,
                                    total_weight),
            rmse=_weighted_mean(rmse / safe_raw * nz, weights, total_weight),
            l1=0.0,
            rmse_with_dropped_partitions=_weighted_mean(
                rmse_dropped / safe_raw * nz, weights, total_weight),
            l1_with_dropped_partitions=0.0)

    # Data dropped: attribute raw mass to bounding stages, then partition
    # selection takes (1 - keep) of what survives; normalize by total raw.
    linf_dropped = clip_min - clip_max  # negate max (negative) side
    l0_dropped = -exp_l0
    survived = raw - l0_dropped - linf_dropped
    selection_dropped = survived * (1 - keep)
    total_raw = float(raw.sum())
    denom = total_raw if total_raw != 0 else 1.0
    data_dropped = metrics_lib.DataDropInfo(
        l0=float(l0_dropped.sum()) / denom,
        linf=float(linf_dropped.sum()) / denom,
        partition_selection=float(selection_dropped.sum()) / denom)

    return metrics_lib.MetricUtility(metric=err.metric,
                                     noise_std=std_noise,
                                     noise_kind=err.noise_kind[c],
                                     ratio_data_dropped=data_dropped,
                                     absolute_error=abs_errors(),
                                     relative_error=rel_errors())


def _partitions_info(arrays: PerPartitionArrays, c: int,
                     part_mask: np.ndarray,
                     public_partitions: bool) -> metrics_lib.PartitionsInfo:
    if public_partitions:
        raw_count = arrays.raw_count[part_mask]
        empty = int((raw_count == 0).sum())
        return metrics_lib.PartitionsInfo(public_partitions=True,
                                          num_dataset_partitions=int(
                                              (raw_count > 0).sum()),
                                          num_non_public_partitions=0,
                                          num_empty_partitions=empty)
    keep = arrays.keep_prob[c][part_mask]
    kept = metrics_lib.MeanVariance(float(keep.sum()),
                                    float((keep * (1 - keep)).sum()))
    return metrics_lib.PartitionsInfo(public_partitions=False,
                                      num_dataset_partitions=int(
                                          part_mask.sum()),
                                      kept_partitions=kept)


def build_utility_report(arrays: PerPartitionArrays, c: int,
                         part_mask: np.ndarray, dp_metrics: Sequence[Metric],
                         public_partitions: bool) -> metrics_lib.UtilityReport:
    """UtilityReport for configuration c restricted to part_mask."""
    metric_errors = None
    if dp_metrics:
        metric_errors = [
            _metric_utility(err, c, part_mask,
                            None if public_partitions else arrays.keep_prob)
            for err in arrays.metric_errors
        ]
    return metrics_lib.UtilityReport(configuration_index=c,
                                     partitions_info=_partitions_info(
                                         arrays, c, part_mask,
                                         public_partitions),
                                     metric_errors=metric_errors)


def _generate_bucket_bounds() -> List[int]:
    bounds = [0, 1]
    for decade in range(1, 13):
        bounds.extend(
            (10**decade, 2 * 10**decade, 5 * 10**decade))
    return bounds


# Logarithmic 1-2-5 bucket lower bounds for the report-by-partition-size
# histogram (parity: analysis/utility_analysis.py:28-39).
BUCKET_BOUNDS = _generate_bucket_bounds()


def partition_size_buckets(sizes: np.ndarray) -> np.ndarray:
    """Lower bucket bound of each partition size."""
    sizes = np.maximum(np.asarray(sizes), 0)
    idx = np.searchsorted(BUCKET_BOUNDS, sizes, side="right") - 1
    return np.asarray(BUCKET_BOUNDS)[np.maximum(idx, 0)]


def bucket_upper_bound(lower: int) -> int:
    idx = BUCKET_BOUNDS.index(lower) + 1
    return BUCKET_BOUNDS[idx] if idx < len(BUCKET_BOUNDS) else -1


def _metric_utility_from_sums(metric, noise_kind, std_noise: float,
                              s: np.ndarray,
                              weight: float) -> metrics_lib.MetricUtility:
    """MetricUtility from the device's per-bucket report sums.

    s is one [device_sweep.N_REPORT_FIELDS] vector: weighted absolute sums
    (0-7), weighted relative sums (8-15), then raw / l0-dropped /
    linf-dropped / selection-dropped mass (16-19). Same arithmetic as
    _metric_utility, with the per-partition reductions already done
    on-device.
    """

    def d(x):
        return float(x) / weight if weight else 0.0

    def value_errors(base):
        return metrics_lib.ValueErrors(
            bounding_errors=metrics_lib.ContributionBoundingErrors(
                l0=metrics_lib.MeanVariance(d(s[base]), d(s[base + 1])),
                linf_min=d(s[base + 2]),
                linf_max=d(s[base + 3])),
            mean=d(s[base + 4]),
            variance=d(s[base + 5]),
            rmse=d(s[base + 6]),
            l1=0.0,
            rmse_with_dropped_partitions=d(s[base + 7]),
            l1_with_dropped_partitions=0.0)

    total_raw = float(s[16])
    denom = total_raw if total_raw != 0 else 1.0
    data_dropped = metrics_lib.DataDropInfo(
        l0=float(s[17]) / denom,
        linf=float(s[18]) / denom,
        partition_selection=float(s[19]) / denom)
    return metrics_lib.MetricUtility(metric=metric,
                                     noise_std=std_noise,
                                     noise_kind=noise_kind,
                                     ratio_data_dropped=data_dropped,
                                     absolute_error=value_errors(0),
                                     relative_error=value_errors(8))


def _build_reports_device(
        arrays: PerPartitionArrays, dp_metrics: Sequence[Metric],
        public_partitions: bool) -> List[metrics_lib.UtilityReport]:
    """Fused device report path: one segment-sum over partition-size
    buckets per metric; only [n_buckets, n_fields, n_configs] sums leave
    the device (the [n_configs, n_partitions] grids are never pulled)."""
    dev = arrays.device
    sizes = (dev.exact_sizes
             if dev.exact_sizes is not None else dev.pull_raw(0))
    buckets = partition_size_buckets(sizes)
    uniq = sorted(set(buckets.tolist()))
    bucket_ids = np.searchsorted(np.asarray(uniq), buckets)
    n_buckets = len(uniq)
    keep = None if public_partitions else arrays.keep_prob
    metric_sums, keep_sums = dev.report_sums(bucket_ids, n_buckets, keep)
    bucket_count = np.bincount(bucket_ids,
                               minlength=n_buckets).astype(np.float64)
    if public_partitions:
        weights = np.broadcast_to(bucket_count[:, None],
                                  (n_buckets, arrays.n_configs))
        raw_count = np.asarray(arrays.raw_count, dtype=np.float64)
        nonempty = np.bincount(bucket_ids,
                               weights=(raw_count > 0).astype(np.float64),
                               minlength=n_buckets)
        empty_count = bucket_count - nonempty
    else:
        weights = keep_sums[:, 0, :]

    def partitions_info(b, c):
        if public_partitions:
            ne = nonempty.sum() if b is None else nonempty[b]
            em = empty_count.sum() if b is None else empty_count[b]
            return metrics_lib.PartitionsInfo(public_partitions=True,
                                              num_dataset_partitions=int(ne),
                                              num_non_public_partitions=0,
                                              num_empty_partitions=int(em))
        ks = keep_sums.sum(axis=0) if b is None else keep_sums[b]
        n = bucket_count.sum() if b is None else bucket_count[b]
        return metrics_lib.PartitionsInfo(
            public_partitions=False,
            num_dataset_partitions=int(n),
            kept_partitions=metrics_lib.MeanVariance(float(ks[0, c]),
                                                     float(ks[1, c])))

    def metric_utilities(b, c):
        out = []
        for err, sums in zip(arrays.metric_errors, metric_sums):
            s = sums.sum(axis=0)[:, c] if b is None else sums[b][:, c]
            w = (float(weights.sum(axis=0)[c])
                 if b is None else float(weights[b, c]))
            out.append(
                _metric_utility_from_sums(err.metric, err.noise_kind[c],
                                          float(err.std_noise[c]), s, w))
        return out

    reports = []
    for c in range(arrays.n_configs):
        report = metrics_lib.UtilityReport(
            configuration_index=c,
            partitions_info=partitions_info(None, c),
            metric_errors=metric_utilities(None, c))
        report.utility_report_histogram = [
            metrics_lib.UtilityReportBin(
                partition_size_from=int(lower),
                partition_size_to=int(bucket_upper_bound(int(lower))),
                report=metrics_lib.UtilityReport(
                    configuration_index=c,
                    partitions_info=partitions_info(b, c),
                    metric_errors=metric_utilities(b, c)))
            for b, lower in enumerate(uniq)
        ]
        reports.append(report)
    return reports


def build_reports_with_histogram(
        arrays: PerPartitionArrays, dp_metrics: Sequence[Metric],
        public_partitions: bool) -> List[metrics_lib.UtilityReport]:
    """Global report + report-by-size-bucket histogram per configuration.

    Partition size is the raw value of the first analyzed metric in the
    first configuration (raw privacy-id count when only partition selection
    is analyzed). When the sweep ran on the device, the reduction is fused
    there (_build_reports_device).
    """
    if (getattr(arrays, "device", None) is not None and arrays.metric_errors
            and dp_metrics):
        return _build_reports_device(arrays, dp_metrics, public_partitions)
    if arrays.metric_errors:
        sizes = arrays.metric_errors[0].raw[0]
    else:
        sizes = arrays.raw_pid_count
    buckets = partition_size_buckets(sizes)
    all_mask = np.ones(arrays.n_partitions, dtype=bool)
    reports = []
    for c in range(arrays.n_configs):
        report = build_utility_report(arrays, c, all_mask, dp_metrics,
                                      public_partitions)
        histogram = []
        for lower in sorted(set(buckets.tolist())):
            mask = buckets == lower
            histogram.append(
                metrics_lib.UtilityReportBin(
                    partition_size_from=int(lower),
                    partition_size_to=int(bucket_upper_bound(int(lower))),
                    report=build_utility_report(arrays, c, mask, dp_metrics,
                                                public_partitions)))
        if dp_metrics:
            report.utility_report_histogram = histogram
        reports.append(report)
    return reports
